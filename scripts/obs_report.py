#!/usr/bin/env python
"""obs_report: one command from a run dir's JSONL to "is this run healthy
and where is the time going".

    python scripts/obs_report.py <run_dir | metrics.jsonl> [--json]

Reads every *.jsonl under the run dir (a run writes metrics.jsonl; serving
side-cars land next to it), validates each line against the obs/ schema
(strict JSON — a bare NaN is a lint error, not a parse pass), and prints:

  * per-role throughput: env frames/sec (learn rows), learner steps/sec and
    learn-step p50/p99 (timing rows), serve request/batch totals;
  * replay occupancy, batch occupancy + pad tax (serve rows);
  * compile counts and span aggregates (timing rows);
  * fault totals by event, shed totals, dead hosts;
  * final eval and overall health (last health row + worst status seen).

Exit codes: 0 = report printed; 1 = no rows found (empty/missing run);
2 = report printed but some lines failed lint (broken producer).

The schema is versioned (obs/schema.py); this tool is the reference
consumer the golden-schema test keeps honest.  docs/OBSERVABILITY.md walks
through reading a report.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from rainbow_iqn_apex_tpu.obs.pipeline_trace import (  # noqa: E402
    critical_path,
    format_critical_path,
)
from rainbow_iqn_apex_tpu.obs.schema import validate_row  # noqa: E402
from scripts.lint_jsonl import lint_line  # noqa: E402


def find_jsonl(path: str) -> List[str]:
    if os.path.isfile(path):
        return [path]
    hits = sorted(glob.glob(os.path.join(path, "**", "*.jsonl"), recursive=True))
    return hits


def load_rows(paths: List[str]) -> Tuple[List[Dict[str, Any]], List[str]]:
    rows, errors = [], []
    for path in paths:
        with open(path) as fh:
            for lineno, line in enumerate(fh, 1):
                if not line.strip():
                    continue
                err = lint_line(line)
                if err is not None:
                    errors.append(f"{path}:{lineno}: {err}")
                    continue
                row = json.loads(line)
                schema_errs = validate_row(row)
                if schema_errs:
                    errors.append(f"{path}:{lineno}: {'; '.join(schema_errs)}")
                rows.append(row)
    return rows, errors


def _last(rows: List[Dict[str, Any]], kind: str) -> Dict[str, Any]:
    for row in reversed(rows):
        if row.get("kind") == kind:
            return row
    return {}


def _last_with(rows: List[Dict[str, Any]], kind: str, key: str) -> Dict[str, Any]:
    """Last row of ``kind`` that carries ``key`` — the final flush at close
    emits without per-loop gauges, so "last row" alone can hide them."""
    for row in reversed(rows):
        if row.get("kind") == kind and row.get(key) is not None:
            return row
    return {}


def _mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def _fleet_section(by_kind: Dict[str, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Fold route/scale/rollout rows into the fleet report: who got served
    (per-tenant accept/shed), how even the fleet ran (per-engine depth and
    version spread from the LAST route row's snapshot), what the autoscaler
    did, and how fast weight rollouts converged."""
    route = by_kind.get("route", [])
    scale = by_kind.get("scale", [])
    rollout = by_kind.get("rollout", [])
    tenants: Dict[str, Dict[str, int]] = {}
    shed_by_reason: Dict[str, int] = {}
    for row in route:
        for tenant, counts in (row.get("tenants") or {}).items():
            agg = tenants.setdefault(tenant, {"accepted": 0, "shed": 0})
            agg["accepted"] += int(counts.get("accepted", 0))
            agg["shed"] += int(counts.get("shed", 0))
        for reason, n in (row.get("shed_by_reason") or {}).items():
            shed_by_reason[reason] = shed_by_reason.get(reason, 0) + int(n)
    engines = {}
    for row in reversed(route):
        if row.get("engines"):
            engines = row["engines"]
            break
    versions = [e.get("version") for e in engines.values()
                if e.get("version") is not None]
    converged = [r for r in rollout if r.get("event") == "converged"]
    return {
        "accepted": sum(int(r.get("accepted", 0)) for r in route),
        "shed": sum(int(r.get("shed", 0)) for r in route),
        "rerouted": sum(int(r.get("rerouted", 0)) for r in route),
        "lost": sum(int(r.get("lost", 0)) for r in route),
        "cancelled": sum(int(r.get("cancelled", 0)) for r in route),
        "shed_by_reason": shed_by_reason,
        "tenants": tenants,
        "engines": engines,
        "version_spread": (max(versions) - min(versions)) if versions else None,
        "scale_out": sum(1 for r in scale if r.get("action") == "out"),
        "scale_in": sum(1 for r in scale if r.get("action") == "in"),
        "rollouts": sum(1 for r in rollout if r.get("event") == "publish"),
        "rollouts_refused": sum(1 for r in rollout
                                if r.get("event") == "refused_backward"),
        "rollout_convergence_s": (converged[-1].get("convergence_s")
                                  if converged else None),
    }


def _games_section(by_kind: Dict[str, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Fold multi-game rows (multitask/; docs/MULTITASK.md): the newest
    `games` row's per-game learn share / replay occupancy, the latest eval
    score per game (eval rows keyed by ``game``), and the newest suite
    human-normalized aggregates.  Empty dict for single-game runs."""
    games_rows = by_kind.get("games", [])
    eval_mt = by_kind.get("eval_mt", [])
    per_game_eval: Dict[str, Dict[str, Any]] = {}
    for row in by_kind.get("eval", []):
        if row.get("game"):
            per_game_eval[str(row["game"])] = row
    if not (games_rows or eval_mt or per_game_eval):
        return {}
    last = games_rows[-1] if games_rows else {}
    games: Dict[str, Dict[str, Any]] = {}
    for name, snap in (last.get("games") or {}).items():
        games[name] = dict(snap)
    for name, row in per_game_eval.items():
        entry = games.setdefault(name, {})
        entry.setdefault("score_mean", row.get("score_mean"))
        if row.get("human_normalized") is not None:
            entry.setdefault("human_normalized", row["human_normalized"])
    agg = eval_mt[-1] if eval_mt else last
    return {
        "n": len(games),
        "schedule": last.get("schedule"),
        "rows": len(games_rows),
        "evals": len(eval_mt),
        "hn_median": agg.get("hn_median"),
        "hn_mean": agg.get("hn_mean"),
        "games": games,
    }


def _league_section(by_kind: Dict[str, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Fold league rows (league/; docs/LEAGUE.md): the newest status row's
    per-member table (fitness, generation, exploit/explore counts, restarts,
    last copy source), exploit/adoption event totals, and whether the
    population ever collapsed.  Empty dict for league-less runs."""
    league = by_kind.get("league", [])
    if not league:
        return {}
    status = [r for r in league if r.get("event") == "status"]
    last = status[-1] if status else {}
    events: Dict[str, int] = {}
    for row in league:
        ev = str(row.get("event", "unknown"))
        events[ev] = events.get(ev, 0) + 1
    return {
        "rows": len(league),
        "events": events,
        "exploits": events.get("exploit", 0),
        "adoptions": events.get("adopt", 0),
        "adopt_refused": events.get("adopt_refused", 0),
        "skips": events.get("exploit_skipped", 0),
        "alive": last.get("alive"),
        "collapsed_ever": any(r.get("collapsed") for r in status),
        "members": last.get("members") or {},
    }


def _failover_section(
    by_kind: Dict[str, List[Dict[str, Any]]]
) -> Dict[str, Any]:
    """Fold learner-failover rows (parallel/failover.py;
    docs/RESILIENCE.md "learner failover"): takeover count and MTTR, the
    claim-vs-restore latency split the RUNBOOK triage keys on, claim races
    lost, and fenced stale publishes/write-backs by surface (a non-empty
    surface table means a ZOMBIE predecessor kept running after takeover
    and every one of its writes was refused).  Empty dict for runs without
    failover rows."""
    rows = by_kind.get("failover", [])
    if not rows:
        return {}
    events: Dict[str, int] = {}
    fenced_by_surface: Dict[str, int] = {}
    for row in rows:
        ev = str(row.get("event", "unknown"))
        events[ev] = events.get(ev, 0) + 1
        if ev == "fenced_stale":
            surface = str(row.get("surface", "unknown"))
            fenced_by_surface[surface] = fenced_by_surface.get(surface, 0) + 1
    takeovers = [r for r in rows if r.get("event") == "takeover"]
    restores = [r for r in rows if r.get("event") == "restore"]
    claims = [r for r in rows if r.get("event") == "claim"]
    last_takeover = takeovers[-1] if takeovers else {}
    last_restore = restores[-1] if restores else {}
    return {
        "rows": len(rows),
        "events": events,
        "takeovers": len(takeovers),
        "mttr_s": last_takeover.get("mttr_s"),
        "warm": last_takeover.get("warm"),
        "epoch": last_takeover.get("epoch"),
        "restore_s": last_restore.get("restore_s"),
        "claims_won": sum(1 for r in claims if r.get("won")),
        "claims_lost": sum(1 for r in claims if not r.get("won")),
        "fenced_stale": events.get("fenced_stale", 0),
        "fenced_by_surface": fenced_by_surface,
    }


def _net_section(by_kind: Dict[str, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Fold cross-host serving rows (serving/net/): per-peer transport
    health — newest rtt/bytes from the periodic stats rows, flap counts
    (disconnects/reconnects/probe timeouts) from the lifecycle events —
    plus the newest gossip freshness.  Empty dict for in-process runs."""
    net = by_kind.get("net", [])
    gossip = by_kind.get("gossip", [])
    if not (net or gossip):
        return {}
    peers: Dict[str, Dict[str, Any]] = {}
    flaps = 0
    for row in net:
        peer = str(row.get("peer", "?"))
        snap = peers.setdefault(peer, {
            "reconnects": 0, "disconnects": 0, "probe_timeouts": 0})
        event = row.get("event")
        if event == "stats":
            # newest stats row wins: these are lifetime counters/gauges
            snap["rtt_ms"] = row.get("rtt_ms")
            snap["bytes_sent"] = row.get("bytes_sent")
            snap["bytes_recv"] = row.get("bytes_recv")
            snap["connected"] = row.get("connected")
            snap["reconnects"] = int(row.get("reconnects", 0) or 0)
            snap["probe_timeouts"] = int(row.get("probe_timeouts", 0) or 0)
        elif event == "disconnect":
            snap["disconnects"] += 1
            flaps += 1
        elif event in ("reconnect", "probe_timeout", "bad_frame"):
            flaps += 1
    last_gossip = gossip[-1] if gossip else {}
    return {
        "rows": len(net),
        "flaps": flaps,
        "peers": peers,
        "gossip_rows": len(gossip),
        "gossip_peers": last_gossip.get("peers"),
        "gossip_fresh": last_gossip.get("fresh"),
        "gossip_stale": last_gossip.get("stale"),
    }


def _replaynet_section(
    by_kind: Dict[str, List[Dict[str, Any]]]
) -> Dict[str, Any]:
    """Fold cross-host replay rows (replay/net/): the newest plane stats
    row (peer counts, aggregate size, mean rtt, spool depth, acked/shed
    append totals, sample/write-back totals) plus lifecycle event counts —
    the RUNBOOK "learner is starving on remote replay" triage reads this
    section first.  Empty dict for in-process-replay runs."""
    rows = by_kind.get("replay_net", [])
    if not rows:
        return {}
    events: Dict[str, int] = {}
    for row in rows:
        ev = str(row.get("event", "unknown"))
        events[ev] = events.get(ev, 0) + 1
    stats = [r for r in rows if r.get("event") == "stats"]
    last = stats[-1] if stats else {}
    flaps = sum(events.get(e, 0) for e in (
        "disconnect", "reconnect", "probe_timeout", "bad_frame",
        "spool_shed", "peer_dead"))
    return {
        "rows": len(rows),
        "events": events,
        "flaps": flaps,
        "peers": last.get("peers"),
        "dead_peers": last.get("dead_peers"),
        "size": last.get("size"),
        "rtt_ms": last.get("rtt_ms"),
        "spool_depth": last.get("spool_depth"),
        "acked_rows": last.get("acked_rows"),
        "shed_ticks": last.get("shed_ticks"),
        "fenced_rows": last.get("fenced_rows"),
        "shed_lanes": last.get("shed_lanes"),
        "batches": last.get("batches"),
        "rows_sampled": last.get("rows_sampled"),
        "updates_sent": last.get("updates_sent"),
        "updates_dropped": last.get("updates_dropped"),
        "rerouted": last.get("rerouted"),
    }


def _obsnet_section(
    by_kind: Dict[str, List[Dict[str, Any]]]
) -> Dict[str, Any]:
    """Fold live-telemetry-plane rows (obs/net/): relay lifecycle/shed
    counts, the newest relay stats row, the newest collector fleet fold,
    and alert edge totals — the offline answer to "was the live view
    complete while this ran".  Empty dict when the plane was off."""
    rows = by_kind.get("obs_net", [])
    alerts = by_kind.get("alert", [])
    fleet = by_kind.get("fleet_health", [])
    if not rows and not alerts and not fleet:
        return {}
    events: Dict[str, int] = {}
    for row in rows:
        ev = str(row.get("event", "unknown"))
        events[ev] = events.get(ev, 0) + 1
    stats = [r for r in rows if r.get("event") == "stats"]
    last = stats[-1] if stats else {}
    last_fleet = fleet[-1] if fleet else {}
    firing = sum(1 for a in alerts if a.get("state") == "firing")
    resolved = sum(1 for a in alerts if a.get("state") == "resolved")
    worst = "ok"
    for r in fleet:
        s = r.get("status")
        if s == "failing" or (s == "degraded" and worst == "ok"):
            worst = s
    return {
        "rows": len(rows),
        "events": events,
        "flaps": sum(events.get(e, 0) for e in
                     ("disconnect", "reconnect", "spool_shed")),
        "sent_rows": last.get("sent_rows"),
        "shed_rows": last.get("shed_rows"),
        "spool_depth": last.get("spool_depth"),
        "reconnects": last.get("reconnects"),
        "alerts_firing_edges": firing,
        "alerts_resolved_edges": resolved,
        "fleet_rows": len(fleet),
        "fleet_last_status": last_fleet.get("status"),
        "fleet_worst_status": worst if fleet else None,
        "fleet_hosts": last_fleet.get("hosts_total"),
        "fleet_offenders": last_fleet.get("offenders", []),
    }


def _quant_section(by_kind: Dict[str, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Fold quant/publish/quant_fallback rows: is the quantized path live,
    what did the gate last measure, and how many publish bytes the delta/
    int8 path saved vs shipping fp32 full (docs/PERFORMANCE.md "quant")."""
    quant = by_kind.get("quant", [])
    fallbacks = by_kind.get("quant_fallback", [])
    publish = by_kind.get("publish", [])
    # the CURRENT state is whichever gate outcome is newest — 'quant' rows
    # are emitted only on PASS, so after a run of fallbacks the last quant
    # row is stale and reporting it as "active" would read the opposite of
    # the truth exactly when the RUNBOOK triage needs it
    last_gate = quant[-1] if quant else {}
    last_fb = fallbacks[-1] if fallbacks else {}
    # ts ties (same-millisecond rows) break toward the FALLBACK: reporting
    # not-active errs toward operator attention, never away from it
    if last_fb and last_fb.get("ts", 0) >= last_gate.get("ts", -1):
        newest = last_fb
    else:
        newest = last_gate
    bytes_total = sum(int(r.get("bytes") or 0) for r in publish)
    bytes_fp32 = sum(int(r.get("bytes_fp32") or 0) for r in publish)
    return {
        "gates": len(quant),
        "fallbacks": len(fallbacks),
        "last_agreement": newest.get("agreement"),
        "last_mode": newest.get("mode"),
        "active": (bool(newest.get("active", False))
                   if (quant or fallbacks) else None),
        "publishes": len(publish),
        "publish_bytes_total": bytes_total,
        "publish_bytes_fp32": bytes_fp32,
        "bytes_saved_frac": (round(1.0 - bytes_total / bytes_fp32, 4)
                             if bytes_fp32 else None),
    }


def aggregate(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    by_kind: Dict[str, List[Dict[str, Any]]] = {}
    for row in rows:
        by_kind.setdefault(str(row.get("kind")), []).append(row)

    learn = by_kind.get("learn", [])
    timing = by_kind.get("timing", [])
    serve = by_kind.get("serve", [])
    health = by_kind.get("health", [])
    faults = by_kind.get("fault", [])

    last_learn = _last(rows, "learn")
    last_timing = _last(rows, "timing")
    last_health = _last(rows, "health")
    last_eval = _last(rows, "eval")

    fault_counts: Dict[str, int] = {}
    for row in faults:
        ev = str(row.get("event", "unknown"))
        fault_counts[ev] = fault_counts.get(ev, 0) + 1

    serve_requests = sum(int(r.get("requests", 0)) for r in serve)
    serve_batches = sum(int(r.get("batches", 0)) for r in serve)
    shed_total = sum(int(r.get("shed", 0)) for r in serve)

    statuses = [str(r.get("status", "ok")) for r in health]
    order = {"ok": 0, "degraded": 1, "failing": 2}
    worst = max(statuses, key=lambda s: order.get(s, 0)) if statuses else None

    # the final flush at close() resets span windows right after the last
    # periodic row, so the very last timing row's spans can be empty — show
    # the last window that actually observed spans
    span_stats = last_timing.get("spans") or {}
    if not any(s.get("count") for s in span_stats.values()):
        for row in reversed(timing):
            spans = row.get("spans") or {}
            if any(s.get("count") for s in spans.values()):
                span_stats = spans
                break
    report = {
        "rows": len(rows),
        "row_kinds": {k: len(v) for k, v in sorted(by_kind.items())},
        "roles": {
            "actor": {
                "frames": int(last_learn.get("frames", 0)),
                "fps_last": float(last_learn.get("fps") or 0.0),
                "fps_mean": round(
                    _mean([float(r.get("fps") or 0.0)
                           for r in learn if r.get("fps")]), 2),
            },
            "learner": {
                "steps": int(last_learn.get("step", 0)
                             or last_timing.get("step", 0)),
                "steps_per_sec": float(
                    last_timing.get("learn_steps_per_sec", 0.0) or 0.0),
                "step_p50_s": last_timing.get("learn_p50_s"),
                "step_p99_s": last_timing.get("learn_p99_s"),
            },
            "replay": {
                "size": _last_with(rows, "health", "replay_size")
                .get("replay_size"),
                "occupancy": _last_with(rows, "health", "replay_occupancy")
                .get("replay_occupancy"),
            },
            "serve": {
                "requests": serve_requests,
                "batches": serve_batches,
                "shed": shed_total,
                "batch_occupancy_mean": round(
                    _mean([float(r.get("batch_occupancy_mean", 0.0))
                           for r in serve if r.get("batches")]), 3),
                "pad_fraction_mean": round(
                    _mean([float(r.get("pad_fraction", 0.0))
                           for r in serve if r.get("batches")]), 4),
                "latency_p99_ms": _last(rows, "serve").get("latency_p99_ms"),
            },
        },
        "compiles": last_timing.get("compiles"),
        "spans": span_stats,
        "faults": fault_counts,
        # elasticity (docs/RESILIENCE.md "heal"): the detect->heal story in
        # counts — deaths vs revivals/readmits, fence episodes, respawns,
        # permanent evictions
        "elastic": {
            "host_dead": fault_counts.get("host_dead", 0),
            "host_alive": len(by_kind.get("host_alive", [])),
            "shard_readmits": len(by_kind.get("shard_readmit", [])),
            "fence_episodes": sum(
                1 for r in by_kind.get("actor_fenced", [])
                if r.get("action") != "resume"
            ),
            "respawns": fault_counts.get("actor_respawn", 0),
            "evictions": fault_counts.get("actor_evicted", 0),
        },
        # learner pipeline (docs/PERFORMANCE.md): write-back ring depth/lag
        # plus prefetch starvation signals — lag == configured depth with an
        # empty-wait count near zero means the hot path is device-bound (the
        # goal); a climbing empty-wait count means the SAMPLER is the
        # bottleneck and deeper write-back will not help.  With device
        # sampling on, the sample_ahead_* / mirror gauges split that further:
        # empty waits with sample_ahead_queue_depth pinned at 0 means the
        # PUSHER can't keep up — a growing stale-indices counter or a fat
        # mirror_reconcile_s points at the frontier (sampler-starved), an
        # otherwise idle frontier points at the host frame gather
        # (gather-starved).
        "pipeline": {
            "writeback_inflight": _last_with(rows, "health", "writeback_inflight")
            .get("writeback_inflight"),
            "writeback_lag_steps": _last_with(rows, "health", "writeback_lag_steps")
            .get("writeback_lag_steps"),
            "prefetch_queue_depth": _last_with(rows, "health", "prefetch_queue_depth")
            .get("prefetch_queue_depth"),
            "prefetch_empty_waits": _last_with(rows, "health", "prefetch_empty_waits")
            .get("prefetch_empty_waits"),
            "sample_ahead_queue_depth": _last_with(
                rows, "health", "sample_ahead_queue_depth")
            .get("sample_ahead_queue_depth"),
            "sample_ahead_stale_indices": _last_with(
                rows, "health", "sample_ahead_stale_indices")
            .get("sample_ahead_stale_indices"),
            "mirror_reconcile_s": _last_with(rows, "health", "mirror_reconcile_s")
            .get("mirror_reconcile_s"),
            # replay reuse (docs/PERFORMANCE.md "Replay reuse"): present
            # only when the run ran cfg.replay_ratio > 1 — K and the newest
            # retired sample's mean reuse-pass clip fraction (a climbing
            # fraction is the K-too-high early warning)
            "replay_ratio": _last_with(rows, "health", "replay_ratio")
            .get("replay_ratio"),
            "reuse_clip_frac": _last_with(rows, "health", "reuse_clip_frac")
            .get("reuse_clip_frac"),
        },
        # critical-path attribution (obs/pipeline_trace.py): which stage
        # owns the largest exclusive share of traced end-to-end latency —
        # sampler-starved vs device-bound vs publish-bound in one line.
        # None when the run was not traced (trace_sample_every = 0).
        "critical_path": critical_path(rows),
        # lag attribution: the newest `lag` row's percentiles (sample age at
        # learn time, ring retirement, publish->adopt per consumer)
        "lag": {k: v for k, v in _last(rows, "lag").items()
                if k not in ("t", "ts", "host", "run", "kind", "schema",
                             "step")},
        # serving fleet (docs/SERVING.md "fleet"): per-tenant accept/shed,
        # per-engine depth/version spread, scale events, rollout convergence
        "fleet": _fleet_section(by_kind),
        # cross-host serving plane (serving/net/): per-peer transport
        # rtt/reconnects/bytes + router-gossip freshness
        "net": _net_section(by_kind),
        # cross-host replay plane (replay/net/): newest plane stats +
        # lifecycle flap counts (the remote-replay starvation triage input)
        "replaynet": _replaynet_section(by_kind),
        # live telemetry plane (obs/net/): relay shed/reconnect counts,
        # alert edges, the collector's newest fleet fold + named offenders
        "obsnet": _obsnet_section(by_kind),
        # quantized inference + compressed distribution: gate agreement,
        # fallback count, publish bytes saved vs fp32-full
        "quant": _quant_section(by_kind),
        # multi-game runs (multitask/): per-game learn share / replay
        # occupancy / latest eval + suite human-normalized aggregates
        "games": _games_section(by_kind),
        # league runs (league/): per-member fitness/generation/exploits +
        # event totals (the PBT story in counts)
        "league": _league_section(by_kind),
        # learner failover (parallel/failover.py): takeovers + MTTR, the
        # claim/restore latency split, fenced zombie writes by surface
        "failover": _failover_section(by_kind),
        "shed_total": shed_total,
        "final_eval": {
            k: v for k, v in last_eval.items()
            if k.startswith("score") or k in ("episodes", "human_normalized")
        },
        "health": {
            "last_status": last_health.get("status"),
            "worst_status": worst,
            "rows": len(health),
            "hosts_dead": last_health.get("hosts_dead", []),
            "hosts_evicted": last_health.get("hosts_evicted", []),
            # consumers whose publish->adopt p99 breached the propagation
            # budget in the newest window (obs/pipeline_trace.py)
            "lag_consumers": last_health.get("lag_consumers", []),
        },
    }
    return report


def render(report: Dict[str, Any]) -> str:
    roles = report["roles"]
    lines = [
        "== obs_report ==",
        f"rows: {report['rows']}  kinds: {report['row_kinds']}",
        (f"actor:   frames={roles['actor']['frames']}  "
         f"fps last={roles['actor']['fps_last']:.1f} "
         f"mean={roles['actor']['fps_mean']:.1f}"),
        (f"learner: steps={roles['learner']['steps']}  "
         f"steps/s={roles['learner']['steps_per_sec']:.2f}  "
         f"step p50={roles['learner']['step_p50_s']}s "
         f"p99={roles['learner']['step_p99_s']}s"),
        (f"replay:  size={roles['replay']['size']}  "
         f"occupancy={roles['replay']['occupancy']}"),
        (f"serve:   requests={roles['serve']['requests']}  "
         f"batches={roles['serve']['batches']}  "
         f"shed={roles['serve']['shed']}  "
         f"batch_occupancy={roles['serve']['batch_occupancy_mean']}  "
         f"pad_tax={roles['serve']['pad_fraction_mean']}  "
         f"latency_p99_ms={roles['serve']['latency_p99_ms']}"),
        f"compiles: {report['compiles']}",
    ]
    for name, snap in sorted((report["spans"] or {}).items()):
        lines.append(f"span {name}: {snap}")
    lines.append(f"faults: {report['faults'] or 'none'}")
    p = report["pipeline"]
    if any(v is not None for v in p.values()):
        line = (
            f"pipeline: writeback_inflight={p['writeback_inflight']} "
            f"lag={p['writeback_lag_steps']} "
            f"prefetch_depth={p['prefetch_queue_depth']} "
            f"empty_waits={p['prefetch_empty_waits']}"
        )
        if p.get("mirror_reconcile_s") is not None:  # device sampling on
            line += (
                f" sample_ahead_depth={p['sample_ahead_queue_depth']} "
                f"stale_indices={p['sample_ahead_stale_indices']} "
                f"mirror_reconcile_s={p['mirror_reconcile_s']}"
            )
        if p.get("replay_ratio") is not None:  # replay reuse on (K > 1)
            line += (
                f" replay_ratio={p['replay_ratio']} "
                f"reuse_clip_frac={p['reuse_clip_frac']}"
            )
        lines.append(line)
    cp = report.get("critical_path")
    if cp:
        lines.append(f"critical_path: {format_critical_path(cp)}")
        for stage, snap in sorted(cp["stages"].items(),
                                  key=lambda kv: -kv[1]["share"]):
            lines.append(f"  stage {stage}: {round(snap['share'] * 100)}% "
                         f"({snap['ms']}ms exclusive)")
    lag = report.get("lag") or {}
    if lag:
        parts = []
        for key in ("sample_age_s", "sample_age_ticks", "ring_retire_ms",
                    "router_dispatch_ms", "batch_slot_wait_ms"):
            if key in lag:
                parts.append(f"{key} p50={lag[key].get('p50')} "
                             f"p99={lag[key].get('p99')}")
        if parts:
            lines.append("lag:     " + "  ".join(parts))
        for consumer, snap in sorted(
                (lag.get("publish_adopt_ms_by_consumer") or {}).items()):
            lines.append(f"  publish->adopt {consumer}: "
                         f"p50={snap.get('p50')}ms p99={snap.get('p99')}ms")
        if lag.get("publish_adopt_budget_ms") is not None:
            lines.append(f"  publish->adopt budget: "
                         f"{lag['publish_adopt_budget_ms']}ms "
                         "(max_weight_lag x publish cadence)")
    f = report["fleet"]
    if f["accepted"] or f["shed"] or f["rollouts"] or f["engines"]:
        lines.append(
            f"fleet:   accepted={f['accepted']} shed={f['shed']} "
            f"rerouted={f['rerouted']} lost={f['lost']} "
            f"cancelled={f['cancelled']} "
            f"scale_out={f['scale_out']} scale_in={f['scale_in']} "
            f"rollouts={f['rollouts']} "
            f"(refused={f['rollouts_refused']}, "
            f"convergence_s={f['rollout_convergence_s']}) "
            f"version_spread={f['version_spread']}"
        )
        for tenant, counts in sorted(f["tenants"].items()):
            lines.append(f"  tenant {tenant}: accepted={counts['accepted']} "
                         f"shed={counts['shed']}")
        for eid, snap in sorted(f["engines"].items()):
            lines.append(f"  engine {eid}: depth={snap.get('depth')} "
                         f"version={snap.get('version')} "
                         f"alive={snap.get('alive')}")
    n = report.get("net") or {}
    if n:
        lines.append(
            f"net:     rows={n['rows']} flaps={n['flaps']} "
            f"gossip_rows={n['gossip_rows']} "
            f"gossip_fresh={n['gossip_fresh']}/{n['gossip_peers']} "
            f"(stale={n['gossip_stale']})"
        )
        for peer, snap in sorted(n["peers"].items()):
            lines.append(
                f"  peer {peer}: rtt_ms={snap.get('rtt_ms')} "
                f"reconnects={snap.get('reconnects')} "
                f"probe_timeouts={snap.get('probe_timeouts')} "
                f"bytes_sent={snap.get('bytes_sent')} "
                f"bytes_recv={snap.get('bytes_recv')}"
                + ("" if snap.get("connected", True) else " DISCONNECTED")
            )
    rn = report.get("replaynet") or {}
    if rn:
        lines.append(
            f"replaynet: peers={rn['peers']} (dead={rn['dead_peers']}) "
            f"size={rn['size']} rtt_ms={rn['rtt_ms']} flaps={rn['flaps']} "
            f"spool_depth={rn['spool_depth']} acked_rows={rn['acked_rows']} "
            f"shed_ticks={rn['shed_ticks']} fenced_rows={rn['fenced_rows']} "
            f"batches={rn['batches']} updates_sent={rn['updates_sent']} "
            f"(dropped={rn['updates_dropped']}, rerouted={rn['rerouted']})"
        )
        if rn.get("events"):
            lines.append(f"  replaynet events: {rn['events']}")
    on = report.get("obsnet") or {}
    if on:
        lines.append(
            f"obsnet:  rows={on['rows']} flaps={on['flaps']} "
            f"sent={on['sent_rows']} shed={on['shed_rows']} "
            f"reconnects={on['reconnects']} "
            f"alert_edges={on['alerts_firing_edges']}+"
            f"{on['alerts_resolved_edges']} "
            f"fleet last={on['fleet_last_status']} "
            f"worst={on['fleet_worst_status']} "
            f"hosts={on['fleet_hosts']}"
        )
        if on.get("fleet_offenders"):
            lines.append(f"  offenders: {on['fleet_offenders']}")
    q = report["quant"]
    if q["gates"] or q["fallbacks"] or q["publishes"]:
        lines.append(
            f"quant:   gates={q['gates']} fallbacks={q['fallbacks']} "
            f"active={q['active']} agreement={q['last_agreement']} "
            f"mode={q['last_mode']} publishes={q['publishes']} "
            f"bytes={q['publish_bytes_total']} "
            f"(saved_frac={q['bytes_saved_frac']})"
        )
    mg = report.get("games") or {}
    if mg:
        lines.append(
            f"games:   n={mg['n']} schedule={mg['schedule']} "
            f"rows={mg['rows']} evals={mg['evals']} "
            f"hn_median={mg['hn_median']} hn_mean={mg['hn_mean']}"
        )
        for name, snap in sorted(mg["games"].items()):
            lines.append(
                f"  game {name}: learn_share={snap.get('learn_share')} "
                f"occupancy={snap.get('replay_occupancy')} "
                f"eval={snap.get('score_mean')} "
                f"hn={snap.get('human_normalized')}"
                + (" DEAD" if snap.get("dead") else "")
            )
    lg = report.get("league") or {}
    if lg:
        lines.append(
            f"league:  members={len(lg['members'])} alive={lg['alive']} "
            f"exploits={lg['exploits']} adoptions={lg['adoptions']} "
            f"refused={lg['adopt_refused']} skips={lg['skips']}"
            + (" COLLAPSED" if lg.get("collapsed_ever") else "")
        )
        for mid, snap in sorted(lg["members"].items(),
                                key=lambda kv: int(kv[0])):
            fit = snap.get("fitness")
            lines.append(
                f"  member m{mid}: fitness="
                f"{round(fit, 4) if fit is not None else None} "
                f"gen={snap.get('generation')} "
                f"exploits={snap.get('exploits')} "
                f"restarts={snap.get('restarts')} "
                f"state={snap.get('state')} "
                f"last_copy_source={snap.get('last_copy_source')} "
                f"lr={snap.get('lr')} n_step={snap.get('n_step')}"
            )
    fo = report.get("failover") or {}
    if fo:
        lines.append(
            f"failover: takeovers={fo['takeovers']} mttr_s={fo['mttr_s']} "
            f"restore_s={fo['restore_s']} warm={fo['warm']} "
            f"epoch={fo['epoch']} claims_won={fo['claims_won']} "
            f"claims_lost={fo['claims_lost']} "
            f"fenced_stale={fo['fenced_stale']}"
        )
        for surface, n in sorted(fo["fenced_by_surface"].items()):
            lines.append(f"  fenced surface {surface}: {n} refused")
    e = report["elastic"]
    if any(e.values()):
        lines.append(
            f"elastic: host_dead={e['host_dead']} host_alive={e['host_alive']} "
            f"readmits={e['shard_readmits']} fences={e['fence_episodes']} "
            f"respawns={e['respawns']} evictions={e['evictions']}"
        )
    lines.append(f"final_eval: {report['final_eval'] or 'none'}")
    h = report["health"]
    lines.append(
        f"health: last={h['last_status']} worst={h['worst_status']} "
        f"rows={h['rows']} hosts_dead={h['hosts_dead']} "
        f"hosts_evicted={h['hosts_evicted']}"
        + (f" lag_consumers={h['lag_consumers']}"
           if h.get("lag_consumers") else "")
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run dir (or one .jsonl file)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    args = ap.parse_args(argv)

    paths = find_jsonl(args.path)
    if not paths:
        print(f"obs_report: no .jsonl under {args.path}", file=sys.stderr)
        return 1
    rows, errors = load_rows(paths)
    if not rows:
        print(f"obs_report: {len(paths)} file(s) but zero rows", file=sys.stderr)
        return 1
    report = aggregate(rows)
    report["lint_errors"] = len(errors)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(report))
    if errors:
        for err in errors[:20]:
            print(f"LINT {err}", file=sys.stderr)
        if len(errors) > 20:
            print(f"LINT ... {len(errors) - 20} more", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
