#!/usr/bin/env python
"""Load-generator bench for the policy server (serving/): N synthetic client
threads drive `PolicyServer.act` as fast as the server completes them, with a
weight hot-swap fired mid-run, and the result is printed as JSON rows in the
bench.py idiom (one object per line, flushed immediately, LAST line is the
headline requests/sec).

What is measured: end-to-end serving throughput and latency through the real
stack — bounded queue, deadline coalescing, bucket padding, lane-sharded
jitted inference, atomic param swap — not a model microbenchmark.  Batch
occupancy tells whether micro-batching actually coalesced (the acceptance
gate is mean occupancy > 4 at 64 clients); shed_total must be 0 when clients
<= queue bound (blocking clients can never overrun it).

CPU smoke shape (default): 44x44x2 frames, hidden 64, IQN taus 8/8/4 — the
same small-but-real network the parallel tests use, so the numbers track the
serving machinery, not conv throughput.

``--fleet-soak`` switches to the heavy-traffic fleet scenario
(serving/fleet/, docs/SERVING.md "fleet"): an in-process router + N-engine
fleet under bursty OPEN-LOOP arrivals from multiple QoS tenants, a cohort of
deliberately slow clients that abandon (cancel) their requests, one engine
killed cold mid-load (lease expiry -> re-route; the supervisor respawns it
with backoff), and two fleet-wide weight rollouts — one of which is a
deliberate BACKWARD publish that must be refused.  Gates (enforced, exit 1):
zero lost accepted requests, every accepted request accounted for, p99 and
shed-rate bounds, rollout convergence with no version rollback.  The result
is one ``fleet_soak`` row in the PR-6 budgeted-row convention (no ``status``
key when healthy; ``"status": "error"/"gate_failed"`` otherwise), plus a
lint-clean run dir of route/scale/rollout/serve JSONL.

``--fleet-soak --net`` runs the SAME scenario with a real loopback socket on
every hop (serving/net/): engines behind `TransportServer`s, the router
dispatching through `RemoteTransport`s, rollouts shipped as int8-delta
packets over the wire with bit-exact adoption gated per engine.  Emits one
``net_soak`` row (aggregate rps, p99, rollout bytes over the wire vs fp32)
for the BENCH_r*.json trajectory.

``--quant`` runs the fp32-vs-int8 serving comparison (`make quant-smoke`):
the same fixed load through a fp32 engine and a quantized one
(``serve_quantize="int8"``, agreement-gated), one ``quant_serve`` row with
both modes' req/s + p99 and the gate outcome — the gate MUST activate the
quantized path and both modes must complete every request (exit 1
otherwise).

Usage:
    JAX_PLATFORMS=cpu python scripts/bench_serve.py --clients 64 --requests 2000
    JAX_PLATFORMS=cpu python scripts/bench_serve.py --fleet-soak --engines 2
    JAX_PLATFORMS=cpu python scripts/bench_serve.py --quant --clients 16
"""

import argparse
import json
import os
import sys
import threading
import time

# The sandbox's sitecustomize registers the remote-TPU PJRT plugin whenever
# PALLAS_AXON_POOL_IPS is set, and a registered plugin blocks `import jax`
# even under JAX_PLATFORMS=cpu (see conftest.py).  This bench is a CPU smoke
# tool unless the caller explicitly pins a device platform.
if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def row(**fields):
    print(json.dumps(fields), flush=True)


class _InProcFleet:
    """The soak's in-process fleet: N PolicyServers wrapped as FleetEngines
    (lease self-registration in a shared heartbeat dir), one EngineRegistry +
    FrontRouter over them, a RoleSupervisor-backed Autoscaler, and a
    FleetRollout — the full serving/fleet composition on one host.

    ``net=True`` (the ``--net`` soak variant) keeps the same topology but
    puts a REAL loopback socket on every hop: each engine serves behind a
    `TransportServer`, the router dispatches through `RemoteTransport`s,
    and the rollout ships int8-delta packets to `RemoteEngine` proxies —
    the full serving/net wire path under the same bursty load and kill."""

    def __init__(self, cfg, num_actions, params, out_dir, net=False):
        import jax

        from rainbow_iqn_apex_tpu.obs.registry import MetricRegistry
        from rainbow_iqn_apex_tpu.parallel.elastic import RoleSupervisor
        from rainbow_iqn_apex_tpu.serving import PolicyServer
        from rainbow_iqn_apex_tpu.serving.fleet import (
            Autoscaler,
            EngineRegistry,
            FleetEngine,
            FleetRollout,
            FrontRouter,
            ScalePolicy,
        )
        from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

        self.cfg = cfg
        self.num_actions = num_actions
        self.params = params
        self.out_dir = out_dir
        self.net = bool(net)
        self._jax = jax
        self._PolicyServer = PolicyServer
        self._FleetEngine = FleetEngine
        self.logger = MetricsLogger(
            os.path.join(out_dir, "metrics.jsonl"), run_id=cfg.run_id,
            echo=False)
        self.obs = MetricRegistry()
        self.hb_dir = os.path.join(out_dir, "heartbeats")
        self.registry = EngineRegistry(
            self.hb_dir, lease_timeout_s=cfg.fleet_lease_timeout_s,
            logger=self.logger, obs_registry=self.obs,
            probe_timeout_s=cfg.serve_net_probe_timeout_s,
            probe_interval_s=cfg.serve_net_probe_interval_s,
            net_stats_interval_s=2.0)
        # --net ships every rollout as int8-delta packets over the wire —
        # the QuaRL byte win is only real once weights actually cross one
        self.rollout = FleetRollout(
            logger=self.logger, obs_registry=self.obs,
            compression="int8_delta" if self.net else "off",
            base_interval=cfg.publish_base_interval)
        self.router = FrontRouter.from_config(
            cfg, self.registry, target_version_fn=self.rollout.version,
            logger=self.logger, obs_registry=self.obs)
        self.router.metrics_interval_s = 1.0
        self.supervisor = RoleSupervisor.from_config(
            cfg, metrics=self.logger, registry=self.obs)
        self.autoscaler = Autoscaler(
            ScalePolicy.from_config(cfg),
            spawn_engine=self.spawn_engine,
            stop_engine=self.stop_engine,
            load_fn=self.load,
            supervisor=self.supervisor,
            logger=self.logger, obs_registry=self.obs)
        self.engines = {}
        self.tservers = {}
        self.transports = {}

    def spawn_engine(self, engine_id, epoch):
        """Boot one engine (fresh PolicyServer + lease at ``epoch``), attach
        it to the registry and catch it up to the rollout target.  Also the
        supervisor's respawn path after a kill."""
        server = self._PolicyServer(
            self.cfg, self.num_actions, self.params,
            devices=self._jax.devices()[:1],
            metrics_path=os.path.join(self.out_dir, f"engine{engine_id}.jsonl"),
        )
        engine = self._FleetEngine(
            server, engine_id, self.hb_dir,
            interval_s=self.cfg.fleet_lease_interval_s, epoch=epoch)
        if self.net:
            from rainbow_iqn_apex_tpu.serving.net import (
                RemoteEngine,
                RemoteTransport,
                TransportServer,
            )

            # the config seam is the on-switch: serve_net_host set by --net
            ts = TransportServer.from_config(self.cfg, engine,
                                             logger=self.logger)
            assert ts is not None, "--net requires serve_net_host"
            ts.start()
            engine.start(warmup=True)
            old = self.transports.get(engine_id)
            if old is not None:  # respawn after a kill: retire the corpse's
                old.close()      # client before attaching the new one
            transport = RemoteTransport(
                "127.0.0.1", ts.port, engine_id=engine_id,
                probe_timeout_s=self.cfg.serve_net_probe_timeout_s,
                logger=self.logger, obs_registry=self.obs)
            self.tservers[engine_id] = ts
            self.transports[engine_id] = transport
            self.engines[engine_id] = engine
            self.registry.attach(engine_id, transport)
            self.rollout.track(RemoteEngine(engine_id, transport))
        else:
            engine.start(warmup=True)
            self.engines[engine_id] = engine
            self.registry.attach(engine_id, engine.transport)
            self.rollout.track(engine)
        self.rollout.sync()
        return engine.proc()

    def stop_engine(self, engine_id):
        engine = self.engines.pop(engine_id, None)
        if engine is not None:
            self.rollout.untrack(engine_id)
            self.registry.detach(engine_id)
            engine.stop()
        ts = self.tservers.pop(engine_id, None)
        if ts is not None:
            ts.stop()
        transport = self.transports.pop(engine_id, None)
        if transport is not None:
            transport.close()

    def kill_engine(self, engine_id):
        """The mid-soak SIGKILL analog: heartbeats stop cold, queued
        requests fail NOW (the router re-routes them), the lease expires on
        the monitor's clock and the supervisor respawns with backoff.  In
        --net mode the transport listener drops FIRST — clients see the
        connection die exactly like a host death, before any engine-side
        cleanup could leak a polite goodbye."""
        ts = self.tservers.pop(engine_id, None)
        if ts is not None:
            ts.stop()
        engine = self.engines.get(engine_id)
        if engine is not None:
            engine.kill()

    def load(self):
        return {
            "engines": len(self.registry.routable()),
            "depth_frac": self.router.mean_depth_fraction(
                self.cfg.serve_queue_bound),
            "p99_ms": self.router.p99_ms(),
        }

    def start(self, n_engines):
        for i in range(n_engines):
            proc = self.spawn_engine(i, 0)
            self.autoscaler.adopt_engine(i, proc=proc)
        self.router.start()

    def stop(self):
        self.router.stop()
        self.supervisor.stop_all()
        for engine_id in list(self.engines):
            self.stop_engine(engine_id)
        self.logger.close()


def quant_bench(args) -> int:
    """``--quant``: fp32 vs int8 serving through the REAL stack at fixed
    load (same clients/requests/buckets), one ``quant_serve`` row with both
    modes' req/s and p99 plus the gate outcome.  Gates (exit 1): the int8
    engine's agreement gate must ACTIVATE the quantized path (this is the
    one real-engine int8 serve `make quant-smoke` requires), and both modes
    must complete every request.

    Honest-numbers note: on the CPU backend weight-only int8 adds an
    in-graph dequantize to every dispatch, so ``speedup_vs_fp32`` near (or
    under) 1.0 here is expected — the capacity win is an accelerator
    story (HBM bandwidth + smaller broadcasts); what this smoke proves is
    the gate, the serving correctness, and the row/metrics surface."""
    import numpy as np

    import jax

    from rainbow_iqn_apex_tpu.config import Config
    from rainbow_iqn_apex_tpu.ops.learn import init_train_state
    from rainbow_iqn_apex_tpu.serving import PolicyServer

    out_dir = (args.out if args.out != "results/serve_bench"
               else "results/quant_bench")
    os.makedirs(out_dir, exist_ok=True)

    def run_mode(quant_mode, params):
        cfg = Config(
            compute_dtype="float32",
            frame_height=44, frame_width=44, history_length=2,
            hidden_size=64, num_cosines=16,
            num_tau_samples=8, num_tau_prime_samples=8,
            num_quantile_samples=4,
            serve_batch_buckets=args.buckets,
            serve_deadline_ms=args.deadline_ms,
            serve_queue_bound=args.queue_bound,
            serve_mode=args.mode,
            serve_metrics_interval_s=1.0,
            serve_quantize=quant_mode,
            quant_agreement_min=args.agreement_min,
            run_id=f"quant_bench_{quant_mode}",
            seed=args.seed,
        )
        server = PolicyServer(
            cfg, args.num_actions, params,
            metrics_path=os.path.join(out_dir, f"serve_{quant_mode}.jsonl"),
        )
        server.start()
        rng = np.random.default_rng(args.seed)
        obs_pool = rng.integers(0, 255, (64, 44, 44, 2), dtype=np.uint8)
        issued = threading.Semaphore(args.requests)
        done = [0]
        lock = threading.Lock()
        errors = []

        def client(idx):
            while issued.acquire(blocking=False):
                try:
                    server.act(obs_pool[idx % len(obs_pool)], timeout=120)
                except Exception as e:  # noqa: BLE001
                    errors.append(f"{type(e).__name__}: {e}")
                    return
                with lock:
                    done[0] += 1

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(args.clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        quant_state = server.engine.quant_state()
        stats = server.stop()
        return {
            "rps": done[0] / max(wall, 1e-9),
            "p99_ms": stats.get("latency_p99_ms"),
            "completed": done[0],
            "errors": len(errors),
            "quant_active": quant_state["quant_active"],
            "quant_agreement": quant_state["quant_agreement"],
            "quant_fallbacks": quant_state["quant_fallbacks"],
        }

    state = init_train_state(
        Config(compute_dtype="float32", frame_height=44, frame_width=44,
               history_length=2, hidden_size=64, num_cosines=16,
               num_tau_samples=8, num_tau_prime_samples=8,
               num_quantile_samples=4),
        args.num_actions, jax.random.PRNGKey(0))
    row(event="quant_bench_start", clients=args.clients,
        requests=args.requests, out=out_dir)
    fp32 = run_mode("off", state.params)
    row(event="quant_bench_fp32_done", **fp32)
    int8 = run_mode("int8", state.params)
    row(event="quant_bench_int8_done", **int8)

    gates = {
        "int8_gate_activated": bool(int8["quant_active"]),
        "fp32_completed": fp32["completed"] == args.requests,
        "int8_completed": int8["completed"] == args.requests,
        "no_errors": fp32["errors"] == 0 and int8["errors"] == 0,
    }
    result = {
        "path": "quant_serve",
        "metric": "quant_serve_requests_per_sec",
        "value": round(int8["rps"], 1),
        "unit": "req/s (int8 engine; fp32 row alongside)",
        "rps_fp32": round(fp32["rps"], 1),
        "rps_int8": round(int8["rps"], 1),
        "speedup_vs_fp32": round(int8["rps"] / max(fp32["rps"], 1e-9), 3),
        "p99_fp32_ms": fp32["p99_ms"],
        "p99_int8_ms": int8["p99_ms"],
        "agreement": int8["quant_agreement"],
        "quant_active": int8["quant_active"],
        "quant_fallbacks": int8["quant_fallbacks"],
        "requests_per_mode": args.requests,
        "gates": gates,
    }
    if not all(gates.values()):
        result["status"] = "gate_failed"
        row(**result)
        return 1
    row(**result)
    return 0


def fleet_soak(args) -> int:
    import numpy as np

    import jax

    from rainbow_iqn_apex_tpu.config import Config
    from rainbow_iqn_apex_tpu.ops.learn import init_train_state
    from rainbow_iqn_apex_tpu.serving import ServerOverloaded

    out_dir = (args.out if args.out != "results/serve_bench"
               else ("results/net_soak" if args.net
                     else "results/fleet_soak"))
    os.makedirs(out_dir, exist_ok=True)
    cfg = Config(
        compute_dtype="float32",
        frame_height=44, frame_width=44, history_length=2,
        hidden_size=64, num_cosines=16,
        num_tau_samples=8, num_tau_prime_samples=8, num_quantile_samples=4,
        serve_batch_buckets=args.buckets,
        serve_deadline_ms=args.deadline_ms,
        serve_queue_bound=64,  # small per-engine bound: the soak WANTS
        # backpressure visible at the router, not hidden in deep queues
        serve_mode=args.mode,
        serve_metrics_interval_s=1.0,
        fleet_min_engines=args.engines,
        fleet_max_engines=args.max_engines,
        fleet_max_inflight=256,
        fleet_tenant_rate=args.rate,  # one tenant alone cannot flood the
        fleet_tenant_burst=64,        # fleet past the aggregate target rate
        fleet_lease_interval_s=0.25,
        fleet_lease_timeout_s=1.5,
        fleet_scale_patience=3,
        fleet_scale_cooldown_s=2.0,
        max_weight_lag=1,  # a respawned engine serves only after it is
        # caught up to within one publish of the rollout target
        respawn_base_s=0.2, respawn_max_s=1.0,
        publish_base_interval=2,  # --net: v1 base + v2 delta, so the wire
        # rollout exercises BOTH packet kinds and the late-joiner chain
        serve_net_host="127.0.0.1" if args.net else "",  # the cross-host
        # on-switch: engines serve behind TransportServer.from_config
        run_id="net_soak" if args.net else "fleet_soak",
        seed=args.seed,
    )
    state = init_train_state(cfg, args.num_actions, jax.random.PRNGKey(0))
    fleet = _InProcFleet(cfg, args.num_actions, state.params, out_dir,
                         net=args.net)
    row(event="net_soak_start" if args.net else "fleet_soak_start",
        engines=args.engines,
        max_engines=args.max_engines, duration_s=args.duration,
        rate=args.rate, out=out_dir)
    t0 = time.monotonic()
    fleet.start(args.engines)
    fleet.rollout.publish(state.params, version=1)
    row(event="fleet_up", engines=len(fleet.engines),
        boot_s=round(time.monotonic() - t0, 2))

    rng = np.random.default_rng(args.seed)
    obs_pool = rng.integers(0, 255, (64, 44, 44, 2), dtype=np.uint8)
    stop_ev = threading.Event()
    lock = threading.Lock()
    counts = {"submitted": 0, "shed": 0, "slow_submitted": 0,
              "slow_cancelled": 0, "slow_served": 0}
    latencies = []

    def collect(fut):
        if fut.cancelled():
            return
        try:
            fut.result(timeout=0)
        except Exception:
            return
        with lock:
            latencies.append((time.monotonic() - fut.t_enqueue) * 1e3)

    # three tenants across the QoS tiers; "burst" rides the lowest class so
    # its flood sheds FIRST under pressure (the QoS story, observable in the
    # route rows' shed_by_reason/tenants split)
    tenants = [("gold_t", "gold", 0.2), ("std_t", "std", 0.5),
               ("burst_t", "batch", 0.3)]

    def arrivals(worker_seed):
        """Open-loop generator: submissions happen on the wall-clock
        schedule whether or not the fleet keeps up — the IMPACT-style
        decoupling the admission layer exists for."""
        wrng = np.random.default_rng(worker_seed)
        t_end = t0_load + args.duration
        i = 0
        while not stop_ev.is_set() and time.monotonic() < t_end:
            phase = ((time.monotonic() - t0_load) % args.burst_period
                     < args.burst_period * 0.5)
            rate = args.rate * (args.burst_factor if phase else 0.3)
            time.sleep(min(float(wrng.exponential(1.0 / max(rate, 1e-6))),
                           0.05))
            r = wrng.random()
            acc = 0.0
            for name, qos, share in tenants:
                acc += share
                if r <= acc:
                    break
            with lock:
                counts["submitted"] += 1
            try:
                fut = fleet.router.submit(
                    obs_pool[i % len(obs_pool)], tenant=name, qos=qos)
                fut.add_done_callback(collect)
            except ServerOverloaded:
                with lock:
                    counts["shed"] += 1
            i += 1

    def slow_client(worker_seed):
        """Deliberately slow cohort: submit, give up almost immediately,
        CANCEL — abandoned futures must not burn batch capacity
        (serve_cancelled_total counts the skips)."""
        wrng = np.random.default_rng(worker_seed)
        t_end = t0_load + args.duration
        i = 0
        while not stop_ev.is_set() and time.monotonic() < t_end:
            with lock:
                counts["slow_submitted"] += 1
            try:
                fut = fleet.router.submit(
                    obs_pool[i % len(obs_pool)], tenant="slow_t", qos="batch")
            except ServerOverloaded:
                time.sleep(0.01)
                continue
            try:
                fut.result(timeout=args.slow_timeout)
                with lock:
                    counts["slow_served"] += 1
            except TimeoutError:
                fut.cancel()
                with lock:
                    counts["slow_cancelled"] += 1
            except Exception:
                pass  # engine-kill window: the error is the router's story
            time.sleep(float(wrng.exponential(0.02)))
            i += 1

    t0_load = time.monotonic()
    threads = [threading.Thread(target=arrivals, args=(args.seed + 1,),
                                daemon=True)]
    threads += [threading.Thread(target=slow_client, args=(args.seed + 10 + k,),
                                 daemon=True)
                for k in range(args.slow_clients)]
    for t in threads:
        t.start()

    killed = rolled_v2 = refused_checked = False
    kill_at = t0_load + args.duration * args.kill_frac
    while time.monotonic() < t0_load + args.duration:
        fleet.autoscaler.evaluate()
        fleet.rollout.sync()
        fleet.rollout.maybe_emit_converged()
        now = time.monotonic()
        if not killed and now >= kill_at:
            victim = min(fleet.engines)
            # catch the victim with requests QUEUED, so the kill provably
            # exercises the re-route path (gated rerouted >= 1 below) —
            # under open-loop load this spin resolves in milliseconds
            spin_deadline = time.monotonic() + 2.0
            transport = fleet.engines[victim].transport
            while (transport.depth() < 2
                   and time.monotonic() < spin_deadline):
                time.sleep(0.001)
            depth_at_kill = transport.depth()
            fleet.kill_engine(victim)
            killed = True
            row(event="engine_killed", engine=victim,
                depth_at_kill=depth_at_kill,
                at_s=round(now - t0_load, 2))
        if killed and not rolled_v2 and now >= kill_at + 0.5:
            perturbed = jax.tree.map(lambda x: x + 0.01, state.params)
            fleet.rollout.publish(perturbed, version=2)
            rolled_v2 = True
            row(event="rollout_fired", version=2)
        if rolled_v2 and not refused_checked:
            refused = fleet.rollout.publish(state.params, version=1)
            refused_checked = True
            row(event="backward_publish_refused",
                ok=refused.get("event") == "refused_backward")
        time.sleep(0.2)
    stop_ev.set()
    for t in threads:
        t.join(timeout=10)

    # drain: every accepted request must settle (complete, cancel or — the
    # gated failure — be lost); respawn/rollout stragglers get a last sync
    drain_deadline = time.monotonic() + 30
    while fleet.router.inflight() > 0 and time.monotonic() < drain_deadline:
        fleet.autoscaler.evaluate()
        fleet.rollout.sync()
        time.sleep(0.1)
    converged = fleet.rollout.wait_converged(timeout_s=15.0)
    versions = fleet.rollout.engine_versions()
    wall_s = time.monotonic() - t0_load
    stats = fleet.router.stats()
    net_capture = None
    if args.net:  # captured BEFORE stop() tears the engine/transport maps down
        net_capture = {
            "target_digest": fleet.rollout.reconstructed_digest(),
            "digests": {str(eid): e.served_digest
                        for eid, e in fleet.engines.items()
                        if e.transport.alive()},
            "rollout_bytes_wire": fleet.rollout.bytes_total,
            "publishes": fleet.rollout.publishes,
            "transport_bytes_sent": sum(
                t.bytes_sent for t in fleet.transports.values()),
            "transport_reconnects": sum(
                t.reconnects for t in fleet.transports.values()),
        }
    fleet.stop()

    lat = sorted(latencies)
    p99 = lat[min(int(len(lat) * 0.99), len(lat) - 1)] if lat else None
    p50 = lat[len(lat) // 2] if lat else None
    accepted = stats["accepted"]
    settled = (stats["completed"] + stats["cancelled"] + stats["failed"]
               + stats["lost"])
    shed_rate = stats["shed"] / max(counts["submitted"]
                                    + counts["slow_submitted"], 1)
    gates = {
        "lost_zero": stats["lost"] == 0,
        "accepted_accounted": settled == accepted,
        "p99_ms": p99 is not None and p99 <= args.p99_gate_ms,
        "shed_rate": shed_rate <= args.shed_gate,
        # the kill waited for queued requests on the victim, so the re-route
        # path MUST have fired — a vacuous pass here would mean the soak
        # never exercised what it claims to gate
        "rerouted_after_kill": stats["rerouted"] >= 1,
        "rollout_converged": converged,
        # the deliberate backward publish was refused AND the fleet target
        # ended where the forward publishes left it — no rollback happened
        "no_rollback": (fleet.rollout.refused == 1
                        and fleet.rollout.target_version == 2),
        "cancel_worked": counts["slow_cancelled"] == 0
        or stats["cancelled"] > 0,
    }
    soak_path = "net_soak" if args.net else "fleet_soak"
    net_fields = {}
    if net_capture is not None:
        # wire weight-rollout economics: bytes the int8-delta packets
        # actually shipped vs what fp32-full would have — the QuaRL/PR-8
        # ratio measured ACROSS a socket, for the BENCH_r*.json trajectory
        from rainbow_iqn_apex_tpu.utils.quantize import tree_bytes

        fp32_total = tree_bytes(state.params) * net_capture["publishes"]
        gates["wire_rollout_bit_exact"] = (
            bool(net_capture["digests"])
            and all(d == net_capture["target_digest"]
                    for d in net_capture["digests"].values()))
        net_fields = {
            "rollout_bytes_wire": net_capture["rollout_bytes_wire"],
            "rollout_bytes_fp32": fp32_total,
            "rollout_bytes_ratio_vs_fp32": round(
                fp32_total / max(net_capture["rollout_bytes_wire"], 1), 3),
            "transport_bytes_sent": net_capture["transport_bytes_sent"],
            "transport_reconnects": net_capture["transport_reconnects"],
        }
    result = {
        "path": soak_path,
        "metric": f"{soak_path}_requests_per_sec",
        "value": round(stats["completed"] / max(wall_s, 1e-9), 1),
        "unit": "req/s",
        **net_fields,
        "wall_s": round(wall_s, 2),
        "submitted": counts["submitted"] + counts["slow_submitted"],
        "accepted": accepted,
        "completed": stats["completed"],
        "shed": stats["shed"],
        "shed_rate": round(shed_rate, 4),
        "shed_by_reason": stats["shed_by_reason"],
        "rerouted": stats["rerouted"],
        "lost": stats["lost"],
        "cancelled": stats["cancelled"],
        "slow_cancelled": counts["slow_cancelled"],
        "latency_p50_ms": None if p50 is None else round(p50, 2),
        "latency_p99_ms": None if p99 is None else round(p99, 2),
        "engine_versions": {str(k): v for k, v in versions.items()},
        "rollout_converged": converged,
        "tenants": stats["tenants"],
        "gates": gates,
    }
    if not all(gates.values()):
        result["status"] = "gate_failed"
        row(**result)
        return 1
    row(**result)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=64)
    ap.add_argument("--requests", type=int, default=2000)
    ap.add_argument("--deadline-ms", type=float, default=5.0)
    ap.add_argument("--buckets", default="8,16,32,64")
    ap.add_argument("--queue-bound", type=int, default=256)
    ap.add_argument("--mode", default="greedy", choices=("greedy", "noisy"))
    ap.add_argument("--no-swap", action="store_true",
                    help="skip the mid-bench weight hot-swap")
    ap.add_argument("--num-actions", type=int, default=6)
    ap.add_argument("--out", default="results/serve_bench",
                    help="directory for the JSONL metrics log")
    # ---- quantized serving (utils/quantize.py; make quant-smoke) ----
    ap.add_argument("--quant", action="store_true",
                    help="run the fp32-vs-int8 serving comparison instead")
    ap.add_argument("--agreement-min", type=float, default=0.99,
                    help="greedy-action agreement gate threshold (--quant)")
    # ---- fleet soak (serving/fleet/) ----
    ap.add_argument("--fleet-soak", action="store_true",
                    help="run the router+fleet heavy-traffic soak instead")
    ap.add_argument("--net", action="store_true",
                    help="with --fleet-soak: put a real loopback socket on "
                         "every hop (TransportServer/RemoteTransport) and "
                         "ship rollouts as int8-delta packets over the "
                         "wire; emits one net_soak row")
    ap.add_argument("--engines", type=int, default=2,
                    help="initial engine count (fleet soak)")
    ap.add_argument("--max-engines", type=int, default=3,
                    help="autoscaler ceiling (fleet soak)")
    ap.add_argument("--duration", type=float, default=8.0,
                    help="seconds of open-loop arrivals (fleet soak)")
    ap.add_argument("--rate", type=float, default=250.0,
                    help="mean arrivals/s across tenants (fleet soak)")
    ap.add_argument("--burst-factor", type=float, default=3.0,
                    help="hi-phase arrival multiplier (lo phase = 0.3x)")
    ap.add_argument("--burst-period", type=float, default=2.0)
    ap.add_argument("--slow-clients", type=int, default=3,
                    help="cohort of clients that abandon (cancel) requests")
    ap.add_argument("--slow-timeout", type=float, default=0.03,
                    help="seconds a slow client waits before giving up")
    ap.add_argument("--kill-frac", type=float, default=0.5,
                    help="fraction of --duration at which an engine is killed")
    ap.add_argument("--p99-gate-ms", type=float, default=2000.0)
    ap.add_argument("--shed-gate", type=float, default=0.6,
                    help="max tolerated shed fraction of submissions")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if args.net and not args.fleet_soak:
        ap.error("--net is a --fleet-soak variant")
    if args.fleet_soak:
        return fleet_soak(args)
    if args.quant:
        return quant_bench(args)

    import jax
    import numpy as np

    from rainbow_iqn_apex_tpu.config import Config
    from rainbow_iqn_apex_tpu.ops.learn import init_train_state
    from rainbow_iqn_apex_tpu.serving import PolicyServer

    cfg = Config(
        compute_dtype="float32",
        frame_height=44,
        frame_width=44,
        history_length=2,
        hidden_size=64,
        num_cosines=16,
        num_tau_samples=8,
        num_tau_prime_samples=8,
        num_quantile_samples=4,
        serve_batch_buckets=args.buckets,
        serve_deadline_ms=args.deadline_ms,
        serve_queue_bound=args.queue_bound,
        serve_mode=args.mode,
        serve_metrics_interval_s=1.0,
        run_id="serve_bench",
    )
    state = init_train_state(cfg, args.num_actions, jax.random.PRNGKey(0))
    os.makedirs(args.out, exist_ok=True)
    metrics_path = os.path.join(args.out, "metrics.jsonl")
    server = PolicyServer(
        cfg, args.num_actions, state.params, metrics_path=metrics_path
    )
    row(event="bench_serve_start", clients=args.clients, requests=args.requests,
        buckets=server.engine.buckets, deadline_ms=args.deadline_ms,
        queue_bound=args.queue_bound, devices=server.engine.n_devices,
        metrics=metrics_path)

    # Pre-compile every bucket OUTSIDE the timed window so latency numbers
    # measure serving, not XLA compilation.
    t0 = time.monotonic()
    compiled = server.warmup()
    row(event="warmup_done", buckets_compiled=compiled,
        compile_s=round(time.monotonic() - t0, 2))
    server.start()

    rng = np.random.default_rng(0)
    obs_pool = rng.integers(0, 255, (64, 44, 44, 2), dtype=np.uint8)
    issued = threading.Semaphore(args.requests)  # total-request budget
    completed = [0]
    completed_lock = threading.Lock()
    swap_at = args.requests // 2
    swap_fired = threading.Event()
    errors = []

    def swap_params():
        """The hot-swap under load: perturbed params in, zero dropped
        requests expected (verified post-hoc from server stats)."""
        perturbed = jax.tree.map(lambda x: x + 0.01, state.params)
        version = server.load_params(perturbed)
        row(event="swap_fired", at_request=swap_at, params_version=version)

    def client(idx: int):
        while issued.acquire(blocking=False):
            try:
                server.act(obs_pool[idx % len(obs_pool)], timeout=120)
            except Exception as e:  # noqa: BLE001 — report, don't hang the bench
                errors.append(f"{type(e).__name__}: {e}")
                return
            should_swap = False
            with completed_lock:
                completed[0] += 1
                if not args.no_swap and completed[0] >= swap_at \
                        and not swap_fired.is_set():
                    swap_fired.set()
                    should_swap = True
            if should_swap:
                # the device_put runs OUTSIDE the lock — holding it would
                # stall every other client's completion path and charge the
                # swap's cost to the measured latency as harness contention
                swap_params()

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(args.clients)
    ]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - t_start
    stats = server.stop()

    occupancy = stats["batch_occupancy_lifetime"]
    rps = completed[0] / max(wall_s, 1e-9)
    row(metric="serve_batch_occupancy_mean", value=occupancy, unit="req/batch")
    for k in ("latency_p50_ms", "latency_p95_ms", "latency_p99_ms"):
        if k in stats:
            row(metric=f"serve_{k}", value=stats[k], unit="ms")
    row(metric="serve_shed_total", value=stats["total_shed"], unit="requests")
    row(metric="serve_swaps", value=stats["total_swaps"], unit="events")
    if errors:
        row(event="client_errors", n=len(errors), first=errors[0])
        return 1
    if completed[0] != args.requests:
        row(event="incomplete", completed=completed[0], expected=args.requests)
        return 1
    # Blocking clients can hold at most `clients` requests in flight, so any
    # shed below the queue bound is a server bug, not an overload.
    if args.clients <= args.queue_bound and stats["total_shed"] > 0:
        row(event="unexpected_shed", shed=stats["total_shed"])
        return 1
    # The coalescing gate from the docstring and docs/SERVING.md, enforced:
    # at 64+ clients a healthy batcher runs far above 4 requests/batch, and
    # occupancy ~1 means micro-batching silently stopped working.
    if args.clients >= 64 and occupancy <= 4:
        row(event="occupancy_below_gate", occupancy=occupancy, gate=4)
        return 1
    row(metric="serve_requests_per_sec", value=round(rps, 1), unit="req/s",
        requests=completed[0], wall_s=round(wall_s, 2),
        occupancy=occupancy, path="in_process")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
