#!/usr/bin/env python
"""Load-generator bench for the policy server (serving/): N synthetic client
threads drive `PolicyServer.act` as fast as the server completes them, with a
weight hot-swap fired mid-run, and the result is printed as JSON rows in the
bench.py idiom (one object per line, flushed immediately, LAST line is the
headline requests/sec).

What is measured: end-to-end serving throughput and latency through the real
stack — bounded queue, deadline coalescing, bucket padding, lane-sharded
jitted inference, atomic param swap — not a model microbenchmark.  Batch
occupancy tells whether micro-batching actually coalesced (the acceptance
gate is mean occupancy > 4 at 64 clients); shed_total must be 0 when clients
<= queue bound (blocking clients can never overrun it).

CPU smoke shape (default): 44x44x2 frames, hidden 64, IQN taus 8/8/4 — the
same small-but-real network the parallel tests use, so the numbers track the
serving machinery, not conv throughput.

Usage:
    JAX_PLATFORMS=cpu python scripts/bench_serve.py --clients 64 --requests 2000
"""

import argparse
import json
import os
import sys
import threading
import time

# The sandbox's sitecustomize registers the remote-TPU PJRT plugin whenever
# PALLAS_AXON_POOL_IPS is set, and a registered plugin blocks `import jax`
# even under JAX_PLATFORMS=cpu (see conftest.py).  This bench is a CPU smoke
# tool unless the caller explicitly pins a device platform.
if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def row(**fields):
    print(json.dumps(fields), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=64)
    ap.add_argument("--requests", type=int, default=2000)
    ap.add_argument("--deadline-ms", type=float, default=5.0)
    ap.add_argument("--buckets", default="8,16,32,64")
    ap.add_argument("--queue-bound", type=int, default=256)
    ap.add_argument("--mode", default="greedy", choices=("greedy", "noisy"))
    ap.add_argument("--no-swap", action="store_true",
                    help="skip the mid-bench weight hot-swap")
    ap.add_argument("--num-actions", type=int, default=6)
    ap.add_argument("--out", default="results/serve_bench",
                    help="directory for the JSONL metrics log")
    args = ap.parse_args()

    import jax
    import numpy as np

    from rainbow_iqn_apex_tpu.config import Config
    from rainbow_iqn_apex_tpu.ops.learn import init_train_state
    from rainbow_iqn_apex_tpu.serving import PolicyServer

    cfg = Config(
        compute_dtype="float32",
        frame_height=44,
        frame_width=44,
        history_length=2,
        hidden_size=64,
        num_cosines=16,
        num_tau_samples=8,
        num_tau_prime_samples=8,
        num_quantile_samples=4,
        serve_batch_buckets=args.buckets,
        serve_deadline_ms=args.deadline_ms,
        serve_queue_bound=args.queue_bound,
        serve_mode=args.mode,
        serve_metrics_interval_s=1.0,
        run_id="serve_bench",
    )
    state = init_train_state(cfg, args.num_actions, jax.random.PRNGKey(0))
    os.makedirs(args.out, exist_ok=True)
    metrics_path = os.path.join(args.out, "metrics.jsonl")
    server = PolicyServer(
        cfg, args.num_actions, state.params, metrics_path=metrics_path
    )
    row(event="bench_serve_start", clients=args.clients, requests=args.requests,
        buckets=server.engine.buckets, deadline_ms=args.deadline_ms,
        queue_bound=args.queue_bound, devices=server.engine.n_devices,
        metrics=metrics_path)

    # Pre-compile every bucket OUTSIDE the timed window so latency numbers
    # measure serving, not XLA compilation.
    t0 = time.monotonic()
    compiled = server.warmup()
    row(event="warmup_done", buckets_compiled=compiled,
        compile_s=round(time.monotonic() - t0, 2))
    server.start()

    rng = np.random.default_rng(0)
    obs_pool = rng.integers(0, 255, (64, 44, 44, 2), dtype=np.uint8)
    issued = threading.Semaphore(args.requests)  # total-request budget
    completed = [0]
    completed_lock = threading.Lock()
    swap_at = args.requests // 2
    swap_fired = threading.Event()
    errors = []

    def swap_params():
        """The hot-swap under load: perturbed params in, zero dropped
        requests expected (verified post-hoc from server stats)."""
        perturbed = jax.tree.map(lambda x: x + 0.01, state.params)
        version = server.load_params(perturbed)
        row(event="swap_fired", at_request=swap_at, params_version=version)

    def client(idx: int):
        while issued.acquire(blocking=False):
            try:
                server.act(obs_pool[idx % len(obs_pool)], timeout=120)
            except Exception as e:  # noqa: BLE001 — report, don't hang the bench
                errors.append(f"{type(e).__name__}: {e}")
                return
            should_swap = False
            with completed_lock:
                completed[0] += 1
                if not args.no_swap and completed[0] >= swap_at \
                        and not swap_fired.is_set():
                    swap_fired.set()
                    should_swap = True
            if should_swap:
                # the device_put runs OUTSIDE the lock — holding it would
                # stall every other client's completion path and charge the
                # swap's cost to the measured latency as harness contention
                swap_params()

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(args.clients)
    ]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.monotonic() - t_start
    stats = server.stop()

    occupancy = stats["batch_occupancy_lifetime"]
    rps = completed[0] / max(wall_s, 1e-9)
    row(metric="serve_batch_occupancy_mean", value=occupancy, unit="req/batch")
    for k in ("latency_p50_ms", "latency_p95_ms", "latency_p99_ms"):
        if k in stats:
            row(metric=f"serve_{k}", value=stats[k], unit="ms")
    row(metric="serve_shed_total", value=stats["total_shed"], unit="requests")
    row(metric="serve_swaps", value=stats["total_swaps"], unit="events")
    if errors:
        row(event="client_errors", n=len(errors), first=errors[0])
        return 1
    if completed[0] != args.requests:
        row(event="incomplete", completed=completed[0], expected=args.requests)
        return 1
    # Blocking clients can hold at most `clients` requests in flight, so any
    # shed below the queue bound is a server bug, not an overload.
    if args.clients <= args.queue_bound and stats["total_shed"] > 0:
        row(event="unexpected_shed", shed=stats["total_shed"])
        return 1
    # The coalescing gate from the docstring and docs/SERVING.md, enforced:
    # at 64+ clients a healthy batcher runs far above 4 requests/batch, and
    # occupancy ~1 means micro-batching silently stopped working.
    if args.clients >= 64 and occupancy <= 4:
        row(event="occupancy_below_gate", occupancy=occupancy, gate=4)
        return 1
    row(metric="serve_requests_per_sec", value=round(rps, 1), unit="req/s",
        requests=completed[0], wall_s=round(wall_s, 2),
        occupancy=occupancy, path="in_process")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
