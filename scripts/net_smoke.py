#!/usr/bin/env python
"""net_smoke: the cross-host serving plane proven end to end, multi-process
(`make net-smoke`; docs/SERVING.md "cross-host").

Topology — every hop a REAL socket, every engine a real process:

    parent: 2 FrontRouters (shared-nothing, own EngineRegistry each,
            federated over UDP RouterGossip) + 1 FleetRollout controller
    children: N engine hosts (default 3), each a separate process running
            PolicyServer + FleetEngine + TransportServer on 127.0.0.1:0,
            advertising addr:port through its lease payload

The routers discover the engines purely from the lease files (no port is
ever passed to the parent), dispatch a closed-loop client load across both
fronts, and mid-load one engine host is SIGKILLed cold — the true
process-death shape: no goodbye frame, connections drop, leases expire.
The rollout controller publishes int8-delta weight versions over the wire
before AND after the kill.

Self-asserted gates (exit 1 on any failure):

  1. both routers discovered all N engines through leases alone;
  2. ZERO lost accepted requests across both routers, through the kill
     (re-route fired: rerouted >= 1);
  3. the int8-delta rollout CONVERGED on every surviving engine, and each
     survivor's served-params digest equals the publisher's closed-loop
     reconstruction digest — bit-exact across the wire, asserted;
  4. the run dir lints as strict schema-versioned JSONL (route/net/gossip/
     rollout rows included — the Makefile runs lint_jsonl after us).

Usage:
    JAX_PLATFORMS=cpu python scripts/net_smoke.py --engines 3 --routers 2 \\
        --duration 6 --out /tmp/ria_net_smoke
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

# CPU smoke tool: strip the remote-TPU plugin trigger before jax imports
# (the bench_serve.py convention; children inherit the sanitised env).
if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def row(**fields):
    print(json.dumps(fields), flush=True)


def toy_cfg(run_id, seed, out_dir):
    from rainbow_iqn_apex_tpu.config import Config

    return Config(
        compute_dtype="float32",
        frame_height=44, frame_width=44, history_length=2,
        hidden_size=64, num_cosines=16,
        num_tau_samples=8, num_tau_prime_samples=8, num_quantile_samples=4,
        serve_batch_buckets="4,8,16",
        serve_deadline_ms=3.0,
        serve_queue_bound=64,
        serve_metrics_interval_s=1.0,
        fleet_lease_interval_s=0.25,
        fleet_lease_timeout_s=1.5,
        max_weight_lag=0,  # the smoke rolls versions mid-kill; survivors
        # must keep serving while a publish propagates, so no fence here
        serve_net_host="127.0.0.1",  # the cross-host on-switch: engine
        # children serve behind TransportServer.from_config
        run_id=run_id, seed=seed,
        results_dir=out_dir,
    )


# ------------------------------------------------------------- engine child
def engine_child(args) -> int:
    """One engine host: PolicyServer + FleetEngine lease + TransportServer,
    addr:port advertised in the lease BEFORE the first beat.  Runs until
    SIGTERM (clean stop) or SIGKILL (the victim's fate)."""
    import jax

    from rainbow_iqn_apex_tpu.serving import PolicyServer
    from rainbow_iqn_apex_tpu.serving.fleet import FleetEngine
    from rainbow_iqn_apex_tpu.serving.net import TransportServer
    from rainbow_iqn_apex_tpu.utils import quantize

    cfg = toy_cfg(f"net_smoke_e{args.engine_id}", args.seed, args.out)
    params = quantize.DeltaDecoder().apply(quantize.load_packet(args.params))
    server = PolicyServer(
        cfg, args.num_actions, params, devices=jax.devices()[:1],
        metrics_path=os.path.join(args.out, f"engine{args.engine_id}.jsonl"),
    )
    engine = FleetEngine(server, args.engine_id, args.hb_dir,
                         interval_s=cfg.fleet_lease_interval_s,
                         epoch=args.epoch)
    ts = TransportServer.from_config(cfg, engine,
                                     logger=server.metrics.logger)
    assert ts is not None  # toy_cfg sets serve_net_host
    ts.start()
    engine.start(warmup=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    ppid = os.getppid()
    while not stop.is_set():
        if os.getppid() != ppid:  # orphaned: the parent died, so should we
            break
        stop.wait(0.2)
    ts.stop()
    engine.stop()
    return 0


# ------------------------------------------------------------------ parent
def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--engines", type=int, default=3)
    ap.add_argument("--routers", type=int, default=2)
    ap.add_argument("--duration", type=float, default=6.0,
                    help="seconds of client load")
    ap.add_argument("--clients-per-router", type=int, default=6)
    ap.add_argument("--kill-frac", type=float, default=0.4,
                    help="fraction of --duration at which a host is killed")
    ap.add_argument("--num-actions", type=int, default=6)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--boot-timeout", type=float, default=120.0)
    ap.add_argument("--out", default="/tmp/ria_net_smoke")
    # internal: engine-child mode
    ap.add_argument("--engine-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--engine-id", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--epoch", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--hb-dir", default="", help=argparse.SUPPRESS)
    ap.add_argument("--params", default="", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.engine_child:
        return engine_child(args)

    import numpy as np

    import jax

    from rainbow_iqn_apex_tpu.obs.registry import MetricRegistry
    from rainbow_iqn_apex_tpu.ops.learn import init_train_state
    from rainbow_iqn_apex_tpu.parallel.elastic import HeartbeatMonitor
    from rainbow_iqn_apex_tpu.serving import ServerOverloaded
    from rainbow_iqn_apex_tpu.serving.fleet import (
        EngineRegistry,
        FleetRollout,
        FrontRouter,
    )
    from rainbow_iqn_apex_tpu.serving.net import (
        RemoteEngine,
        RemoteTransport,
        RouterGossip,
    )
    from rainbow_iqn_apex_tpu.utils import quantize
    from rainbow_iqn_apex_tpu.utils.faults import RetryPolicy
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    out = args.out
    os.makedirs(out, exist_ok=True)
    hb_dir = os.path.join(out, "heartbeats")
    cfg = toy_cfg("net_smoke", args.seed, out)
    state = init_train_state(cfg, args.num_actions, jax.random.PRNGKey(0))
    params_path = os.path.join(out, "boot_params.npz")
    quantize.save_packet(quantize.params_packet(state.params, 0), params_path)
    row(event="net_smoke_start", engines=args.engines, routers=args.routers,
        duration_s=args.duration, out=out)

    # ---- engine hosts: real processes, discovered only via leases --------
    children = {}
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    for eid in range(1, args.engines + 1):
        children[eid] = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--engine-child",
             "--engine-id", str(eid), "--hb-dir", hb_dir,
             "--params", params_path, "--out", out,
             "--seed", str(args.seed), "--num-actions",
             str(args.num_actions)],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)

    # ---- routers: shared-nothing, lease-discovered, gossip-federated -----
    retry = RetryPolicy(attempts=6, base_delay_s=0.1, max_delay_s=1.0,
                        seed=args.seed)
    routers, registries, gossips, loggers = [], [], [], []
    for r in range(args.routers):
        logger = MetricsLogger(os.path.join(out, f"router{r}.jsonl"),
                               run_id="net_smoke", echo=False, host=r)
        obs_reg = MetricRegistry()
        registry = EngineRegistry(
            hb_dir, lease_timeout_s=cfg.fleet_lease_timeout_s,
            logger=logger, obs_registry=obs_reg,
            transport_factory=lambda lease, logger=logger: RemoteTransport(
                lease.addr, lease.port, engine_id=lease.host, retry=retry,
                probe_timeout_s=0.5, logger=logger, connect=False),
            probe_timeout_s=0.5, probe_interval_s=0.5,
            net_stats_interval_s=2.0)
        gossip = RouterGossip(
            r, snapshot_fn=lambda: {}, interval_s=0.25,
            logger=logger, obs_registry=obs_reg)
        router = FrontRouter(
            registry, max_inflight=256,
            logger=logger, obs_registry=obs_reg,
            metrics_interval_s=1.0, poll_interval_s=0.1,
            peer_inflight_fn=gossip.peer_inflight,
            peer_target_fn=gossip.peer_target_version)
        gossip.snapshot_fn = router.gossip_snapshot
        routers.append(router)
        registries.append(registry)
        gossips.append(gossip)
        loggers.append(logger)
    for r, gossip in enumerate(gossips):
        gossip.set_peers([("127.0.0.1", g.port)
                          for i, g in enumerate(gossips) if i != r])
        gossip.start()
    for router in routers:
        router.start()

    # ---- rollout controller: its OWN remote handles (shared-nothing too) -
    ctrl_logger = MetricsLogger(os.path.join(out, "controller.jsonl"),
                                run_id="net_smoke", echo=False, host=99)
    rollout = FleetRollout(logger=ctrl_logger, compression="int8_delta",
                           base_interval=4)
    monitor = HeartbeatMonitor(hb_dir, timeout_s=cfg.fleet_lease_timeout_s)
    remote_engines = {}

    def track_new_engines():
        for hid, lease in monitor.leases().items():
            if (lease.role == "engine" and lease.fresh and lease.addr
                    and lease.port and hid not in remote_engines):
                engine = RemoteEngine.from_lease(
                    lease, retry=retry, logger=ctrl_logger)
                remote_engines[hid] = engine
                rollout.track(engine)

    # ---- boot: every router must see every engine through leases alone ---
    deadline = time.monotonic() + args.boot_timeout
    while time.monotonic() < deadline:
        track_new_engines()
        if (len(remote_engines) == args.engines
                and all(len(reg.routable()) == args.engines
                        for reg in registries)):
            break
        time.sleep(0.25)
    discovered = {r: len(reg.routable()) for r, reg in enumerate(registries)}
    row(event="fleet_discovered", per_router=discovered,
        controller=len(remote_engines))
    if any(n != args.engines for n in discovered.values()):
        row(path="net_smoke", status="error",
            error=f"discovery incomplete: {discovered}")
        for proc in children.values():
            proc.kill()
        return 1

    rollout.publish(state.params, version=1)
    rollout.wait_converged(timeout_s=20.0)

    # ---- client load across both fronts ----------------------------------
    rng = np.random.default_rng(args.seed)
    obs_pool = rng.integers(0, 255, (32, 44, 44, 2), dtype=np.uint8)
    stop_ev = threading.Event()
    lock = threading.Lock()
    counts = {"completed": 0, "shed": 0, "errors": 0}

    def client(router, worker):
        i = 0
        while not stop_ev.is_set():
            try:
                fut = router.submit(obs_pool[(i + worker) % len(obs_pool)],
                                    tenant=f"t{worker % 3}")
                fut.result(timeout=30)
                with lock:
                    counts["completed"] += 1
            except ServerOverloaded:
                with lock:
                    counts["shed"] += 1
                time.sleep(0.005)
            except Exception:
                with lock:
                    counts["errors"] += 1
            i += 1

    threads = [threading.Thread(target=client, args=(router, w), daemon=True)
               for router in routers
               for w in range(args.clients_per_router)]
    t0 = time.monotonic()
    for t in threads:
        t.start()

    victim = min(children)
    killed = False
    rolled = 1
    kill_at = t0 + args.duration * args.kill_frac
    while time.monotonic() < t0 + args.duration:
        track_new_engines()
        rollout.sync()
        rollout.maybe_emit_converged()
        now = time.monotonic()
        if not killed and now >= kill_at:
            # catch the victim with UNANSWERED work queued: the closed-loop
            # clients alone keep engine queues near empty (a result already
            # in the TCP buffer at SIGKILL still reaches its client — no
            # re-route needed), so a burst of accepted requests is piled on
            # first and the kill lands while the victim's batcher is deep.
            # The burst futures re-route like any accepted request; the
            # drain loop below accounts for every one of them.
            burst = []
            for i in range(120):
                try:
                    burst.append(routers[i % len(routers)].submit(
                        obs_pool[i % len(obs_pool)], tenant="burst"))
                except ServerOverloaded:
                    pass
            spin_deadline = time.monotonic() + 2.0
            victim_handle = registries[0].get(victim)
            while (victim_handle is not None and victim_handle.depth() < 2
                   and time.monotonic() < spin_deadline):
                time.sleep(0.001)
            inflight_at_kill = sum(r.engine_inflight().get(victim, 0)
                                   for r in routers)
            children[victim].kill()  # SIGKILL: no goodbye frame, no drain
            killed = True
            row(event="engine_host_killed", engine=victim,
                inflight_at_kill=inflight_at_kill,
                at_s=round(now - t0, 2))
        if killed and rolled < 3 and now >= kill_at + 0.5 * rolled:
            rolled += 1
            perturbed = jax.tree.map(
                lambda x, k=rolled: x + 0.01 * k, state.params)
            rollout.publish(perturbed, version=rolled)
            row(event="rollout_fired", version=rolled)
        time.sleep(0.05)
    stop_ev.set()
    for t in threads:
        t.join(timeout=15)

    # ---- drain + converge + digest ---------------------------------------
    drain_deadline = time.monotonic() + 20
    while (any(r.inflight() > 0 for r in routers)
           and time.monotonic() < drain_deadline):
        rollout.sync()
        time.sleep(0.1)
    # the dead host cannot converge; drop it from the controller's view the
    # way an operator's autoscaler would after the lease expired
    rollout.untrack(victim)
    remote_engines.pop(victim, None)
    converged = rollout.wait_converged(timeout_s=20.0)
    target_digest = rollout.reconstructed_digest()
    digests = {eid: engine.served_digest(timeout_s=2.0)
               for eid, engine in remote_engines.items()}
    stats = [r.stop() for r in routers]
    for g in gossips:
        g.stop()
    gossip_received = sum(g.received for g in gossips)

    # ---- teardown ---------------------------------------------------------
    for eid, proc in children.items():
        if proc.poll() is None:
            proc.terminate()
    for proc in children.values():
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
    for engine in remote_engines.values():
        engine.transport.close()
    for registry in registries:
        for handle in registry.handles():
            if handle.transport is not None and hasattr(
                    handle.transport, "close"):
                handle.transport.close()
    for logger in loggers + [ctrl_logger]:
        logger.close()

    wall_s = time.monotonic() - t0
    total = {k: sum(s[k] for s in stats)
             for k in ("accepted", "completed", "shed", "rerouted", "lost",
                       "failed", "cancelled")}
    gates = {
        "discovered_all": all(n == args.engines
                              for n in discovered.values()),
        "lost_zero": total["lost"] == 0,
        "rerouted_after_kill": total["rerouted"] >= 1,
        "rollout_converged": converged,
        "survivors_bit_exact": (
            target_digest is not None and len(digests) == args.engines - 1
            and all(d == target_digest for d in digests.values())),
        "gossip_flowed": gossip_received >= 1,
        "no_client_errors": counts["errors"] == 0,
    }
    result = {
        "path": "net_smoke",
        "metric": "net_smoke_requests_per_sec",
        "value": round(total["completed"] / max(wall_s, 1e-9), 1),
        "unit": "req/s",
        "wall_s": round(wall_s, 2),
        "routers": args.routers,
        "engines": args.engines,
        **total,
        "client_completed": counts["completed"],
        "client_shed": counts["shed"],
        "client_errors": counts["errors"],
        "rollout_target": rollout.target_version,
        "survivor_digests_equal": gates["survivors_bit_exact"],
        "gossip_received": gossip_received,
        "gates": gates,
    }
    if not all(gates.values()):
        result["status"] = "gate_failed"
        row(**result)
        return 1
    row(**result)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
