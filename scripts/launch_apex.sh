#!/usr/bin/env bash
# Launch glue (parity: the reference's README/shell instructions that start
# redis-server instances and point learner/actor processes at them —
# SURVEY.md par.2 row 10). The TPU-native launch is ONE command per host:
# there is no external replay server to start, and learner + actors are a
# single SPMD program over the host's slice.
#
# Elastic supervision (docs/RESILIENCE.md "heal"): the process-level
# respawn half of parallel/elastic.py RoleSupervisor, in shell — a host
# whose program dies is relaunched with exponential backoff under a bounded
# budget, with `--resume auto` so the respawned incarnation restores the
# newest valid checkpoint instead of starting cold.  Past the budget the
# host is left down (permanent eviction); the surviving hosts' lease
# monitor has long since dropped its shard and will readmit it on the next
# successful relaunch (`host_alive` -> `shard_readmit`).  Disable with
# RIA_RESPAWN_ATTEMPTS=0 for a scheduler that does its own restarts.
set -euo pipefail

GAME="${1:-Pong}"
RUN_ID="${2:-apex_$(date +%s)}"

RESPAWN_ATTEMPTS="${RIA_RESPAWN_ATTEMPTS:-3}"
BACKOFF_S="${RIA_RESPAWN_BASE_S:-5}"

run_once() {
  python train_agent_apex.py \
    --role apex \
    --env-id "atari:${GAME}" \
    --run-id "${RUN_ID}" \
    --num-actors 4 --num-envs-per-actor 16 \
    --replay-shards 2 \
    --learner-devices 0 \
    --t-max 200000000 \
    --resume auto \
    "${@}"
}

if [[ "${RESPAWN_ATTEMPTS}" == "0" ]]; then
  run_once "${@:3}"
  exit $?
fi

attempt=0
until run_once "${@:3}"; do
  rc=$?
  attempt=$((attempt + 1))
  if (( attempt > RESPAWN_ATTEMPTS )); then
    echo "launch_apex: rc=${rc}; respawn budget (${RESPAWN_ATTEMPTS}) exhausted — evicting this host" >&2
    exit "${rc}"
  fi
  delay=$(( BACKOFF_S * (1 << (attempt - 1)) ))
  echo "launch_apex: rc=${rc}; respawn ${attempt}/${RESPAWN_ATTEMPTS} in ${delay}s (--resume auto)" >&2
  sleep "${delay}"
done
