#!/usr/bin/env bash
# Launch glue (parity: the reference's README/shell instructions that start
# redis-server instances and point learner/actor processes at them —
# SURVEY.md par.2 row 10). The TPU-native launch is ONE command per host:
# there is no external replay server to start, and learner + actors are a
# single SPMD program over the host's slice.
set -euo pipefail

GAME="${1:-Pong}"
RUN_ID="${2:-apex_$(date +%s)}"

exec python train_agent_apex.py \
  --role apex \
  --env-id "atari:${GAME}" \
  --run-id "${RUN_ID}" \
  --num-actors 4 --num-envs-per-actor 16 \
  --replay-shards 2 \
  --learner-devices 0 \
  --t-max 200000000 \
  "${@:3}"
