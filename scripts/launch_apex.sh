#!/usr/bin/env bash
# Launch glue (parity: the reference's README/shell instructions that start
# redis-server instances and point learner/actor processes at them —
# SURVEY.md par.2 row 10). The TPU-native launch is ONE command per host:
# there is no external replay server to start, and learner + actors are a
# single SPMD program over the host's slice.
#
# Elastic supervision (docs/RESILIENCE.md "heal"): the process-level
# respawn half of parallel/elastic.py RoleSupervisor, in shell — a host
# whose program dies is relaunched with exponential backoff under a bounded
# budget, with `--resume auto` so the respawned incarnation restores the
# newest valid checkpoint instead of starting cold.  Past the budget the
# host is left down (permanent eviction); the surviving hosts' lease
# monitor has long since dropped its shard and will readmit it on the next
# successful relaunch (`host_alive` -> `shard_readmit`).  Disable with
# RIA_RESPAWN_ATTEMPTS=0 for a scheduler that does its own restarts.
#
# Learner failover (docs/RESILIENCE.md "learner failover"): `--standby`
# launches a hot-standby learner INSTEAD of the blind restart loop — it
# tails the learner's lease (parallel/failover.py) and claims the learner
# role the moment the lease expires, restoring `--resume auto` at the next
# learner epoch, so the fleet converges onto the successor instead of
# waiting out the backoff ladder.  Run it on a second host with the same
# GAME/RUN_ID; the learner itself must run with --failover-standby so its
# publishes carry a fencable epoch.
set -euo pipefail

STANDBY=0
if [[ "${1:-}" == "--standby" ]]; then
  STANDBY=1
  shift
fi

GAME="${1:-Pong}"
RUN_ID="${2:-apex_$(date +%s)}"

RESPAWN_ATTEMPTS="${RIA_RESPAWN_ATTEMPTS:-3}"
BACKOFF_S="${RIA_RESPAWN_BASE_S:-5}"

run_once() {
  python train_agent_apex.py \
    --role apex \
    --env-id "atari:${GAME}" \
    --run-id "${RUN_ID}" \
    --num-actors 4 --num-envs-per-actor 16 \
    --replay-shards 2 \
    --learner-devices 0 \
    --t-max 200000000 \
    --resume auto \
    "${@}"
}

if (( STANDBY )); then
  # the standby is its own supervisor: it blocks on the learner's lease and
  # takes the role over in-process — no respawn loop wraps it.  A distinct
  # --process-id keeps its lease file from clobbering the learner's.
  exec python train_agent_apex.py \
    --role standby \
    --env-id "atari:${GAME}" \
    --run-id "${RUN_ID}" \
    --failover-standby \
    --process-id 1 \
    --resume auto \
    "${@:3}"
fi

if [[ "${RESPAWN_ATTEMPTS}" == "0" ]]; then
  run_once "${@:3}"
  exit $?
fi

attempt=0
until run_once "${@:3}"; do
  rc=$?
  attempt=$((attempt + 1))
  if (( attempt > RESPAWN_ATTEMPTS )); then
    echo "launch_apex: rc=${rc}; respawn budget (${RESPAWN_ATTEMPTS}) exhausted — evicting this host" >&2
    exit "${rc}"
  fi
  delay=$(( BACKOFF_S * (1 << (attempt - 1)) ))
  echo "launch_apex: rc=${rc}; respawn ${attempt}/${RESPAWN_ATTEMPTS} in ${delay}s (--resume auto)" >&2
  sleep "${delay}"
done
