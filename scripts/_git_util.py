"""Shared git helper for the background supervisors (relay_watch,
round5_queue): force-add specific artifact paths and commit, retrying around
the index-lock contention the two concurrently-running supervisors create for
each other."""

import subprocess
import time


def commit_paths(repo: str, paths, msg: str, tries: int = 5,
                 log=print) -> bool:
    """git add -f <paths> && git commit -m <msg>, with backoff retries.

    -f because results/ is gitignored; benchmark JSON/CSV artifacts are
    force-added by convention (VERDICT r4 results-hygiene note) — callers
    must pass explicit artifact paths, never a directory containing ckpt/
    binaries.  Returns True on commit or nothing-to-commit."""
    paths = list(paths)
    if not paths:
        return True
    for i in range(tries):
        add = subprocess.run(["git", "-C", repo, "add", "-f", "--", *paths],
                             capture_output=True, text=True)
        if add.returncode == 0:
            com = subprocess.run(["git", "-C", repo, "commit", "-m", msg],
                                 capture_output=True, text=True)
            if com.returncode == 0 or "nothing to commit" in (
                    com.stdout + com.stderr):
                return True
        time.sleep(7 * (i + 1))
    log(f"git commit failed after {tries} tries: {msg}")
    return False
