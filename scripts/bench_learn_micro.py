#!/usr/bin/env python
"""Device-resident learn-step microbenchmark (batch pre-staged in HBM).

Times the full jitted IQN learn step at the reference Atari shape
(SURVEY §3.4: batch 32, 84x84x4, N=N'=64) with the batch already on
device, so the number isolates pure learn-step dispatch+compute from the
host-feed pipeline that bench.py measures.  One JSON line per row:

    python scripts/bench_learn_micro.py           # device as-is (axon/TPU)
    BENCH_ITERS=50 python scripts/bench_learn_micro.py

History: until 2026-07-31 this file (as bench_pallas.py) compared the
jnp quantile-Huber loss against a hand-written Pallas kernel.  The
first live-TPU sweep (results/relay_watch/pallas.jsonl) resolved the
three-rounds-pending keep-or-delete verdict: the Pallas kernel failed
remote_compile (tpu_compile_helper SIGABRT) at every BLOCK_B while the
jnp path ran 1657 steps/s device-resident, so the kernel was deleted
and this harness keeps only the winning path as the microbench.

`measure_learn` is shared with scripts/tpu_session.py so the two
harnesses cannot drift.
"""

import json
import os
import sys
import time
from typing import Callable, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_learn(
    iters: int,
    stop: Optional[Callable[[], bool]] = None,
) -> dict:
    """Timed full-learn-step loop at the reference Atari shape.

    ``stop`` lets a caller impose a soft wall-clock budget; a run cut
    short reports the iterations it actually completed, and a run with
    ZERO timed iterations reports ``skipped`` instead of a rate.
    """
    import jax
    import numpy as np

    from rainbow_iqn_apex_tpu.agents.agent import to_device_batch
    from rainbow_iqn_apex_tpu.config import Config
    from rainbow_iqn_apex_tpu.ops.learn import build_learn_step, init_train_state
    from rainbow_iqn_apex_tpu.replay.buffer import SampledBatch

    platform = jax.devices()[0].platform
    cfg = Config()
    num_actions = 18
    rng = np.random.default_rng(0)
    state = init_train_state(cfg, num_actions, jax.random.PRNGKey(0))
    learn = jax.jit(build_learn_step(cfg, num_actions), donate_argnums=0)
    b = cfg.batch_size
    batch = to_device_batch(SampledBatch(
        idx=np.arange(b),
        obs=rng.integers(0, 255, (b, *cfg.state_shape), dtype=np.uint8),
        action=rng.integers(0, num_actions, b).astype(np.int32),
        reward=rng.normal(size=b).astype(np.float32),
        next_obs=rng.integers(0, 255, (b, *cfg.state_shape), dtype=np.uint8),
        discount=np.full(b, 0.99**3, np.float32),
        weight=np.ones(b, np.float32),
        prob=np.full(b, 1.0 / b),
    ))
    key = jax.random.PRNGKey(1)
    for _ in range(2):  # compile + warm
        key, k = jax.random.split(key)
        state, info = learn(state, batch, k)
    jax.block_until_ready(info["loss"])
    row = {"loss_impl": "jnp", "platform": platform}
    t0 = time.perf_counter()
    n = 0
    while n < iters and not (stop is not None and stop()):
        key, k = jax.random.split(key)
        state, info = learn(state, batch, k)
        n += 1
    jax.block_until_ready(info["loss"])
    dt = time.perf_counter() - t0
    if n == 0:
        return {**row, "skipped": "budget exhausted before any timed iteration"}
    return {**row, "steps_per_sec": round(n / dt, 2), "iters": n,
            "loss": float(info["loss"])}


def main() -> None:
    import jax

    on_accel = jax.default_backend() in ("tpu", "axon")
    iters = int(os.environ.get("BENCH_ITERS", "100" if on_accel else "3"))
    print(json.dumps(measure_learn(iters)))


if __name__ == "__main__":
    main()
