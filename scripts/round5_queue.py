#!/usr/bin/env python
"""Round-5 serial background queue supervisor (VERDICT r4 items 2 + 3).

Phase 1: breakout + asterix score-sweep rerun at 65536 frames/game (the
budget at which the committed 16k sweep left both games at the floor),
into results/jaxsuite_64k so the 5-game 16k artifacts stay intact.
Phase 2: asterix@var generalization row at 65536 frames (round 4's 32.8k
run landed below the off_random bar), into results/jaxsuite_var64k.

While a phase runs, its benchmark ARTIFACTS (per_game.csv, aggregate.json,
generalization.json, runs/*/metrics.jsonl — never ckpt/ binaries) are
committed every 10 minutes; run_jaxsuite rewrites result files after every
game, so an interrupted phase keeps its completed rows.  All training is
relay-immune (env-stripped JAX_PLATFORMS=cpu, docs/STATUS.md probe
etiquette).

Usage:
  python scripts/round5_queue.py [--adopt-pid PID]
--adopt-pid: phase 1 is already running as PID (supervisor restart); poll it
instead of launching a new sweep.
"""

import argparse
import glob
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the EXACT round-3/4 sweep config (round-4 session's queue_r4.sh), so the
# 64k rows are comparable with the committed 16k sweep and generalization
# tables: CPU-sized IQN (hidden 128, cosines 32, tau 8/8/4), 8 lanes,
# metrics every 1000 steps, no in-train eval, periodic checkpoints
SHARED = ["--role", "anakin", "--compute-dtype", "float32",
          "--history-length", "2", "--hidden-size", "128",
          "--num-cosines", "32", "--num-tau-samples", "8",
          "--num-tau-prime-samples", "8", "--num-quantile-samples", "4",
          "--batch-size", "32", "--learning-rate", "1e-3",
          "--multi-step", "3", "--gamma", "0.9",
          "--memory-capacity", "8192", "--learn-start", "512",
          "--frames-per-learn", "2", "--target-update-period", "200",
          "--num-envs-per-actor", "8", "--anakin-segment-ticks", "32",
          "--learner-devices", "1", "--metrics-interval", "1000",
          "--eval-interval", "0", "--checkpoint-interval", "2000",
          "--eval-episodes", "32"]


def log(msg: str) -> None:
    print(f"queue[{time.strftime('%H:%M:%S', time.gmtime())}] {msg}",
          flush=True)


def artifacts(results_dir: str):
    base = os.path.join(REPO, results_dir)
    paths = [p for p in (os.path.join(base, "per_game.csv"),
                         os.path.join(base, "aggregate.json"),
                         os.path.join(base, "generalization.json"))
             if os.path.exists(p)]
    paths += glob.glob(os.path.join(base, "runs", "*", "metrics.jsonl"))
    return paths


def commit(results_dir: str, msg: str) -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _git_util import commit_paths

    commit_paths(REPO, artifacts(results_dir), msg, log=log)


def wait_and_commit(proc_or_pid, results_dir: str, prefix: str) -> None:
    """Poll a phase (Popen or adopted pid) to completion, committing its
    artifacts every 10 minutes."""
    def alive() -> bool:
        if isinstance(proc_or_pid, int):
            try:
                os.kill(proc_or_pid, 0)
                return True
            except OSError:
                return False
        return proc_or_pid.poll() is None

    last = 0.0
    while alive():
        time.sleep(30)
        if time.monotonic() - last >= 600:
            last = time.monotonic()
            commit(results_dir,
                   f"{prefix}: incremental snapshot "
                   f"({time.strftime('%H:%M', time.gmtime())} UTC)")
    commit(results_dir, f"{prefix}: phase complete")


def launch(argv, logfile: str):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = open(logfile, "a")
    return subprocess.Popen(argv, cwd=REPO, env=env, stdout=out,
                            stderr=subprocess.STDOUT)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--adopt-pid", type=int, default=None)
    ap.add_argument("--phase1-games", nargs="+",
                    default=["breakout", "asterix"],
                    help="subset restart: rerun only these phase-1 games; "
                         "--resume-rows keeps the other games' finished rows")
    ap.add_argument("--skip-phase1", action="store_true")
    args = ap.parse_args()
    py = sys.executable

    if args.skip_phase1:
        log("phase 1 skipped by flag")
    elif args.adopt_pid is not None:
        log(f"adopting running sweep pid {args.adopt_pid}")
        wait_and_commit(args.adopt_pid, "results/jaxsuite_64k",
                        "jaxsuite 64k rerun")
    else:
        log(f"phase 1: 64k sweep over {' '.join(args.phase1_games)}")
        p = launch(
            [py, "scripts/run_jaxsuite.py", "--games", *args.phase1_games,
             "--resume-rows", "--results-dir", "results/jaxsuite_64k",
             "--note",
             "breakout+asterix floor rerun at 65536 frames/game on the "
             "1-core CPU sandbox (VERDICT r4 item 2); the 5-game 16k sweep "
             "in results/jaxsuite left both below 0.2 script-normalized",
             "--per-game-t-max", "breakout=65536", "asterix=65536", "--",
             *SHARED, "--results-dir", "results/jaxsuite_64k/runs",
             "--checkpoint-dir", "results/jaxsuite_64k/ckpt"],
            "/tmp/q5_sweep64k.log")
        wait_and_commit(p, "results/jaxsuite_64k", "jaxsuite 64k rerun")

    log("phase 2: asterix@var 64k generalization")
    p = launch(
        [py, "scripts/run_jaxsuite.py", "--generalization", "--games",
         "asterix", "--results-dir", "results/jaxsuite_var64k",
         "--per-game-t-max", "asterix=65536", "--", *SHARED,
         "--results-dir", "results/jaxsuite_var64k/runs",
         "--checkpoint-dir", "results/jaxsuite_var64k/ckpt"],
        "/tmp/q5_gen_asterix.log")
    wait_and_commit(p, "results/jaxsuite_var64k", "asterix@var 64k")
    log("ALL DONE")


if __name__ == "__main__":
    main()
