#!/usr/bin/env python
"""static_analysis: run the house static analyzers over the repo.

    python scripts/static_analysis.py                 # full run, baseline-filtered
    python scripts/static_analysis.py --analyzer lock-discipline
    python scripts/static_analysis.py --no-baseline   # include grandfathered keys
    python scripts/static_analysis.py --list          # analyzer ids

Analyzers (rainbow_iqn_apex_tpu/analysis/; docs/OBSERVABILITY.md "Static
invariants"): lock-discipline, host-sync, jax-free, config-drift,
doc-drift.  Exit codes: 0 = finding-free, 1 = findings, 2 = usage error.

jax-free itself: this CLI imports only the analysis package + stdlib, so
it runs on boxes with no jax install (the checker self-hosts that claim).
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from rainbow_iqn_apex_tpu.analysis import core, runner  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="house-invariant static analyzers"
    )
    parser.add_argument(
        "--analyzer",
        action="append",
        default=None,
        help="restrict to this analyzer id (repeatable)",
    )
    parser.add_argument(
        "--repo-root", default=_REPO, help="repository root to analyze"
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: the checked-in "
        f"{runner.BASELINE_PATH})",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (report grandfathered findings too)",
    )
    parser.add_argument(
        "--list", action="store_true", help="print analyzer ids and exit"
    )
    args = parser.parse_args(argv)

    if args.list:
        for aid in runner.ANALYZER_IDS:
            print(aid)
        return 0

    baseline = "" if args.no_baseline else args.baseline
    try:
        findings = runner.run_all(
            args.repo_root, analyzers=args.analyzer, baseline_path=baseline
        )
    except ValueError as e:
        print(f"static_analysis: {e}", file=sys.stderr)
        return 2
    print(core.render_report(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
