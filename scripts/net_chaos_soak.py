#!/usr/bin/env python
"""net_chaos_soak: all three wire planes surviving a SEEDED degraded
network, multi-process (`make netchaos-smoke`; docs/RESILIENCE.md
"degraded network").

The clean-death soaks (net_smoke, replay_net_smoke, chaos_soak) prove the
fleet survives SIGKILL; this one proves it survives the failure class
deployments actually die of — corruption, latency, one-way partitions —
injected by the ``netcore/chaos.py`` interposer at the socket seam every
plane already routes through.

Topology — every hop a REAL socket, every role a real process:

    parent:    the learner site — FrontRouter + EngineRegistry (serving),
               RemoteReplayPlane sampling (replay), ObsRelay streaming
               (telemetry), learner-role lease claimed at a fenced epoch
    children:  2 jax-free echo engines (TransportServer + engine lease),
               2 replay shard servers, 1 actor appender (acked-rows
               ledger), 1 obs collector, 1 warm standby (StandbyLearner)

The parent arms a ROTATING seeded schedule through one chaos spec with
@t windows (all relative to arming):  a corruption phase, a latency +
slow-read phase, then TWO one-way partitions at once (learner's egress
to replay shard 1 drops; engine 21's replies to the learner stall) — the
asymmetric-partition shape that splits brains.  Children arm their own
always-on low-rate corruption via ``RIA_NET_CHAOS`` env so server-side
read paths take hits too.

Self-asserted gates (exit 1 on any failure):

  1. every phase actually injected (the chaos ledger is causal: corrupt,
     delay, slow_read AND partition counts all nonzero — no vacuous pass);
  2. serving: ZERO lost accepted requests across the whole schedule
     (typed drops re-route; an asymmetric partition degrades ONLY the
     partitioned engine);
  3. replay: ZERO acked-then-lost transitions — every shard server's
     wire-reported ``rows_appended`` covers every row the actor counted
     as acked to it (at-least-once: corruption may duplicate, never lose);
  4. NO split brain: the warm standby held off for the entire schedule
     (the learner's lease kept beating through every network fault), and
     exactly ONE learner epoch exists after the heal;
  5. the fleet RE-CONVERGES within --mttr-bound of the heal: a serve
     completion, a sampled batch, and a collector ``fleet_health`` status
     ok row all land inside the bound;
  6. ``net_chaos`` rows naming the injected site are in the run dir, and
     the run dir lints as strict schema-versioned JSONL (the Makefile
     runs lint_jsonl after us).

Usage:
    JAX_PLATFORMS=cpu python scripts/net_chaos_soak.py \\
        --out /tmp/ria_netchaos_soak
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

# CPU smoke tool: strip the remote-TPU plugin trigger before any imports
# (the net_smoke.py convention; children inherit the sanitised env).
if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

RUN_ID = "net_chaos_soak"
FRAME = (12, 12)
SHARDS = 2           # replay shard servers (process ids 1..SHARDS)
LANES_PER_SHARD = 2
CAPACITY = 2048
ENGINES = (21, 22)   # engine lease host ids (chaos peer labels "engine21"…)
ACTOR_PID = 31
STANDBY_PID = 9
COLLECTOR_PID = 99


def row(**fields):
    print(json.dumps(fields), flush=True)


def soak_cfg(out_dir, process_id, seed=0, collector=False, **extra):
    from rainbow_iqn_apex_tpu.config import Config

    kwargs = dict(
        run_id=RUN_ID, seed=seed, results_dir=out_dir,
        process_id=process_id,
        replay_shards=SHARDS,
        heartbeat_interval_s=0.25,
        heartbeat_timeout_s=1.5,   # fast lease expiry for the soak
        replay_net_remote=True,
        obs_net=True,
        obs_net_spool=256,
        obs_net_snapshot_s=0.5,
        respawn_base_s=0.05,       # fast relay redial backoff
        respawn_max_s=0.5,
    )
    if collector:
        kwargs.update(
            obs_net_host="127.0.0.1",  # bind gate: this process IS the
            obs_net_stale_s=2.0,       # collector (ephemeral ports)
            obs_net_tick_s=0.3,
            obs_net_resolution_s=0.2,
        )
    kwargs.update(extra)  # per-role overrides win
    return Config(**kwargs)


def _lanes_total() -> int:
    return SHARDS * LANES_PER_SHARD


def _stop_event_for_child():
    """SIGTERM -> clean stop; orphaned (parent died) -> stop too."""
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    ppid = os.getppid()

    def watchdog():
        while not stop.is_set():
            if os.getppid() != ppid:
                stop.set()
            time.sleep(0.2)

    threading.Thread(target=watchdog, daemon=True).start()
    return stop


# ------------------------------------------------------- replay shard child
def shard_child(args) -> int:
    """One replay shard server under its env-armed chaos site (low-rate TX
    corruption: the ACK/sample-response direction takes hits too)."""
    from rainbow_iqn_apex_tpu.parallel.elastic import (
        HeartbeatWriter,
        next_lease_epoch,
    )
    from rainbow_iqn_apex_tpu.parallel.sharded_replay import ShardedReplay
    from rainbow_iqn_apex_tpu.replay.net.server import ReplayShardServer
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    sid = args.child_id
    hb_dir = args.hb_dir
    epoch = next_lease_epoch(hb_dir, sid)
    memory = ShardedReplay.build(
        1, CAPACITY, LANES_PER_SHARD, frame_shape=FRAME, history=2,
        n_step=3, gamma=0.9, seed=args.seed + 100 * sid)
    run_dir = os.path.join(args.out, RUN_ID)
    os.makedirs(run_dir, exist_ok=True)
    logger = MetricsLogger(os.path.join(run_dir, f"shard{sid}.jsonl"),
                           run_id=RUN_ID, echo=False, host=sid)
    srv = ReplayShardServer(
        memory, shard_base=sid - 1, host="127.0.0.1", port=0, epoch=epoch,
        snapshot_prefix=os.path.join(args.out, f"replay_shard{sid}"),
        logger=logger).start()
    writer = HeartbeatWriter(hb_dir, sid, interval_s=0.25,
                             role="replay_shard", shard=sid - 1, epoch=epoch)
    srv.attach_lease(writer)
    writer.start()
    stop = _stop_event_for_child()
    while not stop.is_set():
        stop.wait(0.2)
    writer.stop()
    srv.stop()
    logger.close()
    return 0


# ------------------------------------------------------------- engine child
def engine_child(args) -> int:
    """One jax-free echo engine: try_submit/depth protocol server + pump
    thread + TransportServer, lease-advertised like a real engine host.
    The router's recovery paths (typed reroute, probe suspicion) care
    about the wire, not the model, so no jax is needed here."""
    import numpy as np

    from rainbow_iqn_apex_tpu.parallel.elastic import HeartbeatWriter
    from rainbow_iqn_apex_tpu.serving.batcher import ServeFuture
    from rainbow_iqn_apex_tpu.serving.net import TransportServer
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    eid = args.child_id

    class EchoServer:
        def __init__(self):
            self.q, self.lock = [], threading.Lock()

        def try_submit(self, obs):
            with self.lock:
                if len(self.q) >= 256:
                    return None
                fut = ServeFuture(np.asarray(obs))
                self.q.append(fut)
                return fut

        def depth(self):
            with self.lock:
                return len(self.q)

    run_dir = os.path.join(args.out, RUN_ID)
    os.makedirs(run_dir, exist_ok=True)
    logger = MetricsLogger(os.path.join(run_dir, f"engine{eid}.jsonl"),
                           run_id=RUN_ID, echo=False, host=eid)
    server = EchoServer()
    ts = TransportServer(server, port=0, logger=logger).start()
    writer = HeartbeatWriter(args.hb_dir, eid, interval_s=0.25,
                             role="engine")
    writer.update_payload(addr="127.0.0.1", port=ts.port)
    writer.start()
    stop = _stop_event_for_child()
    q = np.arange(6, dtype=np.float32)
    while not stop.is_set():
        with server.lock:
            pending, server.q = server.q, []
        for fut in pending:
            if not fut.cancelled():
                fut.set_result(3, q)
        stop.wait(0.003)
    writer.stop()
    ts.stop()
    logger.close()
    return 0


# -------------------------------------------------------------- actor child
def actor_child(args) -> int:
    """The appender whose acked ledger backs the zero-loss gate: only rows
    a shard server ACKED over the wire count; shed/spooled don't."""
    import numpy as np

    from rainbow_iqn_apex_tpu.replay.net.plane import RemoteReplayPlane
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    cfg = soak_cfg(args.out, process_id=ACTOR_PID, seed=args.seed,
                   obs_net=False)
    run_dir = os.path.join(args.out, RUN_ID)
    os.makedirs(run_dir, exist_ok=True)
    logger = MetricsLogger(os.path.join(run_dir, "actor.jsonl"),
                           run_id=RUN_ID, echo=False, host=ACTOR_PID)
    plane = RemoteReplayPlane(cfg, _lanes_total(), metrics=logger)
    rng = np.random.default_rng(args.seed + 7)
    stop = _stop_event_for_child()

    deadline = time.monotonic() + args.boot_timeout
    while (len(plane.peers) < SHARDS and not stop.is_set()
           and time.monotonic() < deadline):
        plane.poll(0)
        time.sleep(0.1)

    lanes = _lanes_total()
    tick = 0
    while not stop.is_set():
        rewards = rng.normal(size=lanes).astype(np.float32)
        plane.append_batch(
            rng.integers(0, 255, (lanes, *FRAME), dtype=np.uint8),
            rng.integers(0, 4, lanes),
            rewards,
            rng.random(lanes) < 0.02,
            priorities=np.abs(rewards) + 0.05,
        )
        tick += 1
        if tick % 50 == 0:
            plane.poll(tick)
        time.sleep(0.004)

    for ac in plane._appenders.values():
        ac.flush(timeout_s=10.0)
    stats = {
        "ticks": tick,
        "shed_lanes": plane.shed_lanes,
        "acked_by_server": {
            str(pid): ac.acked_rows for pid, ac in plane._appenders.items()
        },
    }
    path = os.path.join(args.out, "actor_stats.json")
    with open(path + ".tmp", "w") as f:
        json.dump(stats, f)
    os.replace(path + ".tmp", path)
    plane.close()
    logger.close()
    return 0


# ---------------------------------------------------------- collector child
def collector_child(args) -> int:
    from rainbow_iqn_apex_tpu.obs.net.collector import run_collector

    stop = _stop_event_for_child()
    cfg = soak_cfg(args.out, process_id=COLLECTOR_PID, seed=args.seed,
                   collector=True)
    run_collector(cfg, stop_event=stop)
    return 0


# ------------------------------------------------------------ standby child
def standby_child(args) -> int:
    """The split-brain witness: a warm standby polling the learner's lease
    through the whole schedule.  Network faults must never read as
    learner death (the lease is a file, and ``lease_skew_tolerance_s``
    absorbs reader/writer clock skew on top), so its ledger must show
    ZERO claims won."""
    from rainbow_iqn_apex_tpu.parallel.failover import (
        LEARNER_ROLE,
        StandbyLearner,
        latest_role_epoch,
    )
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    cfg = soak_cfg(args.out, process_id=STANDBY_PID, seed=args.seed,
                   obs_net=False, failover_standby=True,
                   lease_skew_tolerance_s=0.5)
    run_dir = os.path.join(args.out, RUN_ID)
    os.makedirs(run_dir, exist_ok=True)
    logger = MetricsLogger(os.path.join(run_dir, "standby.jsonl"),
                           run_id=RUN_ID, echo=False, host=STANDBY_PID)
    standby = StandbyLearner(cfg, takeover=lambda epoch, warm: "recovered",
                             metrics=logger)
    stop = _stop_event_for_child()
    polls = 0
    while not stop.is_set() and standby.result is None:
        standby.poll()
        polls += 1
        stop.wait(0.25)
    ledger = {
        "polls": polls,
        "claims_lost": standby.claims_lost,
        "took_over": standby.result is not None,
        "learner_epoch_seen": latest_role_epoch(standby.directory,
                                                LEARNER_ROLE),
    }
    path = os.path.join(args.out, "standby_stats.json")
    with open(path + ".tmp", "w") as f:
        json.dump(ledger, f)
    os.replace(path + ".tmp", path)
    logger.close()
    return 0


# ------------------------------------------------------------------ parent
def main() -> int:
    from rainbow_iqn_apex_tpu.netcore import chaos as netchaos

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--boot-grace", type=float, default=8.0,
                    help="quiet seconds after arming before the first phase")
    ap.add_argument("--phase", type=float, default=4.0,
                    help="seconds per fault phase (corrupt, then slow)")
    ap.add_argument("--partition", type=float, default=3.0,
                    help="seconds of the one-way partition phase")
    ap.add_argument("--post", type=float, default=16.0,
                    help="seconds of load after the heal (>= --mttr-bound)")
    ap.add_argument("--mttr-bound", type=float, default=15.0,
                    help="max seconds from heal to full re-convergence "
                         "(the sample plane's partition recovery is ~7s by "
                         "its probe/readmit cadence; the margin absorbs a "
                         "loaded CI machine)")
    ap.add_argument("--corrupt-p", type=float, default=0.04)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--boot-timeout", type=float, default=120.0)
    ap.add_argument("--out", default="/tmp/ria_netchaos_soak")
    # internal: child modes
    ap.add_argument("--role-child", default="", help=argparse.SUPPRESS)
    ap.add_argument("--child-id", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--hb-dir", default="", help=argparse.SUPPRESS)
    args = ap.parse_args()
    child_mains = {"shard": shard_child, "engine": engine_child,
                   "actor": actor_child, "collector": collector_child,
                   "standby": standby_child}
    if args.role_child:
        return child_mains[args.role_child](args)

    import numpy as np

    from rainbow_iqn_apex_tpu.obs.net.relay import ObsRelay
    from rainbow_iqn_apex_tpu.obs.registry import MetricRegistry
    from rainbow_iqn_apex_tpu.parallel.elastic import HeartbeatWriter
    from rainbow_iqn_apex_tpu.parallel.failover import (
        LEARNER_ROLE,
        latest_role_epoch,
        learner_epoch_at_start,
    )
    from rainbow_iqn_apex_tpu.replay.net.plane import RemoteReplayPlane
    from rainbow_iqn_apex_tpu.serving.fleet import EngineRegistry, FrontRouter
    from rainbow_iqn_apex_tpu.serving.net import RemoteTransport
    from rainbow_iqn_apex_tpu.utils.faults import RetryPolicy
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    out = args.out
    os.makedirs(out, exist_ok=True)
    run_dir = os.path.join(out, RUN_ID)
    os.makedirs(run_dir, exist_ok=True)
    hb_dir = os.path.join(run_dir, "heartbeats")
    g, p, q = args.boot_grace, args.phase, args.partition
    heal_rel = g + 2 * p + q
    # the rotating schedule, one seeded spec (docstring: the @t windows are
    # seconds since arming; the parent arms right before plane boot)
    spec = ",".join([
        f"corrupt_frame@p={args.corrupt_p}@t={g}..{g + p}",
        f"delay_ms=30+-20@p=0.9@t={g + p}..{g + 2 * p}",
        f"slow_read_bps=256k@t={g + p}..{g + 2 * p}",
        f"partition=learner->replay1@t={g + 2 * p}..{heal_rel}",
        f"partition=engine{ENGINES[0]}->learner@t={g + 2 * p}..{heal_rel}",
    ])
    row(event="net_chaos_soak_start", spec=spec, seed=args.seed, out=out,
        heal_at_s=heal_rel)

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def spawn(role, child_id, site, child_spec):
        child_env = dict(env)
        child_env[netchaos.ENV_VAR] = child_spec
        child_env[netchaos.SITE_ENV_VAR] = site
        child_env[netchaos.SEED_ENV_VAR] = str(args.seed)
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--role-child", role, "--child-id", str(child_id),
             "--hb-dir", hb_dir, "--out", out, "--seed", str(args.seed),
             "--boot-timeout", str(args.boot_timeout)],
            env=child_env, stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT)

    # children: always-on LOW-rate TX corruption at every serving/replay
    # site (server->client direction), so recv paths take seeded hits too;
    # the collector and standby run chaos-free (the standby owns no socket,
    # and the collector's fleet_health is the re-convergence witness)
    trickle = "corrupt_frame@p=0.005"
    children = {}
    for sid in range(1, SHARDS + 1):
        children[f"shard{sid}"] = spawn("shard", sid, f"replay{sid}", trickle)
    for eid in ENGINES:
        children[f"engine{eid}"] = spawn("engine", eid, f"engine{eid}",
                                         trickle)
    children["collector"] = spawn("collector", COLLECTOR_PID, "collector", "")
    children["actor"] = spawn("actor", ACTOR_PID, "actor",
                              "corrupt_frame@p=0.01")
    children["standby"] = spawn("standby", STANDBY_PID, "standby", "")

    def teardown(rc):
        for proc in children.values():
            if proc.poll() is None:
                proc.kill()
        return rc

    # ---- arm, then boot: sockets created from here on are interposed ----
    # failover_standby=True so learner_epoch_at_start writes a real role
    # claim marker — the split-brain gate checks the claimed epoch is
    # still the latest after the partition heals
    cfg = soak_cfg(out, process_id=0, seed=args.seed, failover_standby=True)
    metrics = MetricsLogger(os.path.join(run_dir, "learner.jsonl"),
                            run_id=RUN_ID, echo=False, host=0)
    armed = netchaos.install(
        netchaos.NetChaos(spec, seed=args.seed, site="learner"))
    armed.attach_logger(metrics)
    t_arm = time.monotonic()

    epoch = learner_epoch_at_start(cfg)
    lease = HeartbeatWriter(hb_dir, 0, interval_s=0.25, role=LEARNER_ROLE)
    lease.update_payload(learner_epoch=epoch)
    lease.start()

    retry = RetryPolicy(attempts=6, base_delay_s=0.1, max_delay_s=1.0,
                        seed=args.seed)
    registry = EngineRegistry(
        hb_dir, lease_timeout_s=cfg.heartbeat_timeout_s, logger=metrics,
        transport_factory=lambda lease_: RemoteTransport(
            lease_.addr, lease_.port, engine_id=lease_.host, retry=retry,
            probe_timeout_s=0.5, logger=metrics, connect=False),
        probe_timeout_s=0.5, probe_interval_s=0.5, net_stats_interval_s=2.0)
    router = FrontRouter(registry, max_inflight=256, logger=metrics,
                         metrics_interval_s=1.0, poll_interval_s=0.1)
    router.start()

    plane = RemoteReplayPlane(cfg, _lanes_total(), metrics=metrics)
    obs_registry = MetricRegistry()
    relay = ObsRelay.attach(cfg, metrics, registry=obs_registry,
                            role="learner")
    assert relay is not None  # cfg.obs_net is on

    # ---- boot: discovery through leases alone, warm replay rows ---------
    warm_rows = 4 * args.batch * SHARDS
    deadline = time.monotonic() + args.boot_timeout
    while time.monotonic() < deadline:
        plane.poll(0)
        if (len(plane.peers) == SHARDS
                and len(registry.routable()) == len(ENGINES)
                and plane.size() >= warm_rows and plane.sampleable()):
            break
        time.sleep(0.2)
    booted = (len(plane.peers) == SHARDS
              and len(registry.routable()) == len(ENGINES))
    row(event="fleet_booted", ok=booted, boot_s=round(armed.now(), 2),
        engines=len(registry.routable()), replay_peers=len(plane.peers),
        rows=plane.size())
    if not booted:
        row(path="net_chaos_soak", status="error",
            error=f"boot incomplete: engines={len(registry.routable())} "
                  f"replay={len(plane.peers)} rows={plane.size()}")
        return teardown(1)

    # ---- closed-loop serve clients across the schedule -------------------
    rng = np.random.default_rng(args.seed)
    obs_pool = rng.integers(0, 255, (16, 8, 8, 2), dtype=np.uint8)
    stop_ev = threading.Event()
    lock = threading.Lock()
    completions = []   # monotonic stamps of every completed request
    counts = {"completed": 0, "shed": 0, "errors": 0}

    def client(worker):
        i = 0
        while not stop_ev.is_set():
            try:
                fut = router.submit(obs_pool[(i + worker) % len(obs_pool)],
                                    tenant=f"t{worker}")
                fut.result(timeout=20)
                with lock:
                    counts["completed"] += 1
                    completions.append(time.monotonic())
            except Exception:  # shed AND typed wire errors: the gate is
                with lock:     # the router's lost==0, not per-try success
                    counts["errors"] += 1
                time.sleep(0.01)
            i += 1

    threads = [threading.Thread(target=client, args=(w,), daemon=True)
               for w in range(2)]
    for t in threads:
        t.start()

    # ---- the learner loop: sample straight through the schedule ----------
    sc = plane.start_sampling(args.batch, lambda: 0.5)
    t_heal = t_arm + heal_rel
    t_end = t_heal + max(args.post, args.mttr_bound)
    batch_stamps = []
    get_timeouts = 0
    step = 0
    # run to t_end, then keep sampling (hard-capped) until one POST-HEAL
    # batch lands: on a CPU-starved machine the fixed window can close
    # before the first post-heal batch, and "re-converged at t+Ns" is a
    # diagnosable gate failure where "never sampled again" is not
    t_hard = t_end + 2 * args.mttr_bound
    while time.monotonic() < t_end or (
            not any(s > t_heal for s in batch_stamps)
            and time.monotonic() < t_hard):
        step += 1
        try:
            s = sc.get(timeout=8.0)
        except TimeoutError:
            get_timeouts += 1
            continue
        batch_stamps.append(time.monotonic())
        sc.update_priorities(s.idx, np.abs(s.reward) + 0.01)
        if step % 32 == 0:
            plane.flush_writebacks()
        plane.poll(step)
    stop_ev.set()
    for t in threads:
        t.join(timeout=25)
    wall_s = time.monotonic() - t_arm

    # ---- MTTR: first proof of life on each plane after the heal ----------
    def mttr_of(stamps):
        after = [s - t_heal for s in stamps if s > t_heal]
        return round(min(after), 2) if after else None

    with lock:
        serve_mttr = mttr_of(completions)
    sample_mttr = mttr_of(batch_stamps)
    # the telemetry plane: the collector's own fleet_health row stream
    # (status ok, written after the heal) is the re-convergence witness
    t_heal_wall = time.time() - (time.monotonic() - t_heal)
    fleet_mttr = None
    collector_log = os.path.join(run_dir, "obs_collector.jsonl")
    fleet_deadline = time.monotonic() + args.mttr_bound
    while fleet_mttr is None and time.monotonic() < fleet_deadline:
        try:
            with open(collector_log) as f:
                for line in f:
                    try:
                        r = json.loads(line)
                    except ValueError:
                        continue
                    if (r.get("kind") == "fleet_health"
                            and r.get("status") == "ok"
                            and float(r.get("ts", 0)) > t_heal_wall):
                        fleet_mttr = round(r["ts"] - t_heal_wall, 2)
                        break
        except OSError:
            pass
        if fleet_mttr is None:
            time.sleep(0.3)
    row(event="reconvergence", serve_mttr_s=serve_mttr,
        sample_mttr_s=sample_mttr, fleet_mttr_s=fleet_mttr)

    # ---- drain the actor, then read the acked-rows ledgers ----------------
    children["actor"].terminate()
    try:
        children["actor"].wait(timeout=30)
    except subprocess.TimeoutExpired:
        children["actor"].kill()
    actor_stats = None
    try:
        with open(os.path.join(out, "actor_stats.json")) as f:
            actor_stats = json.load(f)
    except OSError:
        row(event="actor_stats_missing")
    shard_rows = {}
    for sid in range(1, SHARDS + 1):
        try:
            hdr, _ = plane.peers[sid].request({"op": "stats"}, timeout_s=10)
            shard_rows[sid] = int(hdr.get("rows_appended", -1))
        except Exception as e:
            shard_rows[sid] = -1
            row(event="shard_stats_failed", shard=sid,
                error=f"{type(e).__name__}: {e}")
    acked = {sid: int(actor_stats["acked_by_server"].get(str(sid), 0))
             if actor_stats else -1 for sid in range(1, SHARDS + 1)}
    row(event="loss_ledger", shard_rows_appended=shard_rows,
        acked_by_server=acked)

    # ---- the standby's split-brain ledger ---------------------------------
    children["standby"].terminate()
    try:
        children["standby"].wait(timeout=30)
    except subprocess.TimeoutExpired:
        children["standby"].kill()
    standby_stats = None
    try:
        with open(os.path.join(out, "standby_stats.json")) as f:
            standby_stats = json.load(f)
    except OSError:
        row(event="standby_stats_missing")
    final_epoch = latest_role_epoch(hb_dir, LEARNER_ROLE)

    # ---- teardown ---------------------------------------------------------
    stats = router.stop()
    for name, proc in children.items():
        if proc.poll() is None:
            proc.terminate()
    for proc in children.values():
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
    for handle in registry.handles():
        if handle.transport is not None and hasattr(handle.transport,
                                                    "close"):
            handle.transport.close()
    plane.close()
    relay.close(flush_timeout_s=2.0)
    lease.stop()
    metrics.close()

    injected = {f: armed.injected(f)
                for f in ("corrupt", "delay", "slow_read", "partition")}
    chaos_rows = 0
    with open(os.path.join(run_dir, "learner.jsonl")) as f:
        for line in f:
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if r.get("kind") == "net_chaos" and r.get("site") == "learner":
                chaos_rows += 1

    gates = {
        "faults_injected_all_phases": all(n > 0 for n in injected.values()),
        "serving_zero_lost": stats["lost"] == 0 and counts["completed"] > 0,
        "replay_zero_lost_acked": (
            actor_stats is not None
            and sum(acked.values()) > 0
            and all(shard_rows[sid] >= acked[sid] >= 0
                    for sid in range(1, SHARDS + 1))),
        "no_split_brain": (
            standby_stats is not None
            and not standby_stats["took_over"]
            and final_epoch == epoch),
        "reconverged_within_mttr": all(
            m is not None and m <= args.mttr_bound
            for m in (serve_mttr, sample_mttr, fleet_mttr)),
        "chaos_rows_emitted": chaos_rows > 0,
    }
    result = {
        "path": "net_chaos_soak",
        "metric": "net_chaos_soak_completed_per_sec",
        "value": round(counts["completed"] / max(wall_s, 1e-9), 1),
        "unit": "completed serve requests/s across the fault schedule",
        "wall_s": round(wall_s, 2),
        "spec": spec,
        "injected": injected,
        "chaos_rows": chaos_rows,
        "completed": counts["completed"],
        "client_errors": counts["errors"],
        "router_stats": {k: stats[k] for k in ("accepted", "completed",
                                               "rerouted", "lost", "failed")},
        "batches": len(batch_stamps),
        "get_timeouts": get_timeouts,
        "serve_mttr_s": serve_mttr,
        "sample_mttr_s": sample_mttr,
        "fleet_mttr_s": fleet_mttr,
        "learner_epoch": final_epoch,
        "standby": standby_stats,
        "gates": gates,
    }
    if not all(gates.values()):
        result["status"] = "gate_failed"
        row(**result)
        return 1
    row(**result)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
