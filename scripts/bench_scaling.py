#!/usr/bin/env python
"""Batch-scaling + MFU study of the device-resident PER learner.

For each batch size, builds the fused sample->learn->write-back graph
(replay/device.py) at the reference Atari workload shape, times jitted
50-step lax.scan segments, and reports steps/s, samples/s (consumed
transitions/s), per-step model FLOPs (XLA's own cost analysis when the
backend exposes it) and the implied MFU against the chip's bf16 peak.

Relay discipline (docs/STATUS.md round-2 postmortem): soft internal budget
checked between device calls, one clean process, exits on its own — never
run this under an external `timeout`/SIGKILL.

Usage: python scripts/bench_scaling.py [total_budget_seconds=420] [batches]
       e.g. python scripts/bench_scaling.py 420 32,64,128,256
Writes one JSON line per batch point (consumed by docs/SCALING.md).
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET = float(sys.argv[1]) if len(sys.argv) > 1 else 420.0


def _parse_point(tok: str):
    """'128' -> (128, 1); '32x4' -> (32, 4): batch_size x sample_groups —
    the grouped-draw learner (replay/device.sample_grouped) that keeps the
    reference's batch-32 PER stratum width while feeding the MXU a G*B
    GEMM."""
    if "x" in tok:
        b, g = tok.split("x", 1)
        return int(b), int(g)
    return int(tok), 1


BATCHES = [_parse_point(b) for b in
           (sys.argv[2] if len(sys.argv) > 2
            else "32,64,128,256,32x2,32x4").split(",")]
T0 = time.monotonic()

# bf16 peak of the v5-lite (v5e) chip this sandbox tunnels to; override for
# other generations
PEAK_FLOPS = float(os.environ.get("TPU_PEAK_FLOPS", 197e12))


def left() -> float:
    return BUDGET - (time.monotonic() - T0)


def emit(**row) -> None:
    print(json.dumps(row), flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from rainbow_iqn_apex_tpu.config import Config
    from rainbow_iqn_apex_tpu.ops.learn import init_train_state
    from rainbow_iqn_apex_tpu.replay.device import DeviceReplay, build_device_learn

    platform = jax.devices()[0].platform
    emit(phase="hello", platform=platform, budget_s=BUDGET, batches=BATCHES)

    A = 18
    lanes = int(os.environ.get("SCALE_LANES", "16"))
    seg = int(os.environ.get("SCALE_SEG", "2048"))  # 32k-frame ring
    SCAN = int(os.environ.get("SCALE_SCAN", "50"))

    base = Config()
    h, w = base.frame_height, base.frame_width
    replay = DeviceReplay(
        lanes=lanes, seg=seg, frame_shape=(h, w),
        history=base.history_length, n_step=base.multi_step, gamma=base.gamma,
        priority_exponent=base.priority_exponent,
        priority_eps=base.priority_eps,
    )

    # prefill once; every batch point samples from the same warm ring
    def prefill_tick(ds, key):
        kf, ka, kr, kp, kt = jax.random.split(key, 5)
        ds = replay.append(
            ds,
            jax.random.randint(kf, (lanes, h, w), 0, 255, jnp.uint8),
            jax.random.randint(ka, (lanes,), 0, A, jnp.int32),
            jax.random.normal(kr, (lanes,)),
            jax.random.bernoulli(kt, 0.005, (lanes,)),
            jnp.zeros((lanes,), bool),
            jax.random.uniform(kp, (lanes,)) + 0.05,
        )
        return ds, None

    @functools.partial(jax.jit, donate_argnums=0)
    def prefill(ds, key):
        keys = jax.random.split(key, seg)
        ds, _ = jax.lax.scan(prefill_tick, ds, keys)
        return ds

    ds0 = prefill(replay.init_state(), jax.random.PRNGKey(7))
    jax.block_until_ready(ds0.priority)
    emit(phase="prefill", frames=lanes * seg, left_s=round(left(), 1))

    for b, groups in BATCHES:
        label = f"{b}x{groups}" if groups > 1 else str(b)
        if left() < 90:
            emit(phase="scale", batch=label, skipped="budget exhausted")
            continue
        cfg = base.replace(batch_size=b, sample_groups=groups)
        ts = init_train_state(cfg, A, jax.random.PRNGKey(0))
        fused = build_device_learn(cfg, A, replay)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def segment(ts, ds, key, fused=fused):
            # ds rides the scan carry so the priority write-back stays live
            # (dropping it would let XLA DCE update_priorities out of the
            # measurement).  ds0 itself is NOT donated — every batch point
            # reuses the same warm ring; the one ring copy this costs per
            # segment call amortises to microseconds/step.
            def tick(carry, k):
                ts, ds = carry
                ts, ds, info = fused(ts, ds, k, jnp.float32(0.5))
                return (ts, ds), info["loss"]

            (ts, _ds), losses = jax.lax.scan(
                tick, (ts, ds), jax.random.split(key, SCAN)
            )
            return ts, losses[-1]
        flops = None
        try:
            lowered = jax.jit(fused).lower(
                ts, ds0, jax.random.PRNGKey(1), jnp.float32(0.5)
            )
            cost = lowered.compile().cost_analysis()
            if cost:
                c0 = cost[0] if isinstance(cost, (list, tuple)) else cost
                flops = float(c0.get("flops", 0.0)) or None
        except Exception as e:  # noqa: BLE001 — cost analysis is best-effort
            emit(phase="cost_analysis", batch=label, error=repr(e)[:120])

        key = jax.random.PRNGKey(2)
        key, k = jax.random.split(key)
        ts, last = segment(ts, ds0, k)
        jax.block_until_ready(last)
        if left() < 30:
            emit(phase="scale", batch=label,
                 skipped="budget exhausted post-compile")
            continue
        n_seg = 0
        t0 = time.perf_counter()
        while n_seg < 6 and (n_seg < 1 or left() > 30):
            key, k = jax.random.split(key)
            ts, last = segment(ts, ds0, k)
            jax.block_until_ready(last)
            n_seg += 1
        dt = time.perf_counter() - t0
        sps = n_seg * SCAN / dt
        row = {
            "phase": "scale",
            "batch": label,
            "steps_per_sec": round(sps, 2),
            "samples_per_sec": round(sps * b * groups, 1),
            "ms_per_step": round(1e3 / sps, 3),
            "platform": platform,
        }
        if flops:
            row["flops_per_step"] = flops
            row["mfu"] = round(flops * sps / PEAK_FLOPS, 5)
        emit(**row)

    emit(phase="done", elapsed_s=round(time.monotonic() - T0, 1))


if __name__ == "__main__":
    main()
