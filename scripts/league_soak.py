#!/usr/bin/env python
"""league_soak: drive a REAL 2-member PBT population end to end and assert
the exploit/explore loop from its own JSONL (docs/LEAGUE.md).

    python scripts/league_soak.py --out /tmp/league --t-max 4096
    python scripts/league_soak.py --members 2 --json

Topology (the chaos_soak shape, with REAL trainers as the children):

    parent = LeagueController (jax-free)        member children (one per
      RoleSupervisor (respawn keeps member id)    member id, REAL train()
      fitness from tailed eval rows        <---   loops on toy:catch with
      forced truncation exploit sweep             league wiring live)
      winner outbox chain --copy--> loser inbox + directive
                                           --->  drain-boundary adoption
                                                 (digest-asserted)

Each member child runs the genuine single-process training loop
(`rainbow_iqn_apex_tpu.train.train`) at toy scale with
``league_member_id``/``league_dir`` set: genome overlay at loop start,
int8-delta outbox publishes at the weight-publish cadence, exploit
directive polls at drain boundaries, live lr/n-step/omega adoption — the
exact code path a real league member runs, not a mock.

The harness asserts (exit 0 only if ALL hold):
  * >= 1 exploit event fired (forced once both members have fitness);
  * the loser's adoption is BIT-EXACT: its `league` adopt row's digest
    equals the directive digest the controller computed from the winner's
    published outbox reconstruction;
  * the loser's adopted genome differs from the winner's (explore really
    perturbed it);
  * member leases in league_dir/heartbeats carried member/generation
    payloads (the lease contract, parallel/elastic.py);
  * a final `league` status row exists and the population never collapsed;
  * every JSONL under the league dir lints against the obs/ schema.

`make league-smoke` runs this after the league-marked tier-1 tests.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


# ---------------------------------------------------------------- member child
def member_main(args) -> int:
    """One REAL league member: the single-process train loop at toy scale."""
    from rainbow_iqn_apex_tpu.config import Config
    from rainbow_iqn_apex_tpu.train import train

    mdir = os.path.join(args.dir, f"m{args.member_id}")
    cfg = Config(
        run_id=f"member{args.member_id}",
        seed=args.seed + 31 * args.member_id,
        results_dir=os.path.join(mdir, "results"),
        checkpoint_dir=os.path.join(mdir, "ckpt"),
        env_id="toy:catch",
        compute_dtype="float32",
        history_length=2,
        frame_height=10, frame_width=10,  # toy:catch defines its own shape
        hidden_size=32, num_cosines=8,
        num_tau_samples=4, num_tau_prime_samples=4, num_quantile_samples=4,
        batch_size=16, learning_rate=1e-3, multi_step=3, gamma=0.9,
        memory_capacity=4096, learn_start=256, frames_per_learn=2,
        target_update_period=200, num_envs_per_actor=8,
        metrics_interval=50, eval_interval=args.eval_interval,
        checkpoint_interval=0, guard_snapshot_interval=500,
        eval_episodes=2, t_max=args.t_max,
        weight_publish_interval=args.publish_interval,
        heartbeat_interval_s=0.2,
        league_dir=args.dir,
        league_member_id=args.member_id,
    )
    train(cfg)
    return 0


# ------------------------------------------------------------------ controller
def soak_main(args) -> int:
    from rainbow_iqn_apex_tpu.config import Config
    from rainbow_iqn_apex_tpu.league.controller import LeagueController
    from rainbow_iqn_apex_tpu.league.member import EPOCH_ENV
    from rainbow_iqn_apex_tpu.obs.health import RunHealth
    from rainbow_iqn_apex_tpu.obs.registry import MetricRegistry
    from rainbow_iqn_apex_tpu.parallel.elastic import HeartbeatMonitor
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    league_dir = os.path.abspath(args.out)
    os.makedirs(league_dir, exist_ok=True)
    cfg = Config(
        run_id=f"league_{args.seed}",
        seed=args.seed,
        # the controller's config is the population's BASELINE genome
        # (member 0 keeps it; the rest perturb around it) — match the
        # members' toy-scale tuning, not the Atari defaults
        learning_rate=1e-3, multi_step=3, priority_exponent=0.5,
        league_dir=league_dir,
        league_population=args.members,
        league_fitness_window=2,
        league_exploit_interval_s=1e9,  # sweeps fire only when FORCED —
        # the soak's one exploit event is deterministic, not timer-raced
        league_bottom_quantile=0.5,
        league_top_quantile=0.5,
        league_perturb_factor=1.3,
        league_resample_prob=0.0,  # the perturbed-not-equal gate must not
        # depend on which explore branch the rng took
    )
    metrics = MetricsLogger(
        os.path.join(league_dir, "controller", "metrics.jsonl"),
        run_id=cfg.run_id, echo=not args.quiet, host=0)
    registry = MetricRegistry()
    health = RunHealth(registry, metrics, role="league")
    metrics.add_observer(health.observe_row)

    def spawn_member(member_id: int, epoch: int):
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        env[EPOCH_ENV] = str(epoch)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the sandbox's axon sitecustomize would block `import jax` on a
        # TPU tunnel; the soak exercises league plumbing, not accelerators
        env.pop("PALLAS_AXON_POOL_IPS", None)
        argv = [
            sys.executable, os.path.abspath(__file__), "--member",
            "--member-id", str(member_id), "--dir", league_dir,
            "--seed", str(args.seed), "--t-max", str(args.t_max),
            "--eval-interval", str(args.eval_interval),
            "--publish-interval", str(args.publish_interval),
        ]
        log = open(os.path.join(
            league_dir, f"member{member_id}_e{epoch}.log"), "ab")
        return subprocess.Popen(argv, env=env, stdout=log,
                                stderr=subprocess.STDOUT)

    ctl = LeagueController(cfg, spawn_member, metrics=metrics,
                           registry=registry)
    monitor = HeartbeatMonitor(
        os.path.join(league_dir, "heartbeats"), timeout_s=5.0)

    exploits: list = []
    lease_with_member = False
    deadline = time.monotonic() + args.deadline_s
    step = 0
    last_status = {}
    try:
        while time.monotonic() < deadline:
            step += 1
            ctl.poll(step=step)
            for lease in monitor.leases().values():
                if lease.member is not None and lease.generation >= 0:
                    lease_with_member = True
            scored = [m for m in ctl.alive_members()
                      if ctl.fitness.fitness(m) is not None]
            if not exploits and len(scored) >= 2:
                # both members measured: force the one seeded exploit
                # sweep (re-forced next tick if a publish race skipped it)
                exploits = ctl.force_sweep(step=step)
            if step % 20 == 0:
                last_status = ctl.status_row(step=step)
                health.tick(step)
            if exploits and _adoptions(league_dir):
                break  # story complete: exploit fired AND the loser adopted
            time.sleep(args.tick_s)
        last_status = ctl.status_row(step=step)
        health.tick(step + 1)
    finally:
        ctl.stop_all()
        metrics.close()

    # ----------------------------------------------------- harness assertions
    failures = []
    if not exploits:
        failures.append("no exploit event fired before the deadline")
    adopts = _adoptions(league_dir)
    if not adopts:
        failures.append("no member ever adopted (no `league` adopt row)")
    for directive in exploits:
        loser = directive["member"]
        match = [a for a in adopts if a.get("member") == loser
                 and a.get("generation") == directive["generation"]]
        if not match:
            failures.append(
                f"member m{loser} never adopted generation "
                f"{directive['generation']}")
            continue
        adopt = match[0]
        if adopt.get("digest") != directive["digest"]:
            failures.append(
                f"m{loser} adoption digest {adopt.get('digest')!r} != "
                f"directive {directive['digest']!r} — the bit-exact copy "
                "contract broke")
        winner_genome = last_status.get("members", {}).get(
            str(directive["source"]), {})
        if (directive["genome"].get("learning_rate")
                == winner_genome.get("lr")):
            failures.append(
                f"m{loser}'s adopted genome kept the source's learning "
                "rate — explore never perturbed it")
    if not lease_with_member:
        failures.append("no member lease carried member/generation payload")
    if not last_status.get("members"):
        failures.append("no final league status row")
    if last_status.get("collapsed"):
        failures.append("population collapsed")

    # every JSONL under the league dir must lint against the obs schema
    from scripts.lint_jsonl import lint_file  # noqa: E402

    lint_errors = []
    for path in sorted(glob.glob(os.path.join(league_dir, "**", "*.jsonl"),
                                 recursive=True)):
        lint_errors += lint_file(path)
    if lint_errors:
        failures.append(f"lint errors: {lint_errors[:5]}")

    summary = {
        "ok": not failures,
        "exploits": len(exploits),
        "adoptions": len(adopts),
        "members": {k: {"fitness": v.get("fitness"),
                        "generation": v.get("generation"),
                        "restarts": v.get("restarts")}
                    for k, v in (last_status.get("members") or {}).items()},
        "failures": failures,
    }
    with open(os.path.join(league_dir, "soak_summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2) if args.json else (
        f"league_soak: {'OK' if summary['ok'] else 'FAILED'} "
        f"exploits={summary['exploits']} adoptions={summary['adoptions']}"
        + "".join(f"\n  FAIL {f}" for f in failures)))
    return 0 if summary["ok"] else 1


def _adoptions(league_dir: str) -> list:
    """Every `league` adopt row any member has written so far."""
    out = []
    for path in glob.glob(os.path.join(league_dir, "m*", "**", "*.jsonl"),
                          recursive=True):
        try:
            with open(path) as f:
                for line in f:
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    if (row.get("kind") == "league"
                            and row.get("event") == "adopt"):
                        out.append(row)
        except OSError:
            continue
    return out


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--members", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="/tmp/ria_league_soak")
    ap.add_argument("--t-max", type=int, default=6144,
                    help="env frames per member trainer (toy scale)")
    ap.add_argument("--eval-interval", type=int, default=150)
    ap.add_argument("--publish-interval", type=int, default=100)
    ap.add_argument("--deadline-s", type=float, default=300.0)
    ap.add_argument("--tick-s", type=float, default=0.25)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--quiet", action="store_true")
    # internal: member-child mode
    ap.add_argument("--member", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--member-id", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--dir", help=argparse.SUPPRESS)
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.member:
        return member_main(args)
    return soak_main(args)


if __name__ == "__main__":
    raise SystemExit(main())
