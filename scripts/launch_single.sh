#!/usr/bin/env bash
# Single-process Rainbow-IQN (reference parity: the 1-actor no-Ape-X mode).
set -euo pipefail
GAME="${1:-Pong}"
exec python train_agent_apex.py --role single --env-id "atari:${GAME}" "${@:2}"
