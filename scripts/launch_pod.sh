#!/usr/bin/env bash
# Multi-host Ape-X launch (parity: the reference's multi-machine story is
# "start redis-server(s), point remote actor processes at them" — SURVEY.md
# §2 rows 6-7. Here every pod host runs the SAME SPMD command and
# jax.distributed is the fabric; see docs/RUNBOOK.md "Multi-host Ape-X").
#
# Usage, once per host (same command, different HOST_INDEX):
#   HOST_INDEX=0 HOST_COUNT=4 COORDINATOR=host0:12355 \
#     scripts/launch_pod.sh Pong run0 [extra flags...]
#
# On TPU pods launched through the pod runtime, COORDINATOR/HOST_* can be
# omitted and jax.distributed infers them; this script targets manual
# clusters (the direct heir of the reference's redis host/port flags).
set -euo pipefail

GAME="${1:-Pong}"
# RUN_ID must be IDENTICAL on every host (Orbax saves are collective over a
# shared checkpoint dir), so a per-host timestamp default would tear the
# checkpoint — it is required, like the topology vars.
RUN_ID="${2:?pass a run id (same value on every host)}"
: "${HOST_INDEX:?set HOST_INDEX (this hosts id in [0, HOST_COUNT))}"
: "${HOST_COUNT:?set HOST_COUNT (number of pod hosts)}"
: "${COORDINATOR:?set COORDINATOR (host0:port of process 0)}"

exec python train_agent_apex.py \
  --role apex \
  --env-id "atari:${GAME}" \
  --run-id "${RUN_ID}" \
  --process-count "${HOST_COUNT}" \
  --process-id "${HOST_INDEX}" \
  --coordinator-address "${COORDINATOR}" \
  --learner-devices 0 \
  --num-actors 4 --num-envs-per-actor 16 \
  --replay-shards "${HOST_COUNT}" \
  --t-max 200000000 \
  "${@:3}"
