#!/usr/bin/env python
"""Per-level generalization re-eval of an EXISTING variant checkpoint
(VERDICT r4 item 4, for checkpoints that predate the per_level block).

Evaluates the checkpoint with lanes pinned to each of the 16 train levels
and to --levels held-out levels (ids 16..16+levels-1), then writes/updates
results/jaxsuite/generalization_levels.json with per-level means,
across-level spread, and the level-bootstrap gap-sign stability — keyed by
game, with explicit checkpoint provenance (run id + step), because the
re-evaluated checkpoint may not be the one behind the committed two-pool
row in generalization.json.

Example (the round-3 16.4k-frame variant checkpoints):
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu PYTHONPATH=/root/repo \
  python scripts/eval_gen_levels.py --game freeway --run-id jaxsuite_freeway_var \
    --checkpoint-dir results/jaxsuite/ckpt -- \
    --role anakin --history-length 2 --compute-dtype float32
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--game", required=True,
                    help="base game name (must have a seeded-variant mode)")
    ap.add_argument("--run-id", required=True)
    ap.add_argument("--checkpoint-dir", default="results/jaxsuite/ckpt")
    ap.add_argument("--levels", type=int, default=64)
    ap.add_argument("--eps-per-level", type=int, default=8)
    ap.add_argument("--out", default="results/jaxsuite/generalization_levels.json")
    args, passthrough = ap.parse_known_args()
    if passthrough and passthrough[0] == "--":
        passthrough = passthrough[1:]

    from rainbow_iqn_apex_tpu.envs.device_games import N_TRAIN_LEVELS
    from rainbow_iqn_apex_tpu.jaxsuite import (
        eval_checkpoint_per_level,
        per_level_fields,
    )
    from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer

    base_args = [*passthrough, "--checkpoint-dir", args.checkpoint_dir]
    step = Checkpointer(
        os.path.join(args.checkpoint_dir, args.run_id)).latest_step()
    # one call over both pools = one compile + one checkpoint restore
    all_pl = eval_checkpoint_per_level(
        base_args, args.run_id, args.game,
        range(N_TRAIN_LEVELS + args.levels), args.eps_per_level)
    train_pl, held_pl = all_pl[:N_TRAIN_LEVELS], all_pl[N_TRAIN_LEVELS:]
    row = {
        "checkpoint": {"run_id": args.run_id, "step": step,
                       "dir": args.checkpoint_dir},
        **per_level_fields(train_pl, held_pl, N_TRAIN_LEVELS),
    }
    data = {}
    if os.path.exists(args.out):
        with open(args.out) as f:
            data = json.load(f)
    data[args.game] = row
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(data, f, indent=2)
    print(json.dumps({args.game: {k: row[k] for k in
                                  ("train_mean", "heldout_mean", "gap",
                                   "gap_boot_frac_positive",
                                   "gap_boot_ci90")}}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
