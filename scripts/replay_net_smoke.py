#!/usr/bin/env python
"""replay_net_smoke: the cross-host replay plane proven end to end,
multi-process (`make replaynet-smoke`; docs/RESILIENCE.md "replay plane").

Topology — every hop a REAL socket, every role a real process:

    parent:   the learner — a RemoteReplayPlane discovering the shard
              servers purely from lease files, pipelining SampleClient
              batches, writing priorities back, requesting a server-side
              snapshot fenced by its own step
    children: 2 replay shard servers (each owning one ShardedReplay shard
              block, advertising addr:port + shard range + epoch through
              its lease) and 2 actor hosts (each a RemoteReplayPlane in
              append-only mode, spooling lockstep lane ticks)

Mid-load one shard server is SIGKILLed cold — no goodbye frame,
connections drop, its lease expires — and later respawned at the SAME
shard base: `next_lease_epoch` hands the incarnation a bumped epoch, the
server restores its own snapshot, and the plane readmits it epoch-fenced.

Self-asserted gates (exit 1 on any failure):

  1. the learner and both actors discovered both servers via leases alone;
  2. the learner NEVER stalls: no `get()` timeout, and the worst
     inter-batch gap stays bounded straight through the kill
     (survivors-only full batches);
  3. ZERO appended-and-acked transitions lost on survivors: the surviving
     server's wire-reported ``rows_appended`` covers every row the actors
     counted as acked to it (at-least-once append: re-spooled blocks may
     duplicate, never vanish);
  4. readmit restores sampling from the REVIVED incarnation: post-respawn
     batches draw global indices from the victim's shard range again;
  5. the pre-kill server-side snapshot was acked by every server (the
     learner-step fence exercised over the wire);
  6. the run dir lints as strict schema-versioned JSONL (replay_net rows
     included — the Makefile runs lint_jsonl after us).

Usage:
    JAX_PLATFORMS=cpu python scripts/replay_net_smoke.py \\
        --duration 12 --out /tmp/ria_replaynet_smoke
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

# CPU smoke tool: strip the remote-TPU plugin trigger before any imports
# (the net_smoke.py convention; children inherit the sanitised env).
if os.environ.get("JAX_PLATFORMS", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

RUN_ID = "replay_net_smoke"
FRAME = (12, 12)
SERVERS = 2          # one shard block each
LANES_PER_SHARD = 2  # actor lanes_total = SERVERS * LANES_PER_SHARD
CAPACITY = 2048      # per server (== per shard: 1 shard per server)


def row(**fields):
    print(json.dumps(fields), flush=True)


def smoke_cfg(out_dir, process_id, seed=0):
    from rainbow_iqn_apex_tpu.config import Config

    return Config(
        run_id=RUN_ID, seed=seed, results_dir=out_dir,
        process_id=process_id,
        replay_shards=SERVERS,       # global shard blocks == servers here
        heartbeat_timeout_s=1.5,     # fast lease expiry for the soak
        replay_net_remote=True,
    )


def _lanes_total() -> int:
    return SERVERS * LANES_PER_SHARD


def _stop_event_for_child():
    """SIGTERM -> clean stop; orphaned (parent died) -> stop too."""
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    return stop


# ------------------------------------------------------------- server child
def server_child(args) -> int:
    """One replay shard server: ShardedReplay block + ReplayShardServer +
    lease with addr:port/shard range/epoch.  `next_lease_epoch` claims the
    incarnation epoch, so a respawn of the same server id automatically
    registers with a bumped epoch (the fence stale clients trip).  The
    snapshot prefix is stable per server id: a respawned incarnation
    restores what its predecessor snapshotted, fenced by the learner step
    recorded alongside."""
    from rainbow_iqn_apex_tpu.parallel.elastic import (
        HeartbeatWriter,
        next_lease_epoch,
    )
    from rainbow_iqn_apex_tpu.parallel.sharded_replay import ShardedReplay
    from rainbow_iqn_apex_tpu.replay.net.server import ReplayShardServer
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    sid = args.server_id
    epoch = next_lease_epoch(args.hb_dir, sid)
    memory = ShardedReplay.build(
        1, CAPACITY, LANES_PER_SHARD, frame_shape=FRAME, history=2,
        n_step=3, gamma=0.9, seed=args.seed + 100 * sid)
    logger = MetricsLogger(
        os.path.join(args.out, f"server{sid}.e{epoch}.jsonl"),
        run_id=RUN_ID, echo=False, host=sid)
    srv = ReplayShardServer(
        memory, shard_base=args.shard_base, host="127.0.0.1", port=0,
        epoch=epoch,
        snapshot_prefix=os.path.join(args.out, f"replay_shard{sid}"),
        logger=logger).start()
    writer = HeartbeatWriter(args.hb_dir, sid, interval_s=0.25,
                             role="replay_shard", shard=args.shard_base,
                             epoch=epoch)
    srv.attach_lease(writer)  # addr:port + shard range BEFORE the first beat
    writer.start()

    stop = _stop_event_for_child()
    ppid = os.getppid()
    while not stop.is_set():
        if os.getppid() != ppid:  # orphaned: the parent died, so should we
            break
        stop.wait(0.2)
    writer.stop()
    srv.stop()
    logger.close()
    return 0


# -------------------------------------------------------------- actor child
def actor_child(args) -> int:
    """One actor host: a RemoteReplayPlane in append-only mode spooling
    lockstep lane ticks across both servers.  `poll()` drives its own
    discovery/readmit lifecycle, so appends to the killed server spool
    locally and land on the revived incarnation.  On SIGTERM it flushes
    every appender and writes its acked-rows accounting for the parent's
    zero-loss gate."""
    import numpy as np

    from rainbow_iqn_apex_tpu.replay.net.plane import RemoteReplayPlane
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    aid = args.actor_id
    cfg = smoke_cfg(args.out, process_id=10 + aid, seed=args.seed)
    logger = MetricsLogger(os.path.join(args.out, f"actor{aid}.jsonl"),
                           run_id=RUN_ID, echo=False, host=10 + aid)
    plane = RemoteReplayPlane(cfg, _lanes_total(), metrics=logger)
    rng = np.random.default_rng(args.seed + 7 * aid)
    stop = _stop_event_for_child()
    ppid = os.getppid()

    # wait for both servers' leases before appending (bounded): appends to
    # an undiscovered owner shed by design, but a cold-start shed storm
    # would only add noise to the loss accounting
    deadline = time.monotonic() + args.boot_timeout
    while (len(plane.peers) < SERVERS and not stop.is_set()
           and time.monotonic() < deadline):
        plane.poll(0)
        time.sleep(0.1)

    lanes = _lanes_total()
    tick = 0
    while not stop.is_set():
        if os.getppid() != ppid:
            break
        rewards = rng.normal(size=lanes).astype(np.float32)
        plane.append_batch(
            rng.integers(0, 255, (lanes, *FRAME), dtype=np.uint8),
            rng.integers(0, 4, lanes),
            rewards,
            rng.random(lanes) < 0.02,
            priorities=np.abs(rewards) + 0.05,
        )
        tick += 1
        if tick % 50 == 0:
            plane.poll(tick)  # lease edges: drop / epoch-fenced readmit
        time.sleep(0.004)

    # drain, then account: acked_rows per server is the parent's zero-loss
    # ledger (only rows the server ACKED count — shed/spooled don't)
    for ac in plane._appenders.values():
        ac.flush(timeout_s=10.0)
    stats = {
        "actor": aid,
        "ticks": tick,
        "shed_lanes": plane.shed_lanes,
        "acked_by_server": {
            str(pid): ac.acked_rows for pid, ac in plane._appenders.items()
        },
        "fenced_by_server": {
            str(pid): ac.fenced_rows for pid, ac in plane._appenders.items()
        },
        "shed_ticks": sum(ac.shed_ticks for ac in plane._appenders.values()),
    }
    path = os.path.join(args.out, f"actor{aid}_stats.json")
    with open(path + ".tmp", "w") as f:
        json.dump(stats, f)
    os.replace(path + ".tmp", path)
    plane.close()
    logger.close()
    return 0


# ------------------------------------------------------------------ parent
def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--duration", type=float, default=12.0,
                    help="seconds of sampling load (kill + respawn inside)")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--kill-frac", type=float, default=0.4,
                    help="fraction of --duration at which a server is killed")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--boot-timeout", type=float, default=120.0)
    ap.add_argument("--stall-bound", type=float, default=10.0,
                    help="max tolerated gap between batches, seconds")
    ap.add_argument("--out", default="/tmp/ria_replaynet_smoke")
    # internal: child modes
    ap.add_argument("--server-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--actor-child", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--server-id", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--shard-base", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--actor-id", type=int, default=0,
                    help=argparse.SUPPRESS)
    ap.add_argument("--hb-dir", default="", help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.server_child:
        return server_child(args)
    if args.actor_child:
        return actor_child(args)

    import numpy as np

    from rainbow_iqn_apex_tpu.replay.net.plane import RemoteReplayPlane
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    out = args.out
    os.makedirs(out, exist_ok=True)
    hb_dir = os.path.join(out, RUN_ID, "heartbeats")
    row(event="replay_net_smoke_start", servers=SERVERS, actors=2,
        duration_s=args.duration, out=out)

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)

    def spawn_server(sid):
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--server-child",
             "--server-id", str(sid), "--shard-base", str(sid - 1),
             "--hb-dir", hb_dir, "--out", out, "--seed", str(args.seed),
             "--boot-timeout", str(args.boot_timeout)],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)

    def spawn_actor(aid):
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--actor-child",
             "--actor-id", str(aid), "--hb-dir", hb_dir, "--out", out,
             "--seed", str(args.seed),
             "--boot-timeout", str(args.boot_timeout)],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)

    servers = {sid: spawn_server(sid) for sid in range(1, SERVERS + 1)}
    actors = {aid: spawn_actor(aid) for aid in range(1, 3)}

    def teardown(rc):
        for proc in list(servers.values()) + list(actors.values()):
            if proc.poll() is None:
                proc.kill()
        return rc

    # ---- the learner: discovery via leases alone, then pipelined sampling
    cfg = smoke_cfg(out, process_id=0, seed=args.seed)
    metrics = MetricsLogger(os.path.join(out, "learner.jsonl"),
                            run_id=RUN_ID, echo=False, host=0)
    plane = RemoteReplayPlane(cfg, _lanes_total(), metrics=metrics)
    warm_rows = 4 * args.batch * SERVERS
    deadline = time.monotonic() + args.boot_timeout
    while time.monotonic() < deadline:
        plane.poll(0)
        if (len(plane.peers) == SERVERS and plane.size() >= warm_rows
                and plane.sampleable()):
            break
        time.sleep(0.2)
    discovered_peers = len(plane.peers)
    row(event="replay_discovered", peers=discovered_peers,
        rows=plane.size())
    if discovered_peers != SERVERS or plane.size() < warm_rows:
        row(path="replay_net_smoke", status="error",
            error=f"boot incomplete: peers={len(plane.peers)} "
                  f"rows={plane.size()}")
        return teardown(1)

    sc = plane.start_sampling(args.batch, lambda: 0.5)
    victim = 1  # owns shard_base 0: global slots [0, CAPACITY)
    victim_lo, victim_hi = 0, CAPACITY

    t0 = time.monotonic()
    kill_at = t0 + args.duration * args.kill_frac
    snapshot_at = t0 + args.duration * 0.25
    hard_stop = t0 + args.duration * 4 + 60.0
    killed = respawned = False
    snapshot_acked = -1
    readmit_seen = revived_seen = False
    batches = 0
    timeouts = 0
    max_gap = 0.0
    last_batch = time.monotonic()
    kill_time = respawn_time = 0.0
    step = 0

    while True:
        now = time.monotonic()
        if now >= hard_stop:
            break
        if now >= t0 + args.duration and revived_seen:
            break
        step += 1
        try:
            s = sc.get(timeout=args.stall_bound * 2)
        except TimeoutError:
            timeouts += 1
            row(event="learner_get_timeout", at_s=round(now - t0, 2))
            continue
        got = time.monotonic()
        max_gap = max(max_gap, got - last_batch)
        last_batch = got
        batches += 1
        if (respawned and readmit_seen and not revived_seen
                and bool(np.any((s.idx >= victim_lo) & (s.idx < victim_hi)))):
            revived_seen = True
            row(event="revived_range_sampled", at_s=round(got - t0, 2),
                after_respawn_s=round(got - respawn_time, 2))
        sc.update_priorities(s.idx, np.abs(s.reward) + 0.01)
        if batches % 32 == 0:
            plane.flush_writebacks()
        plane.poll(step)
        if snapshot_acked < 0 and now >= snapshot_at:
            snapshot_acked = plane.request_snapshot(step)
            row(event="snapshot_requested", acked=snapshot_acked, step=step)
        if not killed and now >= kill_at:
            servers[victim].kill()  # SIGKILL: no goodbye frame, no drain
            killed = True
            kill_time = now
            row(event="server_killed", server=victim,
                at_s=round(now - t0, 2))
        if (killed and not respawned
                and (victim in sc.dead_peers()
                     or now >= kill_time + 6.0)):
            servers[victim] = spawn_server(victim)
            respawned = True
            respawn_time = time.monotonic()
            row(event="server_respawned", server=victim,
                dropped_first=victim in sc.dead_peers(),
                at_s=round(respawn_time - t0, 2))
        if respawned and not readmit_seen and victim not in sc.dead_peers():
            readmit_seen = True
            row(event="server_readmitted", server=victim,
                at_s=round(time.monotonic() - t0, 2))
        time.sleep(0.005)
    wall_s = time.monotonic() - t0
    plane.flush_writebacks()

    # ---- actors drain + write their acked ledgers ------------------------
    for proc in actors.values():
        if proc.poll() is None:
            proc.terminate()
    actor_stats = []
    for aid, proc in actors.items():
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
        path = os.path.join(out, f"actor{aid}_stats.json")
        try:
            with open(path) as f:
                actor_stats.append(json.load(f))
        except OSError:
            row(event="actor_stats_missing", actor=aid)

    # ---- the zero-loss ledger: survivor's landed rows vs actors' acks ----
    survivor = next(sid for sid in servers if sid != victim)
    acked_to_survivor = sum(
        int(s["acked_by_server"].get(str(survivor), 0)) for s in actor_stats)
    survivor_rows = -1
    try:
        hdr, _ = plane.peers[survivor].request({"op": "stats"}, timeout_s=10)
        survivor_rows = int(hdr.get("rows_appended", -1))
    except Exception as e:
        row(event="survivor_stats_failed", error=f"{type(e).__name__}: {e}")
    row(event="loss_ledger", survivor=survivor,
        survivor_rows_appended=survivor_rows,
        acked_to_survivor=acked_to_survivor)

    # ---- teardown ---------------------------------------------------------
    for proc in servers.values():
        if proc.poll() is None:
            proc.terminate()
    for proc in servers.values():
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
    plane.close()
    metrics.close()

    gates = {
        "discovered_all": discovered_peers == SERVERS
        and len(actor_stats) == 2
        and all(len(s["acked_by_server"]) == SERVERS for s in actor_stats),
        "learner_never_stalled": timeouts == 0
        and max_gap < args.stall_bound,
        "zero_lost_acked": acked_to_survivor > 0
        and survivor_rows >= acked_to_survivor,
        "readmit_restored": readmit_seen and revived_seen,
        "snapshot_acked_all": snapshot_acked == SERVERS,
    }
    result = {
        "path": "replay_net_smoke",
        "metric": "replay_net_smoke_batches_per_sec",
        "value": round(batches / max(wall_s, 1e-9), 1),
        "unit": "batches/s",
        "wall_s": round(wall_s, 2),
        "batches": batches,
        "rows_sampled": sc.rows_sampled,
        "updates_sent": sc.updates_sent,
        "rerouted": sc.rerouted,
        "max_gap_s": round(max_gap, 3),
        "get_timeouts": timeouts,
        "survivor_rows_appended": survivor_rows,
        "acked_to_survivor": acked_to_survivor,
        "snapshot_acked": snapshot_acked,
        "gates": gates,
    }
    if not all(gates.values()):
        result["status"] = "gate_failed"
        row(**result)
        return 1
    row(**result)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
