#!/usr/bin/env python
"""Run the pure-JAX game benchmark suite end-to-end.

Trains each game via the training CLI with the flags you pass through,
evals, measures random/scripted baselines on device, and writes
results/jaxsuite/{per_game.csv, aggregate.json}.

Example (CPU sandbox, short budget):
  python scripts/run_jaxsuite.py --games catch breakout -- \
    --role anakin --t-max 8000 --learn-start 512 --frames-per-learn 2 \
    --history-length 2 --gamma 0.9 --memory-capacity 8192 \
    --learning-rate 1e-3 --target-update-period 200 \
    --compute-dtype float32 --eval-episodes 40

Everything after `--` goes verbatim to train_agent_apex.py.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rainbow_iqn_apex_tpu.atari57 import sanitize_sweep_parent_env  # noqa: E402

# MUST run before anything imports jax: against the single-claim TPU relay
# the sweep PARENT may never initialize the device backend — a parent-held
# claim starves every trainer child forever (observed live 2026-07-31: the
# first on-chip sweep attempt wedged in backend init before its first child
# spawned).  The parent re-execs itself pinned to CPU and stashes the device
# env, which train_one_game restores for each child — children train+eval on
# device one at a time, each releasing the claim at exit; the parent does
# baselines/salvage math on CPU.
sanitize_sweep_parent_env()

from rainbow_iqn_apex_tpu.jaxsuite import JAXSUITE, run_sweep  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--games", nargs="*", default=None, choices=JAXSUITE,
                    help="subset of games (default: all)")
    ap.add_argument("--results-dir", default="results/jaxsuite")
    ap.add_argument("--baseline-episodes", type=int, default=64)
    ap.add_argument("--generalization", action="store_true",
                    help="instead of the score sweep, run the seeded-variant "
                         "train/held-out level split (writes "
                         "generalization.json)")
    ap.add_argument("--levels-eval", type=int, default=64,
                    help="generalization mode: per-level eval over this many "
                         "held-out levels (0 disables the per_level block)")
    ap.add_argument("--eps-per-level", type=int, default=8,
                    help="episodes per pinned level in the per-level eval")
    ap.add_argument("--note", default=None,
                    help="free-text caveat emitted into aggregate.json by the "
                         "writer itself (survives reruns)")
    ap.add_argument("--resume-rows", action="store_true",
                    help="score sweep: seed per_game.csv/aggregate.json from "
                         "the existing rows of games NOT in --games, so "
                         "rerunning a killed sweep's unfinished games keeps "
                         "the finished games' committed rows")
    ap.add_argument("--per-game-t-max", nargs="*", default=[],
                    metavar="GAME=FRAMES",
                    help="per-game --t-max override, e.g. breakout=65536 "
                         "(slow-to-learn games get a bigger budget than the "
                         "shared flags)")
    args, passthrough = ap.parse_known_args()
    if passthrough and passthrough[0] == "--":
        passthrough = passthrough[1:]
    per_game_args = {}
    for spec in args.per_game_t_max:
        game, _, frames = spec.partition("=")
        if not frames.isdigit():
            ap.error(f"--per-game-t-max wants GAME=FRAMES, got {spec!r}")
        if game not in JAXSUITE:
            # fail fast: a typo'd name would otherwise silently train the
            # game at the shared budget for hours (overrides are keyed by
            # BASE name in both modes — no '@var' suffix)
            ap.error(f"--per-game-t-max: unknown game {game!r} "
                     f"(have: {', '.join(JAXSUITE)})")
        per_game_args[game] = ["--t-max", frames]
    if args.generalization:
        from rainbow_iqn_apex_tpu.jaxsuite import run_generalization

        out = run_generalization(passthrough, games=args.games,
                                 results_dir=args.results_dir,
                                 episodes=args.baseline_episodes,
                                 per_game_args=per_game_args, note=args.note,
                                 levels_eval=args.levels_eval,
                                 episodes_per_level=args.eps_per_level)
        print(json.dumps(out))
        return 0
    agg = run_sweep(passthrough, games=args.games,
                    results_dir=args.results_dir,
                    baseline_episodes=args.baseline_episodes,
                    per_game_args=per_game_args, note=args.note,
                    resume_rows=args.resume_rows)
    print(json.dumps(agg))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
