"""Benchmark suite over the pure-JAX game family (envs/device_games.py).

Role: the runnable counterpart of the Atari-57 harness (atari57.py).  The
reference's headline benchmark needs ALE + ROMs, absent in this sandbox
(SURVEY.md §7); this suite gives the framework a benchmark it can actually
execute anywhere: same sweep driver shape, same CSV/aggregate outputs, same
normalisation math — but with baselines that are MEASURED, not recalled:

- random baseline: the measured mean return of a uniform-random policy;
- scripted reference: the measured mean return of a hand-written competent
  policy (state-based, defined per game where one is sensible).

normalized = (score - random) / (scripted - random) — "1.0 plays like the
script, 0.0 plays like noise" — so nothing in the aggregate rests on an
unverifiable constant (contrast atari57.HUMAN_WORLD_RECORDS, which stays
RECON-gated).  Baselines are computed on demand by vmapped device rollouts
of the same in-graph step the trainers use.
"""

from __future__ import annotations

import json
import os
from statistics import median as _median
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.envs.device_games import (
    GAMES,
    build_rollout,
    make_device_game,
    tick_budget,
)

JAXSUITE = sorted(GAMES)


# ---------------------------------------------------------------- policies


def _p_random(game):
    def policy(state, key):
        return jax.random.randint(key, (), 0, game.num_actions, jnp.int32)

    return policy


def _p_catch(game):
    def policy(state, key):
        d = state.ball_c - state.paddle
        return jnp.where(d == 0, 0, jnp.where(d > 0, 2, 1)).astype(jnp.int32)

    return policy


def _p_breakout(game):
    from rainbow_iqn_apex_tpu.envs.device_games import G

    HORIZON = 24  # covers any ascent/descent cycle through the brick wall

    def policy(state, key):
        # trajectory-aware: roll the game's own ball dynamics (side
        # reflection with its one-tick wall dwell, top bounce, brick bounces
        # against a local copy of the wall) forward until the ball first
        # reaches the paddle plane, and head for that column the whole time
        # — chasing the ball's current column drags the paddle out of
        # position for the mirrored descent (measured: ~3 bricks/life vs
        # ~20+ with the trajectory target).  Paddle speed (1 cell/tick) is
        # the remaining, intended limitation of this ceiling.
        def body(_, carry):
            r, c, dr, dc, bricks, landed, land_c = carry
            nc = c + dc
            flip = (nc < 0) | (nc > G - 1)
            dc2 = jnp.where(flip, -dc, dc)
            nc = jnp.clip(nc, 0, G - 1)
            nr = r + dr
            dr2 = jnp.where(nr < 0, jnp.int32(1), dr)
            nr = jnp.where(nr < 0, jnp.int32(1), nr)
            nr_idx = jnp.clip(nr, 0, G - 1)
            hit = bricks[nr_idx, nc]
            bricks = bricks.at[nr_idx, nc].set(
                jnp.where(hit, False, bricks[nr_idx, nc])
            )
            dr2 = jnp.where(hit, -dr2, dr2)
            nr = jnp.where(hit, r, nr)
            at_bottom = nr >= G - 1
            land_c = jnp.where(at_bottom & ~landed, nc, land_c)
            new_landed = landed | at_bottom
            keep = landed
            return (
                jnp.where(keep, r, nr), jnp.where(keep, c, nc),
                jnp.where(keep, dr, dr2), jnp.where(keep, dc, dc2),
                bricks, new_landed, land_c,
            )

        init = (state.ball_r, state.ball_c, state.dr, state.dc,
                state.bricks, jnp.bool_(False), state.ball_c)
        *_, landed, land_c = jax.lax.fori_loop(0, HORIZON, body, init)
        target = jnp.where(landed, land_c, state.ball_c)
        d = target - state.paddle
        return jnp.where(d == 0, 0, jnp.where(d > 0, 2, 1)).astype(jnp.int32)

    return policy


def _p_freeway(game):
    from rainbow_iqn_apex_tpu.envs.device_games import G

    COL = game.CHICKEN_COL

    def _danger(state, row):
        """Will the lane at `row` (chicken rows 1..8) be dangerous next
        tick?  A car within 2 cells and approaching, or parked on the
        crossing column.  Lane dynamics come from the game's
        `_lane_dynamics(state)` hook, NOT the class constants, so the script
        stays a valid ceiling for '@var' levels whose speeds/dirs ride in
        the state."""
        lane = row - 1
        on_road = (lane >= 0) & (lane < 8)
        li = jnp.clip(lane, 0, 7)
        car = state.cars[li]
        _speeds, dirs = game._lane_dynamics(state)
        gap = car - COL  # signed distance to the crossing column
        approaching = jnp.sign(-gap) == jnp.sign(dirs[li])
        near = jnp.abs(gap) <= 2
        return on_road & ((gap == 0) | (near & approaching))

    def policy(state, key):
        # gap-aware crossing: step up when the lane above is clear; if the
        # current lane is about to be hit, prefer up, else retreat; never
        # idle in traffic for no reason
        up_ok = ~_danger(state, state.chicken - 1)
        here_bad = _danger(state, state.chicken)
        down_ok = ~_danger(state, state.chicken + 1)
        a = jnp.where(
            up_ok, 1,
            jnp.where(here_bad & down_ok, 2, 0),
        )
        return a.astype(jnp.int32)

    return policy


def _p_asterix(game):
    from rainbow_iqn_apex_tpu.envs.device_games import G

    def policy(state, key):
        lanes = jnp.arange(8)
        rows = lanes + 1
        enemy = state.active & ~state.gold
        gold = state.active & state.gold
        gap = state.col - state.pc  # per-lane signed distance to player col
        approaching = jnp.sign(-gap) == jnp.sign(state.dirn)
        threat = enemy & (jnp.abs(gap) <= 2) & ((gap == 0) | approaching)

        here = rows == state.pr
        above = rows == state.pr - 1
        below = rows == state.pr + 1
        in_danger = (threat & here).any()
        up_ok = (state.pr > 1) & ~(threat & above).any()
        down_ok = (state.pr < 8) & ~(threat & below).any()

        # nearest gold lane (inactive lanes pushed to +inf distance)
        gdist = jnp.where(gold, jnp.abs(rows - state.pr) * G + jnp.abs(gap),
                          jnp.int32(10 * G))
        gi = jnp.argmin(gdist)
        has_gold = gold.any()
        g_row, g_col = rows[gi], state.col[gi]
        to_gold = jnp.where(
            g_row < state.pr, 3,
            jnp.where(
                g_row > state.pr, 4,
                jnp.where(g_col < state.pc, 1,
                          jnp.where(g_col > state.pc, 2, 0)),
            ),
        )
        chase = jnp.where(has_gold, to_gold, 0)

        # dodge enemies first (vertical escape, sideways as a last resort),
        # otherwise chase the nearest gold
        flee = jnp.where(up_ok, 3, jnp.where(down_ok, 4, jnp.where(
            (threat & here & (gap >= 0)).any(), 1, 2)))
        return jnp.where(in_danger, flee, chase).astype(jnp.int32)

    return policy


def _p_invaders(game):
    from rainbow_iqn_apex_tpu.envs.device_games import G

    def policy(state, key):
        # dodge a falling bomb on our column, else line up with the nearest
        # alien column and fire
        bomb_close = (state.bomb_r >= 0) & (state.bomb_r >= G - 4)
        dodge = bomb_close & (state.bomb_c == state.pc)
        dodge_dir = jnp.where(state.pc > 0, 1, 2)

        cols_occ = state.aliens.any(axis=0)
        cdist = jnp.where(cols_occ, jnp.abs(jnp.arange(G) - state.pc),
                          jnp.int32(10 * G))
        tgt = jnp.argmin(cdist)
        aligned = cols_occ[state.pc]
        can_fire = state.shot_r < 0
        seek = jnp.where(
            aligned, jnp.where(can_fire, 3, 0),
            jnp.where(tgt < state.pc, 1, 2),
        )
        return jnp.where(dodge, dodge_dir, seek).astype(jnp.int32)

    return policy


# game -> scripted policy builder (every game has a competent ceiling so
# "1.0 = plays like the script" is meaningful suite-wide)
SCRIPTED: Dict[str, Optional[Callable]] = {
    "catch": _p_catch,
    "breakout": _p_breakout,
    "freeway": _p_freeway,
    "asterix": _p_asterix,
    "invaders": _p_invaders,
}


# ---------------------------------------------------------------- rollouts


def rollout_returns(name: str, policy_builder, episodes: int = 64,
                    seed: int = 0, max_ticks: Optional[int] = None) -> np.ndarray:
    """FIRST-episode returns of `policy` on `episodes` parallel lanes via the
    shared rollout core (envs/device_games.build_rollout) — same episode
    accounting as the trainers' in-graph eval, including capped-return
    semantics: a lane still mid-episode at the tick budget scores its
    partial return, so long-surviving policies (breakout rallies) are
    counted, never censored."""
    game = make_device_game(name)
    policy = policy_builder(game)
    T = max_ticks or tick_budget(name)

    def action_fn(aux, states, stack, key):
        return jax.vmap(policy)(states, jax.random.split(key, episodes))

    run = build_rollout(game, action_fn, episodes, T, history=0)
    return np.asarray(run(None, jax.random.PRNGKey(seed)))


def measure_baselines(name: str, episodes: int = 64, seed: int = 0) -> Dict:
    """Measured {random, scripted?} mean returns for one game (capped-return
    semantics — every lane contributes; the emptiness guards below are pure
    defence-in-depth)."""
    out: Dict[str, float] = {}
    rnd = rollout_returns(name, _p_random, episodes, seed)
    if len(rnd):
        out["random"] = float(np.mean(rnd))
    builder = SCRIPTED.get(name)
    if builder is not None:
        scr = rollout_returns(name, builder, episodes, seed + 1)
        if len(scr):
            out["scripted"] = float(np.mean(scr))
    return out


def normalized_score(raw: float, baselines: Dict) -> Optional[float]:
    """(raw - random) / (scripted - random); None without a scripted ceiling
    meaningfully above random (or with non-finite baselines)."""
    rnd = baselines.get("random")
    scr = baselines.get("scripted")
    if rnd is None or scr is None:
        return None
    if not (np.isfinite(rnd) and np.isfinite(scr)) or scr <= rnd + 1e-6:
        return None
    return (raw - rnd) / (scr - rnd)


def aggregate(per_game_raw: Dict[str, float],
              baselines: Dict[str, Dict]) -> Dict[str, object]:
    """Suite aggregate: counts, median/mean script-normalized scores, the
    per-game normalized map and the below-0.2 floor count (mixed value
    types — treat as a JSON object, not a float map)."""
    norm = {
        g: n
        for g, s in per_game_raw.items()
        if (n := normalized_score(s, baselines.get(g, {}))) is not None
    }
    out: Dict[str, float] = {"games": len(per_game_raw),
                             "games_normalized": len(norm)}
    if norm:
        out["median_script_normalized"] = _median(norm.values())
        out["mean_script_normalized"] = sum(norm.values()) / len(norm)
        # the median alone flatters a sweep where some games sit at the
        # floor (VERDICT r3): ship the per-game map and the floor count so
        # the headline can't be quoted without its caveat
        out["per_game_normalized"] = {g: round(n, 4)
                                      for g, n in sorted(norm.items())}
        out["games_below_0.2"] = sum(1 for n in norm.values() if n < 0.2)
        # scripted ceilings are asymmetric (VERDICT r4): where the agent
        # BEATS its script (n > 1) the script was floor-quality and "1.0 =
        # plays like the script" understates the agent; the count makes the
        # two meanings of the median separable at a glance
        out["games_above_script"] = sum(1 for n in norm.values() if n > 1.0)
    return out


def _csv_scalar(text: str):
    """Invert csv.DictWriter's stringification for prior-row reload: ints,
    floats and bools come back typed; everything else stays a string."""
    if text in ("True", "False"):
        return text == "True"
    for cast in (int, float):
        try:
            return cast(text)
        except ValueError:
            continue
    return text


def load_prior_rows(results_dir: str, skip_games: List[str]):
    """Reload completed per_game.csv rows for games NOT in this run, so a
    partial rerun (crash-resume, or topping up one game's budget) keeps the
    other games' committed rows instead of overwriting them.  Returns
    (rows, per_game_raw, baselines, failed) in run_sweep's working shapes;
    rows with an error marker reload as rows + a `failed` entry — never into
    the aggregate's score maps — so the rewritten aggregate keeps the
    games_failed caveat the first run's flush() wrote (writer-emits-caveats
    rule: dropping it on resume would un-declare a recorded failure)."""
    import csv as _csv

    path = os.path.join(results_dir, "per_game.csv")
    rows, per_game, baselines, failed = [], {}, {}, []
    if not os.path.exists(path):
        return rows, per_game, baselines, failed
    with open(path, newline="") as f:
        for raw in _csv.DictReader(f):
            game = raw.get("game")
            if not game or game in skip_games:
                continue
            row = {k: _csv_scalar(v) for k, v in raw.items() if v != ""}
            rows.append(row)
            if "error" in row or row.get("score_mean") is None:
                failed.append(game)
            else:
                per_game[game] = row["score_mean"]
                baselines[game] = {"random": row.get("random_baseline"),
                                   "scripted": row.get("scripted_baseline")}
    return rows, per_game, baselines, failed


def run_sweep(base_args: List[str], games: Optional[List[str]] = None,
              results_dir: str = "results/jaxsuite",
              baseline_episodes: int = 64,
              per_game_args: Optional[Dict[str, List[str]]] = None,
              note: Optional[str] = None,
              resume_rows: bool = False) -> Dict[str, object]:
    """Train+eval each jax game via the training CLI (mirror of
    atari57.run_sweep), then aggregate against measured baselines.

    ``per_game_args`` appends extra CLI flags for specific games (e.g. a
    bigger ``--t-max`` for the games whose scripted ceilings encode
    trajectory-level skill).  per_game.csv and aggregate.json are rewritten
    after EVERY game, so an interrupted sweep keeps its completed rows.
    ``note`` rides into aggregate.json verbatim (ADVICE r4: caveats must be
    emitted by the writer, not hand-patched into the artifact, or a rerun
    silently drops them); per-game frame budgets are emitted the same way.
    ``resume_rows`` seeds from the existing per_game.csv (games being rerun
    excluded), so restarting a killed sweep with only its unfinished games
    cannot overwrite the finished ones."""
    from rainbow_iqn_apex_tpu.atari57 import train_one_game, write_results_csv

    games = games or JAXSUITE
    per_game: Dict[str, float] = {}
    baselines: Dict[str, Dict] = {}
    rows = []
    failed = []
    if resume_rows:
        rows, per_game, baselines, failed = load_prior_rows(results_dir,
                                                            games)

    def flush():
        write_results_csv(os.path.join(results_dir, "per_game.csv"), rows)
        agg = aggregate(per_game, baselines)
        agg["games_failed"] = len(failed)
        if failed:
            agg["failed_games"] = failed
        # partial-budget (salvaged) scores sit in the same median — the
        # aggregate must say so itself (writer-emits-caveats rule)
        salvaged = sorted(r["game"] for r in rows if r.get("salvaged"))
        if salvaged:
            agg["games_salvaged"] = len(salvaged)
            agg["salvaged_games"] = salvaged
        frames = {r["game"]: r["train_frames"] for r in rows
                  if r.get("train_frames") is not None}
        if frames:
            agg["train_frames_per_game"] = frames  # always a dict: a
            # schema that flips to a scalar when budgets happen to agree
            # breaks consumers on the next per-game override
        if note:
            agg["note"] = note
        with open(os.path.join(results_dir, "aggregate.json"), "w") as f:
            json.dump(agg, f, indent=2)
        return agg

    for game in games:
        args = [*base_args, *(per_game_args or {}).get(game, [])]
        run_id = f"jaxsuite_{game}"
        summary = train_one_game(f"jaxgame:{game}", run_id, args)
        raw = summary.get("eval_score_mean")
        extra = dict(summary)
        salvaged = False
        if raw is None:
            # an interrupted/killed training still leaves periodic
            # checkpoints — score the latest one rather than dropping hours
            # of training (a wind-down cut mid-sweep is a normal event on
            # budgeted boxes); ANY salvage failure becomes an error row so
            # one broken game can never abort the remaining sweep
            try:
                raw, ck_extra = eval_checkpoint_fused(
                    args, run_id, game, episodes=baseline_episodes,
                    with_extra=True)
                salvaged = True
                extra = {"eval_episodes": baseline_episodes,
                         "frames": ck_extra.get("frames")}
            except FileNotFoundError:
                failed.append(game)
                rows.append({"game": game, "score_mean": None,
                             "error": "training run failed "
                                      "(no checkpoint to salvage)"})
                flush()
                continue
            except Exception as e:  # noqa: BLE001 — keep the sweep alive
                failed.append(game)
                rows.append({"game": game, "score_mean": None,
                             "error": f"salvage eval failed: {e!r}"})
                flush()
                continue
        baselines[game] = measure_baselines(game, episodes=baseline_episodes)
        per_game[game] = raw
        row = {
            "game": game,
            "score_mean": raw,
            "random_baseline": baselines[game].get("random"),
            "scripted_baseline": baselines[game].get("scripted"),
            "script_normalized": normalized_score(raw, baselines[game]),
            "train_frames": extra.get("frames"),
            **{k: v for k, v in extra.items() if k.startswith("eval_")},
        }
        if salvaged:
            row["salvaged"] = True  # scored from the latest periodic
            # checkpoint of an interrupted run, at its true frame count
        rows.append(row)
        flush()
    return flush()


# ------------------------------------------------- generalization (Procgen)


def eval_checkpoint_per_level(base_args: List[str], run_id: str,
                              base_game: str, levels,
                              episodes_per_level: int = 8, seed: int = 4321,
                              chunk_levels: int = 16,
                              max_ticks: Optional[int] = None) -> np.ndarray:
    """[n_levels, episodes_per_level] first-episode returns of a trained
    checkpoint with each lane PINNED to a known level (envs.device_games
    ``init_at_level``) — the measurement VERDICT r4 asked for: the two-pool
    eval can't separate a generalization gap from level-difficulty variance
    at 16-level pools, but per-level means over a 64+ level held-out set
    can.  Levels are free (`fold_in(base, level)`), so this is eval-cost
    only.

    The lane->level assignment rides through the rollout's `aux` argument,
    so every chunk of ``chunk_levels`` levels reuses ONE compiled rollout.
    Works for feedforward AND r2d2 checkpoints (greedy LSTM lanes with
    cut-reset, mirroring build_fused_r2d2_eval)."""
    from rainbow_iqn_apex_tpu.config import parse_config
    from rainbow_iqn_apex_tpu.envs.device_games import build_rollout
    from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer

    cfg = parse_config([*base_args, "--env-id", f"jaxgame:{base_game}@var",
                        "--run-id", run_id])
    levels = list(levels)
    game = make_device_game(f"{base_game}@var")
    h, w = game.frame_shape
    T = max_ticks or tick_budget(base_game)
    eps = episodes_per_level
    C = min(chunk_levels, len(levels))
    lanes = C * eps

    def init_fn(aux, key):
        lane_levels = jnp.repeat(aux[1], eps)
        return jax.vmap(game.init_at_level)(
            lane_levels, jax.random.split(key, lanes)
        )

    if cfg.architecture == "r2d2":
        from rainbow_iqn_apex_tpu.ops.r2d2 import (
            build_r2d2_act_step,
            init_r2d2_state,
        )

        act_fn = build_r2d2_act_step(cfg, game.num_actions,
                                     use_noise=cfg.eval_noisy)

        def action_fn(aux, states, stack, key, lstm):
            a, _q, lstm = act_fn(aux[0], stack, lstm, key)
            return a, lstm

        def actor_init(n):
            z = jnp.zeros((n, cfg.lstm_size), jnp.float32)
            return (z, z)

        run = build_rollout(game, action_fn, lanes, T,
                            history=cfg.history_length,
                            actor_init=actor_init, init_fn=init_fn)
        ts = init_r2d2_state(cfg, game.num_actions, jax.random.PRNGKey(0),
                             (h, w))
    else:
        from rainbow_iqn_apex_tpu.ops.learn import (
            build_act_step,
            init_train_state,
        )

        act_fn = build_act_step(cfg, game.num_actions, use_noise=False)

        def action_fn(aux, states, stack, key):
            actions, _q = act_fn(aux[0], stack, key)
            return actions

        run = build_rollout(game, action_fn, lanes, T,
                            history=cfg.history_length, init_fn=init_fn)
        ts = init_train_state(cfg, game.num_actions, jax.random.PRNGKey(0),
                              state_shape=(h, w, cfg.history_length))
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))
    if ckpt.latest_step() is None:
        raise FileNotFoundError(
            f"no checkpoint under {cfg.checkpoint_dir}/{cfg.run_id}"
        )
    ts, _ = ckpt.restore(ts)
    out = np.empty((len(levels), eps))
    for i in range(0, len(levels), C):
        chunk = levels[i:i + C]
        pad = C - len(chunk)  # final partial chunk: repeat the last level
        arr = jnp.asarray(chunk + [chunk[-1]] * pad, jnp.int32)
        scores = np.asarray(run((ts.params, arr), jax.random.PRNGKey(seed + i)))
        out[i:i + len(chunk)] = scores.reshape(C, eps)[:len(chunk)]
    return out


def bootstrap_gap(train_level_means, heldout_level_means,
                  n_boot: int = 2000, seed: int = 0) -> Dict[str, object]:
    """Generalization gap with LEVEL-resampled uncertainty.  The unit of
    variance that round-4's negative gaps exposed is the level, not the
    episode, so both pools are bootstrapped over level means;
    ``gap_boot_frac_positive`` near 0.5 says the gap's sign is noise,
    near 0 or 1 says it is stable under resampling the pools (VERDICT r4
    item 4's acceptance bar)."""
    rng = np.random.default_rng(seed)
    tm = np.asarray(train_level_means, float)
    hm = np.asarray(heldout_level_means, float)
    it = rng.integers(0, len(tm), (n_boot, len(tm)))
    ih = rng.integers(0, len(hm), (n_boot, len(hm)))
    gaps = tm[it].mean(axis=1) - hm[ih].mean(axis=1)
    return {
        "gap": float(tm.mean() - hm.mean()),
        "gap_boot_frac_positive": float((gaps > 0).mean()),
        "gap_boot_ci90": [float(np.quantile(gaps, 0.05)),
                          float(np.quantile(gaps, 0.95))],
    }


def per_level_fields(train_scores: np.ndarray, heldout_scores: np.ndarray,
                     first_heldout_level: int) -> Dict[str, object]:
    """The generalization row's per-level block: level means, across-level
    spread, and the bootstrap gap-sign stability."""
    tm, hm = train_scores.mean(axis=1), heldout_scores.mean(axis=1)
    return {
        "episodes_per_level": int(train_scores.shape[1]),
        "n_train_levels": int(len(tm)),
        "n_heldout_levels": int(len(hm)),
        "first_heldout_level": int(first_heldout_level),
        "train_level_means": [round(float(x), 4) for x in tm],
        "heldout_level_means": [round(float(x), 4) for x in hm],
        "train_mean": round(float(tm.mean()), 4),
        "train_std_across_levels": round(float(tm.std(ddof=1)), 4),
        "heldout_mean": round(float(hm.mean()), 4),
        "heldout_std_across_levels": round(float(hm.std(ddof=1)), 4),
        **bootstrap_gap(tm, hm),
    }


def eval_checkpoint_fused(base_args: List[str], run_id: str, game_name: str,
                          episodes: int = 64, seed: int = 1234,
                          with_extra: bool = False):
    """Mean first-episode return of a trained checkpoint on `game_name`
    (variant ids welcome), via the in-graph fused eval — the measurement
    half of the train/test generalization split.  ``with_extra=True``
    returns ``(score, extra)`` where extra is the checkpoint's JSON side-car
    (frames counter etc.) — the salvage paths need it and the restore has it
    in hand anyway."""
    from rainbow_iqn_apex_tpu.config import parse_config
    from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer

    cfg = parse_config(
        [*base_args, "--env-id", f"jaxgame:{game_name}", "--run-id", run_id]
    )
    game = make_device_game(game_name)
    h, w = game.frame_shape
    T = tick_budget(game_name)
    if cfg.architecture == "r2d2":
        from rainbow_iqn_apex_tpu.ops.r2d2 import init_r2d2_state
        from rainbow_iqn_apex_tpu.train_anakin_r2d2 import build_fused_r2d2_eval

        ts = init_r2d2_state(cfg, game.num_actions, jax.random.PRNGKey(0),
                             (h, w))
        eval_fn = build_fused_r2d2_eval(cfg, game, episodes, max_ticks=T)
    else:
        from rainbow_iqn_apex_tpu.ops.learn import init_train_state
        from rainbow_iqn_apex_tpu.train_anakin import build_fused_eval

        ts = init_train_state(cfg, game.num_actions, jax.random.PRNGKey(0),
                              state_shape=(h, w, cfg.history_length))
        eval_fn = build_fused_eval(cfg, game, episodes, max_ticks=T)
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))
    if ckpt.latest_step() is None:
        raise FileNotFoundError(
            f"no checkpoint under {cfg.checkpoint_dir}/{cfg.run_id}"
        )
    ts, ck_extra = ckpt.restore(ts)
    scores = np.asarray(eval_fn(ts.params, jax.random.PRNGKey(seed)))
    score = float(scores.mean())
    return (score, ck_extra) if with_extra else score


def run_generalization(base_args: List[str],
                       games: Optional[List[str]] = None,
                       results_dir: str = "results/jaxsuite",
                       episodes: int = 64,
                       per_game_args: Optional[Dict[str, List[str]]] = None,
                       note: Optional[str] = None,
                       levels_eval: int = 64,
                       episodes_per_level: int = 8) -> Dict:
    """Procgen-class generalization check (BASELINE.md config 5 stand-in):
    train each variant game on its 16-seed TRAIN level pool
    (jaxgame:<g>@var), then eval the SAME checkpoint on train levels and on
    the 16 held-out levels (@var-test).  Writes
    results_dir/generalization.json with per-game train/test scores, the
    generalization gap, and the TRAIN-pool random baseline (a train score
    that does not clearly beat random makes the gap meaningless — VERDICT
    r3: such rows are reported with ``off_random: false`` so consumers can
    filter them).  The JSON is rewritten after every game, and
    ``per_game_args`` appends per-game flags (e.g. bigger ``--t-max`` for
    slower-learning games).

    ``levels_eval > 0`` adds a ``per_level`` block per row: the checkpoint
    is additionally evaluated with lanes pinned to each of the 16 train
    levels and to ``levels_eval`` held-out levels (ids 16..16+levels_eval-1
    — the first 16 are the @var-test pool, the rest are drawn from the same
    generative process and are equally unseen), reporting per-level means,
    across-level spread, and a level-bootstrap of the gap's sign (VERDICT
    r4: a ±2-point two-pool gap at 16-level pools is indistinguishable from
    pool-difficulty variance)."""
    from rainbow_iqn_apex_tpu.atari57 import train_one_game
    from rainbow_iqn_apex_tpu.envs.device_games import VARIANT_GAMES

    games = list(games or sorted(VARIANT_GAMES))
    unsupported = [g for g in games if g not in VARIANT_GAMES]
    if unsupported:
        raise ValueError(
            f"no seeded-variant mode for {unsupported} (have: "
            f"{sorted(VARIANT_GAMES)})"
        )
    rows = []
    os.makedirs(results_dir, exist_ok=True)

    def flush():
        out = {"episodes_per_split": episodes, "per_game": rows}
        if note:
            out["note"] = note
        with open(os.path.join(results_dir, "generalization.json"), "w") as f:
            json.dump(out, f, indent=2)
        return out

    for g in games:
        run_id = f"jaxsuite_{g}_var"
        args = [*base_args, *(per_game_args or {}).get(g, [])]
        summary = train_one_game(f"jaxgame:{g}@var", run_id, args)
        trained_ok = summary.get("eval_score_mean") is not None
        try:
            # both splits are scored from the checkpoint anyway, so an
            # interrupted/killed training salvages for free — the row just
            # carries `salvaged` and the checkpoint's true frame count
            train_score, ck_extra = eval_checkpoint_fused(
                args, run_id, f"{g}@var", episodes, with_extra=True)
            test_score = eval_checkpoint_fused(args, run_id, f"{g}@var-test",
                                               episodes)
        except FileNotFoundError:
            # distinguish the mislabel: a COMPLETED training with no
            # checkpoint is a misconfiguration, not a failed run
            rows.append({"game": g, "error":
                         "trained but no checkpoint found (checkpointing "
                         "misconfigured?)" if trained_ok else
                         "training run failed (no checkpoint to salvage)"})
            flush()
            continue
        except Exception as e:  # noqa: BLE001 — keep remaining games alive
            rows.append({"game": g, "error": f"checkpoint eval failed: {e!r}"})
            flush()
            continue
        train_frames = (summary.get("frames") if trained_ok
                        else ck_extra.get("frames"))
        rnd = float(np.mean(rollout_returns(f"{g}@var", _p_random, episodes,
                                            seed=99)))
        # the "clearly off-random" bar: random plus 2x its magnitude (i.e.
        # 3x random when random > 0), or +0.5 absolute when random is ~0 —
        # for negative random baselines (catch-style symmetric scores) this
        # is |random|, comfortably above zero (ADVICE r4 wording fix)
        bar = rnd + max(2.0 * abs(rnd), 0.5)
        row = {
            "game": g,
            "train_levels_score": train_score,
            "heldout_levels_score": test_score,
            "generalization_gap": train_score - test_score,
            "train_random_baseline": rnd,
            "off_random": bool(train_score >= bar),
            "train_frames": train_frames,
        }
        if not trained_ok:
            row["salvaged"] = True  # scored from the latest periodic
            # checkpoint of an interrupted run
        # the two-pool row is hours of training — it goes to disk BEFORE the
        # per-level eval can fail (compile OOM, corrupted checkpoint); the
        # block is added by a re-flush
        rows.append(row)
        flush()
        if levels_eval > 0:
            from rainbow_iqn_apex_tpu.envs.device_games import N_TRAIN_LEVELS

            try:
                # one call over both pools = one compile + one restore (the
                # compile dominates eval cost on CPU); split afterwards
                all_pl = eval_checkpoint_per_level(
                    args, run_id, g,
                    range(N_TRAIN_LEVELS + levels_eval), episodes_per_level)
                row["per_level"] = per_level_fields(
                    all_pl[:N_TRAIN_LEVELS], all_pl[N_TRAIN_LEVELS:],
                    N_TRAIN_LEVELS)
            except Exception as e:  # noqa: BLE001 — never lose the row
                row["per_level_error"] = repr(e)
            flush()
    return flush()
