"""Benchmark suite over the pure-JAX game family (envs/device_games.py).

Role: the runnable counterpart of the Atari-57 harness (atari57.py).  The
reference's headline benchmark needs ALE + ROMs, absent in this sandbox
(SURVEY.md §7); this suite gives the framework a benchmark it can actually
execute anywhere: same sweep driver shape, same CSV/aggregate outputs, same
normalisation math — but with baselines that are MEASURED, not recalled:

- random baseline: the measured mean return of a uniform-random policy;
- scripted reference: the measured mean return of a hand-written competent
  policy (state-based, defined per game where one is sensible).

normalized = (score - random) / (scripted - random) — "1.0 plays like the
script, 0.0 plays like noise" — so nothing in the aggregate rests on an
unverifiable constant (contrast atari57.HUMAN_WORLD_RECORDS, which stays
RECON-gated).  Baselines are computed on demand by vmapped device rollouts
of the same in-graph step the trainers use.
"""

from __future__ import annotations

import json
import os
from statistics import median as _median
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.envs.device_games import (
    EPISODE_TICK_BUDGET,
    GAMES,
    build_rollout,
    make_device_game,
)

JAXSUITE = sorted(GAMES)


# ---------------------------------------------------------------- policies


def _p_random(game):
    def policy(state, key):
        return jax.random.randint(key, (), 0, game.num_actions, jnp.int32)

    return policy


def _p_catch(game):
    def policy(state, key):
        d = state.ball_c - state.paddle
        return jnp.where(d == 0, 0, jnp.where(d > 0, 2, 1)).astype(jnp.int32)

    return policy


def _p_breakout(game):
    def policy(state, key):
        d = state.ball_c - state.paddle
        return jnp.where(d == 0, 0, jnp.where(d > 0, 2, 1)).astype(jnp.int32)

    return policy


def _p_freeway(game):
    def policy(state, key):
        return jnp.int32(1)  # always up

    return policy


def _p_invaders(game):
    def policy(state, key):
        return jnp.int32(3)  # hold fire from the spawn column

    return policy


# game -> scripted policy builder (None: no sensible script; normalisation
# is then undefined and the game reports raw scores only)
SCRIPTED: Dict[str, Optional[Callable]] = {
    "catch": _p_catch,
    "breakout": _p_breakout,
    "freeway": _p_freeway,
    "asterix": None,
    "invaders": _p_invaders,
}


# ---------------------------------------------------------------- rollouts


def rollout_returns(name: str, policy_builder, episodes: int = 64,
                    seed: int = 0, max_ticks: Optional[int] = None) -> np.ndarray:
    """FIRST-episode returns of `policy` on `episodes` parallel lanes via the
    shared rollout core (envs/device_games.build_rollout) — same episode
    accounting as the trainers' in-graph eval, including capped-return
    semantics: a lane still mid-episode at the tick budget scores its
    partial return, so long-surviving policies (breakout rallies) are
    counted, never censored."""
    game = make_device_game(name)
    policy = policy_builder(game)
    T = max_ticks or EPISODE_TICK_BUDGET.get(name, 512)

    def action_fn(aux, states, stack, key):
        return jax.vmap(policy)(states, jax.random.split(key, episodes))

    run = build_rollout(game, action_fn, episodes, T, history=0)
    return np.asarray(run(None, jax.random.PRNGKey(seed)))


def measure_baselines(name: str, episodes: int = 64, seed: int = 0) -> Dict:
    """Measured {random, scripted?} mean returns for one game (capped-return
    semantics — every lane contributes; the emptiness guards below are pure
    defence-in-depth)."""
    out: Dict[str, float] = {}
    rnd = rollout_returns(name, _p_random, episodes, seed)
    if len(rnd):
        out["random"] = float(np.mean(rnd))
    builder = SCRIPTED.get(name)
    if builder is not None:
        scr = rollout_returns(name, builder, episodes, seed + 1)
        if len(scr):
            out["scripted"] = float(np.mean(scr))
    return out


def normalized_score(raw: float, baselines: Dict) -> Optional[float]:
    """(raw - random) / (scripted - random); None without a scripted ceiling
    meaningfully above random (or with non-finite baselines)."""
    rnd = baselines.get("random")
    scr = baselines.get("scripted")
    if rnd is None or scr is None:
        return None
    if not (np.isfinite(rnd) and np.isfinite(scr)) or scr <= rnd + 1e-6:
        return None
    return (raw - rnd) / (scr - rnd)


def aggregate(per_game_raw: Dict[str, float],
              baselines: Dict[str, Dict]) -> Dict[str, float]:
    norm = {
        g: n
        for g, s in per_game_raw.items()
        if (n := normalized_score(s, baselines.get(g, {}))) is not None
    }
    out: Dict[str, float] = {"games": len(per_game_raw),
                             "games_normalized": len(norm)}
    if norm:
        out["median_script_normalized"] = _median(norm.values())
        out["mean_script_normalized"] = sum(norm.values()) / len(norm)
    return out


def run_sweep(base_args: List[str], games: Optional[List[str]] = None,
              results_dir: str = "results/jaxsuite",
              baseline_episodes: int = 64) -> Dict[str, float]:
    """Train+eval each jax game via the training CLI (mirror of
    atari57.run_sweep), then aggregate against measured baselines."""
    from rainbow_iqn_apex_tpu.atari57 import train_one_game, write_results_csv

    games = games or JAXSUITE
    per_game: Dict[str, float] = {}
    baselines: Dict[str, Dict] = {}
    rows = []
    for game in games:
        summary = train_one_game(f"jaxgame:{game}", f"jaxsuite_{game}", base_args)
        raw = summary.get("eval_score_mean")
        if raw is None:
            continue
        baselines[game] = measure_baselines(game, episodes=baseline_episodes)
        per_game[game] = raw
        rows.append({
            "game": game,
            "score_mean": raw,
            "random_baseline": baselines[game].get("random"),
            "scripted_baseline": baselines[game].get("scripted"),
            "script_normalized": normalized_score(raw, baselines[game]),
            **{k: v for k, v in summary.items() if k.startswith("eval_")},
        })
    write_results_csv(os.path.join(results_dir, "per_game.csv"), rows)
    agg = aggregate(per_game, baselines)
    with open(os.path.join(results_dir, "aggregate.json"), "w") as f:
        json.dump(agg, f, indent=2)
    return agg
