"""Flat configuration for the TPU-native Rainbow-IQN Ape-X framework.

Parity note: the reference (`valeoai/rainbow-iqn-apex`, reconstructed in
SURVEY.md §2 row 1 — `rainbowiqn/args.py`) threads a single argparse namespace
through every constructor.  We keep the same spirit — one flat config object,
CLI-overridable — but as a typed frozen dataclass that is hashable, so it can
be closed over by ``jax.jit``-compiled functions as a static argument.

Hyperparameter defaults follow the Rainbow / IQN / Ape-X papers
(arXiv:1710.02298, arXiv:1806.06923, arXiv:1803.00933) and the SABER protocol
(arXiv:1908.04683), which are the reference's own sources (SURVEY.md §2).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from typing import Any, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Config:
    # ---- experiment / bookkeeping -------------------------------------------------
    run_id: str = "run0"
    seed: int = 123
    results_dir: str = "results"
    checkpoint_dir: str = "checkpoints"
    checkpoint_interval: int = 100_000  # learner steps between Orbax saves
    metrics_interval: int = 1_000  # learner steps between JSONL metric rows
    resume: str = ""  # "" = fresh start; "true" = restore latest step (raise
    # on corruption); "auto" = preemption-safe: restore the newest VALID
    # checkpoint, falling back past corrupt steps, fresh start when none —
    # the mode an auto-restarting scheduler should use (docs/RESILIENCE.md).
    # Legacy bool configs (resume=True/False) keep working.
    snapshot_replay: bool = False  # persist replay contents next to checkpoints
    # (parity: the reference's replay survives restarts via Redis persistence;
    # off by default — Atari-scale buffers are ~7GB/host on disk)

    # ---- observability (obs/; docs/OBSERVABILITY.md) ------------------------------
    trace_dir: str = ""  # arm a one-shot jax profiler capture (xplane/
    # TensorBoard format, utils/profiling.device_trace) around the learn-step
    # window [trace_start_step, trace_start_step + trace_num_steps); "" = off
    trace_start_step: int = 50  # past warmup/compile so the capture is steady-state
    trace_num_steps: int = 10
    obs_http_port: int = 0  # serve /metrics + /healthz on this port; 0 = off
    trace_sample_every: int = 0  # pipeline tracing (obs/pipeline_trace.py):
    # every Nth unit of work (env tick, learn step, publish, request) emits
    # causal `span_link` rows — trace_export.py turns them into a Perfetto
    # timeline, obs_report into a `critical_path:` verdict.  0 (default) =
    # spans off; the always-on lag_* metrics cost a few histogram writes per
    # batch either way and change no numerics (off-path stays bitwise).

    # ---- live fleet telemetry plane (obs/net/; docs/OBSERVABILITY.md) -------------
    obs_net: bool = False  # relay gate: attach an ObsRelay to this process's
    # MetricsLogger — every row it logs (and periodic registry snapshots)
    # streams to the lease-discovered obs collector through a bounded
    # non-blocking spool.  False (default) = no relay machinery runs and
    # every code path is bitwise the pre-plane behaviour (tier-1 asserted).
    # Telemetry is never load-bearing: a dead collector sheds rows, the
    # local JSONL continues untouched.
    obs_net_host: str = ""  # bind address for this process's ObsCollector
    # ("" = no collector in this process, the default; the collector
    # process sets it and registers an `obs_collector` lease carrying
    # addr:port, same discovery as the replay/serving planes)
    obs_net_port: int = 0  # collector listen port; 0 = ephemeral — the
    # lease payload advertises whatever was bound
    obs_net_advertise: str = ""  # address relays dial ("" = the bind host;
    # set it when binding a wildcard or behind NAT)
    obs_net_http_port: int = 0  # collector's aggregated /metrics + /fleetz
    # HTTP port; 0 = ephemeral (the lease advertises it as `http_port`)
    obs_net_spool: int = 2048  # relay spool capacity in rows: the buffering
    # horizon an unreachable collector is ridden out over; a FULL spool
    # sheds the NEWEST row with a counted, rate-limited reasoned row — the
    # env/learn loop never blocks on telemetry
    obs_net_snapshot_s: float = 5.0  # tier-2 cost knob: seconds between
    # relay registry snapshots (counters/gauges/histograms shipped as one
    # frame).  0 = rows-only (tier 1): the relay costs one deque append per
    # logged row and nothing else
    obs_net_stale_s: float = 10.0  # collector: a host whose stream has been
    # silent this long degrades the fleet with reason `stale_host`
    obs_net_resolution_s: float = 1.0  # time-series store bucket width —
    # points landing in the same bucket downsample to last-write-wins
    obs_net_window: int = 600  # ring-buffered points kept per series
    obs_net_tick_s: float = 2.0  # collector fold cadence: fleet health +
    # SLO alert evaluation + `fleet_health` row emission interval
    obs_net_learn_floor: float = 0.0  # SLO alert: fleet learner steps/s
    # below this floor fires `slo_learn_floor`; 0 = rule off
    obs_net_shed_ceiling: float = 0.0  # SLO alert: shed rate (rows/s over
    # the window, from health shed_total) above this fires
    # `slo_shed_spike`; 0 = rule off

    # ---- resilience (utils/faults.py + parallel/supervisor.py; RESILIENCE.md) ----
    fault_spec: str = ""  # chaos injection, e.g. "nan_loss@5,checkpoint_write@1"
    # (point@n = fire on n-th call, point:p = seeded probability, bare point =
    # always; RIA_FAULTS env var overrides)
    fault_stall_s: float = 0.0  # injected stall duration for 'stalled_step'
    max_nan_strikes: int = 3  # consecutive non-finite learn steps before abort
    guard_snapshot_interval: int = 500  # learner steps between last-good
    # in-memory state snapshots (the NaN-guard rollback target)
    stall_timeout_s: float = 300.0  # watchdog: no completed learn step for
    # this long -> 'stalled_step' fault row; 0 disables
    io_retry_attempts: int = 3  # checkpoint/replay-snapshot IO tries (total)
    io_retry_base_s: float = 0.05  # backoff base; doubles per retry + jitter
    io_retry_max_s: float = 2.0
    heartbeat_interval_s: float = 0.0  # per-host liveness file cadence; 0 off
    heartbeat_timeout_s: float = 30.0  # peer file older than this = dead host
    lease_skew_tolerance_s: float = 0.0  # extra staleness grace absorbing
    # cross-host wall-clock skew: lease freshness compares the READER's clock
    # against the WRITER's mtime, so a reader running 2s ahead inflates every
    # age by 2s and can false-evict a healthy host.  Freshness becomes
    # age <= heartbeat_timeout_s + this.  0 (default) = the exact pre-skew
    # comparison, bitwise the previous PR
    net_chaos_spec: str = ""  # seeded network-fault interposer over every
    # plane socket (netcore/chaos.py), e.g.
    # "delay_ms=50±20@p=1.0,corrupt_frame@p=0.01,partition=learner->replay1@t=10..12"
    # — clauses: delay_ms / corrupt_frame / torn_write / blackhole /
    # partition=src->dst / slow_read_bps, each taking @p=<prob> and
    # @t=<a>..<b> windows.  RIA_NET_CHAOS env overrides; RIA_NET_CHAOS_SITE
    # names this process for partition matching.  "" (default) = sockets are
    # returned unwrapped — the off path is bitwise the previous PR

    # ---- elasticity (parallel/elastic.py; docs/RESILIENCE.md "heal") --------------
    max_weight_lag: int = 0  # actor staleness fence: pause acting (shed
    # frames, 'actor_fenced' rows) once the adopted weight version trails the
    # published one by more than this many publishes; 0 disables fencing but
    # keeps the weight_version_lag gauge live (IMPACT, arXiv:1912.00167:
    # unboundedly stale actors corrupt learning silently)
    respawn_attempts: int = 3  # RoleSupervisor: restarts per dead actor role
    # before permanent eviction ('actor_evicted' fault row)
    respawn_base_s: float = 0.2  # respawn backoff base (doubles per attempt,
    # deterministic jitter — the shared RetryPolicy schedule)
    respawn_max_s: float = 5.0  # respawn backoff ceiling
    # ---- learner failover (parallel/failover.py; docs/RESILIENCE.md) --------------
    failover_standby: bool = False  # run a hot-standby learner: tail the
    # active learner's lease and, on expiry, claim the learner role at
    # learner_epoch+1 via the O_EXCL per-epoch claim file, restore the newest
    # VALID checkpoint (+ CRC'd replay snapshot) and resume training at
    # weight versions strictly above the deceased learner's.  Off (default)
    # = no standby machinery runs; the training loop is bitwise the
    # pre-failover path (tier-1 asserted).
    failover_warm: bool = False  # warm standby: additionally tail the
    # WeightMailbox so takeover starts from the freshest published params
    # (restore only replays the delta since the last checkpoint).  Requires
    # failover_standby.
    failover_poll_s: float = 0.5  # standby lease-poll cadence in seconds
    # (bounds claim latency at ~poll + heartbeat_timeout_s)
    failover_takeover_deadline_s: float = 120.0  # how long a standby treats
    # a claim marker ABOVE every learner-role lease as "takeover in
    # progress" (a sibling won the race and is mid-restore) before presuming
    # the claimant died without ever leasing the role and reopening the
    # claim race.  A winner that advertises its lease immediately (the
    # run_standby path) never runs this clock out; the deadline is the
    # fallback for a winner killed between its O_EXCL claim and its first
    # lease beat.

    # ---- environment (SURVEY §2 row 2) -------------------------------------------
    env_id: str = "toy:catch"  # "toy:catch", "toy:chain", or "atari:<Game>"
    # ---- multi-game Ape-X (multitask/; docs/MULTITASK.md) -------------------------
    games: str = ""  # comma-separated env ids ("toy:catch,toy:chain" or
    # "atari:Pong,atari:Breakout"): run N games concurrently in ONE apex pod —
    # a task-conditioned learner (game-id embedding into the IQN torso, one
    # jitted dispatch for every game), per-game actor lanes, per-game replay
    # shard blocks behind a game-interleaved sample schedule, and per-game
    # eval/obs rows.  "" (default) = single-game `env_id`, bitwise-identical
    # to the pre-multitask path (tier-1 asserted).  Single-host only.
    multitask_schedule: str = "uniform"  # per-game learner-batch quota:
    # "uniform" (equal rows per alive game), "loss" (proportional to each
    # game's EMA of retired |TD| — games the learner struggles on get more
    # replay), "mass" (proportional to per-game priority mass — the single
    # global-tree distribution, and the only schedule the device sample
    # frontier composes with, since its HBM draw IS mass-proportional)
    history_length: int = 4  # frame-stack depth
    frame_height: int = 84
    frame_width: int = 84
    action_repeat: int = 4  # with max over the last 2 raw frames
    sticky_actions: float = 0.25  # SABER: repeat-previous-action probability
    max_episode_frames: int = 108_000  # SABER 30-minute cap (raw frames)
    full_action_set: bool = True  # SABER: all 18 ALE actions
    terminal_on_life_loss: bool = False  # SABER: episode ends on game over only
    reward_clip: float = 1.0  # clip rewards to [-c, c]; 0 disables

    # ---- model (SURVEY §2 row 3) --------------------------------------------------
    architecture: str = "iqn"  # "iqn" | "r2d2" (recurrent stretch goal)
    hidden_size: int = 512
    num_cosines: int = 64  # cosine tau-embedding features
    noisy_sigma0: float = 0.5  # NoisyLinear initial sigma
    dueling: bool = True
    compute_dtype: str = "bfloat16"  # MXU-friendly compute; params stay fp32
    # R2D2 (stretch) ----------------------------------------------------------------
    lstm_size: int = 512
    r2d2_burn_in: int = 40
    r2d2_seq_len: int = 80  # trained steps per sequence (after burn-in)
    r2d2_overlap: int = 40  # stride = burn_in + seq_len - overlap
    r2d2_eta: float = 0.9  # sequence priority: eta*max|td| + (1-eta)*mean|td|
    value_rescale_eps: float = 1e-3  # h(x) epsilon (R2D2 value rescaling)

    # ---- IQN tau sampling (SURVEY §3.4) -------------------------------------------
    num_tau_samples: int = 64  # N  : online-net tau draws in the loss
    num_tau_prime_samples: int = 64  # N' : target-net tau draws in the loss
    num_quantile_samples: int = 32  # K  : tau draws used for acting
    kappa: float = 1.0  # Huber threshold

    # ---- agent / optimisation (SURVEY §2 row 4) -----------------------------------
    gamma: float = 0.99
    multi_step: int = 3  # n-step return length
    batch_size: int = 32
    sample_groups: int = 1  # anakin learner: stratified draws of batch_size
    # consumed per learn step (one [G*B] GEMM, per-group IS normalisation,
    # G-sequential priority write-back order) — the batch-64/128 TPU knob
    # that keeps the reference's batch-32 PER stratum width (SURVEY §7
    # "prioritized sampling throughput"; docs/SCALING.md)
    learning_rate: float = 6.25e-5
    adam_eps: float = 1.5e-4
    max_grad_norm: float = 10.0  # 0 disables clipping
    target_update_period: int = 8_000  # learner steps between hard target copies
    learn_start: int = 20_000  # transitions stored before learning begins
    frames_per_learn: int = 4  # env frames per SAMPLED learner batch (the
    # single-process / apex interleave cadence; was named `replay_ratio`
    # through PR 11 — renamed because that name now means batch REUSE below,
    # matching the literature's updates-per-sample sense)
    replay_ratio: int = 1  # learner passes per sampled batch (K).  1
    # (default) = the PR-11 path, bitwise: one SGD pass per sample.  K > 1
    # re-uses each device-staged batch K times inside ONE fori_loop'd XLA
    # executable (no K-fold dispatch), with an IMPACT-style clip
    # (arXiv:1912.00167) on reuse passes 2..K: per-row importance ratios of
    # the current Boltzmann policy (softmax over mean-of-tau q-values at the
    # taken action) against the pass-1 behavior snapshot — evaluated under
    # one shared ratio key, so zero parameter drift means ratio == 1 exactly
    # — are clipped to [1/reuse_clip, reuse_clip] and scale the IS weights,
    # so stale re-consumption can't blow up the IQN loss.  Priorities and
    # the finite guard come from the FINAL pass, written back once per
    # sample, so the WritebackRing still sees one entry per sample.  This is
    # the actor-bound -> device-bound knob: learn_steps/s scales ~K at fixed
    # env-frames/s (docs/PERFORMANCE.md "Replay reuse"; RUNBOOK verdict
    # map).  Implemented for the single-process and apex IQN loops
    # (multitask included); the r2d2/anakin loops reject K > 1.
    reuse_clip: float = 2.0  # IMPACT clip bound c for reuse passes: per-row
    # ratios outside [1/c, c] are clipped (and counted — learn rows carry
    # the per-sample mean clip fraction, the K-too-high early warning)
    t_max: int = 200_000_000  # total env frames of training budget

    # ---- prioritized replay (SURVEY §2 rows 5-6) ----------------------------------
    memory_capacity: int = 1_000_000
    prefetch_depth: int = 2  # learner batch pipeline depth; 0 disables
    writeback_depth: int = 2  # priority write-back ring depth K: step t's
    # priorities are materialized + written to the replay only while step
    # t+K executes on device (utils/writeback.py), and the NaN/Inf guard is
    # checked at the same boundary — the learner hot path issues zero
    # blocking device->host transfers per step.  Priorities (and the guard)
    # lag by exactly K steps, the staleness Ape-X already tolerates
    # (arXiv:1803.00933).  0 = seed behaviour: one blocking sync per step.
    # docs/PERFORMANCE.md has tuning guidance.
    device_sampling: bool = False  # device-resident sample frontier
    # (replay/frontier.py): mirror every replay shard's tree-space priority
    # vector into HBM, draw stratified index batches + IS weights with one
    # fused XLA kernel, assemble frames host-side at those indices via the
    # sample-ahead pusher, and retire priority write-backs directly into the
    # mirror (host sum-trees become the cold path, reconciled at ring
    # drains).  Off (default) keeps the PR-5 host sampling path bitwise
    # intact.  Single-host apex/apex_r2d2 loops only (multi-host falls back
    # to host sampling with a logged notice).  docs/PERFORMANCE.md.
    sample_ahead_depth: int = 2  # ready batches the sample-ahead pusher
    # stages ahead of the learner (its bounded queue depth); 0 disables the
    # frontier exactly like device_sampling=false
    priority_exponent: float = 0.5  # omega
    priority_weight: float = 0.4  # beta_0, annealed to 1 over training
    priority_eps: float = 1e-6
    replay_shards: int = 1  # host-DRAM shards (Redis-shard equivalent)
    use_native_sumtree: bool = True  # C++ core; falls back to NumPy if unbuilt

    # ---- Ape-X topology (SURVEY §2 rows 7-8) --------------------------------------
    role: str = "single"  # "single" | "apex" | "anakin" (HBM-resident replay)
    num_actors: int = 1  # actor loops (vector-env lanes per loop below)
    actor_id: int = 0
    num_envs_per_actor: int = 16  # batched vector-env width per actor loop
    weight_publish_interval: int = 400  # learner steps between weight publishes
    weight_poll_interval: int = 400  # actor frames between weight pulls
    device_frame_stack: bool = True  # apex actors: keep the frame stack on
    # device (ship one [L,H,W] frame/tick, shift+reset inside the jitted act
    # step) instead of host-side FrameStacker shifting — 4x less transfer
    # and no strided host copy; bit-identical stacks (tested)
    fused_env: bool = True  # anakin + jaxgame:* envs: compile the env INTO
    # the act->append->learn graph (zero per-tick host traffic); turn off to
    # drive jax games through the host loop instead
    anakin_segment_ticks: int = 64  # env ticks per fused-graph dispatch
    pipelined_actor: bool = False  # overlap device inference with env stepping
    # (one-tick action lag: the action executed at tick t was computed from
    # the observation at t-1 — Podracer/SEED-style; replay stores the action
    # actually executed, so transitions stay valid and only the behaviour
    # policy is one tick stale)
    initial_priority_from_actor: bool = True  # Ape-X: actors compute initial TD

    # ---- device mesh / sharding (TPU-native; replaces Redis TCP, SURVEY §5) -------
    mesh_shape: str = ""  # e.g. "dp=8" or "dp=4,actor=4"; "" = all devices dp
    learner_devices: int = 0  # 0 = all devices are learner devices
    bf16_weight_sync: bool = True  # cast params to bf16 for the actor broadcast
    # ---- multi-host (jax.distributed over DCN; replaces remote Redis actors) ------
    process_count: int = 1  # pod hosts running this SPMD program
    process_id: int = 0  # this host's index in [0, process_count)
    coordinator_address: str = ""  # host:port of process 0 (the Redis-host flag's heir)

    # ---- serving (serving/; batched low-latency inference, docs/SERVING.md) ------
    serve_batch_buckets: str = "8,16,32,64"  # padded batch sizes; one XLA
    # executable per bucket (rounded up to actor-device multiples at runtime)
    serve_deadline_ms: float = 5.0  # max coalescing wait past the oldest request
    serve_queue_bound: int = 256  # bounded request queue; full = shed
    serve_swap_poll_s: float = 2.0  # checkpoint-watcher poll interval (hot-swap)
    serve_mode: str = "greedy"  # "greedy" (noise off) | "noisy" (eval_noisy-style)
    serve_metrics_interval_s: float = 5.0  # seconds between 'serve' JSONL rows

    # ---- quantized inference + compressed weight distribution -----------------
    # (utils/quantize.py; QuaRL arXiv:1910.01055; docs/PERFORMANCE.md
    # "quantization", docs/SERVING.md config table)
    serve_quantize: str = "off"  # "off" | "int8" | "fp8": quantized policy
    # inference in serving/ engines AND the apex actor lanes.  int8 =
    # symmetric per-channel weight quantization, dequantized inside each
    # bucket's XLA executable (params ship/live int8); fp8 = e4m3 cast
    # (needs ml_dtypes).  Guarded by the greedy-action agreement gate below;
    # "off" (default) keeps today's fp32/bf16 paths bitwise intact.
    quant_agreement_min: float = 0.99  # quantized params serve traffic only
    # when their greedy actions agree with the fp32 policy on at least this
    # fraction of the calibration batch; below -> fp32 fallback + one
    # reasoned 'quant_fallback' row per failed gate
    quant_calib_batch: int = 64  # calibration observations for the gate
    # (serving engines synthesize frames unless handed real ones; apex
    # actors draw the batch from replay observation statistics)
    publish_compression: str = "off"  # "off" | "int8_delta": weight
    # DISTRIBUTION compression (WeightMailbox / FleetRollout): a periodic
    # full base snapshot (bf16 under ml_dtypes, else fp32) plus int8
    # per-tensor-scaled deltas against the last reconstruction —
    # subscribers rebuild bit-exact; >=3x fewer bytes/publish than fp32
    # full (gated in `make perf-smoke`).  "off" = today's full publishes.
    publish_base_interval: int = 10  # publishes between full base snapshots
    # (the delta chain a late joiner replays is at most this long)

    # ---- serving fleet (serving/fleet/; docs/SERVING.md "fleet") ------------------
    fleet_min_engines: int = 1  # autoscaler floor
    fleet_max_engines: int = 4  # autoscaler ceiling
    fleet_max_inflight: int = 512  # router global inflight bound (admission
    # backstop; per-class caps are shares of this)
    fleet_qos_classes: str = "gold:50:0.5,std:200:0.35,batch:1000:0.15"
    # priority-ordered deadline tiers, name:deadline_ms:inflight_share —
    # a class is capped at its share of fleet_max_inflight AND lower classes
    # cannot consume headroom still reserved by higher ones, so the shed
    # order under global pressure is strictly lowest-class-first
    fleet_default_class: str = "std"  # tenants with no explicit class
    fleet_tenant_rate: float = 0.0  # per-tenant token-bucket refill
    # (requests/s); 0 = unlimited — rate isolation off
    fleet_tenant_burst: int = 64  # per-tenant token-bucket capacity
    fleet_lease_interval_s: float = 0.5  # engine lease renewal cadence
    fleet_lease_timeout_s: float = 3.0  # lease older than this = dead engine
    fleet_scale_up_depth: float = 0.75  # mean engine queue fill -> scale OUT
    fleet_scale_down_depth: float = 0.2  # ... -> scale IN
    fleet_scale_p99_ms: float = 0.0  # p99 latency scale-out trigger; 0 = off
    fleet_scale_patience: int = 3  # consecutive breaches before acting
    fleet_scale_cooldown_s: float = 10.0  # hold after any scale action

    # ---- cross-host serving plane (serving/net/; docs/SERVING.md "cross-host") ----
    serve_net_host: str = ""  # bind address for this engine's framed-socket
    # TransportServer ("" = cross-host serving OFF, the default: the fleet
    # stays in-process and every code path is bitwise the pre-net behaviour;
    # "0.0.0.0" binds all interfaces and advertises serve_net_advertise)
    serve_net_port: int = 0  # listen port; 0 = ephemeral — the engine's
    # lease payload advertises whatever was bound, so routers discover the
    # endpoint through the lease files they already watch
    serve_net_advertise: str = ""  # address peers dial ("" = the bind host;
    # set it when binding a wildcard or behind NAT)
    serve_net_max_frame_mb: int = 64  # frames declaring more than this are
    # rejected BEFORE allocation with a reasoned error (serving/net/framing)
    serve_net_probe_timeout_s: float = 0.5  # bounded per-probe budget for
    # registry transport-liveness pings — one hung remote can never stall
    # the discovery/eviction sweep past this
    serve_net_probe_interval_s: float = 1.0  # per-engine probe cadence
    serve_net_gossip_port: int = 0  # router-federation UDP bind; 0 = ephemeral
    serve_net_gossip_peers: str = ""  # comma "host:port" list of peer
    # routers; "" = solo router, federation off (no gossip socket at all)
    serve_net_gossip_interval_s: float = 1.0  # snapshot broadcast cadence

    # ---- cross-host replay plane (replay/net/; docs/RESILIENCE.md) ----------------
    replay_net_host: str = ""  # bind address for this process's replay shard
    # server ("" = no shard server in this process, the default; a shard
    # server process sets it and registers a `replay_shard` lease carrying
    # addr:port + shard range + epoch)
    replay_net_port: int = 0  # listen port; 0 = ephemeral — the lease payload
    # advertises whatever was bound, same discovery as serve_net_port
    replay_net_advertise: str = ""  # address peers dial ("" = the bind host;
    # set it when binding a wildcard or behind NAT)
    replay_net_remote: bool = False  # learner/actor client gate: True swaps
    # the in-process ShardedReplay for the cross-host plane (appends spool to
    # AppendClients, samples pipeline through a SampleClient, priorities ride
    # batched update frames).  False — the default — keeps replay in-process
    # and every code path bitwise the pre-plane behaviour (tier-1 asserted).
    replay_net_max_frame_mb: int = 64  # frames declaring more than this are
    # rejected BEFORE allocation with a reasoned error (netcore/framing)
    replay_net_spool: int = 4096  # actor-side spool capacity in ticks: the
    # buffering horizon an unreachable shard server is ridden out over; a
    # FULL spool sheds the newest tick with a reasoned row (actors never
    # block on the wire)
    replay_net_inflight: int = 4  # bounded in-flight append blocks per
    # AppendClient — the backpressure window between spool and wire
    replay_net_probe_timeout_s: float = 0.5  # bounded per-probe budget for
    # plane liveness pings (one hung shard server never stalls the sweep)
    replay_net_shard_base: int = 0  # first GLOBAL shard id this process's
    # shard server owns — multitask pins game-major shard blocks to servers
    # by spacing bases (shards-per-game apart), the multi-host multi-game
    # composition
    replay_net_shard_count: int = 0  # shards this server owns; 0 = all
    # `replay_shards` (the single-server topology)
    replay_net_ring_depth: int = 2  # server-side sample-ahead: pre-assembled,
    # pre-ENCODED batches kept per connected sampler so `sample` answers
    # from the event loop instead of queueing behind appends; 0 disables
    # (every sample assembles on demand).  Staleness bound: a ring entry's
    # priorities are at most ring_depth samples old.
    replay_net_sample_many: int = 4  # batches per sample RPC once codec v2 is
    # negotiated (one frame carries N pre-assembled batches, amortizing
    # header/syscall/queue-wait costs); clamped to [1, 16] server-side
    replay_net_depth_min: int = 1  # floor of the SampleClient's ADAPTIVE
    # pipeline depth (in batches)
    replay_net_depth_max: int = 8  # ceiling of the adaptive pipeline depth:
    # the depth tracks ceil(rtt / consume-gap)+1 between these bounds, so a
    # fast loopback link stops parking depth_max batches of staleness while
    # a slow WAN link pipelines deep enough to never starve the learner
    replay_net_shm_mb: int = 64  # per-sampler-connection shared-memory arena
    # (replay/net/shm.py): colocated samplers receive batches as zero-copy
    # views over a memfd the server writes once, skipping both socket
    # kernel copies.  0 disables arenas (AF_UNIX byte path still applies);
    # only consulted when `replay_net_local_fastpath` is on.
    replay_net_local_fastpath: bool = True  # same-host fast path: the server
    # listens on an abstract AF_UNIX socket beside its TCP port and local
    # clients (host in {127.0.0.1, ::1, localhost}) dial it first, falling
    # back to TCP on any miss.  Off = every connection uses TCP (bitwise
    # the cross-host wire path, useful for debugging)

    # ---- league / population-based training (league/; docs/LEAGUE.md) -------------
    league_dir: str = ""  # shared league state directory (genomes, per-member
    # weight mailboxes, exploit directives).  "" = league OFF everywhere — the
    # default: no league code runs and every training loop is bitwise the
    # pre-league path (tier-1 asserted).  The CONTROLLER (league/controller.py)
    # and every MEMBER trainer point at the same directory.
    league_population: int = 0  # members the league controller supervises
    # (controller side; each member is a RoleSupervisor role with its own
    # lease, genome, and mailbox pair).  0 = off; >= 2 required when on —
    # a 1-member population has nobody to exploit (check_league_config).
    league_member_id: int = -1  # THIS trainer process is league member k
    # (trainer side: genome overlay at loop start, outbox weight publishes,
    # exploit-directive polls at drain boundaries).  < 0 = not a member.
    league_fitness_window: int = 4  # eval rows per member in the windowed
    # human-normalized fitness (league/fitness.py); NaN/missing evals are
    # skipped, a member with zero windowed evals has fitness None and is
    # excluded from exploit on BOTH sides (missing-eval tolerance)
    league_exploit_interval_s: float = 30.0  # controller seconds between
    # truncation exploit/explore sweeps (bottom quantile copies a top-
    # quantile member's weights bit-exactly + perturbs its genome)
    league_bottom_quantile: float = 0.25  # fraction of ranked members that
    # EXPLOIT (copy weights, perturb genome) each sweep
    league_top_quantile: float = 0.25  # fraction of ranked members eligible
    # as copy SOURCES; bottom + top must not overlap (<= 1.0)
    league_perturb_factor: float = 1.2  # explore: continuous genes multiply
    # or divide by this (seeded coin); must be > 0 (check_league_config)
    league_resample_prob: float = 0.1  # explore: probability a perturbed
    # gene is instead resampled fresh from its prior range

    # ---- evaluation (SURVEY §2 row 9) ---------------------------------------------
    eval_episodes: int = 10
    eval_interval: int = 50_000  # learner steps between in-training evals; 0 = off
    eval_noisy: bool = False  # noise off at eval time (§8 open question: default off)

    # -------------------------------------------------------------------------------
    @property
    def state_shape(self) -> Tuple[int, int, int]:
        """Observation shape fed to the network: HWC with stacked history as C.

        NHWC is the TPU-native conv layout (XLA tiles the trailing C dim onto
        the 128-lane axis), unlike the reference's NCHW torch layout.
        """
        return (self.frame_height, self.frame_width, self.history_length)

    def replace(self, **kwargs: Any) -> "Config":
        return dataclasses.replace(self, **kwargs)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "Config":
        return Config(**json.loads(text))


def _add_args(parser: argparse.ArgumentParser) -> None:
    """Expose every Config field as a ``--flag`` (underscores become dashes)."""
    for field in dataclasses.fields(Config):
        name = "--" + field.name.replace("_", "-")
        if field.type == "bool" or isinstance(field.default, bool):
            parser.add_argument(
                name,
                type=lambda s: s.lower() in ("1", "true", "yes", "on"),
                default=field.default,
                metavar="BOOL",
            )
        else:
            parser.add_argument(name, type=type(field.default), default=field.default)


def parse_config(argv: Optional[list] = None, **overrides: Any) -> Config:
    """Build a Config from CLI args (mirrors the reference's single argparse)."""
    parser = argparse.ArgumentParser(description="TPU-native Rainbow-IQN Ape-X")
    _add_args(parser)
    ns = parser.parse_args(argv)
    cfg = Config(**vars(ns))
    if overrides:
        cfg = cfg.replace(**overrides)
    return cfg
