"""Evaluation under the SABER protocol (reference parity: `test_agent.py`,
SURVEY.md §3.5) — load/point at a trained agent, run E episodes with greedy
acting (noise off by default; `eval_noisy` restores noisy eval), report raw
mean/median scores plus normalised scores when baselines are known."""

from __future__ import annotations

import functools

import jax

from typing import Any, Dict, Optional

import numpy as np

from rainbow_iqn_apex_tpu.agents.agent import Agent, FrameStacker
from rainbow_iqn_apex_tpu.atari57 import ATARI57_BASELINES
from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.envs import make_env

# Published per-game random/human baselines used for human-normalised scores
# (Rainbow paper appendix convention), keyed by env_id.  Toy entries are
# analytic; the Atari-57 rows come from the shared table in atari57.py (same
# RECON caveat as there — recall-sourced, re-verify before publication).
HUMAN_BASELINES: Dict[str, Dict[str, float]] = {
    # env_id: {"random": r, "human": h}
    "toy:catch": {"random": -0.8, "human": 1.0},  # analytic: random ~ 2/size - 1
    "toy:chain": {"random": 0.15, "human": 1.0},
}
HUMAN_BASELINES.update(
    {
        f"atari:{game}": {"random": random, "human": human}
        for game, (random, human) in ATARI57_BASELINES.items()
    }
)


def human_normalized(env_id: str, score: float) -> Optional[float]:
    base = HUMAN_BASELINES.get(env_id)
    if not base or base["human"] == base["random"]:
        return None
    return (score - base["random"]) / (base["human"] - base["random"])


def evaluate(
    cfg: Config,
    agent: Agent,
    episodes: Optional[int] = None,
    seed: int = 0,
    max_steps_per_episode: int = 200_000,
) -> Dict[str, Any]:
    """Run E eval episodes on a fresh env; returns score stats."""
    episodes = episodes or cfg.eval_episodes
    env = make_env(cfg.env_id, seed=seed)
    scores = []
    for ep in range(episodes):
        stacker = FrameStacker(1, env.frame_shape, cfg.history_length)
        frame = env.reset()
        ep_ret = 0.0
        for _ in range(max_steps_per_episode):
            stacked = stacker.push(frame[None])
            action = int(agent.act(stacked, eval_mode=True)[0])
            ts = env.step(action)
            frame = ts.obs
            ep_ret += ts.reward
            if ts.terminal or ts.truncated:
                if ts.info and "episode_return" in ts.info:
                    ep_ret = float(ts.info["episode_return"])  # raw, unclipped
                break
        scores.append(ep_ret)
    arr = np.asarray(scores, np.float64)
    out: Dict[str, Any] = {
        "episodes": episodes,
        "score_mean": float(arr.mean()),
        "score_median": float(np.median(arr)),
        "score_min": float(arr.min()),
        "score_max": float(arr.max()),
    }
    hn = human_normalized(cfg.env_id, out["score_mean"])
    if hn is not None:
        out["human_normalized"] = hn
    return out


@functools.lru_cache(maxsize=4)
def _cached_eval_agent(cfg: Config, num_actions: int, frame_shape):
    """One throwaway eval Agent per (cfg, env) — its jitted act function is
    retraced only on a config change, not on every eval interval."""
    return Agent(
        cfg,
        num_actions,
        jax.random.PRNGKey(cfg.seed + 1),
        train=False,
        state_shape=(*frame_shape, cfg.history_length),
    )


def evaluate_state(cfg: Config, env, state, seed: int = 0) -> Dict[str, Any]:
    """Evaluate a learner's current TrainState on a single-device eval agent
    (reference evaluates the learner checkpoint, SURVEY §3.5).  Shared by the
    apex driver and the anakin trainer."""
    agent = _cached_eval_agent(cfg, env.num_actions, tuple(env.frame_shape))
    agent.state = jax.device_put(state, jax.local_devices()[0])
    # fresh key per eval: two evals of the same params draw identical
    # taus/noise (bit-reproducible curves), as the pre-cache fresh-Agent
    # construction did
    agent.key = jax.random.PRNGKey(cfg.seed + 1)
    return evaluate(cfg, agent, seed=seed)
