from rainbow_iqn_apex_tpu.ops.learn import (
    Batch,
    TrainState,
    build_act_step,
    build_learn_step,
    init_train_state,
    make_network,
    make_optimizer,
)
from rainbow_iqn_apex_tpu.ops.losses import huber, quantile_huber_loss

__all__ = [
    "Batch",
    "TrainState",
    "build_act_step",
    "build_learn_step",
    "init_train_state",
    "make_network",
    "make_optimizer",
    "huber",
    "quantile_huber_loss",
]
