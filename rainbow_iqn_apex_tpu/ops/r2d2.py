"""R2D2 sequence learn step: burn-in, value rescaling, n-step double-Q.

Parity: the reference's R2D2 stretch config (BASELINE.json:10) per
Kapturowski et al. (R2D2): train a recurrent Q-net on stored-state replay
sequences — replay the first `burn_in` steps with stop-gradient to warm the
LSTM state, train on the remainder; targets use the invertible value rescale
h(x) = sign(x)(sqrt(|x|+1) - 1) + eps*x; sequence priority is the eta-mix
eta*max|td| + (1-eta)*mean|td|.

Everything is one jitted graph over [B, L] sequences: two lax.scans (burn-in
and train unroll) plus dense [B, T] target algebra — no per-step Python.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import chex
import jax
import jax.numpy as jnp
import optax
from flax import struct

from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.models.r2d2 import LSTMState, R2D2Net
from rainbow_iqn_apex_tpu.ops.learn import make_optimizer
from rainbow_iqn_apex_tpu.ops.losses import huber

Params = Any


# ----------------------------------------------------------- value rescaling
def value_rescale(x: jnp.ndarray, eps: float = 1e-3) -> jnp.ndarray:
    """h(x) = sign(x) * (sqrt(|x| + 1) - 1) + eps * x."""
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def value_unrescale(x: jnp.ndarray, eps: float = 1e-3) -> jnp.ndarray:
    """h^-1: exact closed form (R2D2 appendix)."""
    inner = jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps)) - 1.0
    return jnp.sign(x) * ((inner / (2.0 * eps)) ** 2 - 1.0)


# ------------------------------------------------------------------ batches
@struct.dataclass
class SequenceBatch:
    """[B, L] training sequences; L = burn_in + train_len."""

    obs: jnp.ndarray  # [B, L, H, W, C] uint8
    action: jnp.ndarray  # [B, L] int32
    reward: jnp.ndarray  # [B, L] f32
    done: jnp.ndarray  # [B, L] bool — episode ended AT step t
    valid: jnp.ndarray  # [B, L] bool — step belongs to the episode
    init_c: jnp.ndarray  # [B, lstm] stored recurrent state at sequence start
    init_h: jnp.ndarray  # [B, lstm]
    weight: jnp.ndarray  # [B] f32 IS weights


def stack_seq_frames(obs_seq: jnp.ndarray, history: int) -> jnp.ndarray:
    """Within-sequence frame stacking on device: [B, L, H, W, 1] ->
    [B, L, H, W, history], channel k holding the frame from t-(history-1-k).

    The R2D2 paper feeds 4-stacked frames AND an LSTM; sequences are stored
    as single frames (dedup) and the stack is rebuilt here as shifted slices
    — static shapes, fused by XLA, no extra HBM-resident copies on the host
    path. Steps earlier than the sequence start zero-pad, which only touches
    the first history-1 steps of the burn-in region (burn_in >= history-1 in
    any sane config), whose sole job is LSTM warm-up.
    """
    if history <= 1:
        return obs_seq
    shifted = [
        jnp.pad(obs_seq[:, : obs_seq.shape[1] - k], ((0, 0), (k, 0), (0, 0), (0, 0), (0, 0)))
        for k in range(history - 1, -1, -1)
    ]
    return jnp.concatenate(shifted, axis=-1)


def to_device_seq_batch(s) -> "SequenceBatch":
    """Host SequenceSample -> device SequenceBatch (async jnp.asarray)."""
    return SequenceBatch(
        obs=jnp.asarray(s.obs),
        action=jnp.asarray(s.action),
        reward=jnp.asarray(s.reward),
        done=jnp.asarray(s.done),
        valid=jnp.asarray(s.valid),
        init_c=jnp.asarray(s.init_c),
        init_h=jnp.asarray(s.init_h),
        weight=jnp.asarray(s.weight),
    )


@struct.dataclass
class R2D2TrainState:
    params: Params
    target_params: Params
    opt_state: optax.OptState
    step: jnp.ndarray


def make_r2d2_network(cfg: Config, num_actions: int, use_noise: bool = True) -> R2D2Net:
    return R2D2Net(
        num_actions=num_actions,
        lstm_size=cfg.lstm_size,
        hidden_size=cfg.hidden_size,
        noisy_sigma0=cfg.noisy_sigma0,
        dueling=cfg.dueling,
        use_noise=use_noise,
        compute_dtype=jnp.dtype(cfg.compute_dtype),
    )


def init_r2d2_state(
    cfg: Config,
    num_actions: int,
    key: chex.PRNGKey,
    frame_shape: Tuple[int, int],
    channels: Optional[int] = None,
) -> R2D2TrainState:
    """channels defaults to cfg.history_length (frame-stacked input)."""
    net = make_r2d2_network(cfg, num_actions)
    k1, k2 = jax.random.split(key)
    dummy = jnp.zeros((1, 2, *frame_shape, channels or cfg.history_length), jnp.uint8)
    params = net.init(
        {"params": k1, "noise": k2}, dummy, net.initial_state(1)
    )["params"]
    opt_state = make_optimizer(cfg).init(params)
    return R2D2TrainState(
        params=params,
        target_params=jax.tree.map(jnp.copy, params),
        opt_state=opt_state,
        step=jnp.zeros((), jnp.int32),
    )


def _unroll(
    net: R2D2Net,
    params: Params,
    batch: SequenceBatch,
    burn_in: int,
    noise_key: chex.PRNGKey,
) -> jnp.ndarray:
    """Burn-in (stop-grad) then train unroll; returns q [B, T, A] for the
    train slice.  LSTM state resets where a step follows a terminal."""
    # reset BEFORE step t when the previous step ended the episode
    prev_done = jnp.concatenate(
        [jnp.zeros_like(batch.done[:, :1]), batch.done[:, :-1]], axis=1
    )
    state: LSTMState = (batch.init_c, batch.init_h)
    kb, kt = jax.random.split(noise_key)
    if burn_in > 0:
        _, state = net.apply(
            {"params": params},
            batch.obs[:, :burn_in],
            state,
            resets=prev_done[:, :burn_in],
            rngs={"noise": kb},
        )
        state = jax.lax.stop_gradient(state)
    q, _ = net.apply(
        {"params": params},
        batch.obs[:, burn_in:],
        state,
        resets=prev_done[:, burn_in:],
        rngs={"noise": kt},
    )
    return q  # [B, T, A]


def build_r2d2_learn_step(
    cfg: Config, num_actions: int
) -> Callable[[R2D2TrainState, SequenceBatch, chex.PRNGKey],
              Tuple[R2D2TrainState, Dict[str, jnp.ndarray]]]:
    net = make_r2d2_network(cfg, num_actions)
    tx = make_optimizer(cfg)
    burn, n, gamma = cfg.r2d2_burn_in, cfg.multi_step, cfg.gamma
    eta, eps_h = cfg.r2d2_eta, cfg.value_rescale_eps

    history = cfg.history_length
    if history > 1 and burn < history - 1:
        raise ValueError(
            f"r2d2_burn_in ({burn}) must be >= history_length-1 "
            f"({history - 1}): on-device frame stacking zero-pads the first "
            "history-1 steps of each sequence, which must fall inside the "
            "burn-in region or the loss trains on observations the actor "
            "never saw"
        )

    def learn_step(state: R2D2TrainState, batch: SequenceBatch, key: chex.PRNGKey):
        k_on, k_tgt = jax.random.split(key)
        if history > 1 and batch.obs.shape[-1] == 1:
            # single-frame stored sequences -> stacked network input
            batch = batch.replace(obs=stack_seq_frames(batch.obs, history))
        T = batch.obs.shape[1] - burn  # train slice length

        def loss_fn(params):
            q_on = _unroll(net, params, batch, burn, k_on)  # [B, T, A]
            # Double-Q selection reuses the online unroll (stop-grad) rather
            # than paying a third full conv+LSTM unroll for an independent
            # noise draw — selection and evaluation already use different
            # nets, which is where double-Q's bias correction comes from.
            q_sel = jax.lax.stop_gradient(q_on)
            q_tgt = _unroll(net, state.target_params, batch, burn, k_tgt)

            a = batch.action[:, burn:]  # [B, T]
            r = batch.reward[:, burn:]
            d = batch.done[:, burn:].astype(jnp.float32)
            v = batch.valid[:, burn:].astype(jnp.float32)

            q_taken = jnp.take_along_axis(q_on, a[..., None], axis=-1)[..., 0]

            # --- n-step double-Q bootstrap, all within the train slice ------
            a_star = jnp.argmax(q_sel, axis=-1)  # [B, T]
            q_boot = value_unrescale(
                jnp.take_along_axis(q_tgt, a_star[..., None], axis=-1)[..., 0],
                eps_h,
            )
            # shifted windows: for t in [0, T-n): R = sum_k gamma^k r[t+k]
            # (truncated at terminal), bootstrap from t+n if alive.
            Tn = T - n
            gammas = gamma ** jnp.arange(n, dtype=jnp.float32)
            r_win = jnp.stack([r[:, k : k + Tn] for k in range(n)], axis=-1)  # [B,Tn,n]
            d_win = jnp.stack([d[:, k : k + Tn] for k in range(n)], axis=-1)
            alive_prefix = jnp.cumprod(1.0 - d_win[..., :-1], axis=-1)
            alive_prefix = jnp.concatenate(
                [jnp.ones_like(alive_prefix[..., :1]), alive_prefix], axis=-1
            )
            rn = (r_win * alive_prefix * gammas).sum(axis=-1)  # [B, Tn]
            done_win = jnp.clip(d_win.sum(axis=-1), 0.0, 1.0)
            no_done = 1.0 - done_win
            y = value_rescale(
                rn + (gamma**n) * no_done * q_boot[:, n:], eps_h
            )
            td = jax.lax.stop_gradient(y) - q_taken[:, :Tn]
            # A step's target is usable iff its n-step window ends inside the
            # episode: either a true terminal falls within the window (reward
            # sum truncates there, no bootstrap) or the bootstrap step t+n is
            # itself valid. A time-limit TRUNCATION ends the valid region
            # with done=False (two-channel cuts, replay/sequence.py), so
            # windows that cross it have neither — they are masked out rather
            # than bootstrapping from padding (which would teach V=0 at the
            # cut, the time-limit bias the frame replay also avoids).
            target_ok = jnp.clip(done_win + v[:, n:], 0.0, 1.0)
            mask = v[:, :Tn] * target_ok
            td = td * mask

            per_seq_loss = (huber(td, 1.0).sum(axis=1)) / jnp.maximum(
                mask.sum(axis=1), 1.0
            )
            loss = jnp.mean(batch.weight * per_seq_loss)

            abs_td = jnp.abs(td)
            max_td = abs_td.max(axis=1)
            mean_td = abs_td.sum(axis=1) / jnp.maximum(mask.sum(axis=1), 1.0)
            priorities = eta * max_td + (1.0 - eta) * mean_td
            aux = {
                "priorities": priorities,
                "q_mean": (q_taken * v).sum() / jnp.maximum(v.sum(), 1.0),
            }
            return loss, aux

        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        step = state.step + 1
        do_copy = (step % cfg.target_update_period == 0).astype(jnp.float32)
        target_params = jax.tree.map(
            lambda t, o: do_copy * o + (1.0 - do_copy) * t,
            state.target_params,
            params,
        )
        grad_norm = optax.global_norm(grads)
        info = {
            "loss": loss,
            "priorities": aux["priorities"],
            "q_mean": aux["q_mean"],
            "grad_norm": grad_norm,
            # on-device NaN/Inf guard flag (same contract as ops/learn.py:
            # checked host-side at the write-back ring boundary)
            "finite": jnp.isfinite(loss) & jnp.isfinite(grad_norm),
        }
        return (
            R2D2TrainState(
                params=params,
                target_params=target_params,
                opt_state=opt_state,
                step=step,
            ),
            info,
        )

    return learn_step


def as_actor_input(obs, history: int):
    """Normalise actor observations to [B, H, W, C] and enforce that C
    matches the training channel count (the host FrameStacker supplies the
    stack when history > 1).  Stays host NumPy — the caller decides how the
    array reaches the device (jit argument upload, or
    make_array_from_process_local_data on a multi-host mesh) so no extra
    host->device->host round trip sneaks into the actor tick."""
    import numpy as np

    x = np.asarray(obs)
    if x.ndim == 3:
        x = x[..., None]
    if x.shape[-1] != history:
        raise ValueError(
            f"actor obs has {x.shape[-1]} channels but history_length is "
            f"{history}; feed FrameStacker output (or raw [B,H,W] frames "
            "when history_length == 1)"
        )
    return x


def build_r2d2_act_step(
    cfg: Config, num_actions: int, use_noise: bool = True
) -> Callable:
    """Recurrent acting: (params, obs [B,H,W,C] u8, state, key) ->
    (action [B], q [B,A], new_state).  C must match the training channels
    (cfg.history_length when frame-stacking; the host FrameStacker supplies
    it on the actor side)."""
    net = make_r2d2_network(cfg, num_actions, use_noise=use_noise)

    def act_step(params, obs, state: LSTMState, key):
        q, new_state = net.apply(
            {"params": params},
            obs[:, None],  # [B, 1, H, W, C]
            state,
            rngs={"noise": key},
        )
        q = q[:, 0]
        return jnp.argmax(q, axis=-1).astype(jnp.int32), q, new_state

    return act_step
