from rainbow_iqn_apex_tpu.ops.pallas.quantile_huber import pallas_quantile_huber

__all__ = ["pallas_quantile_huber"]
