"""Pallas TPU kernel: fused pairwise quantile-Huber loss with custom VJP.

The §3.4 kernel's hot middle: u = target[:,None,:] - online[:,:,None] is a
[B, N, N'] intermediate.  XLA usually fuses the elementwise chain, but the
backward pass re-materialises the pairwise tensor from HBM-resident inputs.
This kernel computes, in one VMEM pass per batch block:
  - per-sample loss   sum_i mean_j rho_ij
  - td_abs            mean_ij |u_ij|        (the PER priority signal)
  - d loss / d online (the only input that needs a gradient; taus are
    sampled, targets are stop-gradient)
so the [B, N, N'] tensor never touches HBM in either direction.

TPU lowering constraints (learned from the first on-chip compile, round 2):
  - rank-1 blocks may only tile a rank-1 array if the block spans the whole
    array; the per-sample outputs are therefore carried as rank-2 [B, 1] and
    squeezed on the way out.
  - the sublane (second-to-last) block dim must be a multiple of 8 or span
    the array, so the batch block is 8-aligned with a full-batch fallback.
  - kappa is a static Python float (a nondiff argnum already), so it is
    baked into the kernel instead of riding along as an SMEM ref.

Gated by Config.use_pallas_loss; ops/losses.py is the jnp reference the unit
tests compare against (interpret mode on CPU, compiled on TPU).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BLOCK_B = 8  # samples per program instance (8-aligned; tuned on-chip)


def _make_kernel(kappa: float):
    def _qh_kernel(online_ref, taus_ref, target_ref, loss_ref, td_ref, grad_ref):
        """One batch block: online/taus [TB, N], target [TB, N'] in VMEM."""
        online = online_ref[:]  # [TB, N]
        taus = taus_ref[:]
        target = target_ref[:]  # [TB, N']

        u = target[:, None, :] - online[:, :, None]  # [TB, N, N'] VMEM-only
        abs_u = jnp.abs(u)
        quad = abs_u <= kappa
        hub = jnp.where(quad, 0.5 * u * u, kappa * (abs_u - 0.5 * kappa))
        w = jnp.abs(taus[:, :, None] - (u < 0.0).astype(jnp.float32))
        rho = w * hub / kappa

        npr = u.shape[-1]
        loss_ref[:] = rho.mean(axis=2).sum(axis=1)[:, None]  # [TB, 1]
        td_ref[:] = abs_u.mean(axis=(1, 2))[:, None]  # [TB, 1]
        # d rho/d online_i = -w_ij * clip(u, -kappa, kappa)/kappa ; mean over j
        dhub = jnp.clip(u, -kappa, kappa) / kappa
        grad_ref[:] = -(w * dhub).sum(axis=2) / npr  # [TB, N]

    return _qh_kernel


def _block_b(B: int) -> int:
    """Largest legal batch block: BLOCK_B when it divides B and is 8-aligned
    (TPU sublane rule), else the whole batch (block == array is always legal).
    The 8-alignment clause is live: scripts/bench_pallas.py retunes the
    module-level BLOCK_B at runtime, including non-8-aligned candidates."""
    if B % BLOCK_B == 0 and (BLOCK_B % 8 == 0 or BLOCK_B == B):
        return BLOCK_B
    return B


def _run_kernel(online, taus, target, kappa, interpret):
    B, N = online.shape
    NP = target.shape[1]
    TB = _block_b(B)
    grid = (B // TB,)
    out_shapes = (
        jax.ShapeDtypeStruct((B, 1), jnp.float32),  # loss
        jax.ShapeDtypeStruct((B, 1), jnp.float32),  # td_abs
        jax.ShapeDtypeStruct((B, N), jnp.float32),  # grad wrt online
    )
    loss, td, grad = pl.pallas_call(
        _make_kernel(float(kappa)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TB, N), lambda i: (i, 0)),
            pl.BlockSpec((TB, N), lambda i: (i, 0)),
            pl.BlockSpec((TB, NP), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((TB, 1), lambda i: (i, 0)),
            pl.BlockSpec((TB, 1), lambda i: (i, 0)),
            pl.BlockSpec((TB, N), lambda i: (i, 0)),
        ),
        out_shape=out_shapes,
        interpret=interpret,
    )(online.astype(jnp.float32), taus.astype(jnp.float32),
      target.astype(jnp.float32))
    return loss[:, 0], td[:, 0], grad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def pallas_quantile_huber(
    online: jnp.ndarray,  # [B, N]
    taus: jnp.ndarray,  # [B, N]
    target: jnp.ndarray,  # [B, N']
    kappa: float = 1.0,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (per_sample_loss [B], td_abs [B]); grads flow to online only."""
    loss, td, _ = _run_kernel(online, taus, target, kappa, interpret)
    return loss, td


def _fwd(online, taus, target, kappa, interpret):
    loss, td, grad = _run_kernel(online, taus, target, kappa, interpret)
    return (loss, td), grad


def _bwd(kappa, interpret, grad, cotangents):
    g_loss, _g_td = cotangents  # td_abs path carries no gradient (priorities)
    d_online = grad * g_loss[:, None]
    return d_online, None, None


pallas_quantile_huber.defvjp(_fwd, _bwd)
