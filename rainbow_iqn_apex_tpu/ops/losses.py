"""Quantile-regression losses.

Parity: reference learn-step loss core (SURVEY.md §3.4) — the pairwise
quantile-Huber loss of IQN (Dabney et al. arXiv:1806.06923, eq. 3):

    u_ij   = td_target_j - online_quantile_i
    rho^k  = |tau_i - 1{u_ij < 0}| * Huber_k(u_ij) / k
    loss   = sum_i mean_j rho^k_ij        (per sample)

Everything here is pure jnp on [B, N, N'] tensors; XLA fuses the whole thing
into the learn-step graph (no per-pair Python loops, no dynamic shapes).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def huber(u: jnp.ndarray, kappa: float) -> jnp.ndarray:
    """Elementwise Huber_k(u): quadratic within |u|<=k, linear outside."""
    abs_u = jnp.abs(u)
    return jnp.where(
        abs_u <= kappa,
        0.5 * u**2,
        kappa * (abs_u - 0.5 * kappa),
    )


def quantile_huber_loss(
    online_quantiles: jnp.ndarray,  # [B, N]   Z_tau_i(s, a)
    taus: jnp.ndarray,  # [B, N]   online tau_i
    td_targets: jnp.ndarray,  # [B, N']  r + gamma^n Z_tau'_j(s', a*)
    kappa: float = 1.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pairwise quantile-Huber loss.

    Returns:
        per_sample_loss: [B] — sum over online taus of the mean over target taus.
        td_abs:          [B] — mean |u_ij|, the priority signal
                         (reference uses mean |TD|, SURVEY.md §2 row 4).
    """
    u = td_targets[:, None, :] - online_quantiles[:, :, None]  # [B, N, N']
    indicator = (u < 0.0).astype(jnp.float32)
    weight = jnp.abs(taus[:, :, None] - indicator)  # |tau_i - 1{u<0}|
    rho = weight * huber(u, kappa) / kappa
    per_sample_loss = rho.mean(axis=2).sum(axis=1)  # mean_j, sum_i
    td_abs = jnp.abs(u).mean(axis=(1, 2))
    return per_sample_loss, td_abs
