"""The fused learner step: one XLA graph from tau sampling to Adam update.

Parity: reference `Agent.learn()` (SURVEY.md §2 row 4, §3.1/§3.4) — sample
batch -> N online-tau / N' target-tau quantile-Huber loss with double-Q action
selection, n-step targets, IS-weight multiply -> Adam step -> new priorities
from per-sample |TD|; hard target-net copy on a schedule.

TPU-first design notes (north star: BASELINE.json:5 "compile to a single XLA
graph on the learner cores"):
- `learn_step` is a pure function of (TrainState, Batch, key); jitted once per
  shape, with the TrainState donated so parameter/optimizer buffers update
  in place in HBM.
- The periodic hard target copy is folded into the same graph via a `where`
  select keyed on the step counter, so there is no second dispatch and no
  host round-trip on the update schedule.
- n-step return assembly happens host-side in the replay (ragged, pointer-y
  work); the device sees only dense [B, ...] tensors.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import chex
import jax
import jax.numpy as jnp
import optax
from flax import struct

from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.models.iqn import RainbowIQN, greedy_action, q_values
from rainbow_iqn_apex_tpu.ops.losses import quantile_huber_loss

Params = Any


@struct.dataclass
class Batch:
    """One dense learner batch (all shapes static)."""

    obs: jnp.ndarray  # [B, H, W, C] uint8
    action: jnp.ndarray  # [B] int32
    reward: jnp.ndarray  # [B] f32 — n-step discounted return sum_k gamma^k r_k
    next_obs: jnp.ndarray  # [B, H, W, C] uint8
    discount: jnp.ndarray  # [B] f32 — gamma^n * (1 - done)
    weight: jnp.ndarray  # [B] f32 — PER importance-sampling weights
    game: Optional[jnp.ndarray] = None  # [B] int32 game ids — multi-game
    # runs only (multitask/ops.py conditions the net on it); None on the
    # single-game path, an empty pytree node that changes no numerics


@struct.dataclass
class TrainState:
    params: Params
    target_params: Params
    opt_state: optax.OptState
    step: jnp.ndarray  # [] int32 — learner steps taken


def make_optimizer(cfg: Config) -> optax.GradientTransformation:
    tx = optax.adam(cfg.learning_rate, eps=cfg.adam_eps)
    if cfg.max_grad_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(cfg.max_grad_norm), tx)
    return tx


def make_network(cfg: Config, num_actions: int, use_noise: bool = True) -> RainbowIQN:
    return RainbowIQN(
        num_actions=num_actions,
        hidden_size=cfg.hidden_size,
        num_cosines=cfg.num_cosines,
        noisy_sigma0=cfg.noisy_sigma0,
        dueling=cfg.dueling,
        use_noise=use_noise,
        compute_dtype=jnp.dtype(cfg.compute_dtype),
    )


def init_train_state(
    cfg: Config,
    num_actions: int,
    key: chex.PRNGKey,
    state_shape: Optional[Tuple[int, ...]] = None,
) -> TrainState:
    """state_shape defaults to cfg.state_shape; pass the env's actual
    (H, W, history) when the env defines its own frame size (toy envs)."""
    net = make_network(cfg, num_actions)
    k_init, k_taus, k_noise = jax.random.split(key, 3)
    dummy = jnp.zeros((1, *(state_shape or cfg.state_shape)), jnp.uint8)
    params = net.init(
        {"params": k_init, "taus": k_taus, "noise": k_noise},
        dummy,
        cfg.num_tau_samples,
    )["params"]
    opt_state = make_optimizer(cfg).init(params)
    return TrainState(
        params=params,
        target_params=jax.tree.map(jnp.copy, params),
        opt_state=opt_state,
        step=jnp.zeros((), jnp.int32),
    )


def loss_and_priorities(
    net: RainbowIQN,
    cfg: Config,
    params: Params,
    target_params: Params,
    batch: Batch,
    key: chex.PRNGKey,
    weight_scale: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Quantile-Huber loss (IS-weighted mean) + diagnostics. SURVEY §3.4.

    ``weight_scale`` ([B], optional) multiplies the IS weights — the clipped
    IMPACT reuse ratio on replay-reuse passes (``make_reuse_learn_step``).
    None (the default) leaves the trace byte-identical to the pre-reuse
    path."""
    k_sel_tau, k_sel_noise, k_tgt_tau, k_tgt_noise, k_on_tau, k_on_noise = (
        jax.random.split(key, 6)
    )

    # -- double-Q action selection: online net picks a* on s' (K acting taus).
    sel_q, _ = net.apply(
        {"params": params},
        batch.next_obs,
        cfg.num_quantile_samples,
        rngs={"taus": k_sel_tau, "noise": k_sel_noise},
    )
    a_star = greedy_action(sel_q)  # [B]

    # -- target distribution: target net on s' at a*, N' taus.
    tgt_q, _ = net.apply(
        {"params": target_params},
        batch.next_obs,
        cfg.num_tau_prime_samples,
        rngs={"taus": k_tgt_tau, "noise": k_tgt_noise},
    )  # [B, N', A]
    z_next = jnp.take_along_axis(tgt_q, a_star[:, None, None], axis=-1)[..., 0]
    td_target = jax.lax.stop_gradient(
        batch.reward[:, None] + batch.discount[:, None] * z_next
    )  # [B, N']

    # -- online distribution at the taken action, N taus.
    on_q, taus = net.apply(
        {"params": params},
        batch.obs,
        cfg.num_tau_samples,
        rngs={"taus": k_on_tau, "noise": k_on_noise},
    )  # [B, N, A]
    z_online = jnp.take_along_axis(on_q, batch.action[:, None, None], axis=-1)[..., 0]

    # Measured on-chip 2026-07-31 (results/relay_watch/pallas.jsonl): the
    # hand-written Pallas quantile-Huber kernel failed remote_compile
    # (SIGABRT) at every block size while this jnp path ran 1657 learn
    # steps/s device-resident — XLA's own fusion wins, kernel deleted.
    per_sample, td_abs = quantile_huber_loss(z_online, taus, td_target, cfg.kappa)
    weight = batch.weight
    if weight_scale is not None:
        weight = weight * weight_scale
    loss = jnp.mean(weight * per_sample)
    aux = {
        "td_abs": td_abs,
        "loss_per_sample": per_sample,
        "q_mean": on_q.mean(),
        "target_q_mean": z_next.mean(),
    }
    return loss, aux


def make_policy_logp(
    net: RainbowIQN, cfg: Config
) -> Callable[[Params, Batch, chex.PRNGKey], jnp.ndarray]:
    """[B] log-prob of each row's TAKEN action under the Boltzmann policy
    softmax(mean-of-tau q-values) — the value-based stand-in for IMPACT's
    pi(a|s) (arXiv:1912.00167) that replay-reuse importance ratios are built
    from.  Derived from the online quantile distribution at K acting taus;
    callers hand every pass the SAME key so two calls with identical params
    return bitwise-identical log-probs (ratio drift measures parameter
    drift only, never tau/noise resampling)."""

    def logp(params: Params, batch: Batch, key: chex.PRNGKey) -> jnp.ndarray:
        k_tau, k_noise = jax.random.split(key)
        quantiles, _ = net.apply(
            {"params": params},
            batch.obs,
            cfg.num_quantile_samples,
            rngs={"taus": k_tau, "noise": k_noise},
        )
        logits = jax.nn.log_softmax(q_values(quantiles), axis=-1)
        return jnp.take_along_axis(
            logits, batch.action[:, None], axis=-1)[..., 0]

    return logp


def make_reuse_learn_step(
    cfg: Config,
    pass_fn: Callable[..., Tuple[TrainState, Dict[str, jnp.ndarray]]],
    logp_fn: Callable[[Params, Batch, chex.PRNGKey], jnp.ndarray],
) -> Callable[[TrainState, Batch, chex.PRNGKey], Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """Replay-ratio > 1: one fori_loop'd K-pass learn step (IMPACT-style
    clipped reuse, arXiv:1912.00167) — XLA sees a SINGLE executable, so a
    K-fold learn rate costs one dispatch per sampled batch.

    Pass 1 is the plain learn step and snapshots the behavior policy's
    per-row log-probs (``logp_fn`` under a dedicated ratio key, shared by
    every pass).  Passes 2..K re-run the same batch with the IS weights
    scaled by clip(pi_now / pi_behavior, 1/c, c), c = ``cfg.reuse_clip`` —
    stale re-consumption of rows the policy has already moved away from is
    bounded, which is what makes K > 1 safe under staleness.  The returned
    info carries the FINAL pass's priorities (written back once per sample,
    not once per pass), the AND of every pass's finite flag (a mid-reuse
    NaN can't hide behind a later pass), and ``clip_frac`` = mean fraction
    of rows clipped per reuse pass — the K-too-high early-warning signal.
    ``state.step`` advances K per call (each pass IS an SGD step, so the
    target-copy schedule keeps its meaning)."""
    reuse_k = int(cfg.replay_ratio)
    clip_c = float(cfg.reuse_clip)

    def learn_step(
        state: TrainState, batch: Batch, key: chex.PRNGKey
    ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        k_ratio, k_loop = jax.random.split(key)
        behav_logp = jax.lax.stop_gradient(
            logp_fn(state.params, batch, k_ratio))
        # pass 1: the unscaled learn step (ratio == 1 by definition)
        state, info = pass_fn(state, batch, jax.random.fold_in(k_loop, 0))

        def body(p, carry):
            state, _info, clip_sum, finite = carry
            logp = jax.lax.stop_gradient(logp_fn(state.params, batch, k_ratio))
            ratio = jnp.exp(logp - behav_logp)
            clipped = jnp.clip(ratio, 1.0 / clip_c, clip_c)
            clip_frac = jnp.mean((ratio != clipped).astype(jnp.float32))
            state, info = pass_fn(
                state, batch, jax.random.fold_in(k_loop, p), clipped)
            return (state, info, clip_sum + clip_frac,
                    finite & info["finite"])

        state, info, clip_sum, finite = jax.lax.fori_loop(
            1, reuse_k, body,
            (state, info, jnp.zeros((), jnp.float32), info["finite"]),
        )
        info = dict(info)
        info["finite"] = finite
        info["clip_frac"] = clip_sum / max(reuse_k - 1, 1)
        # static row metadata: learn rows report reuse without a device read
        info["replay_ratio"] = reuse_k
        info["reuse_index"] = reuse_k - 1  # last completed pass this sample
        return state, info

    return learn_step


def build_learn_step(
    cfg: Config, num_actions: int
) -> Callable[[TrainState, Batch, chex.PRNGKey], Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """Returns the un-jitted learn step; callers jit/pjit it with their own
    sharding (single-chip agent vs mesh learner, parallel/apex.py).

    ``cfg.replay_ratio`` = 1 (default) returns the single-pass step,
    bitwise the PR-11 path; K > 1 wraps it in ``make_reuse_learn_step`` —
    one fori_loop'd K-pass executable with the IMPACT clip."""
    net = make_network(cfg, num_actions)
    tx = make_optimizer(cfg)

    def learn_step(
        state: TrainState,
        batch: Batch,
        key: chex.PRNGKey,
        weight_scale: Optional[jnp.ndarray] = None,
    ) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
        def loss_fn(params):
            return loss_and_priorities(
                net, cfg, params, state.target_params, batch, key,
                weight_scale)

        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)

        # Hard target copy on schedule, folded into the same XLA graph.
        step = state.step + 1
        do_copy = (step % cfg.target_update_period == 0).astype(jnp.float32)
        target_params = jax.tree.map(
            lambda t, o: do_copy * o + (1.0 - do_copy) * t,
            state.target_params,
            params,
        )

        grad_norm = optax.global_norm(grads)
        info = {
            "loss": loss,
            "priorities": aux["td_abs"],
            "q_mean": aux["q_mean"],
            "target_q_mean": aux["target_q_mean"],
            "grad_norm": grad_norm,
            # On-device NaN/Inf guard: the same loss/grad-norm finiteness
            # check TrainSupervisor.step_ok used to do with a per-step host
            # sync, folded into the XLA graph so the supervisor can defer
            # reading it to the write-back ring boundary (utils/writeback.py).
            "finite": jnp.isfinite(loss) & jnp.isfinite(grad_norm),
        }
        return (
            TrainState(
                params=params,
                target_params=target_params,
                opt_state=opt_state,
                step=step,
            ),
            info,
        )

    if cfg.replay_ratio <= 1:
        return learn_step
    return make_reuse_learn_step(cfg, learn_step, make_policy_logp(net, cfg))


def build_act_step(
    cfg: Config, num_actions: int, use_noise: bool = True
) -> Callable[[Params, jnp.ndarray, chex.PRNGKey], Tuple[jnp.ndarray, jnp.ndarray]]:
    """Batched greedy acting: (params, obs [B,H,W,C] u8, key) -> (actions [B], q [B,A]).

    Parity: reference `Agent.act` (SURVEY §3.3) — mean over K tau samples,
    argmax; noisy-net noise resampled every call via the explicit key.
    """
    net = make_network(cfg, num_actions, use_noise=use_noise)

    def act_step(params, obs, key):
        k_tau, k_noise = jax.random.split(key)
        quantiles, _ = net.apply(
            {"params": params},
            obs,
            cfg.num_quantile_samples,
            rngs={"taus": k_tau, "noise": k_noise},
        )
        return greedy_action(quantiles), q_values(quantiles)

    return act_step
