"""Process-wide metric registry: named counters/gauges/histograms with role
labels, shared by every role in the process (actor/learner/replay/serve/
supervisor) and drained two ways — periodic JSONL rows through the existing
``MetricsLogger`` surface, and Prometheus text exposition (obs/export.py).

Design points:
  * one lock per registry, shared by its metrics — recording is a dict lookup
    plus a float add under an RLock, cheap enough for per-batch call sites
    (the per-*step* hot path on device never touches this; only host-side
    bookkeeping does);
  * histograms keep a bounded window (deque) for percentiles plus lifetime
    count/sum — ``snapshot(reset=True)`` gives per-interval stats without
    losing the cumulative view;
  * metrics are keyed (name, role): the same metric name can exist per role
    ("frames_total" for actor and learner) and exports with a role label.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional, Tuple


class Counter:
    """Monotone counter.  ``inc`` only; resets never (windows are the
    consumer's job: diff successive scrapes/rows)."""

    kind = "counter"

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self.value += n

    def get(self) -> float:
        with self._lock:
            return self.value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, occupancy, bytes)."""

    kind = "gauge"

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def get(self) -> float:
        with self._lock:
            return self.value


class Histogram:
    """Windowed observations + lifetime count/sum.

    ``snapshot()`` summarises the current window (count/mean/p50/p90/p99/max);
    ``reset=True`` clears the window (per-interval timing rows) while the
    lifetime totals keep accumulating (Prometheus summary export)."""

    kind = "histogram"

    def __init__(self, lock: threading.RLock, window: int = 8192):
        self._lock = lock
        self._win: collections.deque = collections.deque(maxlen=window)
        self.total_count = 0
        self.total_sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._win.append(v)
            self.total_count += 1
            self.total_sum += v

    def snapshot(self, reset: bool = False) -> Dict[str, float]:
        with self._lock:
            laps = sorted(self._win)
            if reset:
                self._win.clear()
        n = len(laps)
        if n == 0:
            return {"count": 0}
        return {
            "count": n,
            "mean": sum(laps) / n,
            "p50": laps[n // 2],
            "p90": laps[min(int(n * 0.9), n - 1)],
            "p99": laps[min(int(n * 0.99), n - 1)],
            "max": laps[-1],
        }


class MetricRegistry:
    """Thread-safe get-or-create registry of (name, role) -> metric."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[Tuple[str, str], Any] = {}

    def _get(self, name: str, role: str, cls, **kwargs):
        key = (name, role)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(self._lock, **kwargs)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} (role={role!r}) already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, role: str = "") -> Counter:
        return self._get(name, role, Counter)

    def gauge(self, name: str, role: str = "") -> Gauge:
        return self._get(name, role, Gauge)

    def histogram(self, name: str, role: str = "", window: int = 8192) -> Histogram:
        return self._get(name, role, Histogram, window=window)

    def collect(self) -> List[Tuple[str, str, Any]]:
        """Stable-ordered [(name, role, metric)] snapshot of registrations."""
        with self._lock:
            items = sorted(self._metrics.items())
        return [(name, role, m) for (name, role), m in items]

    def as_dict(self, reset_histograms: bool = False) -> Dict[str, Any]:
        """Flat {"name{role}": value-or-snapshot} view, the payload the
        periodic 'timing' row and tests read."""
        out: Dict[str, Any] = {}
        for name, role, m in self.collect():
            key = f"{name}{{{role}}}" if role else name
            if isinstance(m, Histogram):
                out[key] = m.snapshot(reset=reset_histograms)
            else:
                out[key] = m.get()
        return out


_global: Optional[MetricRegistry] = None
_global_lock = threading.Lock()


def get() -> MetricRegistry:
    """The process-wide default registry (serving and ad-hoc call sites);
    train loops build a per-run registry via RunObs so concurrent runs in one
    process (the test suite) don't cross-pollute windows."""
    global _global
    with _global_lock:
        if _global is None:
            _global = MetricRegistry()
        return _global


def reset_global() -> None:
    """Test hook: drop the process-wide registry."""
    global _global
    with _global_lock:
        _global = None
