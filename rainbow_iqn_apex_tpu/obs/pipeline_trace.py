"""End-to-end pipeline tracing & lag attribution (jax-free).

Ape-X's learning dynamics are governed by *lags* — how stale a sampled
transition is when the learner consumes it, how far actor weights trail the
learner, how long a publish takes to reach every consumer (Horgan et al.,
arXiv:1803.00933), and IMPACT (arXiv:1912.00167) shows those staleness terms
trade directly against throughput.  PR 3's obs layer measures every stage in
isolation; this module connects them *causally*: units of work (an env tick,
a learn step, a weight publish, a serving request) carry a ``trace_id``, and
every stage they flow through — act/env-step -> replay append -> sample/
gather -> learn dispatch -> ring retirement -> publish -> adoption, plus the
router admit -> dispatch -> reply path — emits a linked span, so one Perfetto
timeline (scripts/trace_export.py) or one ``critical_path:`` verdict
(scripts/obs_report.py) answers "which stage bounds the pipeline".

Two strictly separated cost tiers:

* **lag metrics** are ALWAYS ON: a handful of registry histogram observations
  per batch/publish (``lag_*`` names, surfaced as one periodic ``lag`` JSONL
  row + /metrics).  They touch no RNG and no device state, so default
  behaviour stays bitwise identical to the untraced build (tier-1 asserts
  the off-mode trajectories).
* **span emission** is SAMPLED 1-in-N (``Config.trace_sample_every``;
  0 = off, the default): only every Nth unit of work emits ``span_link``
  rows, so the learn-loop overhead stays within the <=3% bench gate
  (the ``trace_overhead`` bench row) while flows remain reconstructible.

Trace ids are deterministic strings ``"<kind><host>-<unit>"`` (e.g.
``"a0-512"`` = host 0's append tick 512, ``"l0-40"`` = learn step 40,
``"w0-3"`` = weight version 3, ``"r0-17"`` = routed request 17), so two
processes that never exchanged tracer state still stamp the SAME id for the
same logical unit — which is exactly what lets trace_export draw publish ->
adopt flow arrows across hosts.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# Canonical stage -> bottleneck verdict for the critical-path analyzer.
# Stages not listed classify as their own name (still ranked, just unmapped).
STAGE_VERDICTS: Dict[str, str] = {
    "act": "actor-bound",
    "env_step": "actor-bound",
    "append": "actor-bound",
    "replay_sample": "sampler-starved",
    "draw": "sampler-starved",
    "gather": "sampler-starved",
    "learn_step": "device-bound",
    "ring_retire": "writeback-bound",
    "publish": "publish-bound",
    "adopt": "publish-bound",
    "route": "serve-bound",
    "router_dispatch": "serve-bound",
    "batch_slot": "serve-bound",
}


class PipelineTracer:
    """Per-run causal tracer: sampled span emission + always-on lag metrics.

    ``logger`` is a MetricsLogger (or None: metrics-only); ``registry`` is
    the run's MetricRegistry (or None: spans-only); ``sample_every`` is the
    1-in-N span sampling knob (0 disables span rows entirely).  All methods
    are safe from worker threads (span ids come from a process-wide counter,
    per-consumer adopt windows are lock-guarded).
    """

    def __init__(
        self,
        logger=None,
        registry=None,
        sample_every: int = 0,
        host: int = 0,
        role: str = "learner",
        clock: Callable[[], float] = time.time,
    ):
        self.logger = logger
        self.registry = registry
        self.sample_every = max(int(sample_every), 0)
        self.host = int(host)
        self.role = role
        self.clock = clock
        self._span_ids = itertools.count(1)
        self._lock = threading.Lock()
        # publish bookkeeping: version -> publish wall ts (bounded), plus the
        # recent inter-publish gaps the propagation budget derives from
        self._pub_ts: "collections.OrderedDict[int, float]" = (
            collections.OrderedDict())
        self._pub_gaps: collections.deque = collections.deque(maxlen=32)
        self.max_weight_lag = 0  # loops set this; 0 = no propagation budget

    # ------------------------------------------------------------- sampling
    @property
    def spans_on(self) -> bool:
        return self.sample_every > 0 and self.logger is not None

    def sampled(self, unit: int) -> bool:
        """True when unit-of-work ``unit`` should emit full spans."""
        return self.spans_on and int(unit) % self.sample_every == 0

    def trace_id(self, kind: str, unit: int) -> str:
        return f"{kind}{self.host}-{int(unit)}"

    def maybe_trace(self, kind: str, unit: int) -> Optional[str]:
        """The loops' one-liner: a trace id when this unit is sampled, else
        None (and every span() taking None is a zero-cost no-op)."""
        return self.trace_id(kind, unit) if self.sampled(unit) else None

    # ---------------------------------------------------------------- spans
    def emit_span(
        self,
        stage: str,
        trace_id: Optional[str],
        t0: float,
        t1: Optional[float] = None,
        parent_id: int = 0,
        links: Iterable[str] = (),
        **attrs: Any,
    ) -> int:
        """Emit one ``span_link`` row; returns its span id (0 when no row
        was written — trace_id None or no logger)."""
        if trace_id is None or self.logger is None:
            return 0
        t1 = self.clock() if t1 is None else t1
        sid = next(self._span_ids)
        links = [l for l in links if l]
        self.logger.log(
            "span_link",
            stage=stage,
            trace_id=trace_id,
            span_id=sid,
            parent_id=int(parent_id),
            t0=round(float(t0), 6),
            dur_ms=round((t1 - t0) * 1e3, 3),
            role=self.role,
            **({"links": links} if links else {}),
            **attrs,
        )
        return sid

    @contextlib.contextmanager
    def span(self, stage: str, trace_id: Optional[str],
             parent_id: int = 0, links: Iterable[str] = (), **attrs: Any):
        """``with ptrace.span("learn_step", tid):`` — no-op when ``tid`` is
        None (the unsampled/off path pays one ``is None`` check)."""
        if trace_id is None or self.logger is None:
            yield 0
            return
        t0 = self.clock()
        try:
            yield 0
        finally:
            self.emit_span(stage, trace_id, t0, parent_id=parent_id,
                           links=links, **attrs)

    def link_ids(self, kind: str, units: Iterable[int],
                 limit: int = 8) -> List[str]:
        """Trace ids of the SAMPLED units among ``units`` (bounded): the
        learn span links to the env-tick traces of its sampled rows, so
        Perfetto draws append -> learn flow arrows without a row per
        transition."""
        if not self.spans_on:
            return []
        out: List[str] = []
        seen = set()
        for u in units:
            u = int(u)
            # u <= 0 is the "never stamped" sentinel (slots restored from a
            # snapshot, or written before attach_tracer) — linking to a
            # nonexistent trace would join unrelated learn steps in the
            # export's flow pass
            if u > 0 and u % self.sample_every == 0 and u not in seen:
                seen.add(u)
                out.append(self.trace_id(kind, u))
                if len(out) >= limit:
                    break
        return out

    # ----------------------------------------------------------- lag metrics
    def lag(self, name: str, value: float) -> None:
        """Record one always-on lag observation into ``lag_<name>`` (the
        periodic ``lag`` row + /metrics read these back)."""
        if self.registry is not None:
            self.registry.histogram(f"lag_{name}", self.role).observe(
                float(value))

    def note_publish(self, version: int, ts: Optional[float] = None) -> None:
        """A weight publish landed: remember its wall ts (the adopt lag
        anchor) and fold the inter-publish gap into the propagation budget."""
        ts = self.clock() if ts is None else float(ts)
        with self._lock:
            if self._pub_ts:
                gap = ts - self._pub_ts[next(reversed(self._pub_ts))]
                if gap > 0:
                    self._pub_gaps.append(gap)
            self._pub_ts[int(version)] = ts
            while len(self._pub_ts) > 64:
                self._pub_ts.popitem(last=False)

    def note_adopt(self, consumer: str, version: int,
                   lag_ms: Optional[float] = None,
                   ts: Optional[float] = None) -> Optional[float]:
        """A consumer adopted ``version``.  ``lag_ms`` may be supplied
        directly (cross-process consumers measure against the publish row's
        own ts); otherwise it is derived from this tracer's publish table.
        Returns the lag recorded (None when underivable)."""
        ts = self.clock() if ts is None else float(ts)
        if lag_ms is None:
            with self._lock:
                pub = self._pub_ts.get(int(version))
            if pub is None:
                return None
            lag_ms = max((ts - pub) * 1e3, 0.0)
        lag_ms = float(lag_ms)
        # per-consumer window as a registry histogram under a "consumer:"
        # role — the registry's existing bounded-window percentile machinery
        # instead of a second hand-rolled one; lag_snapshot folds these into
        # publish_adopt_ms_by_consumer
        if self.registry is not None:
            self.registry.histogram(
                "lag_publish_adopt_ms", f"consumer:{consumer}"
            ).observe(lag_ms)
        self.lag("publish_adopt_ms", lag_ms)
        return lag_ms

    def publish_cadence_s(self) -> Optional[float]:
        """Median inter-publish gap (seconds); None before 2 publishes."""
        with self._lock:
            gaps = sorted(self._pub_gaps)
        return gaps[len(gaps) // 2] if gaps else None

    def adopt_budget_ms(self) -> Optional[float]:
        """The propagation budget: a consumer may trail by at most
        ``max_weight_lag`` publishes (the staleness fence's own bound), so
        its publish->adopt p99 budget is max_weight_lag * the observed
        publish cadence.  None when fencing is off or cadence unknown."""
        if self.max_weight_lag <= 0:
            return None
        cadence = self.publish_cadence_s()
        if cadence is None:
            return None
        return self.max_weight_lag * cadence * 1e3

    def lag_snapshot(self) -> Dict[str, Any]:
        """The payload of one periodic ``lag`` row: per-metric WINDOW
        percentiles from the ``lag_*`` registry histograms plus per-consumer
        publish->adopt stats and the propagation budget.

        Windows RESET on snapshot (lifetime count/sum stay on the
        histograms): each lag row covers only the interval since the last
        one.  This is what makes RunHealth's heal edge real — a consumer
        that caught back up produces a clean next window instead of one
        early slow burst pinning the cumulative p99 over budget (and the
        run degraded, with the consumer named) for the rest of the run."""
        out: Dict[str, Any] = {}
        by_consumer: Dict[str, Dict[str, float]] = {}
        if self.registry is not None:
            for name, role, m in self.registry.collect():
                if not (name.startswith("lag_") and m.kind == "histogram"):
                    continue
                snap = m.snapshot(reset=True)
                if not snap.get("count"):
                    continue
                snap = {k: round(float(v), 4) for k, v in snap.items()}
                if role.startswith("consumer:"):
                    by_consumer[role[len("consumer:"):]] = snap
                else:
                    out[name[len("lag_"):]] = snap
        if by_consumer:
            out["publish_adopt_ms_by_consumer"] = by_consumer
        budget = self.adopt_budget_ms()
        if budget is not None:
            out["publish_adopt_budget_ms"] = round(budget, 3)
        return out

    def emit_lag_row(self, step: int = 0, **extra: Any) -> Optional[Dict]:
        """One ``lag`` JSONL row at the metrics cadence (loops call this
        from the same place they call obs_run.periodic)."""
        if self.logger is None:
            return None
        snap = self.lag_snapshot()
        if not snap and not extra:
            return None
        return self.logger.log("lag", step=int(step), **snap, **extra)


# --------------------------------------------------------------------------
# Critical-path analysis over span_link rows (shared by obs_report and
# relay_watch — the verdict string must not drift between the two).
# --------------------------------------------------------------------------

def critical_path(rows: Iterable[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Attribute end-to-end latency to pipeline stages from ``span_link``
    rows: each stage's EXCLUSIVE time (its span durations minus its child
    spans' durations — nested spans must not double-bill their parents) is
    summed, and the stage with the largest share is the verdict.

    Returns ``{"stage", "share", "verdict", "stages": {stage: {ms, share}}}``
    or None when no span_link rows are present."""
    spans = [r for r in rows if r.get("kind") == "span_link"]
    if not spans:
        return None
    # child durations roll up by (host, parent span id); span ids are only
    # unique within a process, so key on the emitting host too
    child_ms: Dict[Tuple[int, int], float] = {}
    for r in spans:
        parent = int(r.get("parent_id") or 0)
        if parent:
            key = (int(r.get("host", 0)), parent)
            child_ms[key] = child_ms.get(key, 0.0) + float(r.get("dur_ms", 0.0))
    stages: Dict[str, float] = {}
    for r in spans:
        key = (int(r.get("host", 0)), int(r.get("span_id", 0)))
        excl = max(float(r.get("dur_ms", 0.0)) - child_ms.get(key, 0.0), 0.0)
        stage = str(r.get("stage", "unknown"))
        stages[stage] = stages.get(stage, 0.0) + excl
    total = sum(stages.values())
    if total <= 0:
        return None
    ranked = sorted(stages.items(), key=lambda kv: -kv[1])
    top_stage, top_ms = ranked[0]
    return {
        "stage": top_stage,
        "share": round(top_ms / total, 4),
        "verdict": STAGE_VERDICTS.get(top_stage, top_stage),
        "stages": {
            s: {"ms": round(ms, 3), "share": round(ms / total, 4)}
            for s, ms in ranked
        },
    }


def format_critical_path(cp: Optional[Dict[str, Any]]) -> Optional[str]:
    """One-line rendering shared by obs_report and relay_watch:
    ``gather 61% (sampler-starved)``."""
    if not cp:
        return None
    return f"{cp['stage']} {round(cp['share'] * 100)}% ({cp['verdict']})"
