"""ObsRelay: stream this process's telemetry to the fleet obs collector.

The relay is an OBSERVER, never a participant: it attaches to the process's
``MetricsLogger`` via ``add_observer`` (every sanitized row dict lands in
``observe``) and, when a registry is attached, ships a periodic snapshot of
its counters/gauges/histograms.  Everything rides the netcore framed-socket
codec as header-only JSON frames:

    {op: "hello", host, role, run, pid}        once per connection
    {op: "rows", rows: [row, ...]}             coalesced logged rows
    {op: "snap", metrics: registry.as_dict()}  tier-2 registry snapshot

Non-negotiables, in priority order:

1. **Never stall the env/learn loop.**  ``observe`` is one bounded deque
   append under a lock — no socket I/O, no blocking.  A FULL spool sheds
   the NEWEST row with a counted, rate-limited reasoned `obs_net` row
   (the AppendClient shed story, telemetry edition).
2. **Never load-bearing.**  The local JSONL is written by MetricsLogger
   before observers run; a dead/wedged collector changes nothing about it.
   Delivery is at-most-once by design — the JSONL is the durable record,
   the wire is the live view.
3. **Reconnect rides the shared RetryPolicy.**  The collector is
   re-discovered from its `obs_collector` lease on every dial (it may have
   respawned elsewhere at a new addr:port), and the backoff schedule is
   clamped at its ceiling — a dead collector is retried forever; giving up
   is the operator's call, not the socket's.

jax-free: relays run inside every role, including device-less ones
(league controller, replay shard servers, standbys).
"""

from __future__ import annotations

import collections
import os
import socket
import threading
import time
from typing import Any, Dict, Optional, Tuple

from rainbow_iqn_apex_tpu.netcore import chaos, framing
from rainbow_iqn_apex_tpu.utils.faults import RetryPolicy

_SEND_TIMEOUT_S = 5.0  # blocking-with-a-bound: a wedged collector whose
# kernel buffer filled turns into a timeout -> disconnect -> spool/shed,
# never a worker thread stuck in sendall forever
_COALESCE_ROWS = 64  # rows per "rows" frame
_STATS_EVERY_S = 10.0  # periodic local `obs_net` stats row cadence
_SHED_LOG_EVERY_S = 5.0  # rate limit on the reasoned shed row


class ObsRelay:
    """Bounded non-blocking telemetry spool -> framed-socket stream.

    Construct via ``from_config`` (None when ``cfg.obs_net`` is off — the
    house default-off seam), then ``logger.add_observer(relay.observe)``.
    ``attach`` does both.  Direct ``collector_addr`` bypasses lease
    discovery (tests/bench)."""

    def __init__(
        self,
        heartbeat_dir: str = "",
        host_id: int = 0,
        role: str = "",
        run_id: str = "",
        registry=None,
        logger=None,
        spool_rows: int = 2048,
        snapshot_s: float = 5.0,
        lease_timeout_s: float = 30.0,
        lease_skew_s: float = 0.0,
        retry: Optional[RetryPolicy] = None,
        collector_addr: Optional[Tuple[str, int]] = None,
    ):
        self.heartbeat_dir = heartbeat_dir
        self.host_id = int(host_id)
        self.role = str(role)
        self.run_id = str(run_id)
        self.registry = registry
        self.logger = logger
        self.spool_rows = max(int(spool_rows), 1)
        self.snapshot_s = float(snapshot_s)
        self.lease_timeout_s = float(lease_timeout_s)
        self.retry = retry if retry is not None else RetryPolicy(
            attempts=6, base_delay_s=0.2, max_delay_s=5.0)
        self._fixed_addr = collector_addr
        self._lock = threading.Lock()
        self._spool: "collections.deque" = collections.deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        # shared counters (observe()/worker both write) — under _lock
        self.spooled_rows = 0
        self.shed_rows = 0
        # worker-thread-only state/counters (stats() only reads them)
        self.sent_rows = 0
        self.sent_frames = 0
        self.snapshots_sent = 0
        self.reconnects = 0
        self.collector: str = ""  # "addr:port" of the last connection
        self._sock: Optional[socket.socket] = None
        self._ever_connected = False
        self._fail_streak = 0
        self._next_dial = 0.0
        self._delays = list(self.retry.delays()) or [self.retry.base_delay_s]
        self._last_snap = 0.0
        self._last_stats = time.monotonic()
        self._last_shed_log = 0.0  # observe()-side only (rate limit)
        self._in_shed_log = False  # observe()-side reentrancy guard
        self._monitor = None
        if heartbeat_dir and collector_addr is None:
            from rainbow_iqn_apex_tpu.parallel.elastic import HeartbeatMonitor

            self._monitor = HeartbeatMonitor(
                heartbeat_dir, lease_timeout_s, self_id=None,
                skew_tolerance_s=lease_skew_s)
        self._thread = threading.Thread(
            target=self._run, name=f"obsnet-relay-{role or host_id}",
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- plumbing
    @classmethod
    def from_config(cls, cfg, logger=None, registry=None,
                    role: str = "learner") -> Optional["ObsRelay"]:
        """The default-off seam: None unless ``cfg.obs_net`` is set, so the
        no-flag path constructs nothing and stays bitwise the pre-plane
        behaviour."""
        if not getattr(cfg, "obs_net", False):
            return None
        from rainbow_iqn_apex_tpu.parallel.elastic import heartbeat_dir

        return cls(
            heartbeat_dir(cfg),
            host_id=getattr(cfg, "process_id", 0),
            role=role,
            run_id=getattr(cfg, "run_id", ""),
            registry=registry,
            logger=logger,
            spool_rows=getattr(cfg, "obs_net_spool", 2048),
            snapshot_s=getattr(cfg, "obs_net_snapshot_s", 5.0),
            lease_timeout_s=getattr(cfg, "heartbeat_timeout_s", 30.0),
            lease_skew_s=getattr(cfg, "lease_skew_tolerance_s", 0.0),
            retry=RetryPolicy(
                attempts=6,
                base_delay_s=getattr(cfg, "respawn_base_s", 0.2),
                max_delay_s=getattr(cfg, "respawn_max_s", 5.0),
                seed=getattr(cfg, "seed", 0),
            ),
        )

    @classmethod
    def attach(cls, cfg, logger, registry=None,
               role: str = "learner") -> Optional["ObsRelay"]:
        """from_config + add_observer in one call — the one-line seam every
        role's wiring uses."""
        relay = cls.from_config(cfg, logger=logger, registry=registry,
                                role=role)
        if relay is not None:
            add = getattr(logger, "add_observer", None)
            if add is not None:
                add(relay.observe)
        return relay

    def _log(self, event: str, **fields: Any) -> None:
        if self.logger is not None:
            try:
                self.logger.log("obs_net", event=event, relay=self.role,
                                collector=self.collector, **fields)
            except Exception:
                pass  # telemetry about telemetry must never raise

    # ------------------------------------------------------------- producer
    def observe(self, row: Dict[str, Any]) -> None:
        """MetricsLogger observer: spool one already-sanitized row.  Never
        blocks; a full spool sheds the newest row, counted + reasoned."""
        with self._lock:
            if self._in_shed_log:
                # the reasoned shed row below re-enters here through the
                # logger's observer fan-out; it is local-JSONL-only by
                # design (the spool that would carry it is the full one)
                return
            if len(self._spool) >= self.spool_rows:
                self.shed_rows += 1
                shed = self.shed_rows
            else:
                self._spool.append(dict(row))
                self.spooled_rows += 1
                shed = None
        if shed is None:
            self._wake.set()
            return
        if self.registry is not None:
            self.registry.counter("obsnet_shed_rows_total", "obs_net").inc()
        now = time.monotonic()
        if now - self._last_shed_log > _SHED_LOG_EVERY_S:
            self._last_shed_log = now  # unlocked-ok: observe() runs on the
            # logging thread only (MetricsLogger fans out synchronously)
            with self._lock:
                self._in_shed_log = True
            try:
                self._log("spool_shed", shed_rows=shed,
                          spool=self.spool_rows,
                          why="spool full: collector unreachable or rows "
                              "outpacing the wire; newest row dropped so "
                              "the training loop never waits on telemetry")
            finally:
                with self._lock:
                    self._in_shed_log = False

    def spool_depth(self) -> int:
        with self._lock:
            return len(self._spool)

    # ------------------------------------------------------------ transport
    def _discover(self) -> Optional[Tuple[str, int]]:
        """The freshest `obs_collector` lease's addr:port (highest epoch
        wins — a respawned collector supersedes its stale predecessor)."""
        if self._fixed_addr is not None:
            return self._fixed_addr
        if self._monitor is None:
            return None
        best = None
        for lease in self._monitor.leases().values():
            if (lease.role == "obs_collector" and lease.fresh
                    and lease.addr and lease.port):
                if best is None or lease.epoch > best.epoch:
                    best = lease
        return (best.addr, best.port) if best is not None else None

    def _dial(self) -> bool:
        """One bounded connect + hello; schedules backoff on failure."""
        addr = self._discover()
        if addr is None:
            self._backoff()
            return False
        try:
            sock = socket.create_connection(addr, timeout=_SEND_TIMEOUT_S)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(_SEND_TIMEOUT_S)
            sock = chaos.maybe_wrap(sock, peer="collector",
                                    logger=self.logger)
            framing.send_frame(sock, {
                "op": "hello", "host": self.host_id, "role": self.role,
                "run": self.run_id, "pid": os.getpid()})
        except OSError:
            self._backoff()
            return False
        with self._lock:
            self._sock = sock
            self._fail_streak = 0
            self.collector = f"{addr[0]}:{addr[1]}"
            reconnected = self._ever_connected
            self._ever_connected = True
            if reconnected:
                self.reconnects += 1
        self._log("reconnect" if reconnected else "connect")
        if self.registry is not None and reconnected:
            self.registry.counter(
                "obsnet_reconnects_total", "obs_net").inc()
        return True

    def _backoff(self) -> None:
        with self._lock:
            self._fail_streak += 1
            delay = self._delays[
                min(self._fail_streak - 1, len(self._delays) - 1)]
            self._next_dial = time.monotonic() + delay

    def _drop(self, why: str) -> None:
        # close() also lands here, so the socket handoff takes the lock
        with self._lock:
            sock, self._sock = self._sock, None
            self._next_dial = time.monotonic()  # first re-dial immediate
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            if not self._stop.is_set():
                self._log("disconnect", why=why)

    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        if self._stop.is_set() or time.monotonic() < self._next_dial:
            return False
        return self._dial()

    def _take_rows(self) -> list:
        with self._lock:
            n = min(len(self._spool), _COALESCE_ROWS)
            return [self._spool.popleft() for _ in range(n)]

    def _respool(self, rows: list) -> None:
        """Unsent rows go back to the FRONT (order preserved); whatever no
        longer fits is shed-counted — the spool bound is the bound."""
        dropped = 0
        with self._lock:
            for r in reversed(rows):
                if len(self._spool) >= self.spool_rows:
                    dropped += 1
                else:
                    self._spool.appendleft(r)
            self.shed_rows += dropped

    def _send(self, header: Dict[str, Any]) -> bool:
        sock = self._sock
        if sock is None:
            return False
        try:
            framing.send_frame(sock, header)
            return True
        except (OSError, framing.FrameError) as e:
            self._drop(f"{type(e).__name__}: {e}")
            return False

    def _run(self) -> None:
        while not self._stop.is_set() or self.spool_depth():
            if not self._ensure_connected():
                if self._stop.is_set():
                    return  # draining with no collector: spool dies with us
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            now = time.monotonic()
            rows = self._take_rows()
            if rows:
                if self._send({"op": "rows", "rows": rows}):
                    self.sent_rows += len(rows)
                    self.sent_frames += 1
                else:
                    self._respool(rows)
                    continue
            if (self.registry is not None and self.snapshot_s > 0
                    and now - self._last_snap >= self.snapshot_s):
                self._last_snap = now
                if self._send({"op": "snap",
                               "metrics": self.registry.as_dict()}):
                    self.snapshots_sent += 1
            if now - self._last_stats >= _STATS_EVERY_S:
                self._last_stats = now
                self._log("stats", **self.stats())
            if not rows:
                if self._stop.is_set():
                    return
                self._wake.wait(0.05)
                self._wake.clear()

    # ------------------------------------------------------------ lifecycle
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            spool_depth = len(self._spool)
            spooled, shed = self.spooled_rows, self.shed_rows
        return {"spooled_rows": spooled, "sent_rows": self.sent_rows,
                "shed_rows": shed, "spool_depth": spool_depth,
                "sent_frames": self.sent_frames,
                "snapshots_sent": self.snapshots_sent,
                "reconnects": self.reconnects,
                "connected": self._sock is not None}

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Wait for the spool to drain (smoke/shutdown determinism).  True
        when fully drained in time — False never blocks the caller longer
        than the budget (telemetry's no-stall contract applies to shutdown
        too)."""
        deadline = time.monotonic() + timeout_s
        self._wake.set()
        while time.monotonic() < deadline:
            if not self.spool_depth():
                return True
            time.sleep(0.02)
        return False

    def close(self, flush_timeout_s: float = 2.0) -> None:
        """Best-effort drain, then stop.  Idempotent; never raises."""
        if self._stop.is_set():
            return
        self.flush(flush_timeout_s)
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5)
        self._log("stats", **self.stats())
        self._drop("closed")
