"""Declarative SLO alerting over the collector's fleet view.

An ``AlertRule`` is data, not code: a rule names the series it watches
(row kind + numeric field per (host, role) target), the comparison, and
the debounce — the engine turns the fleet's SeriesStore into firing /
resolved EDGES, emitted as schema'd ``alert`` rows.  Edges, not levels:
a page-worthy condition logs exactly once when it starts and once when it
clears, however many ticks it spans, so the JSONL stays greppable
("alert rows = incidents") and a flapping metric can't flood the log
faster than its own flap rate.

Rule kinds:
  threshold  fire when the latest value (or, with ``rate=True``, the
             per-second rate of a monotone series) crosses ``limit``;
  absence    fire when a target has logged NOTHING for ``absence_s``
             (heartbeat absence — the dead-host alert that needs no
             cooperating signal from the dead host);
  budget     fire when any consumer's publish->adopt p99 in the target's
             newest `lag` row exceeds that row's own carried budget (the
             PR-9 propagation budget, fleet edition).

``default_rules(cfg)`` is the shipped SLO set; all of it is opt-in via
``obs_net_*`` knobs whose 0 defaults leave each rule off.  jax-free.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative SLO.  ``row_kind``/``field`` select the series;
    exactly one of the kind-specific knobs gives the rule its meaning."""

    name: str
    why: str  # human sentence carried in every edge row (RUNBOOK pointer)
    kind: str = "threshold"  # threshold | absence | budget
    row_kind: str = ""  # series selector ("" + absence = any row at all)
    field: str = ""
    op: str = "gt"  # threshold: fire when value <op> limit (gt | lt)
    limit: float = 0.0
    rate: bool = False  # threshold compares the per-second RATE of a
    # monotone series (e.g. learn `step`) instead of its level
    absence_s: float = 0.0
    role: str = ""  # restrict to targets of this role ("" = every target)
    for_s: float = 0.0  # condition must HOLD this long before the firing
    # edge (debounce: one slow tick is noise, a sustained breach is an SLO)


class AlertEngine:
    """Evaluate rules against the collector's store; emit edge rows.

    Single-threaded by contract: only the collector's tick thread calls
    ``evaluate`` (the lock lives in the collector around the store view),
    so firing state needs no lock of its own."""

    def __init__(self, rules: List[AlertRule], logger=None, registry=None):
        self.rules = list(rules)
        self.logger = logger
        self.registry = registry
        # (rule.name, target) -> since-monotonic while breached-not-yet-
        # fired; promoted to -1.0 once the firing edge is emitted
        self._state: Dict[tuple, float] = {}

    def firing(self) -> List[Dict[str, str]]:
        """Currently-firing (rule, target) pairs — the /fleetz view."""
        return [
            {"alert": name, "target": target}
            for (name, target), since in sorted(self._state.items())
            if since < 0
        ]

    def _edge(self, rule: AlertRule, target: str, state: str,
              value: Optional[float]) -> None:
        if self.registry is not None:
            self.registry.counter(
                f"alerts_{state}_total", "obs_net").inc()
        if self.logger is None:
            return
        try:
            self.logger.log(
                "alert", alert=rule.name, state=state, target=target,
                value=value, limit=rule.limit, why=rule.why)
        except Exception:
            pass  # alerting must never take down the collector

    def _value(self, rule: AlertRule, store, target: str
               ) -> Optional[float]:
        if rule.rate:
            return store.rate(target, rule.row_kind, rule.field)
        return store.latest(target, rule.row_kind, rule.field)

    def _breached(self, rule: AlertRule, store, target: str,
                  last_rows: Dict[str, Dict[str, Any]],
                  age_s: float) -> "tuple[bool, Optional[float]]":
        if rule.kind == "absence":
            return age_s > rule.absence_s, age_s
        if rule.kind == "budget":
            row = last_rows.get("lag")
            if not row:
                return False, None
            budget = row.get("publish_adopt_budget_ms")
            per = row.get("publish_adopt_ms_by_consumer") or {}
            if not budget:
                return False, None
            worst = max(
                (float((s or {}).get("p99", 0.0)) for s in per.values()),
                default=0.0)
            return worst > float(budget), worst
        value = self._value(rule, store, target)
        if value is None:
            return False, None  # no data is absence's job, not threshold's
        if rule.op == "lt":
            return value < rule.limit, value
        return value > rule.limit, value

    def evaluate(self, store, targets: Dict[str, Dict[str, Any]],
                 now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One tick: ``targets`` maps "host/role" -> {"role", "age_s",
        "last_rows"} as prepared (under the collector's lock) by the tick
        thread.  Returns the edges emitted, newest state included."""
        now = time.monotonic() if now is None else now
        edges: List[Dict[str, Any]] = []
        live_keys = set()
        for rule in self.rules:
            for target, info in targets.items():
                if rule.role and info.get("role") != rule.role:
                    continue
                key = (rule.name, target)
                breached, value = self._breached(
                    rule, store, target, info.get("last_rows") or {},
                    float(info.get("age_s", 0.0)))
                since = self._state.get(key)
                if breached:
                    live_keys.add(key)
                    if since is None:
                        self._state[key] = now  # breach observed; debounce
                    if self._state[key] >= 0 and (
                            now - self._state[key] >= rule.for_s):
                        self._state[key] = -1.0
                        self._edge(rule, target, "firing", value)
                        edges.append({"alert": rule.name, "target": target,
                                      "state": "firing", "value": value})
                elif since is not None:
                    if since < 0:  # was firing: emit the resolved edge
                        self._edge(rule, target, "resolved", value)
                        edges.append({"alert": rule.name, "target": target,
                                      "state": "resolved", "value": value})
                    del self._state[key]  # sub-debounce breaches just reset
        # a target that vanished entirely (host evicted + lease cleaned up)
        # resolves its firing alerts rather than pinning them forever
        for key in [k for k in self._state if k not in live_keys
                    and k[1] not in targets]:
            if self._state[key] < 0:
                rule = next((r for r in self.rules if r.name == key[0]), None)
                if rule is not None:
                    self._edge(rule, key[1], "resolved", None)
                    edges.append({"alert": key[0], "target": key[1],
                                  "state": "resolved", "value": None})
            del self._state[key]
        return edges


def default_rules(cfg) -> List[AlertRule]:
    """The shipped SLO set; every rule gated on its own knob so the
    zero-config engine evaluates only heartbeat absence + the PR-9 budget
    (both self-calibrating — no threshold to mis-set)."""
    rules: List[AlertRule] = []
    floor = float(getattr(cfg, "obs_net_learn_floor", 0.0) or 0.0)
    if floor > 0:
        rules.append(AlertRule(
            name="learn_steps_floor",
            why=(f"learner throughput below the {floor:g} steps/s SLO "
                 "floor (RUNBOOK: slow learner triage)"),
            row_kind="learn", field="step", rate=True,
            op="lt", limit=floor, role="learner", for_s=5.0))
    ceiling = float(getattr(cfg, "obs_net_shed_ceiling", 0.0) or 0.0)
    if ceiling > 0:
        rules.append(AlertRule(
            name="obs_shed_spike",
            why=(f"telemetry spool shedding above {ceiling:g} rows/s — "
                 "the collector is unreachable or underwater and live "
                 "visibility is lossy (local JSONL remains complete)"),
            row_kind="obs_net", field="shed_rows", rate=True,
            op="gt", limit=ceiling, for_s=2.0))
    stale_s = float(getattr(cfg, "obs_net_stale_s", 10.0) or 10.0)
    rules.append(AlertRule(
        name="host_silent",
        why=("no telemetry from this host past the staleness budget — "
             "process dead, partitioned, or its relay wedged (RUNBOOK: "
             "degraded-host triage)"),
        kind="absence", absence_s=stale_s))
    rules.append(AlertRule(
        name="publish_adopt_budget",
        why=("a consumer's publish->adopt p99 exceeds the propagation "
             "budget its own lag row carries — it will fence (shed "
             "frames) or serve stale-beyond-budget answers"),
        kind="budget"))
    return rules
