"""obs/net/ — the live fleet telemetry plane (docs/OBSERVABILITY.md
"Live fleet telemetry").

Per-process observability (obs/) stayed strictly per-process through PR 17:
every role writes its own JSONL and serves its own /metrics, and the only
cross-process views are offline (obs_report, relay_watch).  This package
makes telemetry a first-class fleet service on the existing substrate, the
same move PR 16 made for replay:

  relay.py      ObsRelay — an observer hook on MetricsLogger + periodic
                registry snapshots, streamed to the lease-discovered
                collector over the netcore framed-socket codec through a
                bounded NON-BLOCKING spool.  Full spool = shed newest row
                with a counted reasoned row; collector death = local JSONL
                continues untouched.  Telemetry is never load-bearing.
  collector.py  ObsCollector — the `obs_collector` lease role: ingests row
                streams from every host, keeps a ring-buffered downsampling
                time-series store keyed (host, role, kind, metric), folds a
                fleet-wide RunHealth (per-host fold, aggregate status with
                offenders NAMED), and re-exports aggregated Prometheus text
                + a /fleetz JSON endpoint on the existing ObsHTTPServer.
  alerts.py     declarative SLO engine over the store (threshold / absence
                / budget / rate rules) emitting schema'd `alert` rows with
                firing/resolved edges.

scripts/obs_top.py is the live terminal dashboard over /fleetz + /metrics.
Everything here is jax-free (analysis/imports.py declares it): relays run
inside every role including device-less ones, and the collector owns no
device at all.
"""

from rainbow_iqn_apex_tpu.obs.net.alerts import (
    AlertEngine,
    AlertRule,
    default_rules,
)
from rainbow_iqn_apex_tpu.obs.net.collector import ObsCollector
from rainbow_iqn_apex_tpu.obs.net.relay import ObsRelay

__all__ = [
    "AlertEngine",
    "AlertRule",
    "ObsCollector",
    "ObsRelay",
    "default_rules",
]
