"""ObsCollector: the `obs_collector` lease role — the fleet's live view.

One collector per run ingests every relay's row stream (netcore framed
sockets, one-way), and turns them into three live surfaces:

  * a ring-buffered downsampling time-series store keyed
    (host/role, row kind, numeric field) — the substrate the SLO alert
    engine (alerts.py) and the dashboard (scripts/obs_top.py) query;
  * a fleet-wide RunHealth: one per-host obs/health.py fold (logger=None —
    the fold is silent; the JSONL of record is each host's own) plus an
    aggregate status that NAMES offenders per host/role.  A host that
    goes silent past ``obs_net_stale_s`` degrades the fleet with reason
    ``stale_host`` — absence is a signal, not a gap;
  * the existing ObsHTTPServer re-exporting aggregated Prometheus text
    (every sample labelled ``host=``) plus a ``/fleetz`` JSON endpoint
    with per-host status + staleness, which scripts/obs_top.py renders.

The collector is NEVER load-bearing: it holds no training state, no relay
blocks on it (their spools shed), and killing it mid-run costs only live
visibility — restart it and the relays re-discover the new incarnation's
lease (epoch bumped, so a lingering stale file never wins) and reconnect.

jax-free: the collector owns no device and typically runs beside the
league controller or on a CPU-only ops host.
"""

from __future__ import annotations

import collections
import selectors
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from rainbow_iqn_apex_tpu.netcore import chaos, framing
from rainbow_iqn_apex_tpu.obs.export import (
    ObsHTTPServer,
    _label_str,
    _prom_name,
    prometheus_text,
)
from rainbow_iqn_apex_tpu.obs.health import RunHealth
from rainbow_iqn_apex_tpu.obs.net.alerts import AlertEngine, default_rules
from rainbow_iqn_apex_tpu.obs.registry import MetricRegistry

_MAX_FRAME = 8 << 20  # telemetry frames are small; a peer declaring more
# is broken or hostile — drop the connection, not the collector
_RECV_BYTES = 1 << 16
_STATUS_RANK = {"ok": 0, "degraded": 1, "failing": 2}


class SeriesStore:
    """Ring-buffered downsampled series: (target, kind, field) -> deque of
    (bucket_start_s, last_value) at ``resolution_s`` granularity, bounded
    at ``window`` buckets.  Last-write-wins within a bucket — telemetry
    trend data, not an archive (the JSONL is the archive)."""

    def __init__(self, resolution_s: float = 1.0, window: int = 600):
        self.resolution_s = max(float(resolution_s), 1e-3)
        self.window = max(int(window), 2)
        self._lock = threading.Lock()
        self._series: Dict[tuple, "collections.deque"] = {}

    def add(self, target: str, kind: str, field: str, value: float,
            now: Optional[float] = None) -> None:
        now = time.time() if now is None else now
        bucket = now - (now % self.resolution_s)
        key = (target, kind, field)
        with self._lock:
            dq = self._series.get(key)
            if dq is None:
                dq = self._series[key] = collections.deque(
                    maxlen=self.window)
            if dq and dq[-1][0] == bucket:
                dq[-1] = (bucket, float(value))
            else:
                dq.append((bucket, float(value)))

    def latest(self, target: str, kind: str, field: str
               ) -> Optional[float]:
        with self._lock:
            dq = self._series.get((target, kind, field))
            return dq[-1][1] if dq else None

    def rate(self, target: str, kind: str, field: str,
             span_s: float = 30.0) -> Optional[float]:
        """Per-second rate of a monotone series over the trailing span
        (first/last sample inside it).  None until two buckets exist."""
        with self._lock:
            dq = self._series.get((target, kind, field))
            if not dq or len(dq) < 2:
                return None
            pts = list(dq)
        cutoff = pts[-1][0] - span_s
        pts = [p for p in pts if p[0] >= cutoff]
        if len(pts) < 2 or pts[-1][0] <= pts[0][0]:
            return None
        return (pts[-1][1] - pts[0][1]) / (pts[-1][0] - pts[0][0])

    def series(self, target: str, kind: str, field: str
               ) -> List[tuple]:
        with self._lock:
            dq = self._series.get((target, kind, field))
            return list(dq) if dq else []

    def keys(self) -> List[tuple]:
        with self._lock:
            return sorted(self._series)


class _HostState:
    """Per-(host/role) fold state; mutated only under the collector's
    lock (the RunHealth inside carries its own)."""

    def __init__(self, host: int, role: str, run: str, pid: int):
        self.host = int(host)
        self.role = str(role)
        self.run = str(run)
        self.pid = int(pid)
        self.health = RunHealth(MetricRegistry(), logger=None, role=role)
        self.last_seen = time.monotonic()
        self.rows = 0
        self.last_step = 0
        self.last_rows: Dict[str, Dict[str, Any]] = {}  # kind -> newest row
        self.snapshot: Dict[str, Any] = {}  # newest registry as_dict()
        self.status = "ok"
        self.reasons: List[str] = []


class _Conn:
    """One relay connection; touched only by the ingest thread."""

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.peer = peer
        self.reader = framing.FrameReader(_MAX_FRAME)
        self.target: Optional[str] = None  # set by the hello frame


class ObsCollector:
    """Accept loop + tick loop + HTTP re-export; see the module docstring.

    ``from_config`` is the default-off seam (None unless
    ``cfg.obs_net_host`` names a bind address); ``attach_lease`` stamps
    the `obs_collector` contract fields onto a HeartbeatWriter so relays
    and dashboards can find this incarnation."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        advertise: str = "",
        http_port: int = 0,
        stale_s: float = 10.0,
        resolution_s: float = 1.0,
        window: int = 600,
        tick_s: float = 2.0,
        logger=None,
        registry: Optional[MetricRegistry] = None,
        rules: Optional[list] = None,
        serve_http: bool = True,
    ):
        self.host = host
        self.advertise = advertise or host
        self.stale_s = float(stale_s)
        self.tick_s = max(float(tick_s), 0.05)
        self.logger = logger
        self.registry = registry if registry is not None else MetricRegistry()
        self.store = SeriesStore(resolution_s=resolution_s, window=window)
        self.engine = AlertEngine(
            rules if rules is not None else [],
            logger=logger, registry=self.registry)
        self._lock = threading.Lock()
        self._hosts: Dict[str, _HostState] = {}
        self._fleet: Dict[str, Any] = {"status": "ok", "hosts": {}}
        self._firing: List[Dict[str, str]] = []
        self._stop = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self._listener.setblocking(False)
        self.port = self._listener.getsockname()[1]
        self.http: Optional[ObsHTTPServer] = None
        if serve_http:
            self.http = ObsHTTPServer(
                self.registry,
                health_fn=self.fleet_healthz,
                port=http_port,
                host=host,
                metrics_text_fn=self.metrics_text,
                routes={"/fleetz": self.fleetz},
            ).start()
        self._serve_thread = threading.Thread(
            target=self._serve, name="obsnet-collector", daemon=True)
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name="obsnet-tick", daemon=True)
        self._serve_thread.start()
        self._tick_thread.start()

    # ------------------------------------------------------------- plumbing
    @classmethod
    def from_config(cls, cfg, logger=None) -> Optional["ObsCollector"]:
        """None unless ``cfg.obs_net_host`` names a bind address — running
        a collector is a per-process role decision, not a fleet default."""
        bind = getattr(cfg, "obs_net_host", "")
        if not bind:
            return None
        return cls(
            host=bind,
            port=getattr(cfg, "obs_net_port", 0),
            advertise=getattr(cfg, "obs_net_advertise", ""),
            http_port=getattr(cfg, "obs_net_http_port", 0),
            stale_s=getattr(cfg, "obs_net_stale_s", 10.0),
            resolution_s=getattr(cfg, "obs_net_resolution_s", 1.0),
            window=getattr(cfg, "obs_net_window", 600),
            tick_s=getattr(cfg, "obs_net_tick_s", 2.0),
            logger=logger,
            rules=default_rules(cfg),
        )

    def attach_lease(self, writer) -> None:
        """Stamp the discovery contract onto this process's lease BEFORE
        ``writer.start()``: relays dial ``addr:port``; dashboards hit
        ``http_port``.  The writer's role must be "obs_collector"."""
        writer.update_payload(
            addr=self.advertise, port=self.port,
            http_port=self.http.port if self.http is not None else 0)

    def _log(self, event: str, **fields: Any) -> None:
        if self.logger is not None:
            try:
                self.logger.log("obs_net", event=event, collector=True,
                                **fields)
            except Exception:
                pass

    # --------------------------------------------------------------- ingest
    def _serve(self) -> None:
        sel = selectors.DefaultSelector()
        sel.register(self._listener, selectors.EVENT_READ, None)
        conns: Dict[int, _Conn] = {}
        try:
            while not self._stop.is_set():
                for key, _ in sel.select(timeout=0.2):
                    if key.data is None:
                        try:
                            sock, addr = self._listener.accept()
                        except OSError:
                            continue
                        sock.setblocking(False)
                        sock = chaos.maybe_wrap(
                            sock, peer=f"{addr[0]}:{addr[1]}",
                            logger=self.logger)
                        conn = _Conn(sock, f"{addr[0]}:{addr[1]}")
                        conns[sock.fileno()] = conn
                        sel.register(sock, selectors.EVENT_READ, conn)
                        self.registry.counter(
                            "obsnet_accepts_total", "obs_net").inc()
                    else:
                        self._read(sel, conns, key.data)
        finally:
            for conn in list(conns.values()):
                try:
                    conn.sock.close()
                except OSError:
                    pass
            sel.close()

    def _read(self, sel, conns, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(_RECV_BYTES)
        except BlockingIOError:
            return
        except OSError:
            data = b""
        if not data:
            self._close_conn(sel, conns, conn, "eof")
            return
        try:
            frames = conn.reader.feed(data)
        except framing.FrameError as e:
            self.registry.counter("obsnet_bad_frames_total", "obs_net").inc()
            self._close_conn(sel, conns, conn, type(e).__name__)
            return
        for header, _ in frames:
            self._ingest(conn, header)

    def _close_conn(self, sel, conns, conn: _Conn, why: str) -> None:
        try:
            sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        conns.pop(conn.sock.fileno(), None)
        try:
            conn.sock.close()
        except OSError:
            pass
        if conn.target is not None:
            self._log("relay_gone", target=conn.target, why=why)

    def _ingest(self, conn: _Conn, header: Dict[str, Any]) -> None:
        op = header.get("op")
        if op == "hello":
            target = f"{header.get('host', 0)}/{header.get('role', '?')}"
            conn.target = target
            with self._lock:
                st = self._hosts.get(target)
                if st is None:
                    st = self._hosts[target] = _HostState(
                        header.get("host", 0), header.get("role", "?"),
                        header.get("run", ""), header.get("pid", 0))
                st.last_seen = time.monotonic()
            self.registry.counter("obsnet_hellos_total", "obs_net").inc()
            self._log("relay_hello", target=target)
            return
        if conn.target is None:
            # rows before hello: a peer not speaking the protocol
            self.registry.counter(
                "obsnet_orphan_frames_total", "obs_net").inc()
            return
        with self._lock:
            st = self._hosts.get(conn.target)
            if st is None:
                return
            st.last_seen = time.monotonic()
            if op == "snap":
                st.snapshot = dict(header.get("metrics") or {})
                return
        if op != "rows":
            self.registry.counter(
                "obsnet_unknown_ops_total", "obs_net").inc()
            return
        rows = header.get("rows") or []
        for row in rows:
            if isinstance(row, dict):
                self._ingest_row(st, conn.target, row)
        self.registry.counter(
            "obsnet_rows_total", "obs_net").inc(len(rows))

    def _ingest_row(self, st: _HostState, target: str,
                    row: Dict[str, Any]) -> None:
        kind = str(row.get("kind", ""))
        # the health fold carries its own lock; numeric fields feed the
        # series store (bool excluded: True is not a sample)
        st.health.observe_row(row)
        for field, value in row.items():
            if field in ("t", "ts", "schema", "host") or isinstance(
                    value, bool):
                continue
            if isinstance(value, (int, float)):
                self.store.add(target, kind, field, value)
        with self._lock:
            st.rows += 1
            st.last_rows[kind] = row
            if kind == "learn":
                st.last_step = int(row.get("step", st.last_step) or 0)

    # ----------------------------------------------------------------- tick
    def _tick_loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:
                # the fleet view is best-effort; one bad tick (e.g. a
                # half-ingested row shape) must not kill the loop
                self.registry.counter(
                    "obsnet_tick_errors_total", "obs_net").inc()

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Fold one fleet-health window: per-host status (stale hosts
        degrade with reason ``stale_host``), aggregate with offenders
        named, one ``fleet_health`` row, one alert-engine pass."""
        now = time.monotonic() if now is None else now
        with self._lock:
            items = list(self._hosts.items())
        targets: Dict[str, Dict[str, Any]] = {}
        hosts_view: Dict[str, Any] = {}
        worst = "ok"
        offenders: List[str] = []
        stale_hosts = 0
        for target, st in items:
            age = max(now - st.last_seen, 0.0)
            if age > self.stale_s:
                status, reasons = "degraded", ["stale_host"]
                stale_hosts += 1
            else:
                hrow = st.health.tick(st.last_step)
                status = hrow["status"]
                reasons = self._reasons(hrow)
            with self._lock:
                st.status, st.reasons = status, reasons
                last_rows = dict(st.last_rows)
                rows = st.rows
            targets[target] = {"role": st.role, "age_s": age,
                               "last_rows": last_rows}
            hosts_view[target] = {
                "host": st.host, "role": st.role, "status": status,
                "reasons": reasons, "age_s": round(age, 3),
                "rows": rows, "step": st.last_step, "pid": st.pid,
            }
            if _STATUS_RANK[status] > _STATUS_RANK[worst]:
                worst = status
            if status != "ok":
                offenders.append(f"{target}: {','.join(reasons) or status}")
        edges = self.engine.evaluate(self.store, targets, now=now)
        firing = self.engine.firing()
        fleet = {
            "status": worst,
            "hosts": hosts_view,
            "offenders": sorted(offenders),
            "hosts_total": len(items),
            "hosts_stale": stale_hosts,
            "alerts_firing": firing,
        }
        with self._lock:
            self._fleet = fleet
            self._firing = firing
        self.registry.gauge("fleet_status", "obs_net").set(
            _STATUS_RANK[worst])
        self.registry.gauge("fleet_hosts", "obs_net").set(len(items))
        self.registry.gauge("fleet_hosts_stale", "obs_net").set(stale_hosts)
        self.registry.gauge("fleet_alerts_firing", "obs_net").set(
            len(firing))
        if self.logger is not None:
            try:
                self.logger.log("fleet_health", **fleet)
            except Exception:
                pass
        return {"fleet": fleet, "edges": edges}

    @staticmethod
    def _reasons(hrow: Dict[str, Any]) -> List[str]:
        out = []
        if hrow.get("faults_window"):
            out.append("faults")
        if hrow.get("hosts_dead"):
            out.append("dead_hosts")
        if hrow.get("hosts_fenced"):
            out.append("fenced")
        if hrow.get("lag_consumers"):
            out.append("lagging")
        if hrow.get("takeover_pending"):
            out.append("takeover_pending")
        if hrow.get("nan_strikes"):
            out.append("nan_strikes")
        if not out and hrow.get("status") not in (None, "ok"):
            out.append(str(hrow.get("status")))
        return out

    # ------------------------------------------------------------- surfaces
    def fleetz(self) -> Dict[str, Any]:
        """/fleetz: the newest fleet fold, verbatim + a timestamp."""
        with self._lock:
            out = dict(self._fleet)
        out["ts"] = round(time.time(), 3)
        out["collector"] = {
            "port": self.port,
            "http_port": self.http.port if self.http is not None else 0,
            "stale_s": self.stale_s,
        }
        return out

    def fleet_healthz(self) -> Dict[str, Any]:
        """/healthz serves the FLEET aggregate: this endpoint is the
        fleet's health, the collector process itself being trivially alive
        if it answered."""
        with self._lock:
            fleet = self._fleet
            return {"status": fleet.get("status", "ok"),
                    "hosts_total": fleet.get("hosts_total", 0),
                    "hosts_stale": fleet.get("hosts_stale", 0),
                    "offenders": fleet.get("offenders", [])}

    def metrics_text(self) -> str:
        """Aggregated Prometheus text: the collector's own registry plus
        every host's newest snapshot re-exported with ``host=`` labels.
        Snapshot scalars export as gauges (the wire as_dict() view does not
        carry counter-vs-gauge kinds; rate() belongs to the scraper) and
        histogram snapshots as summary quantiles."""
        parts = [prometheus_text(self.registry)]
        with self._lock:
            snaps = [(t, dict(st.snapshot)) for t, st in
                     sorted(self._hosts.items()) if st.snapshot]
        for target, snap in snaps:
            lines: List[str] = []
            for key in sorted(snap):
                value = snap[key]
                name, _, rest = key.partition("{")
                role = rest[:-1] if rest.endswith("}") else ""
                pname = _prom_name(name)
                base = ([("role", role)] if role else []) + [
                    ("host", target)]
                if isinstance(value, dict):
                    lines.append(f"# TYPE {pname} summary")
                    for q, k in (("0.5", "p50"), ("0.9", "p90"),
                                 ("0.99", "p99")):
                        if k in value:
                            qlabel = _label_str(base + [("quantile", q)])
                            lines.append(
                                f"{pname}{qlabel} {value[k]:.6g}")
                    lines.append(
                        f"{pname}_count{_label_str(base)} "
                        f"{value.get('count', 0):.6g}")
                elif isinstance(value, (int, float)) and not isinstance(
                        value, bool):
                    lines.append(f"# TYPE {pname} gauge")
                    lines.append(
                        f"{pname}{_label_str(base)} {float(value):.6g}")
            parts.append("\n".join(lines) + "\n" if lines else "")
        return "".join(parts)

    # ------------------------------------------------------------ lifecycle
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"hosts": len(self._hosts),
                    "status": self._fleet.get("status", "ok"),
                    "alerts_firing": len(self._firing),
                    "port": self.port}

    def stop(self) -> None:
        """Idempotent teardown; never raises."""
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        self._serve_thread.join(timeout=5)
        self._tick_thread.join(timeout=5)
        if self.http is not None:
            self.http.stop()
        self._log("collector_stop", **self.stats())


def run_collector(cfg, stop_event=None, ready_fn=None):
    """Run the `obs_collector` role in this process until ``stop_event``.

    The standalone driver: builds the run-dir logger
    (``obs_collector.jsonl``), claims a fresh lease epoch (so a restarted
    collector supersedes its own stale file in every relay's discovery),
    advertises addr/port/http_port on the lease, and parks.  Returns the
    collector's lifetime stats dict.  ``ready_fn(collector)`` fires once
    the lease is live — the smoke's synchronization hook."""
    import os
    import threading as _threading

    from rainbow_iqn_apex_tpu.parallel.elastic import (
        HeartbeatWriter,
        heartbeat_dir,
        next_lease_epoch,
    )
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

    stop_event = stop_event if stop_event is not None else _threading.Event()
    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    os.makedirs(run_dir, exist_ok=True)
    pid = int(getattr(cfg, "process_id", 0) or 0)
    logger = MetricsLogger(
        os.path.join(run_dir, "obs_collector.jsonl"), cfg.run_id,
        echo=False, host=pid)
    collector = ObsCollector.from_config(cfg, logger=logger)
    if collector is None:
        logger.close()
        raise ValueError("run_collector: cfg.obs_net_host is unset — "
                         "nothing to bind (docs/OBSERVABILITY.md)")
    hb = heartbeat_dir(cfg)
    writer = HeartbeatWriter(
        hb, pid, max(getattr(cfg, "heartbeat_interval_s", 1.0), 0.1),
        role="obs_collector", epoch=next_lease_epoch(hb, pid))
    collector.attach_lease(writer)
    writer.start()
    try:
        if ready_fn is not None:
            ready_fn(collector)
        while not stop_event.wait(0.2):
            pass
    finally:
        stats = collector.stats()
        writer.stop()
        collector.stop()
        logger.close()
    return stats
