"""RunHealth: fold every liveness signal into one periodic 'health' row.

Ape-X health is not one number — it is the *joint* state of heartbeats
(PR 2's host_dead rows), supervisor fault rows (nonfinite_step / rollback /
stalled_step / io_retry), serve-side shedding, and the replay/queue gauges.
Before this module a human answered "is this run healthy" by hand-grepping
four row kinds out of metrics.jsonl; RunHealth folds them into a single row

    {"kind": "health", "status": "ok"|"degraded"|"failing", ...}

emitted at the metrics cadence, plus a ``healthz()`` dict the /healthz HTTP
endpoint (obs/export.py) serves live.

Signal plumbing is observational: RunHealth attaches to the MetricsLogger as
a row observer, so every fault/serve/swap row any component logs is counted
here with NO new coupling to the supervisor/serving internals — components
keep reporting exactly as they did in PR 2.

Status rules (deterministic, windowed between ticks):
  failing   - supervisor abort seen (train_aborted), OR consecutive
              non-finite strikes reached the rollback budget, OR a stall
              fired in a window where zero learn steps completed (wedged
              collective/device: the run is not making progress);
  degraded  - any fault row, shed, or dead host in the window, or any host
              currently dead (survivors-only sampling keeps training, but a
              human should know);
  ok        - none of the above.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, Optional

from rainbow_iqn_apex_tpu.obs.registry import MetricRegistry


class RunHealth:
    def __init__(
        self,
        registry: MetricRegistry,
        logger=None,
        role: str = "",
        max_nan_strikes: int = 3,
    ):
        self.registry = registry
        self.logger = logger
        self.role = role
        self.max_nan_strikes = max(int(max_nan_strikes), 1)
        self._lock = threading.Lock()
        self.fault_counts: collections.Counter = collections.Counter()
        self.dead_hosts: set = set()
        # elasticity (docs/RESILIENCE.md "heal"): hosts whose lease came
        # back (host_alive) leave dead_hosts; hosts the RoleSupervisor
        # permanently evicted leave dead_hosts too — a deliberately resized
        # fleet is healthy at its new size, not degraded forever — but stay
        # listed so the report shows the run shrank.  Fenced actors hold the
        # run degraded until they resume.
        self.evicted_hosts: set = set()
        self.fenced_hosts: set = set()
        # pipeline tracing (obs/pipeline_trace.py): consumers whose
        # publish->adopt p99 breached the max_weight_lag-derived budget in
        # the newest `lag` row — the window is degraded and the health row
        # NAMES the offender; a clean lag row clears the set
        self.lag_consumers: set = set()
        self.readmits = 0
        # learner failover (parallel/failover.py; docs/RESILIENCE.md
        # "learner failover"): a takeover latches the run degraded until the
        # SUCCESSOR completes its first clean learn step (note_finite_step
        # is the heal edge) — "a standby claimed the role" is only half the
        # story until the claimed learner actually trains.
        self.takeover_pending = False
        self.takeovers = 0
        self.total_shed = 0
        self._last_strikes = 0
        self._aborted = False
        self._stall_active = False  # set by stalled_step, cleared by a
        # completed finite step — lets healthz() report a LIVE wedge as
        # failing even though the hung loop will never tick() again
        # window state (reset every tick)
        self._win_faults: collections.Counter = collections.Counter()
        self._win_shed = 0
        self._last_step: Optional[int] = None
        self._last_status = "ok"
        self._last_row: Dict[str, Any] = {"status": "ok", "step": 0}

    # ----------------------------------------------------------- observation
    def observe_row(self, row: Dict[str, Any]) -> None:
        """MetricsLogger observer: fold fault/serve rows as they are logged."""
        kind = row.get("kind")
        if kind == "fault":
            self.note_fault(str(row.get("event", "unknown")), row)
        elif kind == "serve":
            shed = row.get("shed") or 0
            if shed:
                with self._lock:
                    self.total_shed += shed
                    self._win_shed += shed
                self.registry.counter("shed_total", "serve").inc(shed)
        elif kind == "host_alive":
            host = row.get("alive_host")
            with self._lock:
                if host is not None:
                    self.dead_hosts.discard(host)
                    self.evicted_hosts.discard(host)
            self.registry.counter("host_alive_total", "health").inc()
        elif kind == "shard_readmit":
            with self._lock:
                self.readmits += 1
            self.registry.counter("shard_readmit_total", "health").inc()
        elif kind == "actor_fenced":
            # fenced_host: set when a controller relays another host's fence
            # state (lease-carried); the envelope host is the emitter
            host = row.get("fenced_host", row.get("host", 0))
            resumed = row.get("action") == "resume"
            with self._lock:
                if resumed:
                    self.fenced_hosts.discard(host)
                else:
                    self.fenced_hosts.add(host)
                    # the fence edge itself is a degradation signal for the
                    # window it happened in (frames are being shed)
                    self.fault_counts["actor_fenced"] += 1
                    self._win_faults["actor_fenced"] += 1
            self.registry.counter("actor_fenced_total", "health").inc()
        elif kind == "route":
            # router sheds degrade exactly like serve sheds; a LOST accepted
            # request (engine died, nowhere to re-route) is a fault — the
            # fleet broke its zero-loss invariant
            shed = row.get("shed") or 0
            lost = row.get("lost") or 0
            with self._lock:
                if shed:
                    self.total_shed += shed
                    self._win_shed += shed
                if lost:
                    self.fault_counts["route_lost"] += lost
                    self._win_faults["route_lost"] += lost
            if shed:
                self.registry.counter("shed_total", "router").inc(shed)
        elif kind == "net":
            # cross-host transport flaps (serving/net/): a disconnect /
            # reconnect / bounded-probe timeout means remote capacity
            # silently came or went this window — requests survived (the
            # re-route invariant), but a human should know the wire is
            # churning; a reconnect STORM holds the run degraded window
            # after window exactly like a crash-looping actor
            event = row.get("event")
            if event in ("disconnect", "reconnect", "probe_timeout",
                         "bad_frame"):
                with self._lock:
                    self.fault_counts["net_flap"] += 1
                    self._win_faults["net_flap"] += 1
                self.registry.counter("net_flaps_total", "health").inc()
        elif kind == "replay_net":
            # cross-host replay plane flaps (replay/net/): a disconnect /
            # reconnect / probe timeout / torn frame means replay capacity
            # came or went (the learner re-routes to survivors), and a
            # spool shed means actor experience is being DROPPED — both are
            # things a human should know about, so a flap storm holds the
            # run degraded window after window like the serving plane's
            event = row.get("event")
            if event in ("disconnect", "reconnect", "probe_timeout",
                         "bad_frame", "spool_shed", "peer_dead"):
                with self._lock:
                    self.fault_counts["replay_net_flap"] += 1
                    self._win_faults["replay_net_flap"] += 1
                self.registry.counter(
                    "replay_net_flaps_total", "health").inc()
        elif kind == "obs_net":
            # live telemetry plane flaps (obs/net/): a relay disconnect /
            # reconnect / spool shed means the LIVE fleet view is lossy or
            # churning this window — training is untouched (the plane is
            # never load-bearing and the local JSONL stays complete), but
            # an operator watching the dashboard is watching a partial
            # fleet, so the window degrades with the reason counted
            event = row.get("event")
            if event in ("disconnect", "reconnect", "spool_shed"):
                with self._lock:
                    self.fault_counts["obs_net_flap"] += 1
                    self._win_faults["obs_net_flap"] += 1
                self.registry.counter(
                    "obs_net_flaps_total", "health").inc()
        elif kind == "gossip":
            # federation visibility only: stale peers skew dispatch but the
            # router stays correct (its own view is authoritative), so the
            # row feeds gauges, not degradation
            self.registry.gauge("gossip_peers_fresh", "health").set(
                int(row.get("fresh", 0) or 0))
        elif kind == "scale":
            # a scale action is a sizing decision, not a degradation; count
            # it and track the fleet size for the health row's gauges
            self.registry.counter("scale_events_total", "health").inc()
            engines = row.get("engines")
            if engines is not None:
                self.registry.gauge("fleet_size", "health").set(int(engines))
        elif kind == "rollout":
            self.registry.counter("rollout_events_total", "health").inc()
            if row.get("event") == "refused_backward":
                # the guard WORKED, but something tried to move the fleet
                # backwards — a human should know this window was degraded
                with self._lock:
                    self.fault_counts["rollout_refused"] += 1
                    self._win_faults["rollout_refused"] += 1
        elif kind == "quant_fallback":
            # the agreement gate refused quantized params: the run keeps
            # serving fp32 correctly, but the window is degraded — the
            # operator is paying full-precision cost they configured away
            # (RUNBOOK "agreement gate keeps falling back")
            with self._lock:
                self.fault_counts["quant_fallback"] += 1
                self._win_faults["quant_fallback"] += 1
            self.registry.counter("quant_fallback_total", "health").inc()
        elif kind == "quant":
            if row.get("agreement") is not None:
                self.registry.gauge("quant_action_agreement", "health").set(
                    float(row["agreement"]))
        elif kind == "publish":
            b = int(row.get("bytes") or 0)
            if b:
                self.registry.counter("publish_bytes_total", "health").inc(b)
            self.registry.gauge("publish_bytes_last", "health").set(b)
        elif kind == "league":
            # population-based training (league/; docs/LEAGUE.md): exploit
            # and adoption are NORMAL operation (counted, not degrading) —
            # but a COLLAPSED population (fewer than 2 members alive: the
            # selection loop has nobody left to select between) and a
            # refused adoption (digest mismatch: the bit-exact copy
            # contract broke) degrade the window with the reason named
            event = row.get("event")
            if event == "exploit":
                self.registry.counter("league_exploits_total", "health").inc()
            elif event == "adopt":
                self.registry.counter("league_adoptions_total", "health").inc()
            elif event == "adopt_refused":
                with self._lock:
                    self.fault_counts["league_adopt_refused"] += 1
                    self._win_faults["league_adopt_refused"] += 1
                self.registry.counter(
                    "league_adopt_refused_total", "health").inc()
            if event == "status":
                alive = row.get("alive")
                if alive is not None:
                    self.registry.gauge(
                        "league_members_alive", "health").set(int(alive))
                if row.get("collapsed"):
                    with self._lock:
                        self.fault_counts["league_collapsed"] += 1
                        self._win_faults["league_collapsed"] += 1
        elif kind == "failover":
            # learner failover lifecycle (parallel/failover.py).  A takeover
            # is the single point of failure actually failing — degrade the
            # window AND latch degraded until the successor's first clean
            # learn step (note_finite_step clears the latch).  A fenced
            # stale publish/write-back means a ZOMBIE predecessor is still
            # running — the fence worked, but a human should know it is
            # firing.  Lost claim races are normal standby operation:
            # counted, never degrading.
            event = row.get("event")
            if event == "takeover":
                with self._lock:
                    self.takeover_pending = True
                    self.takeovers += 1
                    self.fault_counts["failover_takeover"] += 1
                    self._win_faults["failover_takeover"] += 1
                self.registry.counter(
                    "failover_takeovers_total", "health").inc()
                mttr = row.get("mttr_s")
                if mttr is not None:
                    self.registry.gauge("failover_mttr_s", "health").set(
                        float(mttr))
            elif event == "fenced_stale":
                with self._lock:
                    self.fault_counts["failover_fenced"] += 1
                    self._win_faults["failover_fenced"] += 1
                self.registry.counter(
                    "failover_fenced_total", "health").inc()
            elif event == "zombie_exit":
                # the fence's terminal edge: a superseded incarnation saw
                # the successor's claim and exited its train loop — counted
                # like the per-surface refusals (a human should know a
                # zombie existed), degrading the window the same way
                with self._lock:
                    self.fault_counts["failover_zombie_exit"] += 1
                    self._win_faults["failover_zombie_exit"] += 1
                self.registry.counter(
                    "failover_zombie_exits_total", "health").inc()
            elif event == "holdoff":
                # takeover-in-progress wait: a standby deferring to a
                # sibling's claimed-but-not-yet-leased takeover — normal
                # race resolution, counted, never degrading
                self.registry.counter(
                    "failover_holdoffs_total", "health").inc()
            elif event == "claim":
                self.registry.counter(
                    "failover_claims_total", "health").inc()
            elif event == "restore":
                self.registry.counter(
                    "failover_restores_total", "health").inc()
        elif kind == "lag":
            # propagation-lag budget check (obs/pipeline_trace.py): the
            # budget is max_weight_lag publishes' worth of publish cadence —
            # a consumer whose publish->adopt p99 exceeds it is adopting
            # weights slower than the staleness fence tolerates, which means
            # it is about to fence (shed frames) or is already serving
            # stale-beyond-budget answers.  Degrade the window and NAME it.
            budget = row.get("publish_adopt_budget_ms")
            per = row.get("publish_adopt_ms_by_consumer") or {}
            breached = ([c for c, s in per.items()
                         if (s or {}).get("p99", 0) > budget]
                        if budget else [])
            with self._lock:
                if breached:
                    self.lag_consumers.update(breached)
                    self.fault_counts["propagation_lag"] += len(breached)
                    self._win_faults["propagation_lag"] += len(breached)
                elif per:
                    # a lag row with adopt stats and no breach is the heal
                    # edge: stop naming consumers that caught back up
                    self.lag_consumers.clear()
            if breached:
                self.registry.counter(
                    "propagation_lag_breaches_total", "health").inc(
                    len(breached))

    def note_fault(self, event: str, row: Optional[Dict[str, Any]] = None) -> None:
        if event == "actor_done":
            # a clean rc=0 completion (finite league member reached t_max)
            # is lifecycle, not degradation: counted, never window-degrading
            with self._lock:
                self.fault_counts[event] += 1
            self.registry.counter(f"fault_{event}_total", "supervisor").inc()
            return
        with self._lock:
            self.fault_counts[event] += 1
            self._win_faults[event] += 1
            if event == "nonfinite_step":
                strikes = (row or {}).get("strikes")
                self._last_strikes = (
                    int(strikes) if strikes is not None else self._last_strikes + 1
                )
            elif event == "rollback":
                pass  # strikes latch until a finite step clears them
            elif event == "stalled_step":
                self._stall_active = True
            elif event == "train_aborted":
                self._aborted = True
            elif event == "host_dead":
                host = (row or {}).get("dead_host")
                if host is not None:
                    self.dead_hosts.add(host)
            elif event == "actor_evicted":
                # permanent, deliberate fleet resize: the host stops holding
                # the run degraded but stays on the books as evicted
                host = (row or {}).get("role_host")
                if host is not None:
                    self.dead_hosts.discard(host)
                    self.evicted_hosts.add(host)
        self.registry.counter(f"fault_{event}_total", "supervisor").inc()

    def note_finite_step(self) -> None:
        """A completed finite learn step clears the strike latch (mirrors
        TrainSupervisor.step_ok) and ends any live stall episode."""
        with self._lock:
            self._last_strikes = 0
            self._stall_active = False
            self.takeover_pending = False  # successor trained: heal edge

    def note_abort(self) -> None:
        self.note_fault("train_aborted")

    # ------------------------------------------------------------- reporting
    def _status_locked(self, steps_in_window: Optional[int]) -> str:
        if self._aborted or self._last_strikes >= self.max_nan_strikes:
            return "failing"
        # a stall with no progress is failing.  On the tick path progress is
        # the step delta; on the LIVE path (healthz of a wedged loop that
        # will never tick again) it is "has any step completed since the
        # stall fired" — the _stall_active latch.
        if self._stall_active and (steps_in_window is None
                                   or steps_in_window <= 0):
            return "failing"
        if (
            sum(self._win_faults.values()) > 0
            or self._win_shed > 0
            or self.dead_hosts
            or self.fenced_hosts
            or self.takeover_pending
        ):
            return "degraded"
        return "ok"

    def status(self) -> str:
        with self._lock:
            return self._status_locked(None)

    def tick(self, step: int, frames: int = 0, **gauges: Any) -> Dict[str, Any]:
        """Close the current window: compute status, emit one 'health' row
        (when a logger is attached), reset window counters.  Extra ``gauges``
        (replay_occupancy, queue_depth, ...) ride along in the row and are
        mirrored into registry gauges for /metrics."""
        with self._lock:
            steps_in_window = (
                None if self._last_step is None else step - self._last_step
            )
            status = self._status_locked(steps_in_window)
            row = {
                "status": status,
                "step": int(step),
                "frames": int(frames),
                "faults_window": int(sum(self._win_faults.values())),
                "faults_total": int(sum(self.fault_counts.values())),
                "rollbacks": int(self.fault_counts.get("rollback", 0)),
                "stalls": int(self.fault_counts.get("stalled_step", 0)),
                "io_retries": int(self.fault_counts.get("io_retry", 0)),
                "nan_strikes": int(self._last_strikes),
                "shed_total": int(self.total_shed),
                "hosts_dead": sorted(self.dead_hosts),
                "hosts_evicted": sorted(self.evicted_hosts),
                "hosts_fenced": sorted(self.fenced_hosts),
                "lag_consumers": sorted(self.lag_consumers),
                "readmits": int(self.readmits),
                "takeovers": int(self.takeovers),
                "takeover_pending": bool(self.takeover_pending),
            }
            self._win_faults.clear()
            self._win_shed = 0
            if steps_in_window is not None and steps_in_window > 0:
                self._stall_active = False  # progress ended the episode
            self._last_step = step
            self._last_status = status
        for k, v in gauges.items():
            row[k] = v
            try:
                self.registry.gauge(k, self.role).set(float(v))
            except (TypeError, ValueError):
                pass  # non-numeric gauge: row-only
        self.registry.gauge(
            "health_status", self.role
        ).set({"ok": 0, "degraded": 1, "failing": 2}[status])
        self._last_row = row
        if self.logger is not None:
            self.logger.log("health", **row)
        return row

    def healthz(self) -> Dict[str, Any]:
        """Live dict for the /healthz endpoint: the LAST emitted row plus the
        instantaneous status (a stall can flip it between ticks)."""
        with self._lock:
            live = self._status_locked(None)
            out = dict(self._last_row)
        out["status"] = live
        out["ts"] = round(time.time(), 3)
        return out
