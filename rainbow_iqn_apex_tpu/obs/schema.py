"""The one JSONL row schema every role emits (docs/OBSERVABILITY.md).

Every row written through ``utils.logging.MetricsLogger`` — train loops, apex
drivers, serving, supervisor fault rows, obs timing/health/span rows — carries
the same envelope:

    t       seconds since the logger opened (monotone within a run)
    ts      absolute wall-clock epoch seconds (satellite: cross-run alignment)
    host    process index (multi-host attribution; 0 single-host)
    run     run id
    kind    row kind (the tables below)
    schema  this module's SCHEMA_VERSION

and is strict JSON: non-finite floats are sanitized BEFORE serialisation
(``json.dumps(float("nan"))`` emits bare ``NaN``, which is not JSON and broke
every downstream parser on PR 2's fault rows — NaN -> null, +/-inf -> the
string sentinels "inf"/"-inf").

Consumers (scripts/obs_report.py, scripts/lint_jsonl.py, the golden-schema
test) validate against REQUIRED_KEYS; adding a key is backward-compatible,
removing or renaming one means bumping SCHEMA_VERSION.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List

SCHEMA_VERSION = 1

# Envelope keys stamped by MetricsLogger on every row.
ENVELOPE_KEYS = frozenset({"t", "ts", "host", "run", "kind", "schema"})

# Per-kind required payload keys (beyond the envelope).  Kinds not listed
# here are free-form but still get the envelope + sanitisation.
REQUIRED_KEYS: Dict[str, frozenset] = {
    "notice": frozenset({"event"}),  # reasoned one-shot operational notices
    # (quant_fallback_multihost, device_sampling_fallback, ... — a path
    # declined a feature and says why; counted, never health-degrading)
    "actor": frozenset({"tick"}),  # chaos-soak actor-child cadence row
    # (acted/lag/weight_version/produced/shed_frames — scripts/chaos_soak.py)
    "adopt": frozenset({"tick", "version"}),  # out-of-process weight
    # adoption (MailboxSubscriber consumers: version/prev_version/checksum/
    # chain_len/resyncs — the bit-exactness witness chaos_soak asserts)
    "learn": frozenset({"step", "frames", "loss"}),  # per-interval train row
    # (replay-reuse runs — cfg.replay_ratio > 1 — additionally carry
    # `replay_ratio`, `reuse_index` (last completed pass of the newest
    # retired sample) and `clip_frac` (mean fraction of rows the IMPACT
    # clip bounded per reuse pass); optional so K=1 rows stay byte-stable)
    "eval": frozenset({"step", "score_mean"}),
    "fault": frozenset({"event"}),  # supervisor/chaos events (PR 2)
    "serve": frozenset({"requests", "batches", "shed"}),
    "swap": frozenset(),  # rare load-bearing events; payload varies by source
    "resume": frozenset({"step", "frames"}),
    "health": frozenset({"status", "step"}),  # obs/health.py aggregator
    "timing": frozenset({"step"}),  # StepTimer + span aggregates
    "span": frozenset({"name", "span_id", "parent_id", "dur_ms"}),
    "trace": frozenset({"event", "step"}),  # --trace-dir window open/close
    # elasticity rows (parallel/elastic.py; docs/RESILIENCE.md "heal"):
    "host_alive": frozenset({"alive_host", "epoch"}),  # lease revival edge
    "shard_readmit": frozenset({"shard", "epoch"}),  # drop_shard reversed
    "actor_fenced": frozenset({"lag", "max_lag"}),  # staleness fence edge
    # (``action`` is "fence" or "resume"; frames shed ride in the gauges)
    # serving-fleet rows (serving/fleet/; docs/SERVING.md "fleet"):
    "route": frozenset({"accepted", "shed"}),  # router admission window
    # (carries per-tenant accept/shed, shed_by_reason, per-engine
    # depth/version snapshot, rerouted/lost counts)
    "scale": frozenset({"action", "engines"}),  # one autoscaler decision
    "rollout": frozenset({"event", "version"}),  # fleet weight rollout
    # (event: publish/sync/converged/refused_backward)
    # cross-host serving plane rows (serving/net/; docs/SERVING.md
    # "cross-host"):
    "net": frozenset({"event"}),  # transport lifecycle + stats (event:
    # connect/disconnect/reconnect/probe_timeout/bad_frame carry `peer` and
    # `engine`; event "stats" is the periodic per-peer snapshot with
    # rtt_ms/reconnects/bytes_sent/bytes_recv — obs_report's `net:` input.
    # RunHealth folds the flap events as window-degraded: a reconnect storm
    # is capacity silently coming and going)
    # cross-host replay plane rows (replay/net/; docs/RESILIENCE.md):
    "replay_net": frozenset({"event"}),  # replay transport lifecycle +
    # stats (event: connect/disconnect/reconnect/probe_timeout/bad_frame/
    # spool_shed/peer_discovered/peer_dead/peer_readmit/stale_lease_ignored/
    # snapshot/snapshot_failed/restored/restore_failed carry `peer`/`server`;
    # event "stats" is the periodic plane snapshot with peers/dead_peers/
    # size/rtt_ms/spool_depth/acked_rows/shed_ticks/fenced_rows/batches/
    # updates_sent — obs_report's `replaynet:` input.  RunHealth folds the
    # flap + shed events as window-degraded, same story as `net`)
    "gossip": frozenset({"peers"}),  # router-federation health: declared
    # peers vs fresh/stale snapshot counts + sent/received/bad_frames —
    # a federated router whose peers all read stale is dispatching blind
    # quantization rows (utils/quantize.py; docs/PERFORMANCE.md "quant"):
    "publish": frozenset({"version", "bytes"}),  # one weight publish
    # (carries bytes_fp32 + mode ("int8"/"fp8"/"bf16"/"fp32") + quant_active
    # so bytes-saved is computable per row)
    "quant": frozenset({"event"}),  # agreement-gate outcome (event "gate"
    # carries agreement/threshold/mode/active)
    "quant_fallback": frozenset({"reason"}),  # the gate REFUSED quantized
    # params (reason e.g. agreement_below_min; carries agreement/threshold)
    # pipeline tracing rows (obs/pipeline_trace.py; docs/OBSERVABILITY.md
    # "tracing"):
    "span_link": frozenset({"stage", "trace_id", "span_id", "parent_id",
                            "t0", "dur_ms"}),  # one sampled causal span
    # (trace_id is "<kind><host>-<unit>", identical across processes for the
    # same logical unit — the cross-host flow key scripts/trace_export.py
    # turns into Perfetto flow arrows; optional `links` lists other trace
    # ids this span consumed, e.g. a learn step's sampled append ticks)
    # multi-game rows (multitask/; docs/MULTITASK.md):
    "games": frozenset({"step", "games"}),  # periodic per-game breakdown
    # (per-game learn share / replay occupancy / latest eval score keyed by
    # env id, plus suite hn_median/hn_mean aggregates; `eval` rows carry a
    # ``game`` key per game in multi-game runs)
    "eval_mt": frozenset({"step", "hn_median", "hn_mean"}),  # one suite
    # aggregate per multi-game eval pass (human-normalized median/mean over
    # the played games — the Atari-57 reporting convention)
    # league rows (league/; docs/LEAGUE.md):
    "league": frozenset({"event"}),  # population-based training events +
    # status.  event "status" is the periodic per-member table (members=
    # {id: {fitness, generation, exploits, restarts, state, ...}}, alive,
    # exploit_events, collapsed — obs_report's `league:` input; RunHealth
    # degrades on collapsed=True); event "exploit" is one weight copy
    # (member/source/generation/digest/genome); "adopt" is the loser-side
    # confirmation (digest-asserted); "exploit_skipped"/"adopt_refused"
    # carry a reasoned `reason`; "evicted" is a member's permanent death
    # learner-failover rows (parallel/failover.py; docs/RESILIENCE.md
    # "learner failover"):
    "failover": frozenset({"event"}),  # standby/takeover lifecycle (event:
    # claim/holdoff/takeover/restore/fenced_stale/zombie_exit.  "claim" is
    # one O_EXCL role-epoch race outcome — carries epoch + won, losers add
    # a reasoned `reason` and re-arm; "holdoff" is a standby deferring to a
    # sibling's claimed-but-not-yet-leased takeover (epoch/lease_epoch/
    # deadline_s — the dual-takeover guard, once per episode); "restore"
    # carries restore_s (+ step/warm) for the recovery-latency split;
    # "takeover" carries epoch/mttr_s/warm — RunHealth folds it
    # window-degraded until the first clean post-takeover learn row;
    # "fenced_stale" carries `surface` (publish/mailbox/writeback/
    # replay_net/league) + the refused epoch — the zombie-learner refusal
    # witness obs_report's `failover:` section counts; "zombie_exit" is the
    # terminal edge — the superseded incarnation observed the successor's
    # claim (fence_epoch) and exited its train loop)
    "lag": frozenset({"step"}),  # periodic lag-attribution row: per-metric
    # window percentiles of the always-on lag_* histograms (sample age at
    # learn time, ring retirement, router dispatch, batcher slot wait) plus
    # publish_adopt_ms_by_consumer and the max_weight_lag-derived
    # publish_adopt_budget_ms RunHealth folds breaches against
    # live fleet telemetry rows (obs/net/; docs/OBSERVABILITY.md "Live
    # fleet telemetry"):
    "obs_net": frozenset({"event"}),  # telemetry-plane lifecycle + stats
    # (relay side: connect/disconnect/reconnect/spool_shed carry `relay` +
    # `collector`, "stats" is the periodic spool/sent/shed snapshot;
    # collector side: relay_hello/relay_gone/collector_stop carry
    # `collector`: true.  RunHealth folds the relay flap + shed events as
    # window-degraded, same story as `net`/`replay_net` — live visibility
    # is churning even though the local JSONL is untouched)
    "alert": frozenset({"alert", "state"}),  # one SLO edge from the
    # collector's alert engine (obs/net/alerts.py): state firing/resolved,
    # `target` is "host/role", `value`/`limit`/`why` make the row
    # self-contained — alert rows are incidents, not levels
    "fleet_health": frozenset({"status", "hosts"}),  # the collector's
    # periodic fleet fold: aggregate status (worst host wins), per-target
    # status/reasons/staleness under `hosts`, offenders NAMED per
    # host/role, hosts_total/hosts_stale/alerts_firing gauges riding along
    "net_chaos": frozenset({"fault"}),  # one injected network fault edge
    # from the netcore/chaos.py interposer (delay/corrupt/torn_write/
    # blackhole/partition/slow_read), carrying `site` (this process's
    # logical name), `peer` (the far end) and `n` (cumulative count for
    # that fault/peer pair; rows rate-limited to power-of-two counts) —
    # soak assertions match recoveries to the faults that CAUSED them
}

HEALTH_STATUSES = ("ok", "degraded", "failing")

# THE registry of known row kinds.  Every ``kind`` this repo emits must be
# a REQUIRED_KEYS entry (free-form payloads register with an empty set) —
# the config-drift analyzer (analysis/configcheck.py) enforces the
# emission side statically, and lint_jsonl enforces the consumption side
# with ``require_known_kind=True``, so a new kind can never be valid in
# one place and unknown in the other.
KNOWN_KINDS = frozenset(REQUIRED_KEYS)


def sanitize(value: Any) -> Any:
    """Recursively make ``value`` strict-JSON serialisable: non-finite floats
    become null (NaN) or the "inf"/"-inf" string sentinels, numpy scalars
    collapse to Python scalars, arrays to lists.  Idempotent."""
    if isinstance(value, float):
        if math.isnan(value):
            return None
        if math.isinf(value):
            return "inf" if value > 0 else "-inf"
        return value
    if isinstance(value, (str, int, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [sanitize(v) for v in value]
    # numpy scalars / 0-d arrays expose item(); ndarrays expose tolist()
    item = getattr(value, "item", None)
    if item is not None and getattr(value, "ndim", 0) == 0:
        return sanitize(item())
    tolist = getattr(value, "tolist", None)
    if tolist is not None:
        return sanitize(tolist())
    return str(value)  # last resort: never let dumps() raise mid-run


def validate_row(
    row: Dict[str, Any], require_known_kind: bool = False
) -> List[str]:
    """Schema errors for one parsed row ([] = valid).  Checks the envelope,
    the schema version, and the kind's required payload keys.
    ``require_known_kind=True`` (lint_jsonl) additionally rejects kinds
    absent from KNOWN_KINDS — the registry IS the valid set."""
    errors = []
    for key in ("kind", "schema", "ts", "host", "run"):
        if key not in row:
            errors.append(f"missing envelope key '{key}'")
    if row.get("schema") not in (None, SCHEMA_VERSION):
        errors.append(f"unknown schema version {row.get('schema')!r}")
    kind = row.get("kind")
    if require_known_kind and kind not in KNOWN_KINDS:
        errors.append(
            f"unknown row kind {kind!r} (not registered in "
            f"obs/schema.py REQUIRED_KEYS)"
        )
    for key in REQUIRED_KEYS.get(kind, frozenset()):
        if key not in row:
            errors.append(f"'{kind}' row missing required key '{key}'")
    if kind == "health" and row.get("status") not in HEALTH_STATUSES:
        errors.append(f"health status {row.get('status')!r} not in "
                      f"{HEALTH_STATUSES}")
    return errors
