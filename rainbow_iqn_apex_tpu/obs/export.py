"""Prometheus-style text exposition + the tiny stdlib /metrics + /healthz
HTTP endpoint both the serving server and the apex drivers mount.

No third-party client library: the exposition format is plain text and the
server is ``http.server.ThreadingHTTPServer`` on a daemon thread — good
enough for a scrape every few seconds, zero new dependencies (the container
bakes only the jax_graft toolchain).

Endpoints:
  /metrics   registry counters/gauges as ``ria_<name>{role="..."} value``,
             histograms as summary-style quantile rows + _count/_sum;
  /healthz   JSON from the attached health callback; HTTP 200 for
             ok/degraded (the run is alive), 503 for failing (a scheduler
             or LB should act).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from rainbow_iqn_apex_tpu.obs.registry import Histogram, MetricRegistry
from rainbow_iqn_apex_tpu.obs.schema import sanitize

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "ria_" + _NAME_RE.sub("_", name)


def prometheus_text(registry: MetricRegistry) -> str:
    """The registry in Prometheus text exposition format (v0.0.4)."""
    lines = []
    for name, role, metric in registry.collect():
        pname = _prom_name(name)
        label = f'{{role="{role}"}}' if role else ""
        if isinstance(metric, Histogram):
            snap = metric.snapshot()
            lines.append(f"# TYPE {pname} summary")
            for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
                if key in snap:
                    qlabel = (
                        f'{{role="{role}",quantile="{q}"}}'
                        if role
                        else f'{{quantile="{q}"}}'
                    )
                    lines.append(f"{pname}{qlabel} {snap[key]:.6g}")
            lines.append(f"{pname}_count{label} {metric.total_count}")
            lines.append(f"{pname}_sum{label} {metric.total_sum:.6g}")
        else:
            lines.append(f"# TYPE {pname} {metric.kind}")
            lines.append(f"{pname}{label} {metric.get():.6g}")
    return "\n".join(lines) + "\n"


class ObsHTTPServer:
    """Serve /metrics and /healthz for one registry + health callback.

    ``port=0`` binds an ephemeral port (read ``.port`` after construction);
    Config.obs_http_port <= 0 means callers never construct one at all."""

    def __init__(
        self,
        registry: MetricRegistry,
        health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self.registry = registry
        self.health_fn = health_fn
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no stderr chatter per scrape
                pass

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            prometheus_text(outer.registry),
                            "text/plain; version=0.0.4",
                        )
                    elif path == "/healthz":
                        health = (
                            outer.health_fn() if outer.health_fn is not None
                            else {"status": "ok"}
                        )
                        code = 503 if health.get("status") == "failing" else 200
                        self._send(
                            code, json.dumps(sanitize(health)), "application/json"
                        )
                    else:
                        self._send(404, "not found\n", "text/plain")
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-scrape; nothing to serve

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ObsHTTPServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="obs-http",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
