"""Prometheus-style text exposition + the tiny stdlib /metrics + /healthz
HTTP endpoint both the serving server and the apex drivers mount.

No third-party client library: the exposition format is plain text and the
server is ``http.server.ThreadingHTTPServer`` on a daemon thread — good
enough for a scrape every few seconds, zero new dependencies (the container
bakes only the jax_graft toolchain).

Endpoints:
  /metrics   registry counters/gauges as ``ria_<name>{role="..."} value``,
             histograms as summary-style quantile rows + _count/_sum;
  /healthz   JSON from the attached health callback; HTTP 200 for
             ok/degraded (the run is alive), 503 for failing (a scheduler
             or LB should act).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from rainbow_iqn_apex_tpu.obs.registry import Histogram, MetricRegistry
from rainbow_iqn_apex_tpu.obs.schema import sanitize

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "ria_" + _NAME_RE.sub("_", name)


def escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or a hostile/odd role (or host)
    string corrupts the whole exposition (one bad label breaks every
    scraper parsing the page, not just its own line)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(pairs: "list[tuple[str, str]]") -> str:
    if not pairs:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in pairs
    )
    return "{" + inner + "}"


def prometheus_text(
    registry: MetricRegistry,
    extra_labels: Optional[Dict[str, str]] = None,
) -> str:
    """The registry in Prometheus text exposition format (v0.0.4).

    ``extra_labels`` ride on every sample — the obs collector re-exports
    one registry per fleet host with ``{"host": ...}`` here."""
    extra = sorted((extra_labels or {}).items())
    lines = []
    for name, role, metric in registry.collect():
        pname = _prom_name(name)
        base = ([("role", role)] if role else []) + extra
        label = _label_str(base)
        if isinstance(metric, Histogram):
            snap = metric.snapshot()
            lines.append(f"# TYPE {pname} summary")
            for q, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
                if key in snap:
                    qlabel = _label_str(base + [("quantile", q)])
                    lines.append(f"{pname}{qlabel} {snap[key]:.6g}")
            lines.append(f"{pname}_count{label} {metric.total_count}")
            lines.append(f"{pname}_sum{label} {metric.total_sum:.6g}")
        else:
            lines.append(f"# TYPE {pname} {metric.kind}")
            lines.append(f"{pname}{label} {metric.get():.6g}")
    return "\n".join(lines) + "\n"


class ObsHTTPServer:
    """Serve /metrics and /healthz for one registry + health callback.

    ``port=0`` binds an ephemeral port (read ``.port`` after construction);
    Config.obs_http_port <= 0 means callers never construct one at all."""

    def __init__(
        self,
        registry: MetricRegistry,
        health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        port: int = 0,
        host: str = "127.0.0.1",
        metrics_text_fn: Optional[Callable[[], str]] = None,
        routes: Optional[Dict[str, Callable[[], Dict[str, Any]]]] = None,
    ):
        self.registry = registry
        self.health_fn = health_fn
        # the obs collector overrides /metrics with its host-labelled fleet
        # aggregate and mounts extra JSON endpoints (/fleetz) here; plain
        # runs leave both None and serve exactly the pre-fleet surface
        self.metrics_text_fn = metrics_text_fn
        self.routes = dict(routes or {})
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no stderr chatter per scrape
                pass

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        text = (
                            outer.metrics_text_fn()
                            if outer.metrics_text_fn is not None
                            else prometheus_text(outer.registry)
                        )
                        self._send(200, text, "text/plain; version=0.0.4")
                    elif path == "/healthz":
                        health = (
                            outer.health_fn() if outer.health_fn is not None
                            else {"status": "ok"}
                        )
                        code = 503 if health.get("status") == "failing" else 200
                        self._send(
                            code, json.dumps(sanitize(health)), "application/json"
                        )
                    elif path in outer.routes:
                        self._send(
                            200,
                            json.dumps(sanitize(outer.routes[path]())),
                            "application/json",
                        )
                    else:
                        self._send(404, "not found\n", "text/plain")
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-scrape; nothing to serve
                except Exception as e:
                    # a broken health/route callback must answer a reasoned
                    # 500, not kill the response mid-scrape with a traceback
                    # (the pre-r18 /healthz crash path): count it, then try
                    # to tell the scraper what broke — best-effort, the
                    # headers may already be gone
                    outer.registry.counter(
                        "obs_http_errors_total", "obs"
                    ).inc()
                    try:
                        self._send(
                            500,
                            json.dumps(
                                {"status": "error",
                                 "error": type(e).__name__,
                                 "path": path}
                            ),
                            "application/json",
                        )
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ObsHTTPServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="obs-http",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
