"""Span-based tracing that lines host timing up with XLA traces.

``with tracer.span("learn_step"):`` does three things at once:
  * aggregates the region's wall time into a registry histogram
    (``span_<name>_ms``) — the source of the periodic 'timing' row and the
    /metrics summary;
  * emits ONE exemplar 'span' JSONL row per span name per flush interval,
    carrying span_id/parent_id from a thread-local stack — enough to
    reconstruct the nesting without a row per invocation (a learn loop runs
    thousands of spans per second; exemplars keep the JSONL bounded);
  * wraps ``jax.profiler.TraceAnnotation`` so when a --trace-dir capture is
    armed, the host span shows up as a named region in the XLA trace viewer
    aligned with the device timeline.

Also here: the jax-side gauges (compile/retrace counts via jax.monitoring,
device memory via Device.memory_stats) and TraceWindow — the step-windowed
profiler capture that finally wires utils/profiling.device_trace into the
train loops (--trace-dir; the hooks were dead code before this).
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
import weakref
from typing import Any, Dict, Optional

import jax

from rainbow_iqn_apex_tpu.obs.registry import MetricRegistry

_span_ids = itertools.count(1)
_tls = threading.local()


def _stack():
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


class Tracer:
    """Per-run span recorder (see module docstring).  ``logger`` is a
    MetricsLogger (or None: aggregate-only); ``reset_exemplars()`` re-arms
    one exemplar row per span name and is called by RunObs at each periodic
    flush."""

    def __init__(self, registry: MetricRegistry, logger=None, role: str = ""):
        self.registry = registry
        self.logger = logger
        self.role = role
        self._seen: set = set()
        self._seen_lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any):
        sid = next(_span_ids)
        stack = _stack()
        parent = stack[-1] if stack else 0
        stack.append(sid)
        try:
            annotation = jax.profiler.TraceAnnotation(name)
        except Exception:  # pragma: no cover - profiler backend quirks
            annotation = contextlib.nullcontext()
        t0 = time.perf_counter()
        try:
            with annotation:
                yield
        finally:
            dur_ms = (time.perf_counter() - t0) * 1e3
            stack.pop()
            self.registry.histogram(f"span_{name}_ms", self.role).observe(dur_ms)
            if self.logger is not None:
                with self._seen_lock:
                    emit = name not in self._seen
                    if emit:
                        self._seen.add(name)
                if emit:
                    self.logger.log(
                        "span",
                        name=name,
                        span_id=sid,
                        parent_id=parent,
                        dur_ms=round(dur_ms, 3),
                        role=self.role,
                        **attrs,
                    )

    def reset_exemplars(self) -> None:
        with self._seen_lock:
            self._seen.clear()

    def span_stats(self, reset: bool = False) -> Dict[str, Dict[str, float]]:
        """{span_name: snapshot} for every span histogram this tracer's
        registry holds (any role — a run report wants all of them)."""
        out = {}
        for name, role, m in self.registry.collect():
            if name.startswith("span_") and m.kind == "histogram":
                key = name[len("span_"):]
                if role and role != self.role:
                    key = f"{key}@{role}"
                out[key] = m.snapshot(reset=reset)
        return out


class TraceWindow:
    """--trace-dir: arm a one-shot ``utils.profiling.device_trace`` capture
    around learn steps [start_step, start_step + num_steps).

    The loops call ``step(learn_step)`` after every completed learn step;
    the window opens the first time the counter reaches ``start_step`` and
    closes ``num_steps`` later (or at ``close()``, so a short run still
    flushes a partial capture).  Resume-safe: a restored run whose counter
    is already past the window never arms."""

    def __init__(self, logdir: str, start_step: int, num_steps: int,
                 logger=None):
        self.logdir = logdir or None
        self.start_step = int(start_step)
        self.num_steps = max(int(num_steps), 1)
        self.logger = logger
        self._armed = bool(self.logdir)
        self._stack: Optional[contextlib.ExitStack] = None
        self._opened_at: Optional[int] = None

    @property
    def active(self) -> bool:
        return self._stack is not None

    def step(self, step: int) -> None:
        if not self._armed:
            return
        if self._stack is None and step >= self.start_step:
            if step >= self.start_step + self.num_steps:
                self._armed = False  # resumed past the window: never arm
                return
            from rainbow_iqn_apex_tpu.utils.profiling import device_trace

            self._stack = contextlib.ExitStack()
            self._stack.enter_context(device_trace(self.logdir))
            self._opened_at = step
            if self.logger is not None:
                self.logger.log("trace", event="trace_started", step=step,
                                logdir=self.logdir)
            return
        if self._stack is not None and step >= self._opened_at + self.num_steps:
            self._finish(step)

    def _finish(self, step: int) -> None:
        stack, self._stack = self._stack, None
        self._armed = False
        try:
            stack.close()  # stops the profiler; writes the xplane artifacts
        finally:
            if self.logger is not None:
                self.logger.log("trace", event="trace_captured", step=step,
                                steps=step - (self._opened_at or step),
                                logdir=self.logdir)

    def close(self, step: int = 0) -> None:
        if self._stack is not None:
            self._finish(step or ((self._opened_at or 0) + 1))


# --------------------------------------------------------------------------
# jax-side gauges: compile counts + device memory
# --------------------------------------------------------------------------

_compile_registries: "weakref.WeakSet[MetricRegistry]" = weakref.WeakSet()
_compile_listener_attempted = False
_compile_listener_installed = False
_compile_lock = threading.Lock()


def install_compile_counter(registry: MetricRegistry) -> bool:
    """Count XLA compiles/retraces into ``jax_compiles_total`` (role "jax").

    jax.monitoring has no unregister, so ONE module-level listener fans out
    to a WeakSet of live registries — per-run registries drop out when their
    run ends instead of leaking listeners across the test suite.
    Registration is attempted exactly once per process: a partially
    successful attempt (API drift on one of the two hooks) must never be
    retried, or the surviving hook would be registered again on every run
    and multiply the counts."""
    global _compile_listener_attempted, _compile_listener_installed
    with _compile_lock:
        _compile_registries.add(registry)
        if _compile_listener_attempted:
            return _compile_listener_installed
        _compile_listener_attempted = True

        def _on_event(event: str, **kw) -> None:
            if "compil" not in event:
                return
            for reg in list(_compile_registries):
                reg.counter("jax_compiles_total", "jax").inc()

        def _on_duration(event: str, duration: float, **kw) -> None:
            if "compil" not in event:
                return
            for reg in list(_compile_registries):
                reg.histogram("jax_compile_s", "jax").observe(duration)

        try:
            from jax import monitoring

            monitoring.register_event_listener(_on_event)
            _compile_listener_installed = True
        except Exception:  # pragma: no cover - older/newer jax API drift
            pass
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_duration)
            _compile_listener_installed = True
        except Exception:  # pragma: no cover
            pass
        return _compile_listener_installed


def sample_device_gauges(registry: MetricRegistry, role: str = "") -> None:
    """Device-memory gauges from the first local device.  memory_stats() is
    None on CPU and may be absent on exotic backends — silently a no-op
    there (the gauges simply never appear)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # pragma: no cover
        return
    if not stats:
        return
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
        if key in stats:
            registry.gauge(f"device_{key}", role).set(float(stats[key]))
