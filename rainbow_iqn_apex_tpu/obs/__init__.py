"""obs/ — unified telemetry for every role in the system.

One schema (obs/schema.py) over one funnel (utils.logging.MetricsLogger),
fed by one process-wide metric surface:

  registry.py   named counters/gauges/histograms with role labels
  trace.py      `with span("learn_step"):` host spans aligned with XLA
                traces, jax compile counters, device-memory gauges, and the
                --trace-dir step-windowed profiler capture
  health.py     heartbeats + fault rows + stalls + sheds folded into one
                periodic 'health' row with status in {ok, degraded, failing}
  export.py     Prometheus text exposition + stdlib /metrics + /healthz

RunObs below is the per-run bundle the train loops construct right after
their MetricsLogger; scripts/obs_report.py is the offline consumer that
turns a run dir's JSONL back into a report.  docs/OBSERVABILITY.md is the
schema reference.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from rainbow_iqn_apex_tpu.obs.export import ObsHTTPServer, prometheus_text
from rainbow_iqn_apex_tpu.obs.health import RunHealth
from rainbow_iqn_apex_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
)
from rainbow_iqn_apex_tpu.obs.pipeline_trace import (
    PipelineTracer,
    critical_path,
    format_critical_path,
)
from rainbow_iqn_apex_tpu.obs.registry import get as get_registry
from rainbow_iqn_apex_tpu.obs.registry import reset_global as reset_global_registry
from rainbow_iqn_apex_tpu.obs.schema import (
    REQUIRED_KEYS,
    SCHEMA_VERSION,
    sanitize,
    validate_row,
)

# obs.trace imports jax; resolve its names lazily (PEP 562) so jax-free
# consumers (schema/registry/health users like the chaos-soak processes)
# can import the package without paying the device-runtime import.
_TRACE_EXPORTS = (
    "TraceWindow",
    "Tracer",
    "install_compile_counter",
    "sample_device_gauges",
)


def __getattr__(name: str):
    if name in _TRACE_EXPORTS:
        import importlib

        return getattr(
            importlib.import_module("rainbow_iqn_apex_tpu.obs.trace"), name
        )
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "ObsHTTPServer",
    "PipelineTracer",
    "REQUIRED_KEYS",
    "RunHealth",
    "RunObs",
    "SCHEMA_VERSION",
    "TraceWindow",
    "Tracer",
    "critical_path",
    "format_critical_path",
    "get_registry",
    "install_compile_counter",
    "prometheus_text",
    "reset_global_registry",
    "sample_device_gauges",
    "sanitize",
    "validate_row",
]


class RunObs:
    """Everything one training run needs from obs/, in one object.

    Construct right after the MetricsLogger; the loops then touch four seams:

        obs = RunObs(cfg, metrics, role="learner")
        with obs.span("act"): ...                       # hot regions
        obs.after_learn_step(step)                      # per learn step
        obs.periodic(step, frames, replay_occupancy=x)  # at metrics cadence
        obs.close(step, frames)                         # at exit

    ``periodic`` emits the 'timing' row (StepTimer percentiles + span
    aggregates + compile counts) and the 'health' row, samples device-memory
    gauges, and re-arms span exemplars.  When cfg.obs_http_port > 0 the
    /metrics + /healthz endpoint is served for the run's lifetime."""

    def __init__(
        self,
        cfg,
        metrics,
        role: str = "learner",
        registry: Optional[MetricRegistry] = None,
        start_http: bool = True,
    ):
        from rainbow_iqn_apex_tpu.obs.trace import (
            TraceWindow,
            Tracer,
            install_compile_counter,
            sample_device_gauges,
        )
        from rainbow_iqn_apex_tpu.utils.profiling import StepTimer

        self._sample_device_gauges = sample_device_gauges

        self.cfg = cfg
        self.metrics = metrics
        self.role = role
        self.registry = registry if registry is not None else MetricRegistry()
        self.tracer = Tracer(self.registry, metrics, role)
        self.health = RunHealth(
            self.registry,
            metrics,
            role=role,
            max_nan_strikes=getattr(cfg, "max_nan_strikes", 3),
        )
        add_observer = getattr(metrics, "add_observer", None)
        if add_observer is not None:
            add_observer(self.health.observe_row)
        self.timer = StepTimer()
        self.trace_window = TraceWindow(
            getattr(cfg, "trace_dir", ""),
            getattr(cfg, "trace_start_step", 0),
            getattr(cfg, "trace_num_steps", 1),
            logger=metrics,
        )
        install_compile_counter(self.registry)
        self.http: Optional[ObsHTTPServer] = None
        port = int(getattr(cfg, "obs_http_port", 0) or 0)
        if start_http and port > 0:
            self.http = ObsHTTPServer(
                self.registry, self.health.healthz, port=port
            ).start()
        # live fleet telemetry (obs/net/; docs/OBSERVABILITY.md "Live fleet
        # telemetry"): with cfg.obs_net the run's rows + registry snapshots
        # also stream to the lease-discovered collector.  Lazy import keeps
        # the plane's code entirely off the default path (attach returns
        # None when the gate is off, so nothing is constructed either).
        self.relay = None
        if getattr(cfg, "obs_net", False):
            from rainbow_iqn_apex_tpu.obs.net.relay import ObsRelay

            self.relay = ObsRelay.attach(
                cfg, metrics, registry=self.registry, role=role)
        self._steps = self.registry.gauge("learn_step", role)
        self._frames = self.registry.gauge("frames", role)
        self._closed = False

    # ------------------------------------------------------------------ seams
    def span(self, name: str, **attrs: Any):
        return self.tracer.span(name, **attrs)

    def after_learn_step(self, step: int, block_on=None,
                         units: int = 1) -> None:
        """Per-learn-step bookkeeping: StepTimer lap + the --trace-dir
        window.  Leave ``block_on`` None when the loop already syncs on the
        step's scalars (NaN guard / priority write-back) or deliberately
        stays async (anakin) — a gratuitous barrier here would serialize
        the host against the device queue.  ``units`` = SGD steps the call
        covers (replay reuse dispatches K per call — the timing row's
        steps/steps_per_sec must count steps, not dispatches)."""
        self.timer.lap(block_on, units=units)
        self.health.note_finite_step()
        self.trace_window.step(step)

    def periodic(self, step: int, frames: int = 0, **gauges: Any) -> None:
        """Emit 'timing' + 'health' rows for the window ending now."""
        self._steps.set(step)
        self._frames.set(frames)
        self._sample_device_gauges(self.registry, self.role)
        stats = self.timer.stats()
        timing: Dict[str, Any] = {
            f"learn_{k}": round(float(v), 6) for k, v in stats.items()
        }
        timing["spans"] = {
            name: {k: round(float(v), 6) for k, v in snap.items()}
            for name, snap in self.tracer.span_stats(reset=True).items()
        }
        timing["compiles"] = int(
            self.registry.counter("jax_compiles_total", "jax").get()
        )
        self.metrics.log("timing", step=step, frames=frames, **timing)
        self.tracer.reset_exemplars()
        self.health.tick(step, frames, **gauges)

    def close(self, step: int = 0, frames: int = 0, **gauges: Any) -> None:
        """Final flush: close any open trace window, emit the last timing +
        health rows, stop the HTTP endpoint.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.trace_window.close(step)
        try:
            self.periodic(step, frames, **gauges)
        finally:
            if self.relay is not None:
                self.relay.close()
                self.relay = None
            if self.http is not None:
                self.http.stop()
                self.http = None
