"""netcore: the shared jax-free wire layer under both cross-host planes.

`serving/net/` (PR 11, the serving plane) and `replay/net/` (the replay
plane) both speak the same length-prefixed CRC-checked frame protocol; the
codec lives here so neither plane imports the other's package.  The old
import path ``rainbow_iqn_apex_tpu.serving.net.framing`` remains a
back-compat re-export of `netcore.framing`.

Exports resolve lazily (PEP 562, the parallel/ pattern) even though
everything below is jax-free — the house rule is that package ``__init__``s
stay import-cheap so a process that wants only one symbol never pays for
siblings.
"""

from typing import TYPE_CHECKING

_EXPORTS = {
    "framing": "rainbow_iqn_apex_tpu.netcore",
    "chaos": "rainbow_iqn_apex_tpu.netcore",
    "NetChaos": "rainbow_iqn_apex_tpu.netcore.chaos",
    "NetChaosSpecError": "rainbow_iqn_apex_tpu.netcore.chaos",
    "ChaosSocket": "rainbow_iqn_apex_tpu.netcore.chaos",
    "FrameError": "rainbow_iqn_apex_tpu.netcore.framing",
    "FrameProtocol": "rainbow_iqn_apex_tpu.netcore.framing",
    "FrameTooLarge": "rainbow_iqn_apex_tpu.netcore.framing",
    "FrameCorrupt": "rainbow_iqn_apex_tpu.netcore.framing",
    "FrameTruncated": "rainbow_iqn_apex_tpu.netcore.framing",
    "FrameReader": "rainbow_iqn_apex_tpu.netcore.framing",
    "DEFAULT_MAX_FRAME": "rainbow_iqn_apex_tpu.netcore.framing",
    "encode_frame": "rainbow_iqn_apex_tpu.netcore.framing",
    "recv_frame": "rainbow_iqn_apex_tpu.netcore.framing",
    "send_frame": "rainbow_iqn_apex_tpu.netcore.framing",
    "encode_frame_views": "rainbow_iqn_apex_tpu.netcore.framing",
    "send_frame_views": "rainbow_iqn_apex_tpu.netcore.framing",
    "recv_frame_view": "rainbow_iqn_apex_tpu.netcore.framing",
    "ndarray_view": "rainbow_iqn_apex_tpu.netcore.framing",
    "word_sum64": "rainbow_iqn_apex_tpu.netcore.framing",
    "CODECS": "rainbow_iqn_apex_tpu.netcore.framing",
    "encode_ndarray": "rainbow_iqn_apex_tpu.netcore.framing",
    "decode_ndarray": "rainbow_iqn_apex_tpu.netcore.framing",
    "pack_blobs": "rainbow_iqn_apex_tpu.netcore.framing",
    "unpack_blobs": "rainbow_iqn_apex_tpu.netcore.framing",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    if name in ("framing", "chaos"):
        return importlib.import_module(f"{module}.{name}")
    return getattr(importlib.import_module(module), name)


def __dir__():
    return __all__


if TYPE_CHECKING:  # static analyzers see the eager imports
    from rainbow_iqn_apex_tpu.netcore import chaos, framing  # noqa: F401
    from rainbow_iqn_apex_tpu.netcore.chaos import (  # noqa: F401
        ChaosSocket,
        NetChaos,
        NetChaosSpecError,
    )
    from rainbow_iqn_apex_tpu.netcore.framing import (  # noqa: F401
        CODECS,
        DEFAULT_MAX_FRAME,
        FrameCorrupt,
        FrameError,
        FrameProtocol,
        FrameReader,
        FrameTooLarge,
        FrameTruncated,
        decode_ndarray,
        encode_frame,
        encode_frame_views,
        encode_ndarray,
        ndarray_view,
        pack_blobs,
        recv_frame,
        recv_frame_view,
        send_frame,
        send_frame_views,
        unpack_blobs,
        word_sum64,
    )
