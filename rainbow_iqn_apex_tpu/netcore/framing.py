"""Length-prefixed binary framing shared by the cross-host planes.

Hoisted out of ``serving/net/`` (PR 11) so the replay plane (replay/net/)
and the serving plane stop depending on each other's package: both speak
this one codec, and ``serving.net.framing`` remains a back-compat re-export.

The wire is deliberately boring: stdlib ``socket`` bytes, no serialization
dependency (the container bakes only the jax_graft toolchain — same no-deps
contract as the ``/healthz`` server in obs/export.py).  One frame is

    MAGIC(2) | VER(1) | header_len u32 | blob_len u32 | header | blob | crc32 u32

big-endian, where ``header`` is one strict-JSON object (the op + small
fields) and ``blob`` is an optional opaque binary payload (an observation
frame, a Q-vector, a `WeightPacket` npz, a batch of replay transitions).
The CRC32 trailer covers header+blob, so a frame that survived TCP but was
corrupted by a buggy middlebox or a torn writer is rejected instead of
decoded into garbage.

Hardening contract (tests/test_net.py, tests/test_replay_net.py):

- **torn / partial reads**: `recv_frame` loops until the full frame arrived;
  a connection that dies MID-frame raises `FrameTruncated` (distinct from a
  clean EOF *between* frames, which returns None).  The non-blocking
  `FrameReader` buffers arbitrary byte dribbles and only yields complete
  frames.
- **oversize rejection**: a declared length past ``max_frame_bytes`` raises
  `FrameTooLarge` with a reasoned message (the declared size, the limit, and
  the knob that raises it) BEFORE any allocation — a malicious or corrupt
  length header cannot OOM the receiver.
- **checksum**: any header/blob corruption raises `FrameCorrupt`; a wrong
  magic or version raises `FrameProtocol` (a peer speaking something else —
  e.g. HTTP probing the port — is told apart from a corrupted peer).

Everything here is jax-free (numpy only): router front-ends, gossip
daemons, actor spoolers and replay shard servers import it without the
device runtime.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"RN"
VERSION = 1
_PREFIX = struct.Struct(">2sBII")  # magic, version, header_len, blob_len
_TRAILER = struct.Struct(">I")  # crc32(header + blob)
PREFIX_BYTES = _PREFIX.size
TRAILER_BYTES = _TRAILER.size
# 64 MiB; per-plane knob: Config.serve_net_max_frame_mb /
# Config.replay_net_max_frame_mb
DEFAULT_MAX_FRAME = 64 << 20


class FrameError(RuntimeError):
    """Base class: the connection's framing is broken (caller should drop
    the connection — stream state past a framing error is unrecoverable)."""


class FrameProtocol(FrameError):
    """Bad magic/version: the peer is not speaking this protocol."""


class FrameTooLarge(FrameError):
    """Declared frame size exceeds the receiver's bound."""


class FrameCorrupt(FrameError):
    """CRC mismatch or undecodable header: bytes were damaged in flight."""


class FrameTruncated(FrameError):
    """The stream ended mid-frame (peer died with a frame half-sent)."""


def encode_frame(header: Dict[str, Any], blob: bytes = b"") -> bytes:
    """One wire frame for ``header`` (strict JSON) + optional ``blob``."""
    hdr = json.dumps(header, allow_nan=False,
                     separators=(",", ":")).encode("utf-8")
    body = hdr + blob
    return b"".join((
        _PREFIX.pack(MAGIC, VERSION, len(hdr), len(blob)),
        body,
        _TRAILER.pack(zlib.crc32(body) & 0xFFFFFFFF),
    ))


def _check_prefix(prefix: bytes, max_frame_bytes: int) -> Tuple[int, int]:
    magic, version, header_len, blob_len = _PREFIX.unpack(prefix)
    if magic != MAGIC:
        raise FrameProtocol(
            f"bad frame magic {magic!r} (expected {MAGIC!r}): peer is not "
            "speaking the netcore frame protocol")
    if version != VERSION:
        raise FrameProtocol(
            f"frame protocol version {version} != supported {VERSION}")
    total = header_len + blob_len
    if total > max_frame_bytes:
        raise FrameTooLarge(
            f"frame declares {total} bytes (header {header_len} + blob "
            f"{blob_len}), over the {max_frame_bytes}-byte bound — refusing "
            "before allocation; raise this transport's max-frame knob "
            "(serve_net_max_frame_mb / replay_net_max_frame_mb) if this "
            "peer's payloads are legitimately this large")
    return header_len, blob_len


def _decode_body(body: bytes, header_len: int,
                 crc: int) -> Tuple[Dict[str, Any], bytes]:
    if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
        raise FrameCorrupt(
            "frame checksum mismatch: header/blob bytes were damaged in "
            "flight (dropping the connection — stream state is unrecoverable)")
    try:
        header = json.loads(body[:header_len].decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise FrameCorrupt(f"frame header is not strict JSON: {e}")
    if not isinstance(header, dict):
        raise FrameCorrupt(
            f"frame header is {type(header).__name__}, expected object")
    return header, bytes(body[header_len:])


class FrameReader:
    """Incremental decoder for a non-blocking stream: ``feed(bytes)`` returns
    every complete (header, blob) frame the buffer now holds.  Partial frames
    stay buffered; framing errors raise (and poison the reader — drop the
    connection)."""

    def __init__(self, max_frame_bytes: int = DEFAULT_MAX_FRAME):
        self.max_frame_bytes = int(max_frame_bytes)
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Tuple[Dict[str, Any], bytes]]:
        self._buf += data
        out: List[Tuple[Dict[str, Any], bytes]] = []
        while True:
            if len(self._buf) < PREFIX_BYTES:
                return out
            header_len, blob_len = _check_prefix(
                bytes(self._buf[:PREFIX_BYTES]), self.max_frame_bytes)
            need = PREFIX_BYTES + header_len + blob_len + TRAILER_BYTES
            if len(self._buf) < need:
                return out
            body = self._buf[PREFIX_BYTES:need - TRAILER_BYTES]
            (crc,) = _TRAILER.unpack(
                bytes(self._buf[need - TRAILER_BYTES:need]))
            out.append(_decode_body(bytes(body), header_len, crc))
            del self._buf[:need]

    def pending_bytes(self) -> int:
        return len(self._buf)


def recv_exact(sock, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes from a blocking socket.  None on clean EOF
    with ZERO bytes read; `FrameTruncated` on EOF mid-read (torn frame)."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 16))
        if not chunk:
            if got == 0:
                return None
            raise FrameTruncated(
                f"stream ended {n - got} bytes short mid-frame (peer died "
                "with a frame half-sent)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock, max_frame_bytes: int = DEFAULT_MAX_FRAME
               ) -> Optional[Tuple[Dict[str, Any], bytes]]:
    """Blocking read of one frame; None on clean EOF at a frame boundary."""
    prefix = recv_exact(sock, PREFIX_BYTES)
    if prefix is None:
        return None
    header_len, blob_len = _check_prefix(prefix, max_frame_bytes)
    body = recv_exact(sock, header_len + blob_len + TRAILER_BYTES)
    if body is None:
        raise FrameTruncated("stream ended after the frame prefix")
    (crc,) = _TRAILER.unpack(body[-TRAILER_BYTES:])
    return _decode_body(body[:-TRAILER_BYTES], header_len, crc)


def send_frame(sock, header: Dict[str, Any], blob: bytes = b"") -> int:
    """sendall one frame; returns the bytes written (caller serialises
    concurrent writers with its own per-connection lock)."""
    data = encode_frame(header, blob)
    sock.sendall(data)
    return len(data)


# ------------------------------------------------------------ ndarray codec
def encode_ndarray(arr: np.ndarray) -> Tuple[Dict[str, Any], bytes]:
    """(meta fields, raw bytes) for one array — meta rides the frame header
    (spread into it by the caller), bytes ride the blob."""
    arr = np.ascontiguousarray(arr)
    return {"dtype": str(arr.dtype), "shape": list(arr.shape)}, arr.tobytes()


def decode_ndarray(meta: Dict[str, Any], blob: bytes) -> np.ndarray:
    """Inverse of `encode_ndarray`.  The returned array VIEWS the blob
    (read-only); callers that mutate must copy."""
    dtype = np.dtype(str(meta["dtype"]))
    shape = tuple(int(d) for d in meta["shape"])
    expect = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    if len(blob) != expect:
        raise FrameCorrupt(
            f"ndarray blob is {len(blob)} bytes, meta declares {expect} "
            f"(dtype={dtype}, shape={shape})")
    return np.frombuffer(blob, dtype=dtype).reshape(shape)


# ----------------------------------------------------------- blob sequences
def pack_blobs(blobs: List[bytes]) -> bytes:
    """Concatenate N binary payloads with u32 length prefixes (a packet
    chain in one frame)."""
    out = bytearray()
    for blob in blobs:
        out += struct.pack(">I", len(blob))
        out += blob
    return bytes(out)


def unpack_blobs(data: bytes) -> List[bytes]:
    out: List[bytes] = []
    off = 0
    while off < len(data):
        if off + 4 > len(data):
            raise FrameCorrupt("blob sequence truncated in a length prefix")
        (n,) = struct.unpack_from(">I", data, off)
        off += 4
        if off + n > len(data):
            raise FrameCorrupt(
                f"blob sequence declares {n} bytes, only "
                f"{len(data) - off} remain")
        out.append(data[off:off + n])
        off += n
    return out
