"""Length-prefixed binary framing shared by the cross-host planes.

Hoisted out of ``serving/net/`` (PR 11) so the replay plane (replay/net/)
and the serving plane stop depending on each other's package: both speak
this one codec, and ``serving.net.framing`` remains a back-compat re-export.

The wire is deliberately boring: stdlib ``socket`` bytes, no serialization
dependency (the container bakes only the jax_graft toolchain — same no-deps
contract as the ``/healthz`` server in obs/export.py).  One frame is

    MAGIC(2) | VER(1) | header_len u32 | blob_len u32 | header | blob | crc32 u32

big-endian, where ``header`` is one strict-JSON object (the op + small
fields) and ``blob`` is an optional opaque binary payload (an observation
frame, a Q-vector, a `WeightPacket` npz, a batch of replay transitions).
The CRC32 trailer covers header+blob, so a frame that survived TCP but was
corrupted by a buggy middlebox or a torn writer is rejected instead of
decoded into garbage.  Envelope v2 (``VERSION_DELEGATED``) narrows the
trailer to the header only, for blobs whose payload codec carries its own
per-column word-sums (`word_sum64`) — negotiated, never the default.

Hardening contract (tests/test_net.py, tests/test_replay_net.py):

- **torn / partial reads**: `recv_frame` loops until the full frame arrived;
  a connection that dies MID-frame raises `FrameTruncated` (distinct from a
  clean EOF *between* frames, which returns None).  The non-blocking
  `FrameReader` buffers arbitrary byte dribbles and only yields complete
  frames.
- **oversize rejection**: a declared length past ``max_frame_bytes`` raises
  `FrameTooLarge` with a reasoned message (the declared size, the limit, and
  the knob that raises it) BEFORE any allocation — a malicious or corrupt
  length header cannot OOM the receiver.
- **checksum**: any header/blob corruption raises `FrameCorrupt`; a wrong
  magic or version raises `FrameProtocol` (a peer speaking something else —
  e.g. HTTP probing the port — is told apart from a corrupted peer).

Everything here is jax-free (numpy only): router front-ends, gossip
daemons, actor spoolers and replay shard servers import it without the
device runtime.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

MAGIC = b"RN"
VERSION = 1
# Frame envelope v2 ("delegated-integrity"): identical layout, but the
# trailer CRC covers the HEADER only — the payload codec riding the blob
# carries its own per-column integrity (the replay batch codec's ``sum64``
# word-sums).  Motivation: crc32 runs ~1 GB/s, which for multi-MB batch
# blobs costs more CPU than the socket itself; numpy word-sums verify the
# same single-flip corruption class at memory bandwidth.  v2 frames are
# only ever SENT to peers that negotiated a self-checking payload codec
# (replay piggyback ``wire`` >= 2); every receiver accepts both versions.
VERSION_DELEGATED = 2
FRAME_VERSION_MAX = 2
_PREFIX = struct.Struct(">2sBII")  # magic, version, header_len, blob_len
_TRAILER = struct.Struct(">I")  # crc32(header + blob)  [v2: header only]
PREFIX_BYTES = _PREFIX.size
TRAILER_BYTES = _TRAILER.size
# 64 MiB; per-plane knob: Config.serve_net_max_frame_mb /
# Config.replay_net_max_frame_mb
DEFAULT_MAX_FRAME = 64 << 20

# Registered wire-codec versions per payload family.  The frame envelope
# ("frame") is the struct above; payload codecs layered on top of the blob
# (the replay plane's batch codec) register here so the wire-drift analyzer
# (analysis/wirecheck.py) can hold every plane's protocol table to the ONE
# version the framing layer ships.  Bumping a payload codec means bumping
# it here AND in the owning protocol module — the analyzer fails the build
# when they drift apart.
CODECS: Dict[str, int] = {
    "frame": FRAME_VERSION_MAX,
    "replay_batch": 2,  # replay/net/protocol.py WIRE_CODEC_MAX
}

# one sendmsg accepts at most this many iovec entries (Linux UIO_MAXIOV is
# 1024; staying under it keeps the vectored path single-syscall per chunk
# without probing sysconf on every send)
_IOV_MAX = 1024

# buffers acceptable on the zero-copy send path: anything exposing the
# buffer protocol contiguously (bytes, bytearray, memoryview, numpy .data)
Buffer = Union[bytes, bytearray, memoryview]


class FrameError(RuntimeError):
    """Base class: the connection's framing is broken (caller should drop
    the connection — stream state past a framing error is unrecoverable)."""


class FrameProtocol(FrameError):
    """Bad magic/version: the peer is not speaking this protocol."""


class FrameTooLarge(FrameError):
    """Declared frame size exceeds the receiver's bound."""


class FrameCorrupt(FrameError):
    """CRC mismatch or undecodable header: bytes were damaged in flight."""


class FrameTruncated(FrameError):
    """The stream ended mid-frame (peer died with a frame half-sent)."""


def encode_frame(header: Dict[str, Any], blob: bytes = b"") -> bytes:
    """One wire frame for ``header`` (strict JSON) + optional ``blob``."""
    hdr = json.dumps(header, allow_nan=False,
                     separators=(",", ":")).encode("utf-8")
    body = hdr + blob
    return b"".join((
        _PREFIX.pack(MAGIC, VERSION, len(hdr), len(blob)),
        body,
        _TRAILER.pack(zlib.crc32(body) & 0xFFFFFFFF),
    ))


def _check_prefix(prefix: bytes,
                  max_frame_bytes: int) -> Tuple[int, int, int]:
    magic, version, header_len, blob_len = _PREFIX.unpack(prefix)
    if magic != MAGIC:
        raise FrameProtocol(
            f"bad frame magic {magic!r} (expected {MAGIC!r}): peer is not "
            "speaking the netcore frame protocol")
    if not VERSION <= version <= FRAME_VERSION_MAX:
        raise FrameProtocol(
            f"frame protocol version {version} not in supported range "
            f"[{VERSION}, {FRAME_VERSION_MAX}]")
    total = header_len + blob_len
    if total > max_frame_bytes:
        raise FrameTooLarge(
            f"frame declares {total} bytes (header {header_len} + blob "
            f"{blob_len}), over the {max_frame_bytes}-byte bound — refusing "
            "before allocation; raise this transport's max-frame knob "
            "(serve_net_max_frame_mb / replay_net_max_frame_mb) if this "
            "peer's payloads are legitimately this large")
    return version, header_len, blob_len


def _decode_body(body: bytes, header_len: int, crc: int,
                 version: int = VERSION) -> Tuple[Dict[str, Any], bytes]:
    covered = body if version < VERSION_DELEGATED else body[:header_len]
    if (zlib.crc32(covered) & 0xFFFFFFFF) != crc:
        raise FrameCorrupt(
            "frame checksum mismatch: header/blob bytes were damaged in "
            "flight (dropping the connection — stream state is unrecoverable)")
    try:
        header = json.loads(body[:header_len].decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise FrameCorrupt(f"frame header is not strict JSON: {e}")
    if not isinstance(header, dict):
        raise FrameCorrupt(
            f"frame header is {type(header).__name__}, expected object")
    return header, bytes(body[header_len:])


class FrameReader:
    """Incremental decoder for a non-blocking stream: ``feed(bytes)`` returns
    every complete (header, blob) frame the buffer now holds.  Partial frames
    stay buffered; framing errors raise (and poison the reader — drop the
    connection)."""

    def __init__(self, max_frame_bytes: int = DEFAULT_MAX_FRAME):
        self.max_frame_bytes = int(max_frame_bytes)
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Tuple[Dict[str, Any], bytes]]:
        self._buf += data
        out: List[Tuple[Dict[str, Any], bytes]] = []
        while True:
            if len(self._buf) < PREFIX_BYTES:
                return out
            version, header_len, blob_len = _check_prefix(
                bytes(self._buf[:PREFIX_BYTES]), self.max_frame_bytes)
            need = PREFIX_BYTES + header_len + blob_len + TRAILER_BYTES
            if len(self._buf) < need:
                return out
            body = self._buf[PREFIX_BYTES:need - TRAILER_BYTES]
            (crc,) = _TRAILER.unpack(
                bytes(self._buf[need - TRAILER_BYTES:need]))
            out.append(_decode_body(bytes(body), header_len, crc, version))
            del self._buf[:need]

    def pending_bytes(self) -> int:
        return len(self._buf)


def recv_exact(sock, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes from a blocking socket.  None on clean EOF
    with ZERO bytes read; `FrameTruncated` on EOF mid-read (torn frame)."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 16))
        if not chunk:
            if got == 0:
                return None
            raise FrameTruncated(
                f"stream ended {n - got} bytes short mid-frame (peer died "
                "with a frame half-sent)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock, max_frame_bytes: int = DEFAULT_MAX_FRAME
               ) -> Optional[Tuple[Dict[str, Any], bytes]]:
    """Blocking read of one frame; None on clean EOF at a frame boundary."""
    prefix = recv_exact(sock, PREFIX_BYTES)
    if prefix is None:
        return None
    version, header_len, blob_len = _check_prefix(prefix, max_frame_bytes)
    body = recv_exact(sock, header_len + blob_len + TRAILER_BYTES)
    if body is None:
        raise FrameTruncated("stream ended after the frame prefix")
    (crc,) = _TRAILER.unpack(body[-TRAILER_BYTES:])
    return _decode_body(body[:-TRAILER_BYTES], header_len, crc, version)


def send_frame(sock, header: Dict[str, Any], blob: bytes = b"") -> int:
    """sendall one frame; returns the bytes written (caller serialises
    concurrent writers with its own per-connection lock)."""
    data = encode_frame(header, blob)
    sock.sendall(data)
    return len(data)


# ------------------------------------------------- zero-copy vectored frames
def ndarray_view(arr: np.ndarray) -> memoryview:
    """A flat byte view of ``arr`` WITHOUT copying (the `arr.tobytes()` in
    `encode_ndarray` is one of the copies the vectored path exists to kill).
    Non-contiguous input is materialised once — the only copy this path
    ever makes, and replay columns are contiguous ring slices already."""
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return memoryview(arr).cast("B")


def encode_frame_views(header: Dict[str, Any],
                       blobs: Sequence[Buffer] = (),
                       crc_blob: bool = True) -> Tuple[List[Buffer], int]:
    """The iovec form of `encode_frame`: returns ``(buffers, total_bytes)``
    where ``buffers`` is the ordered chain

        prefix | header-json | *blobs | crc-trailer

    with the caller's blob buffers referenced, NOT copied — the CRC is
    accumulated incrementally over each view.  Feed the chain to
    `send_frame_views` or join it for transports without scatter-gather.

    ``crc_blob=False`` emits a VERSION_DELEGATED (v2) frame whose trailer
    CRC covers the header only: use it ONLY when the blob's payload codec
    carries its own integrity (the replay batch codec's per-column
    ``sum64``), and only to peers that negotiated it — crc32 at ~1 GB/s
    over a multi-MB batch otherwise costs more than the socket itself."""
    hdr = json.dumps(header, allow_nan=False,
                     separators=(",", ":")).encode("utf-8")
    blob_len = 0
    crc = zlib.crc32(hdr)
    views: List[Buffer] = []
    for b in blobs:
        if isinstance(b, bytes):
            v: Buffer = b
            n = len(b)
        else:
            mv = b if isinstance(b, memoryview) else memoryview(b)
            # flat byte view so downstream byte-offset slicing (partial
            # sendmsg resume) is exact regardless of the source itemsize
            v = mv if mv.format == "B" and mv.ndim == 1 else mv.cast("B")
            n = v.nbytes
        if n == 0:
            continue
        blob_len += n
        if crc_blob:
            crc = zlib.crc32(v, crc)
        views.append(v)
    version = VERSION if crc_blob else VERSION_DELEGATED
    chain: List[Buffer] = [_PREFIX.pack(MAGIC, version, len(hdr), blob_len),
                           hdr]
    chain.extend(views)
    chain.append(_TRAILER.pack(crc & 0xFFFFFFFF))
    return chain, PREFIX_BYTES + len(hdr) + blob_len + TRAILER_BYTES


def word_sum64(buf: Buffer) -> int:
    """Order-sensitive-enough payload checksum at memory bandwidth: the
    u64 little-endian word sum (mod 2**64) of ``buf``, tail bytes folded
    in as one final little-endian word.  Any single-byte flip perturbs
    exactly one term, so it is ALWAYS detected; numpy sums ~20x faster
    than crc32, which is what lets v2 frames skip the blob CRC without
    giving up the chaos-plane corruption guarantees."""
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    if mv.format != "B" or mv.ndim != 1:
        mv = mv.cast("B")
    n = mv.nbytes
    words = n >> 3
    total = 0
    if words:
        total = int(np.frombuffer(mv[:words << 3], dtype="<u8")
                    .sum(dtype=np.uint64))
    tail = n - (words << 3)
    if tail:
        total += int.from_bytes(mv[n - tail:], "little")
    return total & 0xFFFFFFFFFFFFFFFF


def sendmsg_all(sock, buffers: Sequence[Buffer], total: int) -> int:
    """Flush an iovec chain with ``sock.sendmsg``, resuming after partial
    sends mid-iovec (the kernel may accept any byte count; we re-slice the
    chain from the first unsent byte and go again).  Falls back to one
    join + sendall when the socket lacks sendmsg (test doubles, wrapped
    transports)."""
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None:
        sock.sendall(b"".join(bytes(b) if isinstance(b, memoryview) else b
                              for b in buffers))
        return total
    pending: List[Buffer] = [b for b in buffers if len(b) > 0]
    sent = 0
    while pending:
        n = sendmsg(pending[:_IOV_MAX])
        if n <= 0:
            raise FrameTruncated(
                "sendmsg wrote 0 bytes mid-frame (peer closed the stream "
                "with a frame half-sent)")
        sent += n
        # drop fully-sent buffers; re-slice the first partial one
        while pending and n > 0:
            head = pending[0]
            size = len(head) if isinstance(head, bytes) else head.nbytes
            if n >= size:
                n -= size
                pending.pop(0)
            else:
                pending[0] = memoryview(head)[n:]
                n = 0
    if sent != total:
        raise FrameTruncated(
            f"vectored send wrote {sent} bytes, frame is {total}")
    return sent


def send_frame_views(sock, header: Dict[str, Any],
                     blobs: Sequence[Buffer] = (),
                     crc_blob: bool = True) -> int:
    """Zero-copy `send_frame`: scatter-gather the header + blob views out
    in-place via sendmsg.  Same caller-locks-the-writer contract as
    `send_frame`; returns bytes written.  ``crc_blob=False`` emits a v2
    delegated-integrity frame (see `encode_frame_views`)."""
    chain, total = encode_frame_views(header, blobs, crc_blob=crc_blob)
    return sendmsg_all(sock, chain, total)


def recv_exact_into(sock, view: memoryview) -> int:
    """Fill ``view`` completely from a blocking socket via ``recv_into``
    (no chunk list, no join — the single-allocation receive path).  Returns
    0 on clean EOF with ZERO bytes read, the view's length when filled;
    raises `FrameTruncated` on EOF mid-read."""
    need = view.nbytes
    got = 0
    recv_into = getattr(sock, "recv_into", None)
    while got < need:
        if recv_into is not None:
            n = recv_into(view[got:], need - got)
            if not n:
                chunk = b""
            else:
                got += n
                continue
        else:  # pragma: no cover - exercised via test doubles
            chunk = sock.recv(min(need - got, 1 << 16))
            if chunk:
                view[got:got + len(chunk)] = chunk
                got += len(chunk)
                continue
        if got == 0:
            return 0
        raise FrameTruncated(
            f"stream ended {need - got} bytes short mid-frame (peer died "
            "with a frame half-sent)")
    return got


def recv_frame_view(sock, max_frame_bytes: int = DEFAULT_MAX_FRAME
                    ) -> Optional[Tuple[Dict[str, Any], memoryview]]:
    """Blocking read of one frame into ONE fresh buffer; the returned blob
    is a read-only memoryview of that buffer (decode arrays from it with
    `decode_ndarray` / the batch codec without further copies).  None on
    clean EOF at a frame boundary.  Unlike `FrameReader`, the backing
    buffer is per-frame and owned by the returned view, so holding the
    view never pins a shared receive buffer."""
    prefix = bytearray(PREFIX_BYTES)
    if recv_exact_into(sock, memoryview(prefix)) == 0:
        return None
    version, header_len, blob_len = _check_prefix(
        bytes(prefix), max_frame_bytes)
    body = bytearray(header_len + blob_len + TRAILER_BYTES)
    mv = memoryview(body)
    if recv_exact_into(sock, mv) == 0:
        raise FrameTruncated("stream ended after the frame prefix")
    (crc,) = _TRAILER.unpack(mv[-TRAILER_BYTES:])
    payload = mv[:-TRAILER_BYTES]
    covered = payload if version < VERSION_DELEGATED \
        else payload[:header_len]
    if (zlib.crc32(covered) & 0xFFFFFFFF) != crc:
        raise FrameCorrupt(
            "frame checksum mismatch: header/blob bytes were damaged in "
            "flight (dropping the connection — stream state is unrecoverable)")
    try:
        header = json.loads(bytes(payload[:header_len]).decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise FrameCorrupt(f"frame header is not strict JSON: {e}")
    if not isinstance(header, dict):
        raise FrameCorrupt(
            f"frame header is {type(header).__name__}, expected object")
    return header, payload[header_len:].toreadonly()


# ------------------------------------------------------------ ndarray codec
def encode_ndarray(arr: np.ndarray) -> Tuple[Dict[str, Any], bytes]:
    """(meta fields, raw bytes) for one array — meta rides the frame header
    (spread into it by the caller), bytes ride the blob."""
    arr = np.ascontiguousarray(arr)
    return {"dtype": str(arr.dtype), "shape": list(arr.shape)}, arr.tobytes()


def decode_ndarray(meta: Dict[str, Any], blob: bytes) -> np.ndarray:
    """Inverse of `encode_ndarray`.  The returned array VIEWS the blob
    (read-only); callers that mutate must copy."""
    dtype = np.dtype(str(meta["dtype"]))
    shape = tuple(int(d) for d in meta["shape"])
    expect = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    if len(blob) != expect:
        raise FrameCorrupt(
            f"ndarray blob is {len(blob)} bytes, meta declares {expect} "
            f"(dtype={dtype}, shape={shape})")
    return np.frombuffer(blob, dtype=dtype).reshape(shape)


# ----------------------------------------------------------- blob sequences
def pack_blobs(blobs: List[bytes]) -> bytes:
    """Concatenate N binary payloads with u32 length prefixes (a packet
    chain in one frame)."""
    out = bytearray()
    for blob in blobs:
        out += struct.pack(">I", len(blob))
        out += blob
    return bytes(out)


def unpack_blobs(data: bytes) -> List[bytes]:
    out: List[bytes] = []
    off = 0
    while off < len(data):
        if off + 4 > len(data):
            raise FrameCorrupt("blob sequence truncated in a length prefix")
        (n,) = struct.unpack_from(">I", data, off)
        off += 4
        if off + n > len(data):
            raise FrameCorrupt(
                f"blob sequence declares {n} bytes, only "
                f"{len(data) - off} remain")
        out.append(data[off:off + n])
        off += n
    return out
