"""Seeded network-fault interposer for every socket the wire planes create.

The three production planes — serving (PR 11), replay (PR 16), telemetry
(PR 18) — all build their sockets and immediately pass them through
``chaos.maybe_wrap(sock, peer=...)`` at the single ``netcore`` seam.  When
nothing is armed the call returns the socket unchanged (the off path is
bitwise the previous PR; tier-1 asserts it), so production pays one function
call per *connection*, never per byte.  When armed, the socket comes back
wrapped in a :class:`ChaosSocket` that injects the degraded-network failure
class clean-death soaks never exercise (arXiv:1803.00933 deployments die of
latency spikes and torn frames far more often than of SIGKILL):

===================  ========================================================
clause               effect (per-direction, per-peer-pair, seeded)
===================  ========================================================
``delay_ms=50±20``   sleep before each write (mean ± jitter, blocking paths)
``corrupt_frame``    flip one seeded byte of an outgoing write (CRC witness)
``torn_write``       write a seeded prefix then fail — mid-frame sender death
``blackhole``        silently drop a whole outgoing write (frame-atomic loss)
``partition=a->b``   one-way partition: a's egress to b drops (TX side) and
                     b's ingress from a stalls (RX side); ``*`` wildcards
``slow_read_bps=N``  clamp+pace this process's reads to ~N bytes/s
===================  ========================================================

Every clause takes ``@p=<prob>`` (event probability, default 1) and
``@t=<a>..<b>`` (active window in seconds since arming, default always), so
one spec string expresses a rotating fault schedule:

    delay_ms=50±20@p=1.0,corrupt_frame@p=0.01,partition=learner->replay1@t=10..12

Arming is default-off and dual-path, the ``utils/faults.py`` house pattern:
``Config.net_chaos_spec`` / the ``RIA_NET_CHAOS`` env var (env wins; a soak
harness arms children without touching run configs).  ``RIA_NET_CHAOS_SITE``
names this process's logical site for partition matching ("learner",
"replay0", ...).  Determinism: every wrapped connection draws from its own
``random.Random`` seeded by (seed, site, peer, connection ordinal), so a
soak replays exactly — reconnects included.

The four ``net_*`` points in ``utils.faults.POINTS`` are consulted at the
matching decision sites, so the house ``--fault-spec`` grammar can ALSO
force single injections deterministically (``net_corrupt@3`` corrupts
exactly the third write) without authoring a chaos spec.

Injections are observable, not statistical: each hit increments a
per-(fault, peer) counter and emits a ``net_chaos`` row (rate-limited to
power-of-two counts) naming the injected site, so soak assertions are
causal — "the corruption the spec injected is the corruption the plane
recovered from".
"""

from __future__ import annotations

import dataclasses
import os
import random
import socket
import threading
import time
import zlib
from typing import Any, Callable, Dict, Optional, Tuple

from rainbow_iqn_apex_tpu.utils import faults

ENV_VAR = "RIA_NET_CHAOS"
SITE_ENV_VAR = "RIA_NET_CHAOS_SITE"
SEED_ENV_VAR = "RIA_NET_CHAOS_SEED"

# clause kinds the spec grammar accepts
KINDS = frozenset({
    "delay_ms",
    "corrupt_frame",
    "torn_write",
    "blackhole",
    "partition",
    "slow_read_bps",
})

# faults.POINTS names consulted at the matching decision sites (the house
# --fault-spec grammar can force injections without a chaos spec)
_NET_POINTS = ("net_delay", "net_corrupt", "net_partition", "net_slow_peer")

# defaults used when an injection is forced via faults.fire() alone (no
# chaos clause supplies parameters)
_FORCED_DELAY_S = 0.05
_FORCED_SLOW_CHUNK = 1024

# RX-partition stall quantum: a blocking read inside a partition window
# sleeps this long then raises socket.timeout, so reader loops keep
# observing their stop events (data stays in the kernel buffer — a
# partition delays, it does not lose)
_RX_STALL_S = 0.05


class NetChaosSpecError(ValueError):
    """A malformed ``net_chaos_spec`` / ``RIA_NET_CHAOS`` string."""


@dataclasses.dataclass(frozen=True)
class Clause:
    """One parsed fault clause; inactive outside its ``@t`` window."""

    kind: str
    prob: float = 1.0  # event probability within the window
    t0: Optional[float] = None  # window start (s since arming), None=always
    t1: Optional[float] = None
    mean_ms: float = 0.0  # delay_ms
    jitter_ms: float = 0.0
    bps: int = 0  # slow_read_bps
    src: str = "*"  # partition
    dst: str = "*"


def _parse_size(text: str, entry: str) -> int:
    mult = 1
    low = text.strip().lower()
    if low.endswith("k"):
        mult, low = 1024, low[:-1]
    elif low.endswith("m"):
        mult, low = 1024 * 1024, low[:-1]
    try:
        n = int(float(low) * mult)
    except ValueError:
        raise NetChaosSpecError(f"bad byte rate in chaos entry '{entry}'")
    if n < 1:
        raise NetChaosSpecError(f"byte rate must be >= 1 in '{entry}'")
    return n


def parse_spec(spec: str) -> Tuple[Clause, ...]:
    """``"delay_ms=50±20@p=0.5,partition=a->b@t=10..12"`` -> clauses.

    Grammar per comma-separated entry: ``kind[=value][@p=<prob>][@t=<a>..<b>]``
    (``±`` may be spelled ``+-``).  Raises :class:`NetChaosSpecError` on any
    malformed entry — a chaos spec that silently half-parses would make a
    soak assert against faults that were never injected.
    """
    out = []
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        parts = entry.split("@")
        head, mods = parts[0], parts[1:]
        kind, _, value = head.partition("=")
        kind = kind.strip()
        if kind not in KINDS:
            raise NetChaosSpecError(
                f"unknown chaos clause '{kind}' in '{entry}' "
                f"(known: {', '.join(sorted(KINDS))})"
            )
        prob, t0, t1 = 1.0, None, None
        for mod in mods:
            key, _, mval = mod.partition("=")
            if key == "p":
                try:
                    prob = float(mval)
                except ValueError:
                    raise NetChaosSpecError(
                        f"bad probability in chaos entry '{entry}'")
                if not 0.0 <= prob <= 1.0:
                    raise NetChaosSpecError(
                        f"probability out of [0,1] in '{entry}'")
            elif key == "t":
                a, sep, b = mval.partition("..")
                if not sep:
                    raise NetChaosSpecError(
                        f"bad window (want t=a..b) in chaos entry '{entry}'")
                try:
                    t0, t1 = float(a), float(b)
                except ValueError:
                    raise NetChaosSpecError(
                        f"bad window bounds in chaos entry '{entry}'")
                if t1 < t0 or t0 < 0.0:
                    raise NetChaosSpecError(
                        f"window must satisfy 0 <= a <= b in '{entry}'")
            else:
                raise NetChaosSpecError(
                    f"unknown modifier '@{mod}' in chaos entry '{entry}'")
        fields: Dict[str, Any] = {"kind": kind, "prob": prob,
                                  "t0": t0, "t1": t1}
        if kind == "delay_ms":
            raw = value.replace("+-", "±")
            mean, _, jit = raw.partition("±")
            try:
                fields["mean_ms"] = float(mean)
                fields["jitter_ms"] = float(jit) if jit else 0.0
            except ValueError:
                raise NetChaosSpecError(
                    f"bad delay (want delay_ms=M or M±J) in '{entry}'")
            if fields["mean_ms"] < 0 or fields["jitter_ms"] < 0:
                raise NetChaosSpecError(f"negative delay in '{entry}'")
        elif kind == "slow_read_bps":
            fields["bps"] = _parse_size(value, entry)
        elif kind == "partition":
            src, sep, dst = value.partition("->")
            if not sep or not src.strip() or not dst.strip():
                raise NetChaosSpecError(
                    f"bad partition (want partition=src->dst) in '{entry}'")
            fields["src"], fields["dst"] = src.strip(), dst.strip()
        elif value:
            raise NetChaosSpecError(
                f"clause '{kind}' takes no value (got '{value}') in '{entry}'")
        out.append(Clause(**fields))
    return tuple(out)


def _site_match(pattern: str, site: str) -> bool:
    return pattern == "*" or pattern == site


class NetChaos:
    """Parsed spec + arming state + per-(fault, peer) injection ledger."""

    def __init__(
        self,
        spec: str = "",
        seed: int = 0,
        site: str = "",
        clock: Callable[[], float] = time.monotonic,
    ):
        self.spec = spec
        self.seed = int(seed)
        self.site = site or os.environ.get(SITE_ENV_VAR, "") or "host"
        self.clauses = parse_spec(spec)
        self._clock = clock
        self._epoch = clock()  # @t windows are relative to arming
        self._lock = threading.Lock()
        self._counts: Dict[Tuple[str, str], int] = {}
        self._wraps: Dict[str, int] = {}
        self._logger = None

    @property
    def armed(self) -> bool:
        return bool(self.clauses)

    def now(self) -> float:
        """Seconds since arming (the @t window clock)."""
        return self._clock() - self._epoch

    def attach_logger(self, logger) -> None:
        """First logger wins — every plane offers its own at wrap time."""
        if logger is not None and self._logger is None:
            self._logger = logger

    def active(self, clause: Clause) -> bool:
        """Inside the clause's @t window (probability is drawn per event
        by the connection's own rng, not here)."""
        if clause.t0 is None:
            return True
        return clause.t0 <= self.now() <= clause.t1

    def record(self, fault: str, peer: str) -> None:
        """Count one injection; emit a ``net_chaos`` row at power-of-two
        counts so a pathological spec cannot flood the run log."""
        with self._lock:
            n = self._counts.get((fault, peer), 0) + 1
            self._counts[(fault, peer)] = n
            logger = self._logger
        if logger is not None and (n & (n - 1)) == 0:
            try:
                logger.log("net_chaos", fault=fault, site=self.site,
                           peer=peer, n=n)
            except Exception:
                pass  # telemetry never takes down the wire

    def counts(self) -> Dict[Tuple[str, str], int]:
        with self._lock:
            return dict(self._counts)

    def injected(self, fault: str) -> int:
        with self._lock:
            return sum(n for (f, _), n in self._counts.items() if f == fault)

    def wrap(self, sock, peer: str = "") -> "ChaosSocket":
        """Wrap one socket; each (peer, ordinal) gets its own seeded rng so
        reconnects replay deterministically."""
        with self._lock:
            k = self._wraps.get(peer, 0)
            self._wraps[peer] = k + 1
        key = f"{self.seed}|{self.site}|{peer}|{k}".encode()
        return ChaosSocket(sock, self, peer, random.Random(zlib.crc32(key)))


class ChaosSocket:
    """Delegating socket wrapper that applies the armed clauses.

    TX faults (partition / blackhole / torn_write / corrupt_frame /
    delay_ms) act on writes so the *peer* observes the degradation through
    the real kernel path; RX faults (partition ingress, slow_read_bps) act
    on this process's reads.  Unknown attributes pass straight through, so
    selectors, TCP_NODELAY setup, and getpeername all keep working.
    """

    def __init__(self, sock, chaos: NetChaos, peer: str,
                 rng: random.Random):
        self._sock = sock
        self._chaos = chaos
        self._peer = peer
        self._rng = rng
        self._rng_lock = threading.Lock()
        self._read_credit = 0.0  # slow_read token bucket
        self._read_stamp = chaos.now()

    # ------------------------------------------------------------ plumbing
    def __getattr__(self, name: str):
        return getattr(self._sock, name)

    def fileno(self) -> int:
        return self._sock.fileno()

    def close(self) -> None:
        self._sock.close()

    def unwrap(self):
        """The raw socket underneath (tests and diagnostics only)."""
        return self._sock

    def _hit(self, prob: float) -> bool:
        if prob >= 1.0:
            return True
        if prob <= 0.0:
            return False
        with self._rng_lock:
            return self._rng.random() < prob

    def _rand(self, n: int) -> int:
        with self._rng_lock:
            return self._rng.randrange(n)

    def _uniform(self, a: float, b: float) -> float:
        with self._rng_lock:
            return self._rng.uniform(a, b)

    def _blocking(self) -> bool:
        try:
            return self._sock.gettimeout() != 0.0
        except OSError:
            return False

    # ------------------------------------------------------------ TX path
    def _tx_dropped(self) -> bool:
        """Partition egress / blackhole / forced net_partition: the write
        vanishes wholesale.  send_frame() is one sendall per frame, so a
        dropped write is frame-atomic — the stream stays in sync and the
        peer simply never sees the frame (ack timeout, not corruption)."""
        chaos = self._chaos
        for c in chaos.clauses:
            if c.kind == "partition" and chaos.active(c) \
                    and _site_match(c.src, chaos.site) \
                    and _site_match(c.dst, self._peer) and self._hit(c.prob):
                chaos.record("partition", self._peer)
                return True
            if c.kind == "blackhole" and chaos.active(c) \
                    and self._hit(c.prob):
                chaos.record("blackhole", self._peer)
                return True
        inj = faults.get()
        if inj.has("net_partition") and inj.fire("net_partition"):
            chaos.record("partition", self._peer)
            return True
        return False

    def _tx_transform(self, data) -> bytes:
        """torn_write (prefix then BrokenPipeError), corrupt_frame (one
        seeded byte flip), delay_ms (sleep) — in that order."""
        chaos = self._chaos
        buf = bytes(data)
        for c in chaos.clauses:
            if c.kind == "torn_write" and chaos.active(c) and len(buf) > 1 \
                    and self._hit(c.prob):
                prefix = buf[: 1 + self._rand(len(buf) - 1)]
                try:
                    self._sock.sendall(prefix)
                except OSError:
                    pass
                chaos.record("torn_write", self._peer)
                raise BrokenPipeError(
                    f"chaos: torn write to {self._peer or 'peer'}")
        corrupt = any(
            c.kind == "corrupt_frame" and chaos.active(c) and self._hit(c.prob)
            for c in chaos.clauses)
        inj = faults.get()
        if not corrupt and inj.has("net_corrupt"):
            corrupt = inj.fire("net_corrupt")
        if corrupt and buf:
            # flip past the 11-byte frame prefix (magic+ver+two u32 lengths)
            # when the write is long enough: a flipped LENGTH field makes
            # the peer wait forever for bytes that never come — a hang, not
            # the prompt typed Frame* error corruption is injected to force
            lo = 11 if len(buf) > 11 else 0
            i = lo + self._rand(len(buf) - lo)
            buf = buf[:i] + bytes([buf[i] ^ 0xFF]) + buf[i + 1:]
            chaos.record("corrupt", self._peer)
        delay = 0.0
        for c in chaos.clauses:
            if c.kind == "delay_ms" and chaos.active(c) and self._hit(c.prob):
                jit = self._uniform(-c.jitter_ms, c.jitter_ms)
                delay = max(delay, max(0.0, c.mean_ms + jit) / 1000.0)
        if delay == 0.0 and inj.has("net_delay") and inj.fire("net_delay"):
            delay = _FORCED_DELAY_S
        if delay > 0.0:
            self._chaos.record("delay", self._peer)
            time.sleep(delay)
        return buf

    def send(self, data, *args) -> int:
        if self._tx_dropped():
            return len(data)
        return self._sock.send(self._tx_transform(data), *args)

    def sendall(self, data, *args) -> None:
        if self._tx_dropped():
            return None
        return self._sock.sendall(self._tx_transform(data), *args)

    def sendto(self, data, *args):
        if self._tx_dropped():
            return len(data)
        return self._sock.sendto(self._tx_transform(data), *args)

    def sendmsg(self, buffers, *args):
        """Vectored send under chaos: the iovec chain is judged as ONE
        frame (joined, transformed, flushed) so drops stay frame-atomic
        like `sendall`, corrupt_frame flips a byte anywhere in the chain,
        and torn_write's seeded prefix can end INSIDE any iovec entry —
        the mid-iovec tear `framing.sendmsg_all` must survive.

        A call carrying ancillary data (the replay plane's SCM_RIGHTS
        arena-fd handoff) bypasses the fault model entirely: byte
        transforms cannot be applied to kernel-level fd passing, and the
        handoff is connection setup, not wire traffic."""
        if args and args[0]:
            return self._sock.sendmsg(buffers, *args)
        data = b"".join(buffers)
        if self._tx_dropped():
            return len(data)
        buf = self._tx_transform(data)
        self._sock.sendall(buf)
        return len(buf)

    # ------------------------------------------------------------ RX path
    def _rx_partitioned(self) -> bool:
        chaos = self._chaos
        for c in chaos.clauses:
            if c.kind == "partition" and chaos.active(c) \
                    and _site_match(c.src, self._peer) \
                    and _site_match(c.dst, chaos.site) and self._hit(c.prob):
                return True
        return False

    def _rx_stall(self):
        """Ingress partition: the bytes are 'in flight', not lost.  We do
        not read (the kernel buffer keeps them for after the heal); a
        blocking caller sleeps one quantum then gets socket.timeout, a
        non-blocking caller gets BlockingIOError — both paths every reader
        loop in the planes already treats as 'no data yet'."""
        self._chaos.record("partition", self._peer)
        if not self._blocking():
            raise BlockingIOError(
                f"chaos: rx partition from {self._peer or 'peer'}")
        time.sleep(_RX_STALL_S)
        raise socket.timeout(
            f"chaos: rx partition from {self._peer or 'peer'}")

    def _rx_clamp(self, bufsize: int) -> int:
        """slow_read_bps token bucket: reads above the rate are clamped and
        (on blocking sockets) paced.  Non-blocking event-loop reads are
        clamped only — a slow peer must never stall a shared selector."""
        chaos = self._chaos
        bps = 0
        for c in chaos.clauses:
            if c.kind == "slow_read_bps" and chaos.active(c) \
                    and self._hit(c.prob):
                bps = max(bps, c.bps) if bps else c.bps
        if bps == 0:
            inj = faults.get()
            if inj.has("net_slow_peer") and inj.fire("net_slow_peer"):
                chaos.record("slow_read", self._peer)
                if self._blocking():
                    time.sleep(_FORCED_DELAY_S)
                return max(1, min(bufsize, _FORCED_SLOW_CHUNK))
            return bufsize
        now = chaos.now()
        self._read_credit = min(
            float(bps), self._read_credit + (now - self._read_stamp) * bps)
        self._read_stamp = now
        if self._read_credit < 1.0:
            if self._blocking():
                time.sleep(max(0.0, (1.0 - self._read_credit) / bps))
            self._read_credit = 1.0
        allowed = max(1, min(bufsize, int(self._read_credit)))
        self._read_credit -= allowed
        chaos.record("slow_read", self._peer)
        return allowed

    def recv(self, bufsize: int, *args) -> bytes:
        if self._rx_partitioned():
            self._rx_stall()
        return self._sock.recv(self._rx_clamp(bufsize), *args)

    def recv_into(self, buffer, nbytes: int = 0, *args) -> int:
        if self._rx_partitioned():
            self._rx_stall()
        n = nbytes if nbytes else len(buffer)
        return self._sock.recv_into(buffer, self._rx_clamp(n), *args)

    def recvfrom(self, bufsize: int, *args):
        # UDP: clamping would truncate datagrams (loss, not slowness), so
        # only the ingress partition applies on the receive side
        if self._rx_partitioned():
            self._rx_stall()
        return self._sock.recvfrom(bufsize, *args)


# ------------------------------------------------------------- global access
# The planes cannot thread a chaos handle through every constructor; they
# call maybe_wrap() at each socket-creation site and consult the installed
# interposer.  Default: disarmed (None until first use, then env-armed).
_current: Optional[NetChaos] = None
_install_lock = threading.Lock()


def install(chaos: Optional[NetChaos]) -> NetChaos:
    global _current
    with _install_lock:
        _current = chaos if chaos is not None else NetChaos("")
        return _current


def install_from(cfg) -> NetChaos:
    """Arm from Config/env (env wins, the faults.install_from contract —
    a soak harness arms children without editing run configs)."""
    spec = os.environ.get(ENV_VAR, "") or getattr(cfg, "net_chaos_spec", "")
    seed = int(os.environ.get(SEED_ENV_VAR, "")
               or getattr(cfg, "seed", 0) or 0)
    return install(NetChaos(spec, seed=seed))


def get() -> NetChaos:
    """The installed interposer; first touch self-installs from env so any
    process (smoke children included) arms via RIA_NET_CHAOS alone."""
    global _current
    if _current is None:
        with _install_lock:
            if _current is None:
                _current = NetChaos(
                    os.environ.get(ENV_VAR, ""),
                    seed=int(os.environ.get(SEED_ENV_VAR, "") or 0))
    return _current


def maybe_wrap(sock, peer: str = "", logger=None):
    """The seam every plane calls at socket creation.  Disarmed (the
    default): returns ``sock`` unchanged — zero per-byte cost, the off
    path is bitwise the previous PR.  Armed (chaos spec, or any net_*
    fault point): returns a :class:`ChaosSocket`."""
    chaos = get()
    if not chaos.armed:
        inj = faults.get()
        if not any(inj.has(p) for p in _NET_POINTS):
            return sock
    chaos.attach_logger(logger)
    return chaos.wrap(sock, peer=peer)
