"""Per-game actor lanes: pin contiguous vector-env lane blocks to games.

The lane order is the load-bearing contract: game g owns lanes
[g*lanes_per_game, (g+1)*lanes_per_game), which is exactly the block
`MultiGameReplay` pins to game g's replay shards (ShardedReplay's
contiguous lane->shard split), so appends land on the right game's
priority trees with zero per-tick routing work.  Jax-free.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from rainbow_iqn_apex_tpu.envs.base import Env, TimeStep, VectorEnv
from rainbow_iqn_apex_tpu.multitask.spec import MultiGameSpec


class GameLaneEnv(Env):
    """One game lane behind the suite-common surface.

    Frames are zero-padded bottom/right to the spec's common (H, W) — the
    game's own pixels keep their coordinates, the pad is static black the
    conv trunk learns to ignore.  The declared action space is the padded
    ``spec.max_actions``; in-graph action masks make the policy pick
    in-range actions, and an out-of-range id (possible for a generalist
    net without masks, e.g. the r2d2 multi-game path) is mapped ``a %
    num_actions`` instead of crashing the lane."""

    def __init__(self, env: Env, spec: MultiGameSpec, game_id: int):
        self.env = env
        self.spec = spec
        self.game_id = int(game_id)
        self.game = spec.games[self.game_id]
        self._real_actions = spec.num_actions[self.game_id]
        h, w = env.frame_shape
        H, W = spec.frame_shape
        if h > H or w > W:
            raise ValueError(
                f"game {self.game} frame {h}x{w} exceeds the common "
                f"{H}x{W} — spec.frame_shape must be the suite max"
            )
        self._pad = ((0, H - h), (0, W - w))
        self._needs_pad = (h, w) != (H, W)

    @property
    def num_actions(self) -> int:
        return self.spec.max_actions

    @property
    def frame_shape(self) -> Tuple[int, int]:
        return self.spec.frame_shape

    def _pad_frame(self, frame: np.ndarray) -> np.ndarray:
        if not self._needs_pad:
            return frame
        return np.pad(frame, self._pad)

    def reset(self) -> np.ndarray:
        return self._pad_frame(self.env.reset())

    def step(self, action: int) -> TimeStep:
        ts = self.env.step(int(action) % self._real_actions)
        return TimeStep(
            self._pad_frame(ts.obs), ts.reward, ts.terminal,
            ts.truncated, ts.info,
        )

    def close(self) -> None:
        self.env.close()


def lane_games(spec: MultiGameSpec, lanes_per_game: int) -> np.ndarray:
    """[L] int32 game id per lane, game-major blocks (the lane contract)."""
    return np.repeat(
        np.arange(spec.num_games, dtype=np.int32), lanes_per_game
    )


def build_game_lanes(
    spec: MultiGameSpec, lanes_per_game: int, seed: int = 0
) -> VectorEnv:
    """VectorEnv with ``lanes_per_game`` lanes pinned to each game in spec
    order.  Per-lane seeds stay carved from the global lane index, exactly
    like the single-game `make_vector_env`, so a lane crash rebuilds the
    same stream."""
    from rainbow_iqn_apex_tpu.envs import make_env

    if lanes_per_game < 1:
        raise ValueError("need at least one lane per game")
    games_of_lane = lane_games(spec, lanes_per_game)

    def factory(lane: int) -> Env:
        g = int(games_of_lane[lane])
        return GameLaneEnv(
            make_env(spec.games[g], seed=seed + lane), spec, g
        )

    lanes = [factory(i) for i in range(spec.num_games * lanes_per_game)]
    return VectorEnv(lanes, env_factory=factory)
