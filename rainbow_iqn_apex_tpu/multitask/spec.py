"""MultiGameSpec: the parsed `Config.games` contract.

One frozen, hashable value object that every multitask layer keys on —
the driver closes jitted functions over it, the replay derives its
game-pinned shard map from it, eval walks its game list.  Jax-free.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


def parse_games(games: str) -> Tuple[str, ...]:
    """"a,b,c" -> ("a", "b", "c"); order-preserving, duplicates rejected
    (a duplicated game would double its lane/shard share silently)."""
    names = tuple(g.strip() for g in str(games).split(",") if g.strip())
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate game in games={games!r}")
    return names


@dataclasses.dataclass(frozen=True)
class MultiGameSpec:
    """The static multi-game contract derived from Config.games.

    ``frame_shape`` is the padded COMMON (H, W) every lane/eval env emits
    (max over the suite, zero-padded bottom/right) so one XLA program
    serves every game; ``num_actions`` is per game, ``max_actions`` the
    padded action-space width the network emits — per-game action masks
    (ops.action_mask_table) keep greedy selection inside each game's real
    action set."""

    games: Tuple[str, ...]
    num_actions: Tuple[int, ...]
    frame_shape: Tuple[int, int]

    def __post_init__(self):
        if len(self.games) < 1:
            raise ValueError("MultiGameSpec needs at least one game")
        if len(self.num_actions) != len(self.games):
            raise ValueError("num_actions must align with games")

    @property
    def num_games(self) -> int:
        return len(self.games)

    @property
    def max_actions(self) -> int:
        return max(self.num_actions)

    def game_index(self, name: str) -> int:
        return self.games.index(name)

    @classmethod
    def from_config(cls, cfg) -> Optional["MultiGameSpec"]:
        """None when cfg.games is unset (the single-game seed path);
        otherwise probe each game once for its action/frame spaces."""
        names = parse_games(getattr(cfg, "games", ""))
        if not names:
            return None
        return cls.probe(names)

    @classmethod
    def probe(cls, names: Tuple[str, ...]) -> "MultiGameSpec":
        from rainbow_iqn_apex_tpu.envs import make_env

        actions, heights, widths = [], [], []
        for name in names:
            env = make_env(name, seed=0)
            actions.append(int(env.num_actions))
            h, w = env.frame_shape
            heights.append(int(h))
            widths.append(int(w))
            env.close()
        return cls(
            games=tuple(names),
            num_actions=tuple(actions),
            frame_shape=(max(heights), max(widths)),
        )
