"""MultiGameReplay: game-pinned replay shard blocks behind one interface.

IS-A `ShardedReplay` — every elasticity/persistence/telemetry affordance
(epoch-fenced drop/readmit, CRC snapshots, registry/tracer wiring, the
device sample frontier's mirror, the write-back ring's `update_priorities`
target) is inherited unchanged.  The deltas are the game layer:

- shard k belongs to game ``k // shards_per_game`` (contiguous blocks,
  aligned with lanes.build_game_lanes' lane order), so per-game priority
  trees exist for free: they are the game's shard block;
- ``sample`` draws a GAME-INTERLEAVED batch: an `InterleaveSchedule`
  apportions the batch across alive games (uniform / loss / mass,
  config-selected), then each game's rows come from a proportional draw
  over ITS OWN shard block.  IS weights use each row's true sampling
  probability under the interleaved scheme (share_g * p_local/mass_g), so
  the estimator stays unbiased for whatever schedule is chosen;
- ``update_priorities`` additionally feeds the loss-proportional
  schedule's per-game |TD| EMA and the per-game learn-share counters the
  `games` obs row reports — zero extra device work, the write-back ring
  already hands it the host |TD| rows.

One game losing every shard (drop_shard) just zeroes its schedule share:
the apportionment renormalises over the survivors and the other games'
sampling is never interrupted (tests/test_multitask.py, chaos-marked).

Device sampling composes under ``multitask_schedule="mass"``: the
frontier's HBM draw is proportional to global priority mass, which IS the
mass schedule (the drivers fall back to this host path, with a notice,
for the per-game-quota schedules).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from rainbow_iqn_apex_tpu.multitask.spec import MultiGameSpec
from rainbow_iqn_apex_tpu.parallel.sharded_replay import ShardedReplay
from rainbow_iqn_apex_tpu.replay.buffer import PrioritizedReplay, SampledBatch
from rainbow_iqn_apex_tpu.utils import hostsync

SCHEDULES = ("uniform", "loss", "mass")


def apportion(batch_size: int, shares: np.ndarray) -> np.ndarray:
    """Deterministic largest-remainder apportionment of ``batch_size`` rows
    over ``shares`` (ties break toward the lower game index) — the
    interleave must be reproducible under a fixed seed, so no RNG here."""
    shares = np.asarray(shares, np.float64)
    total = shares.sum()
    if total <= 0:
        raise ValueError("cannot apportion: no positive shares")
    raw = batch_size * shares / total
    base = np.floor(raw).astype(np.int64)
    rem = int(batch_size - base.sum())
    if rem > 0:
        order = np.argsort(-(raw - base), kind="stable")
        base[order[:rem]] += 1
    return base


class InterleaveSchedule:
    """Per-game batch shares for the interleaved sample.

    ``uniform``: equal rows per game with sampleable mass.
    ``loss``:    proportional to each game's EMA of retired mean |TD| —
                 games the learner currently struggles on get more replay
                 (the PER idea lifted one level up).
    ``mass``:    proportional to per-game priority mass — exactly the
                 distribution one global tree (or the device frontier's
                 HBM draw) would give.
    """

    def __init__(self, mode: str, num_games: int, ema: float = 0.95):
        # "fixed:w1,...,wG": explicit per-game shares — the league genome's
        # schedule-shares gene (league/population.py perturbs them;
        # docs/LEAGUE.md).  Dead games still renormalise over survivors.
        self.fixed: Optional[np.ndarray] = None
        if mode.startswith("fixed:"):
            try:
                shares = np.asarray(
                    [float(s) for s in mode.split(":", 1)[1].split(",")],
                    np.float64)
            except ValueError:
                raise ValueError(
                    f"multitask_schedule {mode!r}: shares must be numbers "
                    "(\"fixed:0.6,0.4\")")
            if len(shares) != num_games:
                raise ValueError(
                    f"multitask_schedule {mode!r} names {len(shares)} "
                    f"shares for {num_games} games — one share per game")
            if (not np.isfinite(shares).all() or (shares < 0).any()
                    or shares.sum() <= 0):
                raise ValueError(
                    f"multitask_schedule {mode!r}: shares must be "
                    "finite, >= 0 and sum > 0")
            self.fixed = shares / shares.sum()
        elif mode not in SCHEDULES:
            raise ValueError(
                f"unknown multitask_schedule {mode!r} (want {SCHEDULES} "
                "or \"fixed:w1,...,wG\")")
        self.mode = "fixed" if self.fixed is not None else mode
        self.num_games = int(num_games)
        self.ema = float(ema)
        # |TD| EMA starts flat at 1.0: until real TD lands, "loss" == uniform
        self.td_ema = np.ones(num_games, np.float64)

    def note_td(self, game_ids: np.ndarray, td_abs: np.ndarray) -> None:
        """Fold one retired step's per-row |TD| into the per-game EMA."""
        game_ids = np.asarray(game_ids, np.int64)
        td = np.abs(np.asarray(td_abs, np.float64))
        counts = np.bincount(game_ids, minlength=self.num_games)
        sums = np.bincount(game_ids, weights=td, minlength=self.num_games)
        seen = counts > 0
        means = np.where(seen, sums / np.maximum(counts, 1), 0.0)
        self.td_ema[seen] = (
            self.ema * self.td_ema[seen] + (1.0 - self.ema) * means[seen]
        )

    def shares(self, game_mass: np.ndarray) -> np.ndarray:
        """[G] shares summing to 1 over games with positive priority mass
        (a mass-less game — cold, or every shard dead — gets zero and the
        rest renormalise: per-game isolation)."""
        alive = np.asarray(game_mass, np.float64) > 0
        if not alive.any():
            raise ValueError("cannot sample: every game is empty or dead")
        if self.mode == "uniform":
            raw = alive.astype(np.float64)
        elif self.mode == "loss":
            raw = np.where(alive, np.maximum(self.td_ema, 1e-12), 0.0)
        elif self.mode == "fixed":
            raw = np.where(alive, self.fixed, 0.0)
            if raw.sum() <= 0:  # every positively-weighted game is dead
                raw = alive.astype(np.float64)
        else:  # mass
            raw = np.where(alive, game_mass, 0.0)
        return raw / raw.sum()


class MultiGameReplay(ShardedReplay):
    """K*G game-pinned PER shards behind the ShardedReplay interface."""

    def __init__(self, shards, spec: MultiGameSpec, shards_per_game: int,
                 schedule: str = "uniform"):
        if len(shards) != spec.num_games * shards_per_game:
            raise ValueError(
                f"{len(shards)} shards != {spec.num_games} games x "
                f"{shards_per_game} shards/game")
        super().__init__(shards)
        self.spec = spec
        self.shards_per_game = int(shards_per_game)
        self.schedule = InterleaveSchedule(schedule, spec.num_games)
        # per-game learn-share/telemetry counters (the `games` obs row)
        self.learn_rows_by_game = np.zeros(spec.num_games, np.int64)
        self.sampled_rows_by_game = np.zeros(spec.num_games, np.int64)

    # ------------------------------------------------------------------ build
    @classmethod
    def build_games(
        cls,
        spec: MultiGameSpec,
        shards_per_game: int,
        capacity_total: int,
        lanes_total: int,
        schedule: str = "uniform",
        **kwargs,
    ) -> "MultiGameReplay":
        num_shards = spec.num_games * max(int(shards_per_game), 1)
        if capacity_total % num_shards or lanes_total % num_shards:
            raise ValueError(
                f"capacity {capacity_total} and lanes {lanes_total} must "
                f"divide evenly into {num_shards} game-pinned shards")
        seed = kwargs.pop("seed", 0)
        kwargs.setdefault("frame_shape", spec.frame_shape)
        shards = [
            PrioritizedReplay(
                capacity_total // num_shards,
                lanes=lanes_total // num_shards,
                seed=seed + 1000 * k,
                **kwargs,
            )
            for k in range(num_shards)
        ]
        return cls(shards, spec, max(int(shards_per_game), 1),
                   schedule=schedule)

    # ------------------------------------------------------------------ maps
    def game_of_shard(self, k: int) -> int:
        return int(k) // self.shards_per_game

    def games_of(self, idx: np.ndarray) -> np.ndarray:
        """[B] int32 game id of each global slot id."""
        idx = np.asarray(idx, np.int64)
        return ((idx // self.shard_capacity)
                // self.shards_per_game).astype(np.int32)

    def game_sizes(self) -> np.ndarray:
        """[G] transitions held per game (alive shards only)."""
        out = np.zeros(self.spec.num_games, np.int64)
        for k, shard in enumerate(self.shards):
            if k not in self._dead:
                out[self.game_of_shard(k)] += len(shard)
        return out

    def game_occupancy(self) -> np.ndarray:
        """[G] per-game fill fraction over the game's ALIVE capacity
        (a game with every shard dead reads 0.0)."""
        sizes = self.game_sizes().astype(np.float64)
        caps = np.zeros(self.spec.num_games, np.float64)
        for k in range(len(self.shards)):
            if k not in self._dead:
                caps[self.game_of_shard(k)] += self.shard_capacity
        return np.where(caps > 0, sizes / np.maximum(caps, 1.0), 0.0)

    # ---------------------------------------------------------------- sample
    def sample(self, batch_size: int, beta: float) -> SampledBatch:
        """Game-interleaved proportional sample (see module docstring)."""
        hostsync.check_host_work("replay_sample")
        G, spg = self.spec.num_games, self.shards_per_game
        totals = np.asarray(
            [0.0 if k in self._dead else s.tree.total
             for k, s in enumerate(self.shards)],
            np.float64,
        )
        game_mass = totals.reshape(G, spg).sum(axis=1)
        shares = self.schedule.shares(game_mass)
        counts = apportion(batch_size, shares)
        n_global = len(self)
        parts: List[SampledBatch] = []
        probs: List[np.ndarray] = []
        games: List[np.ndarray] = []
        for g in range(G):
            c = int(counts[g])
            if c == 0:
                continue
            block = slice(g * spg, (g + 1) * spg)
            mass_g = game_mass[g]
            # within the game: the same multinomial shard split the
            # single-game ShardedReplay.sample performs over its shards
            split = self.rng.multinomial(c, totals[block] / mass_g)
            for j, ck in enumerate(split):
                if ck == 0:
                    continue
                k = g * spg + j
                b = self.shards[k].sample(int(ck), beta)
                parts.append(SampledBatch(
                    idx=b.idx + k * self.shard_capacity,
                    obs=b.obs, action=b.action, reward=b.reward,
                    next_obs=b.next_obs, discount=b.discount,
                    weight=b.weight, prob=b.prob,
                ))
                # true row probability under the interleaved scheme
                probs.append(b.prob * (totals[k] / mass_g) * shares[g])
                games.append(np.full(int(ck), g, np.int32))
            self.sampled_rows_by_game[g] += c
        if self._reg is not None:
            self._reg.counter("replay_sampled_rows", self._role).inc(
                batch_size)
        cat = lambda f: np.concatenate([getattr(p, f) for p in parts])  # noqa: E731
        prob = np.concatenate(probs)
        idx_all = cat("idx")
        self._record_sample_age(idx_all)
        weight = (n_global * np.maximum(prob, 1e-12)) ** (-beta)
        weight = (weight / weight.max()).astype(np.float32)
        return SampledBatch(
            idx=idx_all,
            obs=cat("obs"),
            action=cat("action"),
            reward=cat("reward"),
            next_obs=cat("next_obs"),
            discount=cat("discount"),
            weight=weight,
            prob=prob,
            game=np.concatenate(games),
        )

    def assemble_global(self, idx, weight, prob=None) -> SampledBatch:
        """Device-sampling gather path: inherited assembly + game ids
        attached, so the frontier's batches condition the learner too."""
        batch = super().assemble_global(idx, weight, prob)
        batch.game = self.games_of(batch.idx)
        self.sampled_rows_by_game += np.bincount(
            batch.game, minlength=self.spec.num_games).astype(np.int64)
        return batch

    # ------------------------------------------------------------ priorities
    def note_learn_idx(self, idx: np.ndarray) -> None:
        """Per-game learn-row accounting from slot ids alone — the device-
        sampling path's hook: in mirror mode the ring retires |TD| as a
        DEVICE array straight into the frontier (update_priorities below is
        never on the hot path), but the idx vector is host NumPy either
        way, so the `games` row's learn share stays live.  The loss-EMA is
        deliberately NOT fed here (no host |TD| to fold — and the frontier
        only composes with the mass schedule, which ignores it)."""
        g = self.games_of(idx)
        if len(g):
            self.learn_rows_by_game += np.bincount(
                g, minlength=self.spec.num_games).astype(np.int64)

    def update_priorities(self, idx: np.ndarray, td_abs: np.ndarray) -> None:
        g = self.games_of(idx)
        if len(g):
            self.schedule.note_td(g, td_abs)
        self.note_learn_idx(idx)
        super().update_priorities(idx, td_abs)

    def learn_shares(self) -> np.ndarray:
        """[G] fraction of learned (priority-written) rows per game."""
        total = self.learn_rows_by_game.sum()
        if total == 0:
            return np.zeros(self.spec.num_games)
        return self.learn_rows_by_game / total

    def dead_games(self) -> List[int]:
        """Games whose EVERY shard is currently dead."""
        G, spg = self.spec.num_games, self.shards_per_game
        return [
            g for g in range(G)
            if all(g * spg + j in self._dead for j in range(spg))
        ]

    def game_shards(self, g: int) -> List[int]:
        """Shard indices of game ``g``'s block (drop/readmit targets)."""
        spg = self.shards_per_game
        return list(range(g * spg, (g + 1) * spg))
