"""The periodic `games` obs row: per-game training state in one place.

Emitted by both apex drivers at the metrics cadence (schema kind "games",
obs/schema.py), consumed by scripts/obs_report.py's `games:` section and
scripts/relay_watch.py's per-game phase tallies.  Jax-free: the baseline
lookup is deferred to call time so respawned children / offline tools can
import this module without the device runtime.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from rainbow_iqn_apex_tpu.multitask.spec import MultiGameSpec


def aggregate_human_normalized(
    per_game_hn: Dict[str, Optional[float]]
) -> Dict[str, Any]:
    """Suite aggregates over the games with KNOWN baselines (a game missing
    from HUMAN_BASELINES is reported raw but cannot enter the normalized
    aggregate).  Returns hn_median / hn_mean / hn_games."""
    known = [v for v in per_game_hn.values() if v is not None]
    return {
        "hn_games": len(known),
        "hn_median": float(np.median(known)) if known else None,
        "hn_mean": float(np.mean(known)) if known else None,
    }


class GamesObs:
    """Accumulates per-game eval results and renders the `games` row."""

    def __init__(self, spec: MultiGameSpec):
        self.spec = spec
        self._last_eval: Dict[str, Dict[str, Any]] = {}

    def note_eval(self, results: Dict[str, Any]) -> None:
        """Fold one `evaluate_multigame` result (its "games" dict)."""
        for name, row in (results.get("games") or {}).items():
            self._last_eval[name] = dict(row)

    def row(
        self,
        learn_shares: Optional[np.ndarray] = None,
        learn_rows: Optional[np.ndarray] = None,
        sampled_rows: Optional[np.ndarray] = None,
        game_sizes: Optional[np.ndarray] = None,
        game_occupancy: Optional[np.ndarray] = None,
        dead_games: Optional[list] = None,
    ) -> Dict[str, Any]:
        """The `games` row payload: per-game learn share, replay occupancy,
        latest eval score, plus suite human-normalized aggregates."""
        from rainbow_iqn_apex_tpu.eval import human_normalized

        games: Dict[str, Dict[str, Any]] = {}
        per_game_hn: Dict[str, Optional[float]] = {}
        dead = set(dead_games or ())
        for g, name in enumerate(self.spec.games):
            entry: Dict[str, Any] = {"dead": g in dead}
            if learn_shares is not None:
                entry["learn_share"] = round(float(learn_shares[g]), 4)
            if learn_rows is not None:
                entry["learn_rows"] = int(learn_rows[g])
            if sampled_rows is not None:
                entry["sampled_rows"] = int(sampled_rows[g])
            if game_sizes is not None:
                entry["replay_size"] = int(game_sizes[g])
            if game_occupancy is not None:
                entry["replay_occupancy"] = round(float(game_occupancy[g]), 4)
            ev = self._last_eval.get(name)
            if ev is not None:
                entry["score_mean"] = ev.get("score_mean")
                hn = ev.get("human_normalized")
                if hn is None and ev.get("score_mean") is not None:
                    hn = human_normalized(name, float(ev["score_mean"]))
                if hn is not None:
                    entry["human_normalized"] = round(float(hn), 4)
                per_game_hn[name] = hn
            else:
                per_game_hn[name] = None
            games[name] = entry
        return {"games": games, **aggregate_human_normalized(per_game_hn)}
