"""multitask/ — multi-game Ape-X on one pod (docs/MULTITASK.md).

The original Ape-X scale claim (arXiv:1803.00933) was demonstrated across
the Atari suite, and `eval.HUMAN_BASELINES` already carries the full
Atari-57 random/human table — this subsystem runs N games concurrently in
ONE apex pod instead of one game per run:

  spec.py    MultiGameSpec: the parsed `Config.games` contract (per-game
             action counts, the padded common frame shape, lane/shard maps)
  lanes.py   per-game actor lanes: GameLaneEnv pads frames to the common
             shape + maps out-of-range actions; build_game_lanes pins
             contiguous lane blocks to games (the lane<->shard alignment
             the replay relies on)
  model.py   MultiGameIQN: RainbowIQN with a zero-initialized game-id
             embedding added to the conv torso output — ONE jitted dispatch
             for every game (shapes are game-invariant, XLA compiles once),
             per-game action masks applied at greedy selection
  ops.py     task-conditioned act/learn step builders (Batch.game threads
             the game ids through the existing learn pipeline)
  replay.py  MultiGameReplay: game-pinned ShardedReplay shard blocks behind
             a game-interleaved sample schedule (uniform / loss / mass)
  eval.py    vectorized multi-game eval: per-game scores + human-normalized
             median/mean aggregates over the played suite
  obs.py     the periodic `games` row (per-game learn share, replay
             occupancy, latest eval, human-normalized aggregate)

Everything importable from here lazily (PEP 562), and `MultiGameSpec`/
`parse_games` are jax-free — respawned child processes and offline tools
pay no device-runtime import tax.
"""

from __future__ import annotations

import importlib

_LAZY = {
    "MultiGameSpec": "rainbow_iqn_apex_tpu.multitask.spec",
    "parse_games": "rainbow_iqn_apex_tpu.multitask.spec",
    "GameLaneEnv": "rainbow_iqn_apex_tpu.multitask.lanes",
    "build_game_lanes": "rainbow_iqn_apex_tpu.multitask.lanes",
    "MultiGameIQN": "rainbow_iqn_apex_tpu.multitask.model",
    "build_mt_act_step": "rainbow_iqn_apex_tpu.multitask.ops",
    "build_mt_learn_step": "rainbow_iqn_apex_tpu.multitask.ops",
    "init_mt_train_state": "rainbow_iqn_apex_tpu.multitask.ops",
    "InterleaveSchedule": "rainbow_iqn_apex_tpu.multitask.replay",
    "MultiGameReplay": "rainbow_iqn_apex_tpu.multitask.replay",
    "aggregate_human_normalized": "rainbow_iqn_apex_tpu.multitask.obs",
    "evaluate_multigame": "rainbow_iqn_apex_tpu.multitask.eval",
    "GamesObs": "rainbow_iqn_apex_tpu.multitask.obs",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(mod), name)
