"""Vectorized multi-game evaluation under the SABER protocol.

Per-game: E greedy episodes (noise off unless cfg.eval_noisy) on the
game's OWN env behind the suite-common padded surface, same loop shape as
`eval.evaluate`.  Suite: human-normalized median/mean aggregates — the
Atari-57 reporting convention the `eval.HUMAN_BASELINES` table exists for
(Rainbow paper appendix; median human-normalized score is the headline).

The eval act executable is cached per (cfg, spec, noisy) like
`eval._cached_eval_agent` — retraced on a config change, not per eval
interval — and is ONE program for the whole suite (game id is data).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import numpy as np

from rainbow_iqn_apex_tpu.agents.agent import FrameStacker
from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.eval import human_normalized
from rainbow_iqn_apex_tpu.multitask.lanes import GameLaneEnv
from rainbow_iqn_apex_tpu.multitask.obs import aggregate_human_normalized
from rainbow_iqn_apex_tpu.multitask.spec import MultiGameSpec

__all__ = ["aggregate_human_normalized", "evaluate_multigame"]


@functools.lru_cache(maxsize=4)
def _cached_mt_act(cfg: Config, spec: MultiGameSpec, noisy: bool):
    import jax

    from rainbow_iqn_apex_tpu.multitask.ops import build_mt_act_step

    return jax.jit(build_mt_act_step(cfg, spec, use_noise=noisy))


def evaluate_multigame(
    cfg: Config,
    spec: MultiGameSpec,
    params,
    seed: int = 0,
    episodes: Optional[int] = None,
    max_steps_per_episode: int = 200_000,
) -> Dict[str, Any]:
    """Evaluate task-conditioned ``params`` on every game in the spec.

    Returns {"games": {env_id: {episodes, score_mean, score_median,
    score_min, score_max, human_normalized?}}, hn_median, hn_mean,
    hn_games, score_mean (suite mean of per-game means)}.
    """
    import jax

    from rainbow_iqn_apex_tpu.agents.agent import put_frames
    from rainbow_iqn_apex_tpu.envs import make_env

    episodes = episodes or cfg.eval_episodes
    act = _cached_mt_act(cfg, spec, bool(cfg.eval_noisy))
    # fresh key per eval: two evals of the same params draw identical
    # taus/noise (bit-reproducible curves), matching eval.evaluate_state
    key = jax.random.PRNGKey(cfg.seed + 1)
    per_game: Dict[str, Dict[str, Any]] = {}
    per_game_hn: Dict[str, Optional[float]] = {}
    for g, name in enumerate(spec.games):
        env = GameLaneEnv(make_env(name, seed=seed + g), spec, g)
        game_ids = np.full(1, g, np.int32)
        scores = []
        for _ep in range(episodes):
            stacker = FrameStacker(1, env.frame_shape, cfg.history_length)
            frame = env.reset()
            ep_ret = 0.0
            for _ in range(max_steps_per_episode):
                stacked = stacker.push(frame[None])
                key, k = jax.random.split(key)
                a, _q = act(params, put_frames(stacked), game_ids, k)
                ts = env.step(int(np.asarray(a)[0]))
                frame = ts.obs
                ep_ret += ts.reward
                if ts.terminal or ts.truncated:
                    if ts.info and "episode_return" in ts.info:
                        ep_ret = float(ts.info["episode_return"])
                    break
            scores.append(ep_ret)
        env.close()
        arr = np.asarray(scores, np.float64)
        row: Dict[str, Any] = {
            "episodes": episodes,
            "score_mean": float(arr.mean()),
            "score_median": float(np.median(arr)),
            "score_min": float(arr.min()),
            "score_max": float(arr.max()),
        }
        hn = human_normalized(name, row["score_mean"])
        per_game_hn[name] = hn
        if hn is not None:
            row["human_normalized"] = hn
        per_game[name] = row
    out: Dict[str, Any] = {
        "games": per_game,
        "score_mean": float(np.mean(
            [r["score_mean"] for r in per_game.values()])),
        **aggregate_human_normalized(per_game_hn),
    }
    return out
