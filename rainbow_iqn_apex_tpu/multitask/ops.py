"""Task-conditioned act/learn steps: ops/learn.py with a game-id input.

Mirrors `build_learn_step`/`build_act_step` exactly — same tau sampling,
same quantile-Huber loss, same in-graph target copy and finite flag — with
two multi-game deltas:

- the network is `MultiGameIQN` (game-embedding torso), applied with the
  batch's per-row game ids;
- every greedy selection (double-Q a* in the loss, the act step's action)
  is restricted to each row's own game's action set via the static
  [G, max_actions] mask table, so a 2-action game never "selects" the pad
  slot a 3-action sibling owns.

One jitted dispatch serves every game: game ids are DATA, shapes are
suite-invariant, so XLA compiles once per role for the whole suite.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import chex
import jax
import jax.numpy as jnp
import numpy as np
import optax

from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.multitask.model import (
    MultiGameIQN,
    masked_greedy_action,
    masked_q_values,
)
from rainbow_iqn_apex_tpu.multitask.spec import MultiGameSpec
from rainbow_iqn_apex_tpu.ops.learn import (
    Batch,
    TrainState,
    make_optimizer,
    make_reuse_learn_step,
)
from rainbow_iqn_apex_tpu.ops.losses import quantile_huber_loss


def action_mask_table(spec: MultiGameSpec) -> np.ndarray:
    """[G, max_actions] bool: True where the action id is real for the game."""
    table = np.zeros((spec.num_games, spec.max_actions), bool)
    for g, n in enumerate(spec.num_actions):
        table[g, :n] = True
    return table


def make_mt_network(
    cfg: Config, spec: MultiGameSpec, use_noise: bool = True
) -> MultiGameIQN:
    return MultiGameIQN(
        num_games=spec.num_games,
        num_actions=spec.max_actions,
        hidden_size=cfg.hidden_size,
        num_cosines=cfg.num_cosines,
        noisy_sigma0=cfg.noisy_sigma0,
        dueling=cfg.dueling,
        use_noise=use_noise,
        compute_dtype=jnp.dtype(cfg.compute_dtype),
    )


def init_mt_train_state(
    cfg: Config, spec: MultiGameSpec, key: chex.PRNGKey
) -> TrainState:
    """TrainState over MultiGameIQN params (suite-common obs shape)."""
    net = make_mt_network(cfg, spec)
    k_init, k_taus, k_noise = jax.random.split(key, 3)
    dummy = jnp.zeros(
        (1, *spec.frame_shape, cfg.history_length), jnp.uint8
    )
    params = net.init(
        {"params": k_init, "taus": k_taus, "noise": k_noise},
        dummy,
        jnp.zeros((1,), jnp.int32),
        cfg.num_tau_samples,
    )["params"]
    opt_state = make_optimizer(cfg).init(params)
    return TrainState(
        params=params,
        target_params=jax.tree.map(jnp.copy, params),
        opt_state=opt_state,
        step=jnp.zeros((), jnp.int32),
    )


def build_mt_learn_step(
    cfg: Config, spec: MultiGameSpec
) -> Callable[[TrainState, Batch, chex.PRNGKey],
              Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """Un-jitted task-conditioned learn step; callers jit with their own
    sharding exactly like `ops.learn.build_learn_step`."""
    net = make_mt_network(cfg, spec)
    tx = make_optimizer(cfg)
    mask_table = jnp.asarray(action_mask_table(spec))

    def loss_fn(params, target_params, batch: Batch, key, weight_scale=None):
        (k_sel_tau, k_sel_noise, k_tgt_tau, k_tgt_noise,
         k_on_tau, k_on_noise) = jax.random.split(key, 6)
        game = batch.game
        # double-Q a* on s': online net, K acting taus, masked to the
        # row's own game
        sel_q, _ = net.apply(
            {"params": params}, batch.next_obs, game,
            cfg.num_quantile_samples,
            rngs={"taus": k_sel_tau, "noise": k_sel_noise},
        )
        a_star = masked_greedy_action(sel_q, game, mask_table)  # [B]
        tgt_q, _ = net.apply(
            {"params": target_params}, batch.next_obs, game,
            cfg.num_tau_prime_samples,
            rngs={"taus": k_tgt_tau, "noise": k_tgt_noise},
        )
        z_next = jnp.take_along_axis(
            tgt_q, a_star[:, None, None], axis=-1)[..., 0]
        td_target = jax.lax.stop_gradient(
            batch.reward[:, None] + batch.discount[:, None] * z_next
        )
        on_q, taus = net.apply(
            {"params": params}, batch.obs, game, cfg.num_tau_samples,
            rngs={"taus": k_on_tau, "noise": k_on_noise},
        )
        z_online = jnp.take_along_axis(
            on_q, batch.action[:, None, None], axis=-1)[..., 0]
        per_sample, td_abs = quantile_huber_loss(
            z_online, taus, td_target, cfg.kappa)
        weight = batch.weight
        if weight_scale is not None:  # clipped reuse ratio (ops/learn.py)
            weight = weight * weight_scale
        loss = jnp.mean(weight * per_sample)
        aux = {
            "td_abs": td_abs,
            "q_mean": on_q.mean(),
            "target_q_mean": z_next.mean(),
        }
        return loss, aux

    def learn_step(state: TrainState, batch: Batch, key: chex.PRNGKey,
                   weight_scale=None):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, state.target_params, batch, key, weight_scale
        )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        step = state.step + 1
        do_copy = (step % cfg.target_update_period == 0).astype(jnp.float32)
        target_params = jax.tree.map(
            lambda t, o: do_copy * o + (1.0 - do_copy) * t,
            state.target_params,
            params,
        )
        grad_norm = optax.global_norm(grads)
        info = {
            "loss": loss,
            "priorities": aux["td_abs"],
            "q_mean": aux["q_mean"],
            "target_q_mean": aux["target_q_mean"],
            "grad_norm": grad_norm,
            "finite": jnp.isfinite(loss) & jnp.isfinite(grad_norm),
        }
        return (
            TrainState(
                params=params,
                target_params=target_params,
                opt_state=opt_state,
                step=step,
            ),
            info,
        )

    if cfg.replay_ratio <= 1:
        return learn_step

    # replay-ratio > 1 (ops/learn.py `make_reuse_learn_step`): the ratio's
    # Boltzmann policy is masked to each row's own game, so a pad slot a
    # sibling game owns can never contribute probability mass
    def logp(params, batch: Batch, key):
        k_tau, k_noise = jax.random.split(key)
        quantiles, _ = net.apply(
            {"params": params}, batch.obs, batch.game,
            cfg.num_quantile_samples,
            rngs={"taus": k_tau, "noise": k_noise},
        )
        q = masked_q_values(quantiles, batch.game, mask_table)
        logits = jax.nn.log_softmax(q, axis=-1)
        return jnp.take_along_axis(
            logits, batch.action[:, None], axis=-1)[..., 0]

    return make_reuse_learn_step(cfg, learn_step, logp)


def build_mt_act_step(
    cfg: Config, spec: MultiGameSpec, use_noise: bool = True
) -> Callable[[chex.ArrayTree, jnp.ndarray, jnp.ndarray, chex.PRNGKey],
              Tuple[jnp.ndarray, jnp.ndarray]]:
    """Batched task-conditioned greedy acting:
    (params, obs [B,H,W,C] u8, game [B] i32, key) -> (actions [B], q [B,A]).

    The returned q values carry MASK_FILL on out-of-game slots, so
    downstream max/argmax (the actor-side priority estimator) stays inside
    the row's real action set."""
    net = make_mt_network(cfg, spec, use_noise=use_noise)
    mask_table = jnp.asarray(action_mask_table(spec))

    def act_step(params, obs, game, key):
        k_tau, k_noise = jax.random.split(key)
        quantiles, _ = net.apply(
            {"params": params}, obs, game, cfg.num_quantile_samples,
            rngs={"taus": k_tau, "noise": k_noise},
        )
        q = masked_q_values(quantiles, game, mask_table)
        return jnp.argmax(q, axis=-1).astype(jnp.int32), q

    return act_step
