"""MultiGameIQN: the task-conditioned flagship model.

RainbowIQN with one addition — a per-game embedding table, zero-initialized
and ADDED to the conv torso output phi(s) before the tau merge:

    phi(s, g) = ConvTrunk(s) + E[g]          E in R^{G x F}, E_0 = 0

Zero init makes the N=1 (and t=0) forward pass IDENTICAL to the
single-game RainbowIQN given the same trunk/head params
(tests/test_multitask.py parity test); training then learns per-game
feature shifts.  Every other design choice is inherited: taus folded into
the batch for one [B*N, F] GEMM, static tau counts, uint8 frames
normalised on-chip.

Shapes are game-INVARIANT — obs padded to the suite-common frame, the
action dim padded to ``max_actions`` — so XLA compiles ONE executable per
role for the whole suite (the "bucketed shapes" promise: the bucket is the
suite).  Per-game action masks are applied at greedy selection
(`masked_greedy_action`), never inside the quantile head, so Q estimates
for real actions are untouched.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from rainbow_iqn_apex_tpu.models.iqn import q_values
from rainbow_iqn_apex_tpu.models.layers import (
    ConvTrunk,
    CosineTauEmbedding,
    NoisyLinear,
)

Dtype = Any

# large-negative (not -inf) mask fill: -inf would poison downstream
# arithmetic (actor-side priority estimates take q.max over the row) with
# NaNs on an all-masked row instead of degrading gracefully
MASK_FILL = -1e9


class MultiGameIQN(nn.Module):
    """Task-conditioned dueling noisy-net IQN.

    Call signature:
        quantiles, taus = model.apply(params, obs, game, num_taus,
                                      rngs={"taus": k1, "noise": k2})

    obs:       [B, H, W, C] uint8 (suite-common padded frame)
    game:      [B] int32 game ids in [0, num_games)
    quantiles: [B, num_taus, max_actions] fp32
    """

    num_games: int
    num_actions: int  # padded suite max
    hidden_size: int = 512
    num_cosines: int = 64
    noisy_sigma0: float = 0.5
    dueling: bool = True
    use_noise: bool = True
    compute_dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(
        self,
        obs: jnp.ndarray,
        game: jnp.ndarray,
        num_taus: int,
        taus: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        batch = obs.shape[0]
        if obs.dtype == jnp.uint8:
            obs = obs.astype(self.compute_dtype) * (1.0 / 255.0)

        phi = ConvTrunk(compute_dtype=self.compute_dtype)(obs)  # [B, F]
        feat = phi.shape[-1]
        # the game conditioning: a learned per-game feature shift, zero at
        # init so the N=1 path reproduces the single-game network exactly
        emb = nn.Embed(
            self.num_games, feat,
            embedding_init=nn.initializers.zeros,
            param_dtype=jnp.float32,
            name="game_embed",
        )(game.astype(jnp.int32))
        phi = phi + emb.astype(phi.dtype)

        if taus is None:
            taus = jax.random.uniform(
                self.make_rng("taus"), (batch, num_taus), jnp.float32
            )
        psi = CosineTauEmbedding(
            features=feat,
            num_cosines=self.num_cosines,
            compute_dtype=self.compute_dtype,
        )(taus)  # [B, N, F]

        h = phi[:, None, :].astype(self.compute_dtype) * psi
        h = h.reshape(batch * num_taus, feat)

        def head(name: str, out_dim: int) -> jnp.ndarray:
            h1 = NoisyLinear(
                self.hidden_size,
                sigma0=self.noisy_sigma0,
                use_noise=self.use_noise,
                compute_dtype=self.compute_dtype,
                name=f"{name}_hidden",
            )(h)
            h1 = nn.relu(h1)
            return NoisyLinear(
                out_dim,
                sigma0=self.noisy_sigma0,
                use_noise=self.use_noise,
                compute_dtype=self.compute_dtype,
                name=f"{name}_out",
            )(h1)

        if self.dueling:
            value = head("value", 1)  # [B*N, 1]
            adv = head("advantage", self.num_actions)  # [B*N, A]
            q = value + adv - adv.mean(axis=-1, keepdims=True)
        else:
            q = head("q", self.num_actions)

        quantiles = q.reshape(
            batch, num_taus, self.num_actions
        ).astype(jnp.float32)
        return quantiles, taus


def masked_q_values(
    quantiles: jnp.ndarray, game: jnp.ndarray, mask_table: jnp.ndarray
) -> jnp.ndarray:
    """[B, N, A] -> [B, A] expected Q with each row's out-of-game action
    slots dropped to MASK_FILL (mask_table: [G, A] bool)."""
    q = q_values(quantiles)
    return jnp.where(mask_table[game], q, MASK_FILL)


def masked_greedy_action(
    quantiles: jnp.ndarray, game: jnp.ndarray, mask_table: jnp.ndarray
) -> jnp.ndarray:
    """Greedy action restricted to each row's OWN game's action set."""
    return jnp.argmax(
        masked_q_values(quantiles, game, mask_table), axis=-1
    ).astype(jnp.int32)
