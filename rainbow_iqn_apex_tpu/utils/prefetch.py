"""Learner-side batch prefetch pipeline.

Parity: the reference learner's Redis batch fetch overlaps the GPU step only
by accident of redis-py socket buffering (SURVEY.md §3.1); here the overlap
is explicit — a worker thread samples the replay, assembles the dense batch,
and stages it to the device while the learn step for the previous batch is
still executing.  With JAX's async dispatch the main thread never blocks on
host-side sampling, so the accelerator step time is the loop's floor.

Priority write-back consequently lags by the pipeline depth — exactly the
staleness semantics the distributed reference already has (the learner's
priority updates race later samples through Redis).  The write-back side of
that overlap is the depth-K ring in utils/writeback.py: together they make
the steady-state learn loop issue zero blocking host<->device transfers per
step (docs/PERFORMANCE.md has the sync-point inventory).
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Callable, Optional

import jax


class BatchPrefetcher:
    """Background sampler: fn() -> host batch, staged to device ahead of use.

    The GIL is the synchronisation story, matching the replay's in-process
    single-writer discipline (appends happen on the main thread between
    get() calls; NumPy ops release the GIL only inside C loops that don't
    observe partial Python-level state).

    When an obs MetricRegistry is attached, the pipeline exports its own
    health onto it (role "prefetch"), so obs_report can tell learner
    STARVATION (sampler too slow: queue depth pinned at 0, empty-wait count
    climbing) from device-bound steps (queue full, no empty waits):

      prefetch_queue_depth       gauge: staged batches ready to consume
      prefetch_empty_wait_total  counter: get() calls that found it empty
      prefetch_empty_wait_s     histogram: how long those gets blocked
    """

    def __init__(
        self,
        sample_fn: Callable[[], Any],
        depth: int = 2,
        device_put: bool = True,
        registry=None,
        role: str = "prefetch",
    ):
        self.sample_fn = sample_fn
        self.depth = depth
        self.device_put = device_put
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self._g_depth = self._c_empty = self._h_wait = None
        if registry is not None:
            self._g_depth = registry.gauge("prefetch_queue_depth", role)
            self._c_empty = registry.counter("prefetch_empty_wait_total", role)
            self._h_wait = registry.histogram("prefetch_empty_wait_s", role)
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                batch = self.sample_fn()
                if self.device_put:
                    batch = jax.tree.map(jax.device_put, batch)
            except BaseException as e:  # surfaced on the consumer thread
                self._exc = e
                self._q.put(None)
                return
            # block while the queue is full (bounded staleness)
            while not self._stop.is_set():
                try:
                    self._q.put(batch, timeout=0.1)
                    if self._g_depth is not None:
                        self._g_depth.set(self._q.qsize())
                    break
                except queue.Full:
                    continue

    def get(self, timeout: float = 60.0):
        if self._exc is not None and self._q.empty():
            # repeated get() after a surfaced failure: fail fast, don't hang
            raise RuntimeError("prefetch worker failed") from self._exc
        empty_at_get = self._q.empty()
        if empty_at_get and self._c_empty is not None:
            self._c_empty.inc()  # starvation signal: consumer outran sampler
            t0 = time.monotonic()
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"prefetch worker produced nothing for {timeout}s "
                "(replay sampler stalled or device transfer wedged)"
            ) from None
        if self._g_depth is not None:
            self._g_depth.set(self._q.qsize())
            if empty_at_get:
                self._h_wait.observe(time.monotonic() - t0)
        if item is None and self._exc is not None:
            raise RuntimeError("prefetch worker failed") from self._exc
        return item

    def close(self) -> None:
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)


class SampleAheadPusher(BatchPrefetcher):
    """Sample-ahead PUSH pipeline over the device sample frontier
    (replay/frontier.py): the worker consumes device-drawn index blocks,
    assembles frames from host DRAM at those indices, stages them to the
    device, and pushes ready ``(idx, batch)`` pairs into the learner's
    bounded queue — the learner never initiates sampling, it only pops.

    Mechanics per worker turn: keep ``draw_ahead`` index BLOCKS (each
    ``draw_block`` stratified batches in one fused dispatch — the dispatch
    overhead amortisation the sample_path bench row measures) in flight on
    device; materialize the oldest block on THIS thread (the guard flags
    are thread-local, so the learner's ``forbid_host_sync()`` region is
    untouched); then gather one batch per turn through ``assemble_fn``.

    Extra gauges on the shared registry (role ``prefetch``; surfaced in
    obs_report's ``pipeline:`` line):

      sample_ahead_queue_depth          staged batches ready to pop
      sample_ahead_stale_indices_total  rows served across a shard
                                        drop/readmit epoch flip (the
                                        accepted sample-ahead staleness,
                                        made visible)

    ``prefetch_queue_depth`` / ``prefetch_empty_wait_*`` stay live through
    the base class, so existing starvation triage keeps working.

    ``reuse`` (cfg.replay_ratio, docs/PERFORMANCE.md "Replay reuse"): one
    staged batch feeds K fused learn passes, so the learner pops K-fold
    fewer batches per learn step — BOTH the staged-queue ``depth`` and the
    device-side ``draw_ahead`` shrink by the same factor (ceil, floor 1)
    HERE, in one place, keeping HBM index blocks and host gather work
    proportional to the SAMPLE rate instead of the step rate.  Callers
    pass their un-shrunk depths plus ``reuse``.
    """

    def __init__(
        self,
        frontier,
        assemble_fn: Callable[[Any, Any], Any],  # (idx, weight) -> item
        batch_size: int,
        beta_fn: Callable[[], float],
        n_items_fn: Callable[[], int],
        depth: int = 2,
        draw_ahead: int = 2,
        reuse: int = 1,
        registry=None,
        role: str = "prefetch",
    ):
        self.frontier = frontier
        self._assemble = assemble_fn
        self._B = int(batch_size)
        self._beta_fn = beta_fn
        self._n_items_fn = n_items_fn
        shrink = max(int(reuse), 1)
        self._draw_ahead = max(-(-int(draw_ahead) // shrink), 1)
        depth = max(-(-int(depth) // shrink), 1)
        self._blocks: collections.deque = collections.deque()
        self._batches: collections.deque = collections.deque()
        self._g_sa_depth = self._c_stale = None
        if registry is not None:
            self._g_sa_depth = registry.gauge("sample_ahead_queue_depth", role)
            self._c_stale = registry.counter(
                "sample_ahead_stale_indices_total", role
            )
        super().__init__(
            self._produce, depth=max(int(depth), 1), device_put=False,
            registry=registry, role=role,
        )

    def _produce(self):
        while len(self._blocks) < self._draw_ahead:
            self._blocks.append(self.frontier.draw(
                self._B, self._beta_fn(), self._n_items_fn()
            ))
        if not self._batches:
            import numpy as np

            block = self._blocks.popleft()
            # worker-thread sync: by now draw_ahead-1 newer blocks are queued
            # behind it on device, so the values are (nearly always) ready
            idx = np.asarray(block.idx)
            weight = np.asarray(block.weight)
            stale = self.frontier.stale_rows(idx, block.stamp)
            if stale and self._c_stale is not None:
                self._c_stale.inc(stale)
            for g in range(block.groups):
                self._batches.append((idx[g].astype(np.int64), weight[g]))
        idx_b, w_b = self._batches.popleft()
        return self._assemble(idx_b, w_b)

    def get(self, timeout: float = 60.0):
        item = super().get(timeout=timeout)
        if self._g_sa_depth is not None:
            self._g_sa_depth.set(self._q.qsize())
        return item


def make_replay_prefetcher(
    memory, cfg, beta_fn: Callable[[], float], registry=None
) -> "BatchPrefetcher":
    """The train-loop wiring, shared by the single-process and apex loops:
    sample -> (idx, device-staged Batch); jnp.asarray inside to_device_batch
    already performs the (async) host->device transfer, so device_put=False.
    """
    from rainbow_iqn_apex_tpu.agents.agent import to_device_batch

    def _sample():
        s = memory.sample(cfg.batch_size, beta_fn())
        return s.idx, to_device_batch(s)

    return BatchPrefetcher(
        _sample, depth=cfg.prefetch_depth, device_put=False, registry=registry
    )
