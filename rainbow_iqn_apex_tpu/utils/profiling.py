"""Tracing / profiling hooks.

Parity: the reference has no first-party tracing (SURVEY.md §5); the build
contract asks for JAX profiler traces plus block_until_ready-bracketed step
timing and per-role FPS counters (FPS lives in MetricsLogger.fps)."""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Optional

import jax


@contextlib.contextmanager
def device_trace(logdir: Optional[str]) -> Iterator[None]:
    """Capture a JAX profiler trace (TensorBoard/xplane format) around a code
    region.  No-op when logdir is None, so call sites can be unconditional."""
    if not logdir:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock timing of device steps with explicit completion barriers.

    Usage:
        with timer.step(result_to_block_on):
            ...
    or functional:  timer.lap(info["loss"]) each step, then timer.stats().
    """

    def __init__(self, warmup: int = 3):
        self.warmup = warmup
        self._laps = []
        self._units = 0  # SGD steps covered by the recorded laps
        self._count = 0
        self._last: Optional[float] = None

    def lap(self, block_on=None, units: int = 1) -> Optional[float]:
        """``units``: SGD steps this lap covers — replay reuse (cfg.
        replay_ratio = K > 1) makes one timed dispatch K steps, and
        ``steps_per_sec`` must report steps, not dispatches."""
        if block_on is not None:
            jax.block_until_ready(block_on)
        now = time.perf_counter()
        dt = None
        if self._last is not None:
            dt = now - self._last
            self._count += 1
            if self._count > self.warmup:
                self._laps.append(dt)
                self._units += max(int(units), 1)
        self._last = now
        return dt

    def stats(self) -> Dict[str, float]:
        if not self._laps:
            return {"steps": 0}
        laps = sorted(self._laps)
        n = len(laps)
        return {
            # percentiles are per timed LAP (one dispatch); steps /
            # steps_per_sec are in SGD steps (== laps unless reuse ran)
            "steps": self._units,
            "mean_s": sum(laps) / n,
            "p50_s": laps[n // 2],
            "p90_s": laps[min(int(n * 0.9), n - 1)],
            "p99_s": laps[min(int(n * 0.99), n - 1)],
            "steps_per_sec": self._units / sum(laps),
        }
