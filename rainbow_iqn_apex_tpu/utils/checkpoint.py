"""Checkpoint / resume via Orbax.

Parity: reference saves model weights with torch.save on an interval and at
eval time (SURVEY.md §5 "Checkpoint/resume"); resume = load weights + refill
replay.  Here the full TrainState (params, target params, optimizer state,
step counter) plus the actor RNG seed state and env-frame counter are saved,
so resume is exact for the learner and statistically faithful for actors.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from rainbow_iqn_apex_tpu.ops.learn import TrainState
from rainbow_iqn_apex_tpu.utils import faults


class CheckpointWriteError(IOError):
    """Injected/observed checkpoint write failure (utils/faults.py)."""


class Checkpointer:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: TrainState, extra: Optional[Dict[str, Any]] = None) -> None:
        # Crash-safety: drain the previous async save BEFORE starting this
        # one.  Orbax prunes past max_to_keep as part of save; if a prior
        # save were still in flight, a crash here could leave the newest
        # step torn while the pruned step is already gone — waiting first
        # guarantees at least one fully-committed checkpoint survives any
        # single crash point.
        self._mngr.wait_until_finished()
        if step in self._mngr.all_steps():
            # A NaN-guard rollback can replay the loop back over a step that
            # already checkpointed; the existing save is a valid consistent
            # cut (state + RNG + frames from one instant), and re-saving the
            # same step would raise StepAlreadyExistsError inside Orbax.
            return
        if faults.get().fire("checkpoint_write"):
            raise CheckpointWriteError(
                f"injected checkpoint write failure at step {step}"
            )
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                extra=ocp.args.JsonSave(extra or {}),
            ),
            # force: Orbax's should_save silently SKIPS any step at or below
            # latest_step.  A failover successor restores an epoch-ranked
            # OLDER step and re-saves below the zombie predecessor's
            # in-flight high-water mark — those saves must land
            # (``_steps_by_epoch`` orders restores by epoch, not step).
            # Same-step overwrites are already returned above, so force
            # never clobbers an existing committed cut.
            force=True,
        )

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self) -> Tuple[int, ...]:
        return tuple(self._mngr.all_steps())

    # ------------------------------------------------------------- integrity
    def latest_valid_step(
        self, abstract_state: Optional[TrainState] = None
    ) -> Optional[int]:
        """Newest step whose checkpoint actually restores, scanning PAST
        corrupt ones (torn writes, bit rot) instead of crashing on them.

        With ``abstract_state`` the validation is a full params restore (the
        only honest check — Orbax's commit markers can't see post-commit
        corruption); without it only the JSON side-car is validated (cheap,
        catches truncated step dirs but not every torn params file).
        """
        out = self._restore_newest_valid(abstract_state)
        return None if out is None else out[2]

    def restore_latest_valid(
        self, abstract_state: TrainState
    ) -> Optional[Tuple[TrainState, Dict[str, Any], int]]:
        """(state, extra, step) from the newest restorable checkpoint, or
        None when no step restores.  One descending pass: validation IS the
        restore, so the winner is never read twice."""
        out = self._restore_newest_valid(abstract_state)
        return None if out is None or out[0] is None else out

    def _restore_newest_valid(self, abstract_state: Optional[TrainState]):
        for step in self._steps_by_epoch():
            try:
                if abstract_state is None:
                    extra = self.restore_extra(step)
                    return None, extra, step
                state, extra = self.restore(abstract_state, step=step)
                return state, extra, step
            except Exception:  # corrupt/torn step: fall back to the previous
                continue
        return None

    def _steps_by_epoch(self) -> Tuple[int, ...]:
        """Candidate steps ordered newest-first by (learner_epoch, step).

        Learner failover (parallel/failover.py) stamps ``learner_epoch``
        into the extras; ordering on it FIRST means a successor's epoch-k+1
        checkpoint outranks the deceased epoch-k learner's in-flight save
        even when the zombie's step counter ran ahead — the successor can
        never be outranked by its predecessor.  Checkpoints without the
        stamp (every pre-failover run) read as epoch 0, so the order
        degenerates to plain step-descending — the seed behaviour.

        The side-car reads are ranked in ONE pass per scan with a retry:
        a MISSING stamp is epoch 0 (a valid pre-failover save), while a
        side-car that fails to READ twice (torn write, or a genuinely flaky
        filesystem) ranks -1 — below every whole checkpoint but still a
        candidate — and is logged, so one transient hiccup can neither
        silently demote the newest valid step for good nor pass unnoticed."""
        steps = sorted(self._mngr.all_steps(), reverse=True)
        if len(steps) < 2:
            return tuple(steps)
        epochs: Dict[int, int] = {}
        for step in steps:
            for attempt in (0, 1):
                try:
                    epochs[step] = int(
                        self.restore_extra(step).get("learner_epoch", 0))
                    break
                except Exception:
                    if attempt:  # failed twice: torn side-car, rank lowest
                        logging.getLogger(__name__).warning(
                            "checkpoint step %d: extras side-car unreadable "
                            "after retry; ranking it below intact steps",
                            step,
                        )
                        epochs[step] = -1
        return tuple(sorted(steps, key=lambda s: (epochs[s], s),
                            reverse=True))

    def refresh(self) -> Optional[int]:
        """Re-read the step list from disk and return the latest step.

        The manager caches its directory listing, so steps written by
        ANOTHER process (or another Checkpointer on the same dir) are
        invisible to plain latest_step() — a follower (serving/swap.py's
        CheckpointWatcher tailing a learner's dir) must refresh first."""
        self._mngr.reload()
        return self._mngr.latest_step()

    def restore_extra(self, step: Optional[int] = None) -> Dict[str, Any]:
        """The JSON side-car alone (frames counter etc.) without building an
        abstract TrainState — for tooling that inspects a run (frame count,
        resume point) without paying a params restore.  The in-harness
        salvage paths use eval_checkpoint_fused(with_extra=True) instead,
        which gets the side-car from the full restore they do anyway."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        out = self._mngr.restore(
            step, args=ocp.args.Composite(extra=ocp.args.JsonRestore())
        )
        return dict(out["extra"] or {})

    def restore(
        self, abstract_state: TrainState, step: Optional[int] = None
    ) -> Tuple[TrainState, Dict[str, Any]]:
        """Restore into the structure of ``abstract_state`` (shapes/dtypes)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        template = jax.tree.map(np.asarray, abstract_state)
        out = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                extra=ocp.args.JsonRestore(),
            ),
        )
        return out["state"], out["extra"]

    def close(self) -> None:
        self._mngr.close()


# ---------------------------------------------------------------- replay I/O
def replay_snapshot_path(cfg) -> str:
    """Replay snapshots live NEXT TO the Orbax dir, never inside it (the
    manager owns its directory's step layout).  Multi-host runs write one
    file set per host (shard-per-host topology; a shared filesystem sees
    distinct names)."""
    suffix = f"_h{cfg.process_id}" if cfg.process_count > 1 else ""
    return os.path.join(
        cfg.checkpoint_dir, cfg.run_id + "_replay", "replay" + suffix
    )


def save_replay_snapshot(cfg, memory) -> None:
    """Persist replay contents when cfg.snapshot_replay is set (works for
    PrioritizedReplay, ShardedReplay and SequenceReplay — all expose
    snapshot(path))."""
    if not cfg.snapshot_replay:
        return
    path = replay_snapshot_path(cfg)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    memory.snapshot(path)


def maybe_restore_replay(cfg, memory) -> bool:
    """Restore a replay snapshot if a usable one exists; returns whether it
    did.  Missing, torn, or CRC-failing files (kill mid-write, disk
    corruption) degrade to a cold replay; genuine mismatches (wrong shapes)
    still raise."""
    from rainbow_iqn_apex_tpu.replay import snapshot_io

    if not cfg.snapshot_replay:
        return False
    try:
        memory.restore(replay_snapshot_path(cfg))
        return True
    except snapshot_io.MISSING:
        return False


# ------------------------------------------------------------------- resume
def resume_mode(resume) -> str:
    """Normalise Config.resume (legacy bool or string flag) to one of
    ``"off"`` | ``"latest"`` | ``"auto"``.

    ``latest`` is the pre-resilience behaviour: restore the newest step and
    raise if it is corrupt.  ``auto`` is preemption-safe: restore the newest
    step that VALIDATES, falling back past corrupt ones, and start fresh
    when nothing restores — the mode an auto-restarting scheduler should use.
    """
    if isinstance(resume, bool):
        return "latest" if resume else "off"
    text = str(resume).strip().lower()
    if text in ("", "0", "false", "no", "off", "none"):
        return "off"
    if text == "auto":
        return "auto"
    if text in ("true", "1", "yes", "on", "latest"):
        return "latest"
    # a typo'd mode silently meaning "strict" would crash-loop the exact
    # preemption case "auto" exists for — refuse loudly instead
    raise ValueError(
        f"unrecognised resume mode {resume!r} (want ''/false, true, or auto)"
    )


def maybe_resume(
    cfg, ckpt: Checkpointer, abstract_state
) -> Optional[Tuple[Any, Dict[str, Any], int]]:
    """The one resume gate every train loop shares: returns
    (state, extra, step) when cfg.resume asks for a restart and a usable
    checkpoint exists, else None."""
    mode = resume_mode(cfg.resume)
    if mode == "off":
        return None
    if mode == "auto":
        out = ckpt.restore_latest_valid(abstract_state)
        if out is None and ckpt.all_steps():
            # Checkpoints EXIST but none restores.  That is either a fully
            # corrupt set or (more likely) a model-config change that no
            # longer matches the saved shapes — silently reinitialising
            # would discard the whole run, so refuse and make the operator
            # decide (delete the run dir, or fix the config).
            raise RuntimeError(
                f"--resume auto: {len(ckpt.all_steps())} checkpoint step(s) "
                f"under {ckpt.directory} but none restores into this run's "
                "state (all corrupt, or the model config changed); refusing "
                "to silently start fresh — remove the checkpoint dir to "
                "really restart from scratch"
            )
        return out
    if ckpt.latest_step() is None:
        return None
    state, extra = ckpt.restore(abstract_state)
    return state, extra, int(ckpt.latest_step())


# ------------------------------------------------------------ RNG side-car
def rng_extra(key) -> Dict[str, Any]:
    """Serialise a jax PRNG key into checkpoint 'extra' JSON, so resume can
    continue the exact tau/noise/action sample stream (preemption-safe
    resume must be numerically identical, not just statistically)."""
    return {"rng_key": [int(x) for x in np.asarray(key).ravel().tolist()]}


def rng_from_extra(extra: Dict[str, Any], fallback):
    """The saved key, or ``fallback`` for pre-resilience checkpoints."""
    if not extra or "rng_key" not in extra:
        return fallback
    import jax.numpy as jnp

    return jnp.asarray(extra["rng_key"], dtype=jnp.uint32)
