"""Checkpoint / resume via Orbax.

Parity: reference saves model weights with torch.save on an interval and at
eval time (SURVEY.md §5 "Checkpoint/resume"); resume = load weights + refill
replay.  Here the full TrainState (params, target params, optimizer state,
step counter) plus the actor RNG seed state and env-frame counter are saved,
so resume is exact for the learner and statistically faithful for actors.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from rainbow_iqn_apex_tpu.ops.learn import TrainState


class Checkpointer:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: TrainState, extra: Optional[Dict[str, Any]] = None) -> None:
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                extra=ocp.args.JsonSave(extra or {}),
            ),
        )

    def wait(self) -> None:
        self._mngr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def refresh(self) -> Optional[int]:
        """Re-read the step list from disk and return the latest step.

        The manager caches its directory listing, so steps written by
        ANOTHER process (or another Checkpointer on the same dir) are
        invisible to plain latest_step() — a follower (serving/swap.py's
        CheckpointWatcher tailing a learner's dir) must refresh first."""
        self._mngr.reload()
        return self._mngr.latest_step()

    def restore_extra(self, step: Optional[int] = None) -> Dict[str, Any]:
        """The JSON side-car alone (frames counter etc.) without building an
        abstract TrainState — for tooling that inspects a run (frame count,
        resume point) without paying a params restore.  The in-harness
        salvage paths use eval_checkpoint_fused(with_extra=True) instead,
        which gets the side-car from the full restore they do anyway."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        out = self._mngr.restore(
            step, args=ocp.args.Composite(extra=ocp.args.JsonRestore())
        )
        return dict(out["extra"] or {})

    def restore(
        self, abstract_state: TrainState, step: Optional[int] = None
    ) -> Tuple[TrainState, Dict[str, Any]]:
        """Restore into the structure of ``abstract_state`` (shapes/dtypes)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        template = jax.tree.map(np.asarray, abstract_state)
        out = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                extra=ocp.args.JsonRestore(),
            ),
        )
        return out["state"], out["extra"]

    def close(self) -> None:
        self._mngr.close()


# ---------------------------------------------------------------- replay I/O
def replay_snapshot_path(cfg) -> str:
    """Replay snapshots live NEXT TO the Orbax dir, never inside it (the
    manager owns its directory's step layout).  Multi-host runs write one
    file set per host (shard-per-host topology; a shared filesystem sees
    distinct names)."""
    suffix = f"_h{cfg.process_id}" if cfg.process_count > 1 else ""
    return os.path.join(
        cfg.checkpoint_dir, cfg.run_id + "_replay", "replay" + suffix
    )


def save_replay_snapshot(cfg, memory) -> None:
    """Persist replay contents when cfg.snapshot_replay is set (works for
    PrioritizedReplay, ShardedReplay and SequenceReplay — all expose
    snapshot(path))."""
    if not cfg.snapshot_replay:
        return
    path = replay_snapshot_path(cfg)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    memory.snapshot(path)


def maybe_restore_replay(cfg, memory) -> bool:
    """Restore a replay snapshot if a usable one exists; returns whether it
    did.  Missing or torn files (kill mid-write, pre-atomic era) degrade to
    a cold replay; genuine mismatches (wrong shapes) still raise."""
    from rainbow_iqn_apex_tpu.replay import snapshot_io

    if not cfg.snapshot_replay:
        return False
    try:
        memory.restore(replay_snapshot_path(cfg))
        return True
    except snapshot_io.MISSING:
        return False
