from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger

__all__ = ["Checkpointer", "MetricsLogger"]
