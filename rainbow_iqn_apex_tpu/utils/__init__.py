"""utils/ — checkpointing, logging, faults, prefetch, profiling.

Lazy exports (PEP 562): `utils.checkpoint` imports jax + orbax, which the
jax-free callers (`utils.faults` users like parallel/elastic.py and the
chaos-soak actor children) must not pay for just by touching the package.
"""

from typing import TYPE_CHECKING

_EXPORTS = {
    "Checkpointer": "rainbow_iqn_apex_tpu.utils.checkpoint",
    "MetricsLogger": "rainbow_iqn_apex_tpu.utils.logging",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)


def __dir__():
    return __all__


if TYPE_CHECKING:
    from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer  # noqa: F401
    from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger  # noqa: F401
