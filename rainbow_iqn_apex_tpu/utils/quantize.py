"""Quantized policy inference + delta-compressed weight distribution.

Two costs grow with the fleet, not the model (Ape-X, arXiv:1803.00933):
every actor lane / serving engine runs a full-precision forward pass per
frame, and every weight publish ships full fp32 (or bf16-cast) params to
every subscriber.  QuaRL (arXiv:1910.01055) shows post-training int8 policy
inference holds RL returns; this module supplies both halves:

- **Weight quantization** (`quantize_tree` / `dequantize_tree`): symmetric
  per-channel int8 for every leaf of a param pytree — scale = max|w| / 127
  per output channel (last axis) for rank>=2 tensors, per-tensor for
  vectors.  The jax twins (`quantize_tree_jax`, `dequantize_tree_jax`)
  run the same math in-graph, so a quantized publish ships int8 over
  ICI/DCN (4x less than fp32) and the act step dequantizes on the fly
  inside its own XLA executable.  An optional fp8 cast
  (`serve_quantize="fp8"`) sits behind the `ml_dtypes` availability guard.
- **Delta compression** (`DeltaEncoder` / `DeltaDecoder`): a periodic full
  base snapshot (bf16 when ml_dtypes is present, else fp32) plus int8
  per-tensor-scaled deltas against the *reconstructed* previous state.
  Encoding is closed-loop: the encoder quantizes the delta against what
  subscribers actually hold, so encoder and every in-sync decoder agree
  **bit-exact** after each packet and quantization error can never
  accumulate across the chain.  A decoder that missed a packet raises
  `DeltaChainBroken` and resyncs by replaying the chain-from-base the
  encoder keeps (`WeightMailbox` / `FleetRollout` wire this up).
- **Accuracy gate** (`greedy_agreement`): quantized params serve traffic
  only after their greedy actions agree with the fp32 policy on a
  calibration batch (threshold `cfg.quant_agreement_min`); a failed gate
  falls back to fp32 with a reasoned ``quant_fallback`` row.

This module is deliberately **jax-free at import** (the `utils` package
contract): the numpy codec runs in router front-ends and mailbox readers
that own no device; everything jax lives behind function-local imports.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np

try:  # bf16 base snapshots + the fp8 serve path ride on ml_dtypes; its
    import ml_dtypes  # absence degrades to fp32 bases and refuses fp8
    HAVE_ML_DTYPES = True
except ImportError:  # pragma: no cover - the build image bakes it in
    ml_dtypes = None
    HAVE_ML_DTYPES = False

QUANT_MODES = ("off", "int8", "fp8")
_INT8_MAX = 127.0


def fp8_available() -> bool:
    """fp8 serving needs ml_dtypes' float8_e4m3fn (jax shares the dtype)."""
    return HAVE_ML_DTYPES and hasattr(ml_dtypes, "float8_e4m3fn")


def check_mode(mode: str) -> str:
    if mode not in QUANT_MODES:
        raise ValueError(f"serve_quantize must be one of {QUANT_MODES}, "
                         f"got {mode!r}")
    if mode == "fp8" and not fp8_available():
        raise ValueError("serve_quantize='fp8' needs ml_dtypes.float8_e4m3fn "
                         "(not available in this environment)")
    return mode


# ------------------------------------------------------------ tree plumbing
# Param pytrees here are nested string-keyed mappings with array leaves (the
# flax params dict).  A hand-rolled flatten keeps this file importable
# without jax; paths are "/"-joined sorted keys, so flatten order — and
# therefore packet layout — is deterministic across processes.

def flatten_tree(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, Mapping):
        for key in sorted(tree):
            out.update(flatten_tree(tree[key], f"{prefix}{key}/"))
        return out
    out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def unflatten_tree(flat: Mapping[str, np.ndarray]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, leaf in flat.items():
        node = root
        parts = path.split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = leaf
    return root


def tree_bytes(tree: Any) -> int:
    """Logical payload bytes of a pytree (what a publish would ship)."""
    return int(sum(leaf.nbytes for leaf in flatten_tree(tree).values()))


# -------------------------------------------------- symmetric int8 (numpy)
def quantize_array(arr: np.ndarray,
                   per_channel: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric int8: returns (q int8, scale f32).  Rank>=2 arrays get one
    scale per OUTPUT channel (last axis — the flax kernel convention); rank
    0/1 arrays one per-tensor scale.  An all-zero channel gets scale 1 so
    dequantize is exact (0 -> 0), never 0/0."""
    arr = np.asarray(arr, np.float32)
    if per_channel and arr.ndim >= 2:
        axes = tuple(range(arr.ndim - 1))
        max_abs = np.max(np.abs(arr), axis=axes)  # [C]
    else:
        max_abs = np.max(np.abs(arr)) if arr.size else np.float32(0.0)
    scale = np.where(max_abs > 0, max_abs / _INT8_MAX, 1.0).astype(np.float32)
    q = np.clip(np.rint(arr / scale), -_INT8_MAX, _INT8_MAX).astype(np.int8)
    return q, np.atleast_1d(scale)


def dequantize_array(q: np.ndarray, scale: np.ndarray) -> np.ndarray:
    scale = np.asarray(scale, np.float32)
    if scale.size == 1:
        scale = scale.reshape(())
    return (q.astype(np.float32) * scale).astype(np.float32)


def quantize_tree(tree: Any, per_channel: bool = True) -> Dict[str, Any]:
    """Pytree -> same-shape pytree with each leaf replaced by
    ``{"q": int8, "s": f32 scale}`` (device_put- and jax.tree-friendly)."""
    flat = flatten_tree(tree)
    qflat = {}
    for path, leaf in flat.items():
        q, s = quantize_array(leaf, per_channel=per_channel)
        qflat[path] = {"q": q, "s": s}
    return unflatten_tree(qflat)


def dequantize_tree(qtree: Any) -> Dict[str, Any]:
    """Inverse of `quantize_tree` (host/numpy path)."""
    def walk(node):
        if isinstance(node, Mapping) and set(node) == {"q", "s"}:
            return dequantize_array(np.asarray(node["q"]),
                                    np.asarray(node["s"]))
        return {k: walk(v) for k, v in node.items()}

    return walk(qtree)


def is_quantized_tree(tree: Any) -> bool:
    """True when ``tree`` is a `quantize_tree` output (its leaves are
    {"q","s"} cells) — how act paths tell qparams from plain params."""
    node = tree
    while isinstance(node, Mapping):
        if set(node) == {"q", "s"}:
            return True
        if not node:
            return False
        node = node[sorted(node)[0]]
    return False


def greedy_agreement(actions_a: np.ndarray, actions_b: np.ndarray) -> float:
    """Fraction of identical greedy actions — the accuracy gate's metric."""
    a = np.asarray(actions_a).reshape(-1)
    b = np.asarray(actions_b).reshape(-1)
    if a.shape != b.shape:
        raise ValueError(f"action shape mismatch {a.shape} vs {b.shape}")
    if a.size == 0:
        return 0.0
    return float(np.mean(a == b))


# -------------------------------------------------------- jax-side helpers
def quantize_tree_jax(params: Any) -> Any:
    """In-graph twin of `quantize_tree` (jit-able): per-output-channel
    symmetric int8.  Ships 4x fewer bytes per publish than fp32 and the
    actor/serve act step dequantizes in its own executable."""
    import jax
    import jax.numpy as jnp

    def quant(leaf):
        x = leaf.astype(jnp.float32)
        if x.ndim >= 2:
            axes = tuple(range(x.ndim - 1))
            max_abs = jnp.max(jnp.abs(x), axis=axes)
        else:
            max_abs = jnp.max(jnp.abs(x))
        scale = jnp.where(max_abs > 0, max_abs / _INT8_MAX, 1.0)
        q = jnp.clip(jnp.rint(x / scale), -_INT8_MAX, _INT8_MAX)
        return {"q": q.astype(jnp.int8),
                "s": jnp.atleast_1d(scale.astype(jnp.float32))}

    return jax.tree.map(quant, params)


def cast_tree_fp8(params: Any) -> Any:
    """fp8 (e4m3) cast of every leaf — the `serve_quantize="fp8"` payload.
    Same {"q","s"} cell shape as int8 (scale 1) so one act wrapper serves
    both modes."""
    import jax
    import jax.numpy as jnp

    if not fp8_available():  # pragma: no cover - guarded by check_mode
        raise RuntimeError("fp8 quantization needs ml_dtypes.float8_e4m3fn")
    fp8 = jnp.dtype(ml_dtypes.float8_e4m3fn)
    return jax.tree.map(
        lambda x: {"q": x.astype(fp8), "s": jnp.ones((1,), jnp.float32)},
        params,
    )


def quantize_for_mode(params: Any, mode: str) -> Any:
    if mode == "int8":
        return quantize_tree_jax(params)
    if mode == "fp8":
        return cast_tree_fp8(params)
    raise ValueError(f"no quantized payload for mode {mode!r}")


def dequantize_tree_jax(qtree: Any, dtype: Any = None) -> Any:
    """In-graph dequantize of a `quantize_tree_jax`/`cast_tree_fp8` tree.
    XLA fuses this into the act executable, so weights stay int8/fp8 in HBM
    and the multiply-by-scale rides the first use of each tensor."""
    import jax
    import jax.numpy as jnp

    dt = jnp.float32 if dtype is None else dtype

    def dequant(cell):
        q, s = cell["q"], cell["s"]
        # scale broadcasts over the last axis (per-channel [C]) or the whole
        # tensor (per-tensor [1]); the reshape restores rank-0 leaves
        return jnp.reshape(q.astype(dt) * s.astype(dt), q.shape)

    return jax.tree.map(dequant, qtree,
                        is_leaf=lambda n: isinstance(n, dict)
                        and set(n) == {"q", "s"})


def wrap_act_quantized(act_fn: Callable) -> Callable:
    """Wrap an act step so its first argument is a quantized tree; the
    dequantize happens inside the same (to-be-jitted) function, i.e. inside
    the same XLA executable per bucket."""
    def act_q(qparams, *args, **kwargs):
        return act_fn(dequantize_tree_jax(qparams), *args, **kwargs)

    return act_q


# --------------------------------------------------------- delta packets
class DeltaChainBroken(RuntimeError):
    """The decoder was handed a delta it cannot apply (missed packet, fresh
    subscriber): resync from the chain-from-base the encoder keeps."""


@dataclasses.dataclass
class WeightPacket:
    """One publish on the wire: a full base snapshot or an int8 delta.

    ``leaves`` maps flat tree paths to ``(payload, scale)``; base packets
    carry (bf16-or-fp32 array, None), delta packets (int8 array, one
    per-tensor f32 scale).  ``prev_version`` is the version this delta
    applies on top of (-1 for a base).  Packets are value objects — safe to
    fan out to N subscribers concurrently."""

    kind: str  # "base" | "delta"
    version: int
    prev_version: int
    base_version: int
    leaves: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]]

    def nbytes(self) -> int:
        """Logical wire bytes: payload + scales (the bench/row number)."""
        total = 0
        for data, scale in self.leaves.values():
            total += data.nbytes + (scale.nbytes if scale is not None else 0)
        return int(total)


def _packet_arrays(packet: WeightPacket) -> Dict[str, np.ndarray]:
    """The npz array dict for one packet (shared by the file and wire
    serialisations, so a packet saved to disk and one framed over a socket
    are byte-identical payloads)."""
    arrays: Dict[str, np.ndarray] = {}
    for leaf_path, (data, scale) in packet.leaves.items():
        if HAVE_ML_DTYPES and data.dtype == np.dtype(ml_dtypes.bfloat16):
            # np.load cannot round-trip ml_dtypes' bfloat16; ship the raw
            # bits as uint16 under a marker key and re-view on load
            arrays[f"b::{leaf_path}"] = data.view(np.uint16)
        else:
            arrays[f"d::{leaf_path}"] = data
        if scale is not None:
            arrays[f"s::{leaf_path}"] = scale
    arrays["__meta__"] = np.array(
        [packet.version, packet.prev_version, packet.base_version,
         1 if packet.kind == "base" else 0], np.int64)
    return arrays


def _packet_from_npz(z) -> WeightPacket:
    meta = z["__meta__"]
    leaves: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]] = {}
    for key in z.files:
        if not key.startswith(("d::", "b::")):
            continue
        leaf_path = key[3:]
        data = z[key]
        if key.startswith("b::"):
            data = data.view(np.dtype(ml_dtypes.bfloat16))
        scale_key = f"s::{leaf_path}"
        leaves[leaf_path] = (
            data, z[scale_key] if scale_key in z.files else None
        )
    return WeightPacket(
        kind="base" if int(meta[3]) else "delta",
        version=int(meta[0]), prev_version=int(meta[1]),
        base_version=int(meta[2]), leaves=leaves,
    )


def save_packet(packet: WeightPacket, path: str) -> None:
    """One .npz per packet (WeightMailbox's payload files).  Written via
    tmp + rename so a reader never sees a torn file."""
    import os

    tmp = path + ".tmp.npz"
    with open(tmp, "wb") as fh:
        np.savez(fh, **_packet_arrays(packet))
    os.replace(tmp, path)


def load_packet(path: str) -> WeightPacket:
    with np.load(path, allow_pickle=False) as z:
        return _packet_from_npz(z)


def packet_to_bytes(packet: WeightPacket) -> bytes:
    """In-memory npz serialisation — the wire payload the cross-host
    rollout frames over serving/net (same bytes `save_packet` writes)."""
    import io

    buf = io.BytesIO()
    np.savez(buf, **_packet_arrays(packet))
    return buf.getvalue()


def packet_from_bytes(data: bytes) -> WeightPacket:
    import io

    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        return _packet_from_npz(z)


def params_packet(params: Any, version: int) -> WeightPacket:
    """An UNCOMPRESSED full-fp32 base packet for ``params`` — the wire shape
    of a compression="off" rollout (`RemoteEngine.adopt`): the decode is a
    plain fp32 round-trip, so the remote engine adopts bit-exact params
    without holding any delta-chain state."""
    flat = {p: np.asarray(leaf, np.float32)
            for p, leaf in flatten_tree(params).items()}
    return WeightPacket(
        kind="base", version=int(version), prev_version=-1,
        base_version=int(version),
        leaves={p: (leaf, None) for p, leaf in flat.items()},
    )


def tree_digest(tree: Any) -> str:
    """Order-stable sha256 over a param pytree's fp32 leaf bytes — the
    bit-exactness witness for cross-host rollouts (publisher reconstruction
    vs every engine's adopted params).  jax arrays are pulled to host."""
    import hashlib

    h = hashlib.sha256()
    for path in sorted(flat := flatten_tree(tree)):
        arr = np.ascontiguousarray(np.asarray(flat[path], np.float32))
        h.update(path.encode("utf-8"))
        h.update(arr.tobytes())
    return h.hexdigest()


def _base_dtype():
    """Base snapshots ship bf16 when ml_dtypes is importable (half the
    bytes, and training already broadcasts bf16 — cfg.bf16_weight_sync);
    fp32 otherwise.  The choice is per-encoder, stamped into the packets."""
    return np.dtype(ml_dtypes.bfloat16) if HAVE_ML_DTYPES else np.float32


class DeltaEncoder:
    """Closed-loop delta encoder for versioned weight publishes.

    Every `base_interval`-th publish emits a full base snapshot; the ones
    between emit int8 per-tensor deltas against `self._recon` — the state a
    decoder that applied every packet holds, NOT the true fp32 params.
    Quantizing against the reconstruction makes encoder and subscribers
    agree bit-exact after every packet and bounds drift at one delta's
    quantization error regardless of chain length.

    `chain()` returns the packets since (and including) the current base —
    what a late joiner or a gap-hit decoder replays to resync.
    """

    def __init__(self, base_interval: int = 10):
        self.base_interval = max(int(base_interval), 1)
        self.base_dtype = _base_dtype()
        self._recon: Optional[Dict[str, np.ndarray]] = None
        self._chain: List[WeightPacket] = []
        self.version = -1
        self._since_base = 0
        self.publishes = 0
        self.bytes_total = 0

    def encode(self, params: Any, version: int) -> WeightPacket:
        if version <= self.version:
            raise ValueError(
                f"delta encoder is monotone: version {version} <= "
                f"current {self.version}")
        flat = {p: np.asarray(leaf, np.float32)
                for p, leaf in flatten_tree(params).items()}
        make_base = (
            self._recon is None
            or self._since_base >= self.base_interval
            or sorted(flat) != sorted(self._recon)  # reshaped model: resync
        )
        if make_base:
            leaves = {p: (leaf.astype(self.base_dtype), None)
                      for p, leaf in flat.items()}
            # the decoder holds the dtype-rounded values; so must we
            self._recon = {p: data.astype(np.float32)
                           for p, (data, _) in leaves.items()}
            packet = WeightPacket(
                kind="base", version=int(version), prev_version=-1,
                base_version=int(version), leaves=leaves,
            )
            self._chain = [packet]
            self._since_base = 1
        else:
            leaves = {}
            base_version = self._chain[0].base_version
            for path, leaf in flat.items():
                delta = leaf - self._recon[path]
                q, s = quantize_array(delta, per_channel=False)
                leaves[path] = (q, s)
                self._recon[path] = (
                    self._recon[path] + dequantize_array(q, s)
                ).astype(np.float32)
            packet = WeightPacket(
                kind="delta", version=int(version),
                prev_version=self.version, base_version=base_version,
                leaves=leaves,
            )
            self._chain.append(packet)
            self._since_base += 1
        self.version = int(version)
        self.publishes += 1
        self.bytes_total += packet.nbytes()
        return packet

    def chain(self) -> List[WeightPacket]:
        return list(self._chain)

    def reconstructed(self) -> Dict[str, Any]:
        """The fp32 tree every in-sync subscriber currently holds."""
        if self._recon is None:
            raise RuntimeError("nothing encoded yet")
        return unflatten_tree({p: leaf.copy()
                               for p, leaf in self._recon.items()})


class DeltaDecoder:
    """Subscriber state: applies base/delta packets, detects chain gaps."""

    def __init__(self):
        self.version = -1
        self._recon: Optional[Dict[str, np.ndarray]] = None

    def apply(self, packet: WeightPacket) -> Dict[str, Any]:
        """Apply one packet; returns the reconstructed fp32 param tree.
        Backward/duplicate packets raise ValueError (the mailbox mirror of
        FleetRollout's refused_backward); a delta whose prev_version is not
        the held version raises `DeltaChainBroken`."""
        if packet.version <= self.version:
            raise ValueError(
                f"refusing backward/duplicate weight packet "
                f"{packet.version} (holding {self.version})")
        if packet.kind == "base":
            self._recon = {p: data.astype(np.float32)
                           for p, (data, _) in packet.leaves.items()}
        else:
            if self._recon is None or packet.prev_version != self.version:
                raise DeltaChainBroken(
                    f"delta v{packet.version} applies on v{packet.prev_version}, "
                    f"holding v{self.version}: resync from base")
            for path, (q, s) in packet.leaves.items():
                if path not in self._recon:
                    raise DeltaChainBroken(f"unknown leaf {path!r}: resync")
                self._recon[path] = (
                    self._recon[path] + dequantize_array(q, s)
                ).astype(np.float32)
        self.version = int(packet.version)
        return self.params()

    def apply_chain(self, packets: List[WeightPacket]) -> Dict[str, Any]:
        """Replay a chain-from-base, skipping packets already held — the
        late-joiner / gap-recovery path.  The chain's base resets state, so
        this always converges to the encoder's reconstruction."""
        if not packets:
            raise DeltaChainBroken("empty chain")
        for packet in packets:
            if packet.version <= self.version:
                continue  # already held (idempotent catch-up)
            if packet.kind == "delta" and packet.prev_version != self.version:
                # mid-chain join without the base applied first
                raise DeltaChainBroken(
                    f"chain gap at v{packet.version} (holding v{self.version})")
            self.apply(packet)
        return self.params()

    def params(self) -> Dict[str, Any]:
        if self._recon is None:
            raise DeltaChainBroken("no base applied yet")
        return unflatten_tree({p: leaf.copy()
                               for p, leaf in self._recon.items()})
