"""The host<->device sync seam: every *sanctioned* blocking device->host
materialization in the learner hot path goes through this module, and tests
can statically forbid everything else.

Why a seam at all: the learner loop's throughput floor is the device step
time only while the host never blocks on a device value mid-loop
(docs/PERFORMANCE.md sync-point inventory).  One reintroduced
``float(info["loss"])`` or ``int(state.step)`` silently re-serializes the
whole pipeline — the exact regression BENCH_r01-r05 measured.  The seam
makes that failure loud:

- ``to_host(x)`` / ``scalar(x)``: the sanctioned materialization calls
  (WritebackRing retirement, supervisor snapshots, cadence reads).  Inside a
  ``forbid_host_sync()`` region they only work under ``sanctioned()``.
- ``check_host_work(tag)``: the same fence for tagged host-side hot-path
  WORK rather than transfers — host replay sampling joined the forbidden
  set when the device sample frontier landed (replay/frontier.py).
- ``forbid_host_sync()``: the tier-1 guard context.  It layers two fences:
  (1) ``jax.transfer_guard_device_to_host("disallow")`` — catches real
  device->host copies on accelerator backends; vacuous on the CPU platform
  where host "transfers" are zero-copy, hence (2) a patch of
  ``ArrayImpl._value`` — the property behind ``float()``/``int()``/
  ``.item()``/``__bool__`` on jax arrays — that raises ``HostSyncError``
  for the guarded thread.  Plain ``np.asarray`` of a CPU-backed jax array
  goes through the buffer protocol below any Python hook and cannot be
  caught on CPU; the write-back lag determinism test (tests/test_writeback)
  covers that hole from the other side.

Thread story: the forbid/sanction flags are thread-local, so the guard
constrains only the thread that entered it — the prefetch worker and the
stall watchdog are unaffected.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any

import numpy as np


class HostSyncError(RuntimeError):
    """A blocking device->host materialization inside a no-sync region."""


_tls = threading.local()


def _forbidden() -> bool:
    return (
        getattr(_tls, "forbid", 0) > 0 and getattr(_tls, "sanction", 0) == 0
    )


@contextlib.contextmanager
def sanctioned():
    """Mark the enclosed block as an allowed sync point (ring retirement,
    snapshot capture, cadence reads).  Composes with an enclosing
    ``forbid_host_sync()``: transfers inside are allowed again."""
    import jax

    _tls.sanction = getattr(_tls, "sanction", 0) + 1
    try:
        with jax.transfer_guard_device_to_host("allow"):
            yield
    finally:
        _tls.sanction -= 1


def to_host(x: Any) -> np.ndarray:
    """Materialize a (possibly device) array on host — THE sanctioned
    device->host array copy of the hot path."""
    if isinstance(x, np.ndarray):
        return x
    if _forbidden():
        raise HostSyncError(
            "to_host() outside a sanctioned() block inside a no-sync region"
        )
    with sanctioned():
        return np.asarray(x)


def check_host_work(tag: str) -> None:
    """Forbidden-set membership check for tagged host-side hot-path WORK —
    not a transfer, but work the zero-sync learner thread must delegate.
    Replay SAMPLING joined the set with the device sample frontier
    (replay/frontier.py): ``PrioritizedReplay.sample`` /
    ``ShardedReplay.sample`` / ``SequenceReplay.sample`` call this, so a
    learner thread inside ``forbid_host_sync()`` that walks a host sum-tree
    per step (instead of consuming the sample-ahead pusher's device-drawn
    batches) fails tier-1 loudly.  Worker threads (prefetcher, pusher) are
    unaffected — the flags are thread-local."""
    if _forbidden():
        raise HostSyncError(
            f"host-side '{tag}' on a thread inside a forbid_host_sync() "
            "region (delegate it to a worker, or wrap a cold-path call in "
            "sanctioned())"
        )


def scalar(x: Any) -> float:
    """Materialize a scalar on host (blocks until the value is ready)."""
    if isinstance(x, (float, int)):
        return float(x)
    if _forbidden():
        raise HostSyncError(
            "scalar() outside a sanctioned() block inside a no-sync region"
        )
    with sanctioned():
        return float(x)


# --------------------------------------------------------------- test guard
_patch_lock = threading.Lock()
_patch_depth = 0
_orig_value = None


def _install_value_guard() -> None:
    """Patch ``ArrayImpl._value`` so float()/int()/.item() on a jax array
    raise inside this thread's forbidden region.  Idempotent/refcounted;
    other threads (prefetcher, watchdog) never see the flag."""
    global _patch_depth, _orig_value
    from jax._src import array as jarray

    with _patch_lock:
        if _patch_depth == 0:
            _orig_value = jarray.ArrayImpl.__dict__["_value"]
            orig = _orig_value

            def _guarded(self):
                if _forbidden():
                    raise HostSyncError(
                        "blocking device->host scalar materialization "
                        "(float/int/item on a jax array) inside a "
                        "forbid_host_sync() region"
                    )
                return orig.fget(self)

            jarray.ArrayImpl._value = property(_guarded)
        _patch_depth += 1


def _remove_value_guard() -> None:
    global _patch_depth
    from jax._src import array as jarray

    with _patch_lock:
        _patch_depth -= 1
        if _patch_depth == 0 and _orig_value is not None:
            jarray.ArrayImpl._value = _orig_value


@contextlib.contextmanager
def forbid_host_sync():
    """Tier-1 static guard: inside this context, any blocking device->host
    materialization on the current thread outside ``sanctioned()`` raises
    ``HostSyncError`` (scalar conversions on every backend; array transfers
    on non-CPU backends via jax's transfer guard)."""
    import jax

    _install_value_guard()
    _tls.forbid = getattr(_tls, "forbid", 0) + 1
    try:
        with jax.transfer_guard_device_to_host("disallow"):
            yield
    finally:
        _tls.forbid -= 1
        _remove_value_guard()
