"""Pipelined priority write-back: the depth-K in-flight ring that makes the
learner hot path issue zero blocking host<->device transfers per step.

The seed loop dispatched one jitted learn step and then immediately blocked
on ``np.asarray(info["priorities"])`` plus the supervisor's ``float(loss)``
NaN guard — so the prefetcher's documented overlap never happened and the
accelerator idled between steps.  Ape-X's own semantics say that is
unnecessary: priority updates may be stale by the pipeline depth (Horgan et
al., arXiv:1803.00933 — the reference's updates race later samples through
Redis anyway), and async learner architectures (IMPACT, arXiv:1912.00167)
put the loop floor at device step time, not dispatch+sync time.

Mechanics: the loop pushes each dispatched step's ``(step, idx, info)`` with
``info`` still DEVICE arrays — including the on-device ``finite`` flag the
learn step now computes in-graph (ops/learn.py) so the NaN/Inf guard costs
no host round-trip.  Once more than ``depth`` entries are in flight, the
oldest retires: its priorities/scalars are materialized (a sanctioned sync —
by then the device has K newer steps queued, so the value is ready and the
copy overlaps their execution) and handed back for replay write-back and the
deferred ``TrainSupervisor.retire_ok`` check.

Rollback contract (parallel/apex.py): when a retired entry is non-finite the
caller must quarantine the retired idx AND every idx still in the ring —
``flush()`` hands those back without touching their (poisoned) device infos
— then roll back to a snapshot taken at a drain point, which is by
construction >= K steps behind the poisoned step.

depth=0 degenerates to the seed behaviour: push retires immediately, one
sync per step, bitwise-identical trajectories (tests/test_writeback.py).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.utils import hostsync


@dataclasses.dataclass
class RetiredStep:
    """One learn step, materialized on host at ring retirement.

    With ``materialize_priorities=False`` (device sampling: the write-back
    target is the HBM priority mirror, replay/frontier.py) ``priorities``
    stays the DEVICE |TD| array — only the finite flag and the scalar
    metrics come to host."""

    step: int
    idx: np.ndarray
    priorities: Any  # np.ndarray, or a device array in mirror mode
    finite: bool
    scalars: Dict[str, float]  # loss, grad_norm, q_mean, ... (host floats)
    lag: int  # newest dispatched step - this step, at retirement


class WritebackRing:
    """Depth-K ring of in-flight ``(step, idx, device info)`` learn steps.

    ``priorities_to_host`` customizes the priority materialization (the
    multi-host loops pass ``multihost.local_rows`` so each host extracts its
    local rows of the global dp-sharded array at retirement instead of at
    dispatch).  Gauges (in-flight depth, write-back lag) land on the shared
    obs registry when one is attached.
    """

    def __init__(
        self,
        depth: int,
        registry=None,
        role: str = "learner",
        priorities_to_host: Optional[Callable[[Any], np.ndarray]] = None,
        materialize_priorities: bool = True,
        tracer=None,
    ):
        self.depth = max(int(depth), 0)
        self._q: collections.deque = collections.deque()
        self._to_host = priorities_to_host
        # pipeline tracing (obs/pipeline_trace.py): dispatch->retire wall lag
        # is recorded always-on (`lag_ring_retire_ms`); sampled steps emit a
        # `ring_retire` span under the learn step's own trace id
        self._tracer = tracer
        # False when the write-back target consumes DEVICE arrays (the HBM
        # priority mirror): retirement then syncs only the finite flag +
        # scalars, and the |TD| vector never crosses to host in the hot path
        self._materialize = bool(materialize_priorities)
        self._last_pushed = 0
        self._retired_total = 0
        self.last_lag = 0  # dispatch-to-retire lag of the newest retirement
        self._g_depth = self._g_lag = None
        if registry is not None:
            self._g_depth = registry.gauge("writeback_inflight", role)
            self._g_lag = registry.gauge("writeback_lag_steps", role)

    def __len__(self) -> int:
        return len(self._q)

    @property
    def retired_total(self) -> int:
        return self._retired_total

    def push(
        self, step: int, idx: np.ndarray, info: Dict[str, Any]
    ) -> Optional[RetiredStep]:
        """Enqueue a dispatched step; returns the retired oldest entry when
        the ring was already holding ``depth`` steps (None otherwise)."""
        self._q.append((int(step), idx, info, time.time()))
        self._last_pushed = int(step)
        retired = self.retire_one() if len(self._q) > self.depth else None
        if self._g_depth is not None:
            self._g_depth.set(len(self._q))
        return retired

    def retire_one(self) -> RetiredStep:
        """Materialize and pop the OLDEST in-flight step (sanctioned sync)."""
        step, idx, info, t_push = self._q.popleft()
        t_retire = time.time()
        with hostsync.sanctioned():
            finite = bool(info["finite"]) if "finite" in info else True
            pri = info["priorities"]
            if self._to_host is not None:
                pri = self._to_host(pri)
            if self._materialize:
                pri = np.asarray(pri)
            scalars = {
                k: float(v)
                for k, v in info.items()
                if k not in ("priorities", "finite") and np.ndim(v) == 0
            }
        lag = self._last_pushed - step
        self.last_lag = lag
        self._retired_total += 1
        if self._g_depth is not None:
            self._g_depth.set(len(self._q))
            self._g_lag.set(lag)
        if self._tracer is not None:
            # the LAG metric is dispatch->retire wall time (how stale the
            # priorities are when they land — the quantity Ape-X bounds);
            # the SPAN is only the retirement WORK (sync + materialize) —
            # the in-flight wait is deliberate pipelining, and billing it to
            # this stage would misattribute the critical path to the ring
            self._tracer.lag("ring_retire_ms", (time.time() - t_push) * 1e3)
            if self._tracer.sampled(step):
                self._tracer.emit_span(
                    "ring_retire", self._tracer.trace_id("l", step),
                    t_retire, step=step, lag_steps=lag,
                )
        return RetiredStep(
            step=step, idx=idx, priorities=pri, finite=finite,
            scalars=scalars, lag=lag,
        )

    def drain(self) -> List[RetiredStep]:
        """Retire everything in flight, oldest first (ring-boundary sync:
        snapshot capture, weight publish, checkpoint, end of run).  Callers
        that can roll back should prefer retiring one at a time via
        ``retire_one`` so entries behind a tripped flag stay quarantinable."""
        return [self.retire_one() for _ in range(len(self._q))]

    def flush(self) -> List[Tuple[int, np.ndarray]]:
        """Drop every in-flight entry WITHOUT materializing its device info
        (it may be poisoned); returns ``[(step, idx), ...]`` oldest-first for
        quarantine write-back."""
        out = [(step, idx) for step, idx, _, _ in self._q]
        self._q.clear()
        if self._g_depth is not None:
            self._g_depth.set(0)
        return out


def cadence_hit(step: int, interval: int, reuse_k: int = 1) -> bool:
    """Did the step counter CROSS a multiple of ``interval`` in the jump
    that landed on ``step``?  With replay reuse (cfg.replay_ratio = K > 1)
    the learner step advances K per fused dispatch, so ``step % interval ==
    0`` would silently skip any cadence not divisible by K; ``step %
    interval < K`` fires exactly once per crossing instead (intervals are
    assumed >= K — every production cadence is orders of magnitude above
    it).  K = 1 degenerates to the exact ``% == 0`` the pre-reuse loops
    ran, so the default path's behaviour is unchanged."""
    return bool(interval) and step % interval < max(int(reuse_k), 1)


def check_reuse_cadences(cfg, *names: str) -> None:
    """``cadence_hit`` (and the delta-based publish/snapshot cadences) fire
    once per interval CROSSING under step jumps of K = cfg.replay_ratio,
    assuming every live interval >= K; a sub-K interval fires every fused
    dispatch — eval/drain after each learn call, the per-step-sync loop the
    ring exists to avoid — with no error.  The reuse loops call this at
    start to make the documented assumption real."""
    k = max(int(cfg.replay_ratio), 1)
    if k == 1:
        return
    for name in names:
        iv = int(getattr(cfg, name) or 0)
        if iv and iv < k:
            raise ValueError(
                f"{name} ({iv}) must be 0 (off) or >= replay_ratio ({k}): "
                "the step counter advances K per fused reuse dispatch and "
                "cadences fire once per interval crossing, so a sub-K "
                "interval would fire EVERY dispatch "
                "(docs/PERFORMANCE.md \"Replay reuse\")")


def reuse_learn_row(reuse_k: int,
                    scalars: Dict[str, Any]) -> Dict[str, Any]:
    """Learn-row extras for a replay-reuse run (docs/PERFORMANCE.md "Replay
    reuse"), from the newest RETIRED sample's host scalars — one definition
    so train.py and parallel/apex.py can't drift on the row surface (same
    rationale as ``pipeline_gauges``).  Empty at K = 1 so default-path rows
    stay byte-identical."""
    if reuse_k == 1:
        return {}
    ri = scalars.get("reuse_index")
    return {
        "replay_ratio": reuse_k,
        # host-sync-ok: ring-retired host scalars, already materialized
        "reuse_index": None if ri is None else int(ri),
        "clip_frac": scalars.get("clip_frac"),
    }


def reuse_health(reuse_k: int,
                 scalars: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The ``pipeline_gauges(reuse=)`` payload for health rows: None at
    K = 1 (rows stay byte-identical), else K + the newest retired sample's
    mean reuse-pass clip fraction (the K-too-high early warning)."""
    if reuse_k == 1:
        return None
    return {
        "replay_ratio": reuse_k,
        "reuse_clip_frac": scalars.get("clip_frac"),
    }


def pipeline_gauges(ring: WritebackRing, registry,
                    frontier=None, reuse: Optional[Dict[str, Any]] = None,
                    ) -> Dict[str, float]:
    """The pipeline-health gauges every loop feeds to ``obs_run.periodic``
    (and obs_report keys on as the ``pipeline:`` line) — one definition so
    the three loops can't drift on the surface (docs/PERFORMANCE.md)."""
    out = {
        "writeback_inflight": len(ring),
        "writeback_lag_steps": ring.last_lag,
        "prefetch_queue_depth": registry.gauge(
            "prefetch_queue_depth", "prefetch"
        ).get(),
        "prefetch_empty_waits": registry.counter(
            "prefetch_empty_wait_total", "prefetch"
        ).get(),
    }
    if reuse:
        # replay reuse live (cfg.replay_ratio > 1): present on health rows
        # ONLY then, so a K=1 run's rows stay byte-identical and obs_report
        # can tell a reusing run at a glance (replay_ratio, newest retired
        # sample's mean reuse-pass clip fraction — the K-too-high signal)
        out.update(reuse)
    if frontier is not None:
        # device-sampling pipeline (replay/frontier.py) — present on health
        # rows ONLY when the frontier is live, so obs_report can tell a
        # device-sampling run from a host-sampling one.  empty_waits
        # climbing + a pinned-zero sample_ahead_queue_depth says the PUSHER
        # can't keep up; mirror_reconcile_s vs the stale-indices counter
        # then splits sampler-starved (draws slow) from gather-starved
        # (host assembly slow) — docs/PERFORMANCE.md.
        out.update({
            "sample_ahead_queue_depth": registry.gauge(
                "sample_ahead_queue_depth", "prefetch"
            ).get(),
            "sample_ahead_stale_indices": registry.counter(
                "sample_ahead_stale_indices_total", "prefetch"
            ).get(),
            "mirror_reconcile_s": registry.gauge(
                "mirror_reconcile_s", "frontier"
            ).get(),
        })
    return out


class RingCommitter:
    """The commit/quarantine/drain protocol around a WritebackRing — ONE
    implementation shared by the three pipelined train loops (train.py,
    parallel/apex.py, parallel/apex_r2d2.py), which must not drift on the
    rollback contract.

    ``commit(retired)``: the deferred guard.  A finite step writes its
    priorities back and keeps its host scalars readable via ``scalars`` (the
    metric cadence reads these instead of syncing on the device queue).  A
    non-finite step quarantines EVERY in-flight idx set — the tripped
    entry's AND everything still in the ring (they were sampled/learned from
    states downstream of the poison; |TD|=0 drops them to the eps^omega
    priority floor so none can re-sample into a rollback livelock) — then
    rolls back via ``load_snapshot(*supervisor.rollback())`` to the last
    drained-and-verified snapshot, which is by construction >= the ring
    depth behind the poison.

    Multi-host note: the in-graph finite flag derives from the all-reduced
    loss, so every host makes the SAME commit/rollback decision — provided
    the loops call ``drain()`` at host-invariant points only (snapshot /
    publish / eval / checkpoint cadences, which are functions of the
    lockstep step counter).
    """

    def __init__(self, ring: WritebackRing, update_priorities, supervisor,
                 load_snapshot, on_drain: Optional[Callable[[], Any]] = None):
        self.ring = ring
        self._update = update_priorities
        self._sup = supervisor
        self._load_snapshot = load_snapshot
        # drain-boundary hook: device sampling reconciles the HBM priority
        # mirror back into the host sum-trees here (replay/frontier.py), so
        # snapshot/publish/checkpoint always read a caught-up cold path
        self._on_drain = on_drain
        self.scalars: Dict[str, float] = {}  # newest retired step's scalars

    def _quarantine_and_rollback(self, bad: RetiredStep) -> None:
        self._update(bad.idx, np.zeros(len(bad.idx)))
        for _step_no, idx in self.ring.flush():
            self._update(idx, np.zeros(len(idx)))
        self._load_snapshot(*self._sup.rollback())

    def commit(self, retired: Optional[RetiredStep]) -> bool:
        """True when the step (or None) is fine; False after a quarantine +
        rollback — the loop should ``continue``."""
        if retired is None:
            return True
        if not self._sup.retire_ok(retired):
            self._quarantine_and_rollback(retired)
            return False
        self._update(retired.idx, retired.priorities)
        self.scalars.update(retired.scalars)
        return True

    def drain(self) -> bool:
        """Ring boundary: retire everything in flight; False when one
        tripped and we rolled back (the ``on_drain`` reconcile is skipped —
        the next clean drain catches the cold path up)."""
        while len(self.ring):
            if not self.commit(self.ring.retire_one()):
                return False
        if self._on_drain is not None:
            self._on_drain()
        return True
