"""Structured metrics + stdout logging.

Parity: the reference logs episode scores to stdout and plots curves
(SURVEY.md §5 "Metrics/logging"); the build contract upgrades this to
structured JSONL rows (one object per line, machine-readable) plus the same
human-readable stdout stream.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, Optional


class MetricsLogger:
    """Append-only JSONL metrics with wall-clock stamps and an FPS meter."""

    def __init__(self, path: Optional[str], run_id: str = "run", echo: bool = True):
        self.path = path
        self.echo = echo
        self.run_id = run_id
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)
        self._t0 = time.time()
        self._last_t: Optional[float] = None
        self._last_frames = 0

    def log(self, kind: str, **fields: Any) -> Dict[str, Any]:
        row = {
            "t": round(time.time() - self._t0, 3),
            "run": self.run_id,
            "kind": kind,
            **fields,
        }
        if self._fh:
            self._fh.write(json.dumps(row) + "\n")
        if self.echo:
            pretty = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in fields.items()
            )
            print(f"[{row['t']:9.1f}s] {kind:8s} {pretty}", file=sys.stderr)
        return row

    def fps(self, frames: int) -> float:
        """Rolling frames/sec between successive calls."""
        now = time.time()
        if self._last_t is None:
            self._last_t, self._last_frames = now, frames
            return 0.0
        dt = max(now - self._last_t, 1e-9)
        fps = (frames - self._last_frames) / dt
        self._last_t, self._last_frames = now, frames
        return fps

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None
