"""Structured metrics + stdout logging.

Parity: the reference logs episode scores to stdout and plots curves
(SURVEY.md §5 "Metrics/logging"); the build contract upgrades this to
structured JSONL rows (one object per line, machine-readable) plus the same
human-readable stdout stream.

Every row carries the shared obs/ envelope (schema version, absolute ``ts``
wall clock, ``host`` process index — obs/schema.py) and is STRICT JSON:
``json.dumps(float("nan"))`` emits bare ``NaN``, which is invalid JSON and
broke downstream parsers on PR 2's fault rows, so non-finite floats are
sanitized (NaN -> null, +/-inf -> "inf"/"-inf") before serialisation.

Observers: ``add_observer(fn)`` registers a callback invoked with every
sanitized row — obs/health.RunHealth uses this to fold fault/serve rows into
the run's health state without coupling to their emitters.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional

from rainbow_iqn_apex_tpu.obs.schema import SCHEMA_VERSION, sanitize


class MetricsLogger:
    """Append-only JSONL metrics with wall-clock stamps and an FPS meter."""

    def __init__(
        self,
        path: Optional[str],
        run_id: str = "run",
        echo: bool = True,
        host: int = 0,
    ):
        self.path = path
        self.echo = echo
        self.run_id = run_id
        self.host = int(host)
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)
        self._t0 = time.time()
        self._last_t: Optional[float] = None
        self._last_frames = 0
        self._observers: List[Callable[[Dict[str, Any]], None]] = []

    def add_observer(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """Register a callback receiving every sanitized row dict."""
        self._observers.append(fn)

    def log(self, kind: str, **fields: Any) -> Dict[str, Any]:
        now = time.time()
        row = sanitize(
            {
                "t": round(now - self._t0, 3),
                "ts": round(now, 3),
                "host": self.host,
                "run": self.run_id,
                "kind": kind,
                "schema": SCHEMA_VERSION,
                **fields,
            }
        )
        if self._fh:
            # allow_nan=False is the backstop: sanitize() already cleared
            # non-finite floats, so a bare NaN can never reach the file
            self._fh.write(json.dumps(row, allow_nan=False) + "\n")
        for fn in self._observers:
            try:
                fn(row)
            except Exception:
                pass  # a broken observer must never kill the training loop
        if self.echo:
            skip = ("t", "ts", "host", "run", "kind", "schema")
            pretty = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in row.items()
                if k not in skip
            )
            print(f"[{row['t']:9.1f}s] {kind:8s} {pretty}", file=sys.stderr)
        return row

    def fps(self, frames: int) -> float:
        """Rolling frames/sec between successive calls."""
        now = time.time()
        if self._last_t is None:
            self._last_t, self._last_frames = now, frames
            return 0.0
        dt = max(now - self._last_t, 1e-9)
        fps = (frames - self._last_frames) / dt
        self._last_t, self._last_frames = now, frames
        return fps

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None
