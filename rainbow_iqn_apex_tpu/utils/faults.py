"""Deterministic fault injection + the one retry/backoff policy.

Ape-X's premise is long-running distributed training where actors, the
replay fabric, and the learner fail independently (arXiv:1803.00933); a
resilient stack therefore needs a way to *manufacture* those failures on
demand, deterministically, so chaos tests and soak runs exercise the same
recovery code that real preemptions will.  This module is that mechanism:

- ``FaultInjector``: named injection points, armed from ``Config.fault_spec``
  or the ``RIA_FAULTS`` env var (env wins — a soak harness can arm faults
  without touching run configs).  Firing is deterministic: ``point@n`` fires
  on the n-th call, ``point:p`` fires with seeded probability p, bare
  ``point`` fires every call.  The hooks live where real faults strike —
  Checkpointer.save (write failure), snapshot_io.atomic_savez (torn file),
  the supervisor's step loop (NaN batch, stalled step), the heartbeat writer
  (dead host) — so an injected fault takes the same code path as a real one.
- ``RetryPolicy`` / ``retry_call``: bounded retry with exponential backoff
  and deterministic jitter, shared by training checkpoint/snapshot IO and
  the serving hot-swap (one retry policy across serving + training).
- ``FailureBudget``: bounded per-key strike counting with poisoning — the
  policy serving/swap.py previously hand-rolled per checkpoint step.

The fault matrix (injection point -> detection -> recovery) is documented in
docs/RESILIENCE.md.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Set, Tuple

# Named injection points.  Adding one means adding the hook AND a row to the
# docs/RESILIENCE.md fault matrix AND a chaos test exercising it.
POINTS = (
    "checkpoint_write",  # Checkpointer.save raises IOError (flaky/remote FS)
    "replay_snapshot_corrupt",  # atomic_savez lands a corrupt file (torn write)
    "nan_loss",  # the sampled batch is poisoned with non-finite rewards
    "stalled_step",  # the learn step blocks (wedged device / collective)
    "heartbeat_loss",  # a host stops writing its heartbeat file (preemption)
    "actor_exit",  # an actor process exits mid-run (OOM kill, crash loop)
    "lease_lost",  # a LIVE process stops renewing its lease (zombie / split
    # brain: the incarnation epoch fencing exists for)
    "shard_rejoin",  # shard readmission fails once (re-registration raced)
    "learner_exit",  # the LEARNER process exits mid-run (the last single
    # point of failure; a live standby claims the role — failover)
    "standby_claim",  # a standby's takeover claim attempt fails once
    # (filesystem hiccup mid-O_EXCL; the standby re-arms and re-claims)
    "net_delay",  # a wire write stalls (latency spike; netcore/chaos.py
    # consults this at its delay decision site)
    "net_corrupt",  # one outgoing wire byte flips (CRC catches it; the
    # plane must convert to its typed Frame* error and reconnect)
    "net_partition",  # an outgoing wire write is silently dropped
    # (one-way partition / frame-atomic loss; ack timeout, not corruption)
    "net_slow_peer",  # this process reads the wire slowly (slow consumer;
    # the peer's bounded per-conn queue must shed only THIS connection)
)

ENV_VAR = "RIA_FAULTS"


class FaultSpecError(ValueError):
    pass


def _parse_spec(spec: str) -> Dict[str, Tuple[Set[int], float, bool]]:
    """``"nan_loss@5,checkpoint_write@1,heartbeat_loss:0.5"`` ->
    {point: (fire_at_calls, probability, always)}."""
    out: Dict[str, Tuple[Set[int], float, bool]] = {}
    for entry in filter(None, (e.strip() for e in spec.split(","))):
        name, at, prob, always = entry, None, 0.0, False
        if "@" in entry:
            name, _, n = entry.partition("@")
            try:
                at = int(n)
            except ValueError:
                raise FaultSpecError(f"bad call index in fault entry '{entry}'")
            if at < 1:
                raise FaultSpecError(f"call index must be >= 1 in '{entry}'")
        elif ":" in entry:
            name, _, p = entry.partition(":")
            try:
                prob = float(p)
            except ValueError:
                raise FaultSpecError(f"bad probability in fault entry '{entry}'")
            if not 0.0 <= prob <= 1.0:
                raise FaultSpecError(f"probability out of [0,1] in '{entry}'")
        else:
            always = True
        if name not in POINTS:
            raise FaultSpecError(
                f"unknown fault point '{name}' (known: {', '.join(POINTS)})"
            )
        ats, pr, alw = out.get(name, (set(), 0.0, False))
        if at is not None:
            ats.add(at)
        out[name] = (ats, max(pr, prob), alw or always)
    return out


class FaultInjector:
    """Seeded, counter-based fault firing at named points.

    Call counters are per-point and thread-safe (the prefetcher and the
    heartbeat writer run off the main thread).  ``fire(point)`` increments
    the point's counter and reports whether this call should fault; the
    decision sequence is a pure function of (spec, seed, call order), so a
    chaos test replays exactly.
    """

    def __init__(self, spec: str = "", seed: int = 0):
        self.spec = spec
        self._rules = _parse_spec(spec)
        self._rng = random.Random(seed)
        self._calls: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(self._rules)

    def has(self, point: str) -> bool:
        """Whether the spec arms ``point`` at all, WITHOUT counting a call
        (hot paths gate on this before paying for ``fire()``)."""
        return point in self._rules

    def fire(self, point: str) -> bool:
        """True when the current call at ``point`` should fault."""
        if point not in POINTS:
            raise FaultSpecError(f"unknown fault point '{point}'")
        with self._lock:
            n = self._calls.get(point, 0) + 1
            self._calls[point] = n
            rule = self._rules.get(point)
            if rule is None:
                return False
            ats, prob, always = rule
            hit = always or n in ats or (prob > 0.0 and self._rng.random() < prob)
            if hit:
                self._fired[point] = self._fired.get(point, 0) + 1
            return hit

    def calls(self, point: str) -> int:
        with self._lock:
            return self._calls.get(point, 0)

    def fired(self, point: str) -> int:
        with self._lock:
            return self._fired.get(point, 0)


# ------------------------------------------------------------- global access
# Deep hooks (snapshot_io, checkpoint) cannot thread an injector argument
# through every caller; they consult the installed one.  Default: disabled.
_NULL = FaultInjector("")
_current: FaultInjector = _NULL


def install(injector: Optional[FaultInjector]) -> FaultInjector:
    global _current
    _current = injector if injector is not None else _NULL
    return _current


def install_from(cfg) -> FaultInjector:
    """Arm injection from Config/env (env var wins so soak harnesses can arm
    chaos without editing run configs).  No spec -> the null injector."""
    spec = os.environ.get(ENV_VAR, "") or getattr(cfg, "fault_spec", "")
    return install(FaultInjector(spec, seed=getattr(cfg, "seed", 0)))


def get() -> FaultInjector:
    return _current


# ------------------------------------------------------------ retry/backoff
@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff + deterministic jitter.

    ``attempts`` is the TOTAL number of tries (1 = no retry).  Delay before
    retry k (k>=1) is ``min(base_delay_s * 2**(k-1), max_delay_s)`` scaled by
    a jitter factor in [1-jitter, 1+jitter] drawn from a seeded stream, so
    two runs with the same seed back off identically (and a fleet of runs
    with different seeds doesn't thundering-herd a shared filesystem).
    """

    attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.5
    seed: int = 0

    @classmethod
    def from_config(cls, cfg) -> "RetryPolicy":
        return cls(
            attempts=cfg.io_retry_attempts,
            base_delay_s=cfg.io_retry_base_s,
            max_delay_s=cfg.io_retry_max_s,
            seed=cfg.seed,
        )

    def delays(self) -> Sequence[float]:
        """The full backoff schedule (delay before retry 1..attempts-1)."""
        rng = random.Random(self.seed)
        out = []
        for k in range(1, self.attempts):
            d = min(self.base_delay_s * (2 ** (k - 1)), self.max_delay_s)
            out.append(d * (1.0 + self.jitter * (2.0 * rng.random() - 1.0)))
        return out


def retry_call(
    fn: Callable,
    policy: RetryPolicy = RetryPolicy(),
    retry_on: Tuple = (OSError, IOError),
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` under ``policy``; re-raises the last error when the
    budget is exhausted.  ``on_retry(attempt, exc)`` observes each failure
    (metrics hook)."""
    delays = policy.delays()
    last: Optional[BaseException] = None
    for attempt in range(1, policy.attempts + 1):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — bounded, IO-dominated
            last = e
            if on_retry is not None:
                on_retry(attempt, e)
            if attempt >= policy.attempts:
                raise
            sleep(delays[attempt - 1])
    raise last  # unreachable; keeps type-checkers honest


# ----------------------------------------------------------- failure budget
class FailureBudget:
    """Bounded per-key failure counting with poisoning.

    The policy serving/swap.py hand-rolled for checkpoint steps, shared:
    ``record(key)`` counts a failure; once a key accumulates
    ``max_failures`` it is poisoned — callers stop retrying it (no retry
    storm against a genuinely bad artifact).  ``clear(key)`` un-poisons
    after a success (a recovered artifact is whole again).
    """

    def __init__(self, max_failures: int = 3):
        self.max_failures = int(max_failures)
        self._counts: Dict = {}
        self._lock = threading.Lock()

    def record(self, key) -> int:
        with self._lock:
            n = self._counts.get(key, 0) + 1
            self._counts[key] = n
            return n

    def failures(self, key) -> int:
        with self._lock:
            return self._counts.get(key, 0)

    def poisoned(self, key) -> bool:
        with self._lock:
            return self._counts.get(key, 0) >= self.max_failures

    def clear(self, key) -> None:
        with self._lock:
            self._counts.pop(key, None)
