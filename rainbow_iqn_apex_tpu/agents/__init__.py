from rainbow_iqn_apex_tpu.agents.agent import Agent, FrameStacker

__all__ = ["Agent", "FrameStacker"]
