"""The Agent: acting, learning, target sync, checkpoint save/load.

Parity: reference `rainbowiqn/agent.py` `Agent` (SURVEY.md §2 row 4, §3.3) —
`act(state)` (greedy over the mean of K tau samples, noisy-net exploration),
`learn(memory)` (quantile-Huber + Adam + priority write-back), scheduled
target-net update, save/load.

TPU-first notes: the Agent is a thin host-side facade over two pure jitted
functions (act_step, learn_step).  All mutable state lives in one TrainState
pytree in device memory (donated through the learn step) and an explicit PRNG
key; nothing else to get wrong under jit.  The per-lane frame-stack rolling
state is host NumPy — it belongs to the env/actor side of the host-device
seam, so frames cross to HBM exactly once per tick as one [L, H, W, hist]
uint8 tensor.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import chex
import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.ops.learn import (
    Batch,
    TrainState,
    build_act_step,
    build_learn_step,
    init_train_state,
)
from rainbow_iqn_apex_tpu.replay.buffer import SampledBatch
from rainbow_iqn_apex_tpu.utils import hostsync


def put_frames(x: np.ndarray) -> jnp.ndarray:
    """Transfer uint8 frame tensors as a flat byte stream, reshape on device.

    Rank>=3 uint8 transfers pay a per-array host/transport (re)tiling cost on
    some PJRT transports — measured 4-7x slower than the same bytes rank-1
    through this sandbox's TPU relay (docs/STATUS.md round-2 perf notes).  The
    flat view is zero-copy on the host and the device-side reshape is layout
    bookkeeping, so this is never worse than the shaped transfer.
    """
    arr = np.ascontiguousarray(x)
    return jnp.asarray(arr.reshape(-1)).reshape(arr.shape)


def to_device_batch(sample: SampledBatch) -> Batch:
    """Host SampledBatch -> device Batch (async transfers via jnp.asarray)."""
    game = getattr(sample, "game", None)
    return Batch(
        obs=put_frames(sample.obs),
        action=jnp.asarray(sample.action),
        reward=jnp.asarray(sample.reward),
        next_obs=put_frames(sample.next_obs),
        discount=jnp.asarray(sample.discount),
        weight=jnp.asarray(sample.weight),
        game=None if game is None else jnp.asarray(game, jnp.int32),
    )


class FrameStacker:
    """Rolling [L, H, W, hist] uint8 stack with per-lane terminal reset."""

    def __init__(self, lanes: int, frame_shape: Tuple[int, int], history: int):
        self.buf = np.zeros((lanes, *frame_shape, history), np.uint8)

    def push(self, frames: np.ndarray) -> np.ndarray:
        """Shift in the newest frame; returns the stacked state (a view copy)."""
        self.buf[..., :-1] = self.buf[..., 1:]
        self.buf[..., -1] = frames
        return self.buf.copy()

    def reset_lanes(self, mask: np.ndarray) -> None:
        """Zero the history of lanes whose episode just ended (reference
        zero-stack reset semantics)."""
        self.buf[mask] = 0


class Agent:
    def __init__(
        self,
        cfg: Config,
        num_actions: int,
        key: chex.PRNGKey,
        train: bool = True,
        state_shape: Optional[Tuple[int, ...]] = None,
    ):
        self.cfg = cfg
        self.num_actions = num_actions
        key, init_key = jax.random.split(key)
        self.key = key
        # replay reuse (cfg.replay_ratio = K > 1): one learn_batch dispatch
        # is a fused K-pass executable, so state.step — and the host mirror
        # — advance K per call (ops/learn.py make_reuse_learn_step)
        self.reuse_k = max(int(cfg.replay_ratio), 1)
        self._host_step: Optional[int] = None  # host mirror of state.step
        self.state: TrainState = init_train_state(
            cfg, num_actions, init_key, state_shape=state_shape
        )
        self._act = jax.jit(build_act_step(cfg, num_actions, use_noise=True))
        self._act_eval = jax.jit(
            build_act_step(cfg, num_actions, use_noise=cfg.eval_noisy)
        )
        self._learn = (
            jax.jit(build_learn_step(cfg, num_actions), donate_argnums=0)
            if train
            else None
        )

    # ------------------------------------------------------------------ acting
    def _next_key(self) -> chex.PRNGKey:
        self.key, k = jax.random.split(self.key)
        return k

    def act(self, stacked_obs: np.ndarray, eval_mode: bool = False) -> np.ndarray:
        """Greedy actions for a [L, H, W, hist] uint8 batch.  Noisy-net noise
        is resampled every call (reference per-step resample, SURVEY §3.2)."""
        fn = self._act_eval if eval_mode else self._act
        actions, _ = fn(self.state.params, put_frames(stacked_obs), self._next_key())
        # the actor->env hand-off is an OBLIGATORY host materialization (the
        # env lives on host) — same sanctioned sync as ApexDriver.act
        with hostsync.sanctioned():
            return np.asarray(actions)

    # ---------------------------------------------------------------- learning
    def learn(self, sample: SampledBatch) -> Dict[str, Any]:
        """One learner step on a host SampledBatch; returns info with host
        priorities for the replay write-back."""
        return self.learn_batch(to_device_batch(sample))

    def learn_batch(self, batch: Batch) -> Dict[str, Any]:
        """One learner step on an already-staged device Batch (prefetch
        path).  Dispatch-only: ``info`` values stay device arrays (JAX async
        dispatch) so the caller decides when — if ever per step — to sync."""
        self._state, info = self._learn(self._state, batch, self._next_key())
        if self._host_step is not None:
            self._host_step += self.reuse_k
        return info

    # `state` invalidates the host step mirror on direct assignment (resume,
    # tests); learn_batch bypasses the setter and increments the mirror, so
    # reading `step` in the hot loop never blocks on the device queue.
    @property
    def state(self) -> TrainState:
        return self._state

    @state.setter
    def state(self, value: TrainState) -> None:
        self._state = value
        self._host_step = None

    @property
    def step(self) -> int:
        if self._host_step is None:
            with hostsync.sanctioned():
                self._host_step = int(np.asarray(self._state.step))
        return self._host_step

    # ---------------------------------------------------------------- rollback
    def load_snapshot(self, state, key) -> None:
        """NaN-guard rollback target (parallel/supervisor.py): replace the
        live TrainState + PRNG key with the supervisor's last-good host
        copy.  The poisoned donated buffers are simply dropped."""
        self.state = jax.tree.map(jnp.asarray, state)
        self.key = jnp.asarray(key)

    # ------------------------------------------------------- league adoption
    def adopt_params(self, host_params) -> None:
        """League exploit adoption (league/member.py, docs/LEAGUE.md):
        replace online AND target params with a copied member's weights —
        called only at a drained boundary.  Optimizer moments are re-init
        fresh: Adam statistics accumulated around the LOSER's trajectory
        are meaningless at the winner's point in weight space, and a
        deterministic re-init is reproducible where stale moments are not.
        The step counter and PRNG stream are untouched (cadences and
        exploration continue where the member left off)."""
        from rainbow_iqn_apex_tpu.league.member import graft_tree
        from rainbow_iqn_apex_tpu.ops.learn import make_optimizer

        params = jax.tree.map(
            jnp.asarray, graft_tree(self._state.params, host_params))
        self.state = self._state.replace(
            params=params,
            target_params=jax.tree.map(jnp.copy, params),
            opt_state=make_optimizer(self.cfg).init(params),
        )

    def retune(self, learning_rate: Optional[float] = None) -> None:
        """Mid-run live-gene adoption: rebuild the jitted learn step under
        the new hyperparameters (one recompile per exploit event — rare by
        construction).  Replay-side genes (n_step, priority_exponent) are
        retuned on the replay object by the loop; this covers the genes
        baked into the learn executable."""
        if learning_rate is None or self._learn is None:
            return
        self.cfg = self.cfg.replace(learning_rate=float(learning_rate))
        self._learn = jax.jit(
            build_learn_step(self.cfg, self.num_actions), donate_argnums=0
        )

    # ------------------------------------------------------------- weight sync
    def params_for_publish(self):
        """Online params as the learner publishes them to actors (the Redis
        weight-mailbox equivalent; bf16-cast when configured to halve sync
        bytes — SURVEY §5 'weight mailbox')."""
        if self.cfg.bf16_weight_sync:
            return jax.tree.map(lambda p: p.astype(jnp.bfloat16), self.state.params)
        return self.state.params

    def load_published(self, params) -> None:
        self.state = self.state.replace(
            params=jax.tree.map(lambda p: p.astype(jnp.float32), params)
        )
