"""Anakin trainer: the whole Rainbow-IQN learner ON the chip — device-resident
PER replay (replay/device.py) + the fused sample->learn->write-back tick —
with host envs feeding one small [L, H, W] frame tensor per tick.

Reference parity: same algorithm and schedules as the single-process mode
(`train.py`, SURVEY.md §3.1+§3.2) — act/learn interleaved at `replay_ratio`,
n-step PER with the reference's max-priority insertion for fresh transitions,
scheduled target update (inside the learn graph), Orbax checkpoints, JSONL
metrics, periodic eval.  What changes is WHERE the replay lives: the
reference keeps it in Redis (a network hop per sample, SURVEY §2 row 6), the
host trainers here keep it in host DRAM (a PCIe hop), and this one keeps it
in HBM — zero per-step transfer, which round-2 profiling showed is >90% of
the learner's wall time on this hardware (docs/STATUS.md).

Per tick, exactly TWO dispatches and ~7 KB/lane of host->device traffic:
  1. act_append: append LAST tick's completed transition into the HBM ring
     (lag-one, so reward/terminal are known) + shift the device-resident
     frame stack + act on it.
  2. fused learn (when due): sample + learn + priority write-back, one graph.
"""

from __future__ import annotations

import collections
import functools
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.agents.agent import put_frames
from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.envs import make_vector_env
from rainbow_iqn_apex_tpu.ops.learn import build_act_step, init_train_state
from rainbow_iqn_apex_tpu.parallel.multihost import shift_stack
from rainbow_iqn_apex_tpu.replay.device import DeviceReplay, build_device_learn
from rainbow_iqn_apex_tpu.train import priority_beta
from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger


def _replay_snapshot_path(cfg: Config) -> str:
    return os.path.join(cfg.checkpoint_dir, cfg.run_id, "replay_anakin.npz")


def _save_replay(cfg: Config, ds) -> None:
    if not cfg.snapshot_replay:
        return
    from rainbow_iqn_apex_tpu.replay import snapshot_io

    host = jax.device_get(ds)
    snapshot_io.atomic_savez(
        _replay_snapshot_path(cfg),
        frames=host.frames, actions=host.actions, rewards=host.rewards,
        terminals=host.terminals, cuts=host.cuts, priority=host.priority,
        pos=host.pos, filled=host.filled, max_priority=host.max_priority,
    )


def _maybe_restore_replay(cfg: Config, ds):
    """Returns (state, restored_ticks) — ticks drive the host-side warmness
    counters, which must match the restored ring."""
    path = _replay_snapshot_path(cfg)
    if not (cfg.snapshot_replay and os.path.exists(path)):
        return ds, 0
    from rainbow_iqn_apex_tpu.replay import snapshot_io

    z = snapshot_io.load(path)
    if tuple(z["frames"].shape) != tuple(ds.frames.shape):
        return ds, 0  # shape change: degrade to cold replay, same as host path
    ds = ds.replace(
        frames=jnp.asarray(z["frames"]), actions=jnp.asarray(z["actions"]),
        rewards=jnp.asarray(z["rewards"]), terminals=jnp.asarray(z["terminals"]),
        cuts=jnp.asarray(z["cuts"]), priority=jnp.asarray(z["priority"]),
        pos=jnp.asarray(z["pos"]), filled=jnp.asarray(z["filled"]),
        max_priority=jnp.asarray(z["max_priority"]),
    )
    return ds, int(z["filled"])


def train_anakin(cfg: Config, max_frames: Optional[int] = None) -> Dict[str, Any]:
    """Runs training; returns a summary dict (final eval, fps, steps)."""
    total_frames = max_frames or cfg.t_max
    lanes = cfg.num_envs_per_actor
    env = make_vector_env(cfg.env_id, lanes, seed=cfg.seed)
    if cfg.memory_capacity % lanes:
        raise ValueError(
            f"memory capacity {cfg.memory_capacity} not divisible by {lanes} lanes"
        )
    seg = cfg.memory_capacity // lanes
    replay = DeviceReplay(
        lanes=lanes, seg=seg, frame_shape=env.frame_shape,
        history=cfg.history_length, n_step=cfg.multi_step, gamma=cfg.gamma,
        priority_exponent=cfg.priority_exponent, priority_eps=cfg.priority_eps,
    )
    ds = replay.init_state()
    key = jax.random.PRNGKey(cfg.seed)
    key, k_init = jax.random.split(key)
    ts = init_train_state(
        cfg, env.num_actions, k_init,
        state_shape=(*env.frame_shape, cfg.history_length),
    )
    act_fn = build_act_step(cfg, env.num_actions, use_noise=True)

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def act_append(params, stack, ds, frame, keep, prev, key):
        """Dispatch 1: append last tick's completed transition (None on the
        first tick), shift the device stack, act."""
        if prev is not None:
            ds = replay.append(ds, *prev)
        stack = shift_stack(stack, frame, keep)
        a, _q = act_fn(params, stack, key)
        return a, stack, ds

    fused = jax.jit(
        build_device_learn(cfg, env.num_actions, replay), donate_argnums=(0, 1)
    )

    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(os.path.join(run_dir, "metrics.jsonl"), cfg.run_id)
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))

    frames = 0
    ticks = 0
    if cfg.resume and ckpt.latest_step() is not None:
        ts, extra = ckpt.restore(ts)
        frames = int(extra.get("frames", 0))
        ds, ticks = _maybe_restore_replay(cfg, ds)
        metrics.log("resume", step=int(ts.step), frames=frames)
    learn_steps = int(ts.step)

    h, w = env.frame_shape
    stack = jnp.zeros((lanes, h, w, cfg.history_length), jnp.uint8)
    obs = env.reset()
    prev_cuts = np.zeros(lanes, bool)
    prev = None  # device-resident (frame, action, reward, term, trunc) tuple
    returns: collections.deque = collections.deque(maxlen=100)
    device = jax.devices()[0]

    while frames < total_frames:
        frame_d = put_frames(obs)  # flat-byte staging (rank-3 put penalty)
        keep_d = jax.device_put((~prev_cuts).astype(np.uint8), device)
        key, k = jax.random.split(key)
        actions_d, stack, ds = act_append(ts.params, stack, ds, frame_d, keep_d, prev, k)
        actions = np.asarray(actions_d)
        new_obs, rewards, terminals, truncs, ep_returns = env.step(actions)
        # held for NEXT tick's append: reference memory layout (pre-step
        # frame + this step's action/reward/terminal, SURVEY §2 row 5); the
        # fresh-transition priority is the running max, exactly the
        # reference's single-process insertion rule.
        prev = (
            frame_d,
            actions_d,
            jax.device_put(rewards.astype(np.float32), device),
            jax.device_put(terminals, device),
            jax.device_put(truncs, device),
        )
        prev_cuts = terminals | truncs
        obs = new_obs
        frames += lanes
        ticks += 1
        for r in ep_returns[~np.isnan(ep_returns)]:
            returns.append(float(r))

        # warmness from host-side lockstep counters (appends lag one tick)
        stored = min(max(ticks - 1, 0), seg) * lanes
        if stored >= cfg.learn_start and ticks - 1 > cfg.multi_step:
            steps_due = frames // cfg.replay_ratio - learn_steps
            for _ in range(max(steps_due, 0)):
                key, k = jax.random.split(key)
                ts, ds, info = fused(ts, ds, k, jnp.float32(priority_beta(cfg, frames)))
                learn_steps += 1
                if learn_steps % cfg.metrics_interval == 0:
                    metrics.log(
                        "train",
                        step=learn_steps,
                        frames=frames,
                        fps=metrics.fps(frames),
                        loss=float(info["loss"]),
                        q_mean=float(info["q_mean"]),
                        grad_norm=float(info["grad_norm"]),
                        mean_return=float(np.mean(returns)) if returns else float("nan"),
                    )
                if cfg.eval_interval and learn_steps % cfg.eval_interval == 0:
                    metrics.log("eval", step=learn_steps, **_eval(cfg, env, ts))
                if cfg.checkpoint_interval and learn_steps % cfg.checkpoint_interval == 0:
                    ckpt.save(learn_steps, ts, {"frames": frames})
                    _save_replay(cfg, ds)

    final_eval = _eval(cfg, env, ts)
    metrics.log("eval", step=learn_steps, **final_eval)
    ckpt.save(learn_steps, ts, {"frames": frames})
    _save_replay(cfg, ds)
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": learn_steps,
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }


def _eval(cfg: Config, env, ts) -> Dict[str, Any]:
    from rainbow_iqn_apex_tpu.eval import evaluate_state

    return evaluate_state(cfg, env, ts, seed=cfg.seed + 977)
