"""Anakin trainer: the whole Rainbow-IQN learner ON the chip — device-resident
PER replay (replay/device.py) + the fused sample->learn->write-back tick —
with host envs feeding one small [L, H, W] frame tensor per tick.

Reference parity: same algorithm and schedules as the single-process mode
(`train.py`, SURVEY.md §3.1+§3.2) — act/learn interleaved at `frames_per_learn`,
n-step PER with the reference's max-priority insertion for fresh transitions,
scheduled target update (inside the learn graph), Orbax checkpoints, JSONL
metrics, periodic eval.  What changes is WHERE the replay lives: the
reference keeps it in Redis (a network hop per sample, SURVEY §2 row 6), the
host trainers here keep it in host DRAM (a PCIe hop), and this one keeps it
in HBM — zero per-step transfer, which round-2 profiling showed is >90% of
the learner's wall time on this hardware (docs/STATUS.md).

Per tick, exactly TWO dispatches and ~7 KB/lane of host->device traffic:
  1. act_append: append LAST tick's completed transition into the HBM ring
     (lag-one, so reward/terminal are known) + shift the device-resident
     frame stack + act on it.
  2. fused learn (when due): sample + learn + priority write-back, one graph.
"""

from __future__ import annotations

import collections
import functools
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.agents.agent import put_frames
from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.envs import make_vector_env
from rainbow_iqn_apex_tpu.obs import RunObs
from rainbow_iqn_apex_tpu.ops.learn import build_act_step, init_train_state
from rainbow_iqn_apex_tpu.parallel.multihost import shift_stack
from rainbow_iqn_apex_tpu.replay.device import DeviceReplay, build_device_learn
from rainbow_iqn_apex_tpu.train import priority_beta
from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer, maybe_resume
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger


def _replay_snapshot_path(cfg: Config) -> str:
    return os.path.join(cfg.checkpoint_dir, cfg.run_id, "replay_anakin.npz")


def _save_replay(cfg: Config, ds) -> None:
    if not cfg.snapshot_replay:
        return
    from rainbow_iqn_apex_tpu.replay import snapshot_io

    host = jax.device_get(ds)
    snapshot_io.atomic_savez(
        _replay_snapshot_path(cfg),
        frames=host.frames, actions=host.actions, rewards=host.rewards,
        terminals=host.terminals, cuts=host.cuts, priority=host.priority,
        pos=host.pos, filled=host.filled, max_priority=host.max_priority,
    )


def _maybe_restore_replay(cfg: Config, ds):
    """Returns (state, restored_ticks) — ticks drive the host-side warmness
    counters, which must match the restored ring."""
    path = _replay_snapshot_path(cfg)
    if not (cfg.snapshot_replay and os.path.exists(path)):
        return ds, 0
    from rainbow_iqn_apex_tpu.replay import snapshot_io

    z = snapshot_io.load(path)
    if tuple(z["frames"].shape) != tuple(ds.frames.shape):
        return ds, 0  # shape change: degrade to cold replay, same as host path
    ds = ds.replace(
        frames=jnp.asarray(z["frames"]), actions=jnp.asarray(z["actions"]),
        rewards=jnp.asarray(z["rewards"]), terminals=jnp.asarray(z["terminals"]),
        cuts=jnp.asarray(z["cuts"]), priority=jnp.asarray(z["priority"]),
        pos=jnp.asarray(z["pos"]), filled=jnp.asarray(z["filled"]),
        max_priority=jnp.asarray(z["max_priority"]),
    )
    return ds, int(z["filled"])


def train_anakin(cfg: Config, max_frames: Optional[int] = None) -> Dict[str, Any]:
    """Runs training; returns a summary dict (final eval, fps, steps).

    With a pure-JAX env (`jaxgame:*`) and `fused_env` on, dispatches to the
    fully fused variant (env compiled into the graph) below."""
    if cfg.replay_ratio > 1:
        raise ValueError(
            "replay_ratio > 1 (clipped replay reuse) targets the actor-bound "
            "apex/single loops; the anakin learner is already fused "
            "device-resident — reuse there is the recorded ROADMAP follow-up")
    if cfg.fused_env and cfg.env_id.startswith("jaxgame:"):
        return train_anakin_fused(cfg, max_frames)
    total_frames = max_frames or cfg.t_max
    lanes = cfg.num_envs_per_actor
    env = make_vector_env(cfg.env_id, lanes, seed=cfg.seed)
    if cfg.memory_capacity % lanes:
        raise ValueError(
            f"memory capacity {cfg.memory_capacity} not divisible by {lanes} lanes"
        )
    seg = cfg.memory_capacity // lanes
    replay = DeviceReplay(
        lanes=lanes, seg=seg, frame_shape=env.frame_shape,
        history=cfg.history_length, n_step=cfg.multi_step, gamma=cfg.gamma,
        priority_exponent=cfg.priority_exponent, priority_eps=cfg.priority_eps,
    )
    ds = replay.init_state()
    key = jax.random.PRNGKey(cfg.seed)
    key, k_init = jax.random.split(key)
    ts = init_train_state(
        cfg, env.num_actions, k_init,
        state_shape=(*env.frame_shape, cfg.history_length),
    )
    act_fn = build_act_step(cfg, env.num_actions, use_noise=True)

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def act_append(params, stack, ds, frame, keep, prev, key):
        """Dispatch 1: append last tick's completed transition (None on the
        first tick), shift the device stack, act."""
        if prev is not None:
            ds = replay.append(ds, *prev)
        stack = shift_stack(stack, frame, keep)
        a, _q = act_fn(params, stack, key)
        return a, stack, ds

    fused = jax.jit(
        build_device_learn(cfg, env.num_actions, replay), donate_argnums=(0, 1)
    )

    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(os.path.join(run_dir, "metrics.jsonl"), cfg.run_id)
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))
    obs_run = RunObs(cfg, metrics, role="learner")

    frames = 0
    ticks = 0
    restored = maybe_resume(cfg, ckpt, ts)
    if restored is not None:
        ts, extra, _ = restored
        frames = int(extra.get("frames", 0))
        ds, ticks = _maybe_restore_replay(cfg, ds)
        metrics.log("resume", step=int(ts.step), frames=frames)
    learn_steps = int(ts.step)

    h, w = env.frame_shape
    stack = jnp.zeros((lanes, h, w, cfg.history_length), jnp.uint8)
    obs = env.reset()
    prev_cuts = np.zeros(lanes, bool)
    prev = None  # device-resident (frame, action, reward, term, trunc) tuple
    returns: collections.deque = collections.deque(maxlen=100)
    device = jax.devices()[0]

    try:
        while frames < total_frames:
            frame_d = put_frames(obs)  # flat-byte staging (rank-3 put penalty)
            keep_d = jax.device_put((~prev_cuts).astype(np.uint8), device)
            key, k = jax.random.split(key)
            with obs_run.span("act_append"):
                actions_d, stack, ds = act_append(
                    ts.params, stack, ds, frame_d, keep_d, prev, k
                )
                actions = np.asarray(actions_d)
            new_obs, rewards, terminals, truncs, ep_returns = env.step(actions)
            # held for NEXT tick's append: reference memory layout (pre-step
            # frame + this step's action/reward/terminal, SURVEY §2 row 5); the
            # fresh-transition priority is the running max, exactly the
            # reference's single-process insertion rule.
            prev = (
                frame_d,
                actions_d,
                jax.device_put(rewards.astype(np.float32), device),
                jax.device_put(terminals, device),
                jax.device_put(truncs, device),
            )
            prev_cuts = terminals | truncs
            obs = new_obs
            frames += lanes
            ticks += 1
            for r in ep_returns[~np.isnan(ep_returns)]:
                returns.append(float(r))

            # warmness from host-side lockstep counters (appends lag one tick)
            stored = min(max(ticks - 1, 0), seg) * lanes
            if stored >= cfg.learn_start and ticks - 1 > cfg.multi_step:
                steps_due = frames // cfg.frames_per_learn - learn_steps
                for _ in range(max(steps_due, 0)):
                    key, k = jax.random.split(key)
                    with obs_run.span("learn_step"):
                        ts, ds, info = fused(
                            ts, ds, k, jnp.float32(priority_beta(cfg, frames))
                        )
                    learn_steps += 1
                    # no block_on: this loop's dispatches stay async between
                    # metrics intervals, and a per-step barrier would kill the
                    # host/device overlap that IS the anakin design.  StepTimer
                    # laps then measure dispatch gaps — steady-state the device
                    # queue throttles the host, so steps_per_sec stays true.
                    obs_run.after_learn_step(learn_steps)
                    if learn_steps % cfg.metrics_interval == 0:
                        metrics.log(
                            "learn",
                            step=learn_steps,
                            frames=frames,
                            fps=metrics.fps(frames),
                            loss=float(info["loss"]),
                            q_mean=float(info["q_mean"]),
                            grad_norm=float(info["grad_norm"]),
                            mean_return=float(np.mean(returns)) if returns else float("nan"),
                        )
                        obs_run.periodic(
                            learn_steps, frames,
                            replay_occupancy=round(stored / cfg.memory_capacity, 4),
                        )
                    if cfg.eval_interval and learn_steps % cfg.eval_interval == 0:
                        metrics.log("eval", step=learn_steps, **_eval(cfg, env, ts))
                    if cfg.checkpoint_interval and learn_steps % cfg.checkpoint_interval == 0:
                        ckpt.save(learn_steps, ts, {"frames": frames})
                        _save_replay(cfg, ds)

    finally:
        obs_run.close(learn_steps, frames)
    final_eval = _eval(cfg, env, ts)
    metrics.log("eval", step=learn_steps, **final_eval)
    ckpt.save(learn_steps, ts, {"frames": frames})
    _save_replay(cfg, ds)
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": learn_steps,
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }


def _eval(cfg: Config, env, ts) -> Dict[str, Any]:
    from rainbow_iqn_apex_tpu.eval import evaluate_state

    return evaluate_state(cfg, env, ts, seed=cfg.seed + 977)


# ---------------------------------------------------------------------------
# Fully fused Anakin: the ENV inside the graph (jaxgame:* pure-JAX games)
# ---------------------------------------------------------------------------


def build_fused_segment(cfg: Config, game, replay: DeviceReplay, learn_fn):
    """The fused Anakin program: a jitted (carry, key) -> (carry, outs)
    scanning `cfg.anakin_segment_ticks` ticks of
    act -> env.step -> replay.append -> lax.cond(warm, k x learn).

    carry = (ts, ds, env_states, ep_returns, stack, frame, keep, frames);
    outs = per-tick (ep_return [L] NaN-except-on-cut, loss/q_mean/grad_norm
    [learns_per_tick] NaN-when-cold).  `learn_fn` is either the single-chip
    `build_device_learn` graph or the mesh-sharded
    `build_device_learn_sharded` one — the tick body is identical, which is
    what lets the trainer, the multichip dryrun, and the TPU capture harness
    share this exact program."""
    from rainbow_iqn_apex_tpu.envs.device_games import batched_reset_step

    lanes = cfg.num_envs_per_actor
    learns_per_tick = lanes // cfg.frames_per_learn
    seg = replay.seg
    act_fn = build_act_step(cfg, game.num_actions, use_noise=True)
    env_step = batched_reset_step(game)
    bw = cfg.priority_weight

    def tick(carry, k):
        ts, ds, env_s, ep, stack, frame, keep, frames = carry
        ka, ks, kl = jax.random.split(k, 3)
        stack = shift_stack(stack, frame, keep)
        actions, _q = act_fn(ts.params, stack, ka)
        env_s, ep, nframe, reward, term, trunc, out_ret = env_step(
            env_s, ep, actions, ks
        )
        # the completed transition, appended the same tick (the host loop's
        # lag-one bookkeeping exists only because its env stepped off-device)
        ds = replay.append(ds, frame, actions, reward, term, trunc)
        frames = frames + lanes

        stored = jnp.minimum(ds.filled, seg) * lanes
        warm = (stored >= cfg.learn_start) & (ds.filled > cfg.multi_step)
        beta = jnp.float32(
            bw + (1.0 - bw) * jnp.minimum(frames / float(cfg.t_max), 1.0)
        )

        def do_learn(args):
            ts, ds = args

            def one(c, kk):
                ts, ds = c
                ts, ds, info = learn_fn(ts, ds, kk, beta)
                return (ts, ds), (info["loss"], info["q_mean"], info["grad_norm"])

            (ts, ds), infos = jax.lax.scan(
                one, (ts, ds), jax.random.split(kl, learns_per_tick)
            )
            return ts, ds, infos

        def no_learn(args):
            ts, ds = args
            nanv = jnp.full((learns_per_tick,), jnp.nan, jnp.float32)
            return ts, ds, (nanv, nanv, nanv)

        ts, ds, infos = jax.lax.cond(warm, do_learn, no_learn, (ts, ds))
        keep = (~(term | trunc)).astype(jnp.uint8)
        out = (out_ret, infos[0], infos[1], infos[2])
        return (ts, ds, env_s, ep, stack, nframe, keep, frames), out

    @functools.partial(jax.jit, donate_argnums=(0,))
    def segment(carry, key):
        return jax.lax.scan(tick, carry, jax.random.split(key, cfg.anakin_segment_ticks))

    return segment


def build_fused_eval(cfg: Config, game, episodes: int, max_ticks: int = 1024):
    """In-graph evaluation: `episodes` parallel lanes played greedily (noise
    OFF, per-tick tau draws as in eval.py) for up to `max_ticks` — one
    jitted (params, key) -> returns call instead of per-step host dispatches
    through the Env adapter.  Built on the shared rollout core
    (envs/device_games.build_rollout): each lane scores its FIRST episode,
    with capped-return semantics at the tick budget."""
    from rainbow_iqn_apex_tpu.envs.device_games import build_rollout

    act_fn = build_act_step(cfg, game.num_actions, use_noise=False)

    def action_fn(params, states, stack, key):
        actions, _q = act_fn(params, stack, key)
        return actions

    return build_rollout(game, action_fn, episodes, max_ticks,
                         history=cfg.history_length)


def fused_eval_scores(eval_fn, params, key) -> Dict[str, Any]:
    """Host-side summary of build_fused_eval's output, with the same keys as
    eval.evaluate (so metrics rows are interchangeable)."""
    scores = np.asarray(eval_fn(params, key))
    return {
        "episodes": int(len(scores)),
        "score_mean": float(scores.mean()),
        "score_median": float(np.median(scores)),
        "score_min": float(scores.min()),
        "score_max": float(scores.max()),
    }


def init_fused_carry(cfg: Config, game, replay: DeviceReplay, ts, ds, key,
                     frames: int = 0):
    """Fresh lane states + empty device stack for build_fused_segment."""
    from rainbow_iqn_apex_tpu.envs.device_games import batched_init

    lanes = cfg.num_envs_per_actor
    h, w = game.frame_shape
    env_s = batched_init(game, key, lanes)
    ep = jnp.zeros(lanes)
    stack = jnp.zeros((lanes, h, w, cfg.history_length), jnp.uint8)
    frame = jax.vmap(game.render)(env_s)
    keep = jnp.ones(lanes, jnp.uint8)
    return (ts, ds, env_s, ep, stack, frame, keep, jnp.int32(frames))


def train_anakin_fused(cfg: Config, max_frames: Optional[int] = None) -> Dict[str, Any]:
    """Everything on chip: act -> env.step -> replay.append -> (learn x k),
    scanned over `anakin_segment_ticks` ticks per dispatch.

    This is the Podracer/Anakin topology proper — the reference's whole
    actor+learner+Redis loop (SURVEY §3.1-3.2) collapses into ONE jitted
    program; host traffic is a handful of scalars per segment for metrics.
    Semantics kept from the host anakin path: same IQN learn graph, same
    max-priority fresh insertion, same two-channel terminal/truncation cuts,
    same beta anneal (computed in-graph from the frame counter), learning
    gated in-graph on the same warmness rule.  One deliberate deviation: the
    learn cadence is `lanes/frames_per_learn` steps per tick (lanes must divide
    by frames_per_learn), the in-graph form of `frames // frames_per_learn`.
    """
    from rainbow_iqn_apex_tpu.envs.device_games import make_device_game

    total_frames = max_frames or cfg.t_max
    lanes = cfg.num_envs_per_actor
    if lanes % cfg.frames_per_learn:
        raise ValueError(
            f"fused anakin needs lanes ({lanes}) divisible by frames_per_learn "
            f"({cfg.frames_per_learn}) — the learn cadence is in-graph"
        )
    T = cfg.anakin_segment_ticks
    game = make_device_game(cfg.env_id.split(":", 1)[1])
    h, w = game.frame_shape
    if cfg.memory_capacity % lanes:
        raise ValueError(
            f"memory capacity {cfg.memory_capacity} not divisible by {lanes} lanes"
        )
    seg = cfg.memory_capacity // lanes
    replay = DeviceReplay(
        lanes=lanes, seg=seg, frame_shape=(h, w),
        history=cfg.history_length, n_step=cfg.multi_step, gamma=cfg.gamma,
        priority_exponent=cfg.priority_exponent, priority_eps=cfg.priority_eps,
    )
    key = jax.random.PRNGKey(cfg.seed)
    key, k_init, k_env = jax.random.split(key, 3)
    ts = init_train_state(
        cfg, game.num_actions, k_init, state_shape=(h, w, cfg.history_length)
    )

    # multi-device: one dp mesh; env lanes + HBM replay lane-sharded over it,
    # learn dp-sharded with per-shard draws (build_device_learn_sharded) —
    # the env/act/append half needs no collectives, so GSPMD shards it from
    # the lane-dim placements alone.  learner_devices follows the config
    # contract: 0 = all visible devices (anakin has no separate actor mesh).
    n_dev = cfg.learner_devices if cfg.learner_devices > 0 else len(jax.devices())
    mesh = None
    if n_dev > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from rainbow_iqn_apex_tpu.replay.device import (
            build_device_learn_sharded,
            device_replay_shardings,
        )

        if lanes % n_dev or cfg.batch_size % n_dev:
            raise ValueError(
                f"fused anakin over {n_dev} devices needs lanes ({lanes}) and "
                f"batch ({cfg.batch_size}) divisible by the device count"
            )
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("dp",))
        local_replay = DeviceReplay(
            lanes=lanes // n_dev, seg=seg, frame_shape=(h, w),
            history=cfg.history_length, n_step=cfg.multi_step, gamma=cfg.gamma,
            priority_exponent=cfg.priority_exponent, priority_eps=cfg.priority_eps,
        )
        learn_fn = build_device_learn_sharded(cfg, game.num_actions,
                                              local_replay, mesh)
        _lane = NamedSharding(mesh, P("dp"))
        _rep = NamedSharding(mesh, P())

        def place(carry):
            ts, ds, env_s, ep, stack, frame, keep, frames = carry
            lane_tree = jax.tree.map(lambda x: jax.device_put(x, _lane),
                                     (env_s, ep, stack, frame, keep))
            return (
                jax.device_put(ts, _rep),
                jax.device_put(ds, device_replay_shardings(mesh)),
                *lane_tree,
                jax.device_put(frames, _rep),
            )
    else:
        learn_fn = build_device_learn(cfg, game.num_actions, replay)
        place = lambda carry: carry  # noqa: E731

    segment = build_fused_segment(cfg, game, replay, learn_fn)

    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(os.path.join(run_dir, "metrics.jsonl"), cfg.run_id)
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))
    obs_run = RunObs(cfg, metrics, role="learner")

    frames = 0
    ds = replay.init_state()
    restored = maybe_resume(cfg, ckpt, ts)
    if restored is not None:
        ts, extra, _ = restored
        frames = int(extra.get("frames", 0))
        # replay snapshot only on an actual resume (host-path parity): a
        # fresh run with the same run_id must cold-start its ring
        ds, _ = _maybe_restore_replay(cfg, ds)
        metrics.log("resume", step=int(ts.step), frames=frames)
    learn_steps = int(ts.step)

    carry = place(init_fused_carry(cfg, game, replay, ts, ds, k_env, frames))

    # eval is in-graph too: greedy lanes scanned on device, one dispatch
    from rainbow_iqn_apex_tpu.envs.device_games import tick_budget

    game_name = cfg.env_id.split(":", 1)[1]
    eval_fn = build_fused_eval(
        cfg, game, cfg.eval_episodes, max_ticks=tick_budget(game_name, 1024)
    )

    def run_eval(params, step_no: int) -> Dict[str, Any]:
        # deterministic per eval point (bit-reproducible curves, as eval.py)
        k = jax.random.fold_in(jax.random.PRNGKey(cfg.seed + 977), step_no)
        return fused_eval_scores(eval_fn, params, k)

    returns: collections.deque = collections.deque(maxlen=100)

    def crossed(interval: int, before: int, after: int) -> bool:
        return interval > 0 and before // interval != after // interval

    try:
        while frames < total_frames:
            key, k = jax.random.split(key)
            with obs_run.span("segment", ticks=T):
                carry, (out_ret, loss, q_mean, grad_norm) = segment(carry, k)
                ts, ds = carry[0], carry[1]
                frames += T * lanes
                prev_steps = learn_steps
                learn_steps = int(ts.step)  # in-graph counter is authoritative
            # the segment IS the dispatch unit here; the int(ts.step) readback
            # above already synced, so the lap needs no extra block
            obs_run.after_learn_step(learn_steps)
            for r in np.asarray(out_ret)[~np.isnan(np.asarray(out_ret))]:
                returns.append(float(r))

            if crossed(cfg.metrics_interval, prev_steps, learn_steps):
                l = np.asarray(loss)
                metrics.log(
                    "learn",
                    step=learn_steps,
                    frames=frames,
                    fps=metrics.fps(frames),
                    loss=float(np.nanmean(l)) if np.any(~np.isnan(l)) else float("nan"),
                    q_mean=float(np.nanmean(np.asarray(q_mean)))
                    if np.any(~np.isnan(np.asarray(q_mean))) else float("nan"),
                    grad_norm=float(np.nanmean(np.asarray(grad_norm)))
                    if np.any(~np.isnan(np.asarray(grad_norm))) else float("nan"),
                    mean_return=float(np.mean(returns)) if returns else float("nan"),
                )
                obs_run.periodic(learn_steps, frames)
            if crossed(cfg.eval_interval, prev_steps, learn_steps):
                metrics.log("eval", step=learn_steps,
                            **run_eval(carry[0].params, learn_steps))
            if crossed(cfg.checkpoint_interval, prev_steps, learn_steps):
                ckpt.save(learn_steps, ts, {"frames": frames})
                _save_replay(cfg, ds)

    finally:
        obs_run.close(learn_steps, frames)
    final_eval = run_eval(carry[0].params, learn_steps)
    metrics.log("eval", step=learn_steps, **final_eval)
    ckpt.save(learn_steps, ts, {"frames": frames})
    _save_replay(cfg, ds)
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": learn_steps,
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }
