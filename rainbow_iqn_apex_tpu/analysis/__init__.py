"""analysis/ — house-invariant static analyzers (docs/OBSERVABILITY.md
"Static invariants").

Four stdlib-``ast`` analyzers over the whole package, run as a tier-1 test
and via ``make static-smoke`` / ``scripts/static_analysis.py``:

  locks.py          lock discipline for thread-shared attribute writes
                    (+ the ``*_locked`` caller-holds-the-lock contract)
  hostsync_lint.py  the utils/hostsync.py forbidden set declared statically
  imports.py        jax-free import claims, transitively verified
  configcheck.py    cfg.* reads vs Config fields, emitted row kinds vs
                    obs/schema.py + the docs row-kind table, default-off
                    flag families, and backticked ``cfg.<name>`` doc refs

core.py is the shared finding/pragma/baseline framework; runner.py composes
the full-package run against the checked-in (empty) baseline.txt.

Exports resolve lazily (PEP 562, the house pattern) and every submodule
imports jax-free — imports.py verifies that about this package itself.
"""

from typing import TYPE_CHECKING

_EXPORTS = {
    "Finding": "rainbow_iqn_apex_tpu.analysis.core",
    "load_baseline": "rainbow_iqn_apex_tpu.analysis.core",
    "render_report": "rainbow_iqn_apex_tpu.analysis.core",
    "run_all": "rainbow_iqn_apex_tpu.analysis.runner",
    "BASELINE_PATH": "rainbow_iqn_apex_tpu.analysis.runner",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)


def __dir__():
    return __all__
