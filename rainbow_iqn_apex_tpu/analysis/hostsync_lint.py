"""Host-sync static lint (id ``host-sync``).

The runtime half of this invariant lives in ``utils/hostsync.py``:
``forbid_host_sync()`` makes a blocking device->host materialization raise
on the guarded thread, and tier-1 runs the real train loops under it.  The
runtime guard only sees the paths a test happens to execute; this analyzer
declares the forbidden set STATICALLY — the modules/functions below are the
learner/actor hot path, and inside them every host-materialization shape
(``float()`` / ``int()`` / ``bool()`` on a non-config value, ``.item()``,
``np.asarray`` / ``np.array``, ``jax.device_get``, ``.block_until_ready()``)
must sit inside a ``with hostsync.sanctioned():`` scope or go through the
sanctioned seam calls (``hostsync.to_host`` / ``hostsync.scalar``), which
re-check at runtime.

``np.asarray`` matters even though the runtime guard cannot catch it on the
CPU backend (zero-copy through the buffer protocol below any Python hook —
the hole the hostsync docstring records): statically it is just a call
node, so the lint closes exactly the gap the runtime guard leaves open.

False-positive escape: ``# host-sync-ok: <reason>`` on (or directly above)
the call line; the reason is mandatory.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Tuple

from rainbow_iqn_apex_tpu.analysis.core import (
    Finding,
    SourceModule,
    apply_pragmas,
    dotted_name,
)

ANALYZER = "host-sync"

# The statically-declared hot path: module -> qualname prefixes ("*" = the
# whole module).  This is the utils/hostsync.py forbidden set written down:
# the write-back ring and the device sample frontier run inside the
# zero-sync learner loop wholesale; the drivers/agents contribute their
# act/learn/step surfaces (their cold paths — restore, eval, checkpoint —
# stay out, matching where forbid_host_sync() actually brackets them).
HOT_PATH: Dict[str, Sequence[str]] = {
    "rainbow_iqn_apex_tpu/utils/writeback.py": ("*",),
    "rainbow_iqn_apex_tpu/replay/frontier.py": ("*",),
    "rainbow_iqn_apex_tpu/agents/agent.py": (
        "Agent.act",
        "Agent.learn",
        "Agent.learn_batch",
        "Agent.step",
        "FrameStacker.push",
        "put_frames",
        "to_device_batch",
    ),
    # the wire replay plane's learner-side hot path: decode/gather of
    # pipelined sample batches and the write-back routing math both run
    # inside the zero-sync learn loop, so their host materializations must
    # sit under sanctioned() exactly like the frontier's gathers
    "rainbow_iqn_apex_tpu/replay/net/client.py": (
        "SampleClient.get",
        "SampleClient._decode_reply",
        "SampleClient.update_priorities",
    ),
    "rainbow_iqn_apex_tpu/parallel/apex.py": (
        "ActorPriorityEstimator.push",
        "ApexDriver.act",
        "ApexDriver.act_async",
        "ApexDriver.act_frames",
        "ApexDriver.act_local",
        "ApexDriver.learn",
        "ApexDriver.learn_batch",
        "ApexDriver.learn_local",
        "ApexDriver.step",
    ),
}

_SYNC_NAME_CALLS = frozenset({"float", "int", "bool"})
_SYNC_ATTR_CALLS = frozenset({"item", "block_until_ready"})
_SYNC_DOTTED = frozenset(
    {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
     "jax.device_get"}
)
# arguments float()/int()/bool() may legally take in a hot function: config
# reads and host-side bookkeeping that never touch a device value
_CFG_ROOTS = frozenset({"cfg", "config", "_cfg", "_config", "args"})
_HOST_CALL_LEAVES = frozenset({"len", "time", "monotonic", "perf_counter",
                               "scalar", "to_host"})
# builtins that stay host-side when their arguments do
_HOST_FOLD_LEAVES = frozenset({"max", "min", "abs", "round", "len"})
_SCALAR_ANNOTATIONS = frozenset({"int", "float", "bool"})
_NDARRAY_ANNOTATIONS = frozenset({"np.ndarray", "numpy.ndarray", "ndarray"})


def _param_annotations(fn: ast.AST) -> Dict[str, str]:
    """name -> dotted annotation string for the function's parameters.
    A parameter annotated ``int``/``float``/``bool`` or ``np.ndarray`` is a
    HOST value by declaration — the signature is the hot function's
    contract with its callers, so coercing it is not a device sync."""
    out: Dict[str, str] = {}
    args = getattr(fn, "args", None)
    if args is None:
        return out
    for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
        ann = a.annotation
        if ann is None:
            continue
        # Optional[int] declares the same host contract as int
        if isinstance(ann, ast.Subscript) and (
            dotted_name(ann.value) or ""
        ).rsplit(".", 1)[-1] == "Optional":
            ann = ann.slice
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            out[a.arg] = ann.value
        else:
            name = dotted_name(ann)
            if name:
                out[a.arg] = name
    return out


def _is_sanctioned_with(node: ast.With) -> bool:
    for item in node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func) or ""
            if name.rsplit(".", 1)[-1] == "sanctioned":
                return True
    return False


def _benign_scalar_arg(arg: ast.AST, params: Dict[str, str]) -> bool:
    """True when float()/int()/bool() is over a value that cannot be a
    device array: literals, config attribute reads, len()/clock calls,
    parameters ANNOTATED as host scalars, or arithmetic over those."""
    if isinstance(arg, ast.Constant):
        return True
    if isinstance(arg, ast.Name):
        ann = params.get(arg.id, "")
        return ann.rsplit(".", 1)[-1] in _SCALAR_ANNOTATIONS
    if isinstance(arg, ast.Attribute):
        root = arg
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name) and (
            root.id in _CFG_ROOTS or root.id == "self"
        ):
            # self.<x> scalars are host mirrors by construction in the hot
            # classes (the PR-5 step-mirror pattern); device values live in
            # locals between dispatch and retirement
            return True
        return False
    if isinstance(arg, ast.Call):
        name = dotted_name(arg.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _HOST_CALL_LEAVES:
            return True
        if leaf in _HOST_FOLD_LEAVES:
            return all(_benign_scalar_arg(a, params) for a in arg.args)
        if leaf == "getattr" and arg.args:
            first = arg.args[0]
            return isinstance(first, ast.Name) and (
                first.id in _CFG_ROOTS or first.id == "self"
            )
        return False
    if isinstance(arg, ast.BinOp):
        return _benign_scalar_arg(arg.left, params) and _benign_scalar_arg(
            arg.right, params
        )
    if isinstance(arg, ast.BoolOp):
        return all(_benign_scalar_arg(v, params) for v in arg.values)
    if isinstance(arg, ast.UnaryOp):
        return _benign_scalar_arg(arg.operand, params)
    return False


def _benign_asarray_arg(arg: ast.AST, params: Dict[str, str]) -> bool:
    """np.asarray over a parameter annotated np.ndarray is host->host
    staging (the act-path frame inputs), not a device pull."""
    if isinstance(arg, ast.Name):
        return params.get(arg.id, "") in _NDARRAY_ANNOTATIONS
    if isinstance(arg, ast.UnaryOp):
        return _benign_asarray_arg(arg.operand, params)
    return False


def _match_hot(qualname: str, prefixes: Sequence[str]) -> bool:
    if "*" in prefixes:
        return True
    return any(
        qualname == p or qualname.startswith(p + ".") for p in prefixes
    )


def check_module(
    module: SourceModule, hot_path: Dict[str, Sequence[str]] = None
) -> List[Finding]:
    hot_path = HOT_PATH if hot_path is None else hot_path
    prefixes = hot_path.get(module.path)
    if not prefixes:
        return []

    findings: List[Finding] = []

    def flag(node: ast.Call, what: str, qualname: str) -> None:
        findings.append(
            Finding(
                analyzer=ANALYZER,
                path=module.path,
                line=node.lineno,
                key=f"{ANALYZER}:{module.path}:{qualname}:{what}",
                message=(
                    f"{what} in hot-path function {qualname}() outside a "
                    f"sanctioned() scope — a blocking device->host sync "
                    f"re-serializes the learner pipeline; use "
                    f"hostsync.to_host()/scalar() under sanctioned(), or "
                    f"move the materialization to the ring/drain boundary"
                ),
            )
        )

    def scan_call(
        node: ast.Call, qualname: str, params: Dict[str, str]
    ) -> None:
        func = node.func
        if isinstance(func, ast.Name) and func.id in _SYNC_NAME_CALLS:
            if len(node.args) == 1 and not _benign_scalar_arg(
                node.args[0], params
            ):
                flag(node, f"{func.id}()", qualname)
            return
        if isinstance(func, ast.Attribute):
            if func.attr in _SYNC_ATTR_CALLS:
                flag(node, f".{func.attr}()", qualname)
                return
            name = dotted_name(func)
            if name in _SYNC_DOTTED:
                if node.args and _benign_asarray_arg(node.args[0], params):
                    return
                flag(node, f"{name}()", qualname)

    def visit(
        node: ast.AST,
        stack: Tuple[str, ...],
        sanctioned: bool,
        params: Dict[str, str],
    ) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub = stack + (node.name,)
            sub_params = _param_annotations(node)
            for child in node.body:
                visit(child, sub, sanctioned, sub_params)
            return
        if isinstance(node, ast.ClassDef):
            sub = stack + (node.name,)
            for child in node.body:
                visit(child, sub, sanctioned, {})
            return
        if isinstance(node, ast.With):
            inner = sanctioned or _is_sanctioned_with(node)
            for item in node.items:
                visit(item.context_expr, stack, sanctioned, params)
            for child in node.body:
                visit(child, stack, inner, params)
            return
        if isinstance(node, ast.Call) and not sanctioned:
            qualname = ".".join(stack) if stack else "<module>"
            if _match_hot(qualname, prefixes):
                scan_call(node, qualname, params)
        for child in ast.iter_child_nodes(node):
            visit(child, stack, sanctioned, params)

    for top in module.tree.body:
        visit(top, (), False, {})
    return apply_pragmas(module, findings)
