"""Full-package analyzer run + baseline compare (the tier-1 entry point).

``run_all(repo_root)`` executes every analyzer over its declared scope and
returns the findings NOT grandfathered by the checked-in baseline
(analysis/baseline.txt — shipped empty, so everything fails tier-1).
``scripts/static_analysis.py`` is the CLI; tests/test_analysis.py is the
tier-1 meta-test; ``make static-smoke`` runs both.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from rainbow_iqn_apex_tpu.analysis import (
    configcheck,
    core,
    hostsync_lint,
    imports,
    locks,
    wirecheck,
)
from rainbow_iqn_apex_tpu.analysis.core import Finding

# repo-relative; "empty at merge" — any new finding fails tier-1 rather
# than joining a debt pile
BASELINE_PATH = "rainbow_iqn_apex_tpu/analysis/baseline.txt"

ANALYZER_IDS = (
    locks.ANALYZER,
    hostsync_lint.ANALYZER,
    imports.ANALYZER,
    configcheck.ANALYZER,
    configcheck.DOC_ANALYZER,
    wirecheck.ANALYZER,
)


def run_all(
    repo_root: str,
    analyzers: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
) -> List[Finding]:
    """All findings (baseline-filtered, sorted by path/line).

    ``analyzers`` restricts to a subset of ANALYZER_IDS; ``baseline_path``
    overrides the checked-in baseline (None = the checked-in file,
    "" = no baseline at all)."""
    wanted = set(analyzers) if analyzers is not None else set(ANALYZER_IDS)
    unknown = wanted - set(ANALYZER_IDS)
    if unknown:
        raise ValueError(
            f"unknown analyzer id(s) {sorted(unknown)}; "
            f"valid: {list(ANALYZER_IDS)}"
        )
    findings: List[Finding] = []

    per_module = []
    if locks.ANALYZER in wanted:
        per_module.append(locks.check_module)
    if hostsync_lint.ANALYZER in wanted:
        per_module.append(hostsync_lint.check_module)

    # parse each file ONCE: locks/host-sync scan the package, config-drift
    # additionally scans scripts/ (its soak harnesses emit row kinds)
    need_modules = bool(per_module) or configcheck.ANALYZER in wanted
    modules = []
    if need_modules:
        paths = core.iter_package_files(
            repo_root, subdirs=("rainbow_iqn_apex_tpu", "scripts")
        )
        modules = [core.SourceModule(p, repo_root) for p in paths]
    for module in modules:
        if module.path.startswith("rainbow_iqn_apex_tpu/"):
            for check in per_module:
                findings.extend(check(module))

    if imports.ANALYZER in wanted:
        findings.extend(imports.check_repo(repo_root))
    if configcheck.ANALYZER in wanted:
        findings.extend(configcheck.check_repo(repo_root, modules=modules))
    if configcheck.DOC_ANALYZER in wanted:
        findings.extend(configcheck.check_docs(repo_root))
    if wirecheck.ANALYZER in wanted:
        findings.extend(wirecheck.check_repo(repo_root))

    if baseline_path is None:
        baseline_path = os.path.join(repo_root, BASELINE_PATH)
    baseline = (
        core.load_baseline(baseline_path) if baseline_path else frozenset()
    )
    findings = core.filter_baseline(findings, baseline)
    return sorted(findings, key=lambda f: (f.path, f.line, f.analyzer, f.key))
