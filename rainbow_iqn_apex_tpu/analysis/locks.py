"""Lock-discipline analyzer (id ``lock-discipline``).

The invariant (docs/OBSERVABILITY.md "Static invariants"): in a class that
runs code on more than one thread — it spawns a ``threading.Thread`` whose
target is one of its own methods (or a function nested in one), or it hands
a bound method to another thread as a callback (``add_observer``/
``add_done_callback`` argument, or any ``target=``/``*_fn=``/``*_cb=``/
``*_callback=``/``*_hook=`` keyword, the ``HeartbeatWriter(payload_fn=...)``
shape behind PR 7's heartbeat-payload race) — every attribute that is
WRITTEN both from the thread-entry-reachable method set and from the
public-method-reachable set must be written under a held lock-family
attribute (``with self._lock:`` / ``_swap_lock`` / ``_cv`` ... — any
``with`` whose subject name matches ``lock|cv|cond|mutex``).

Scope rules that keep the signal honest:

- ``__init__`` writes are exempt: construction happens before the object is
  shared (the thread does not exist yet).
- Reachability is the closure of ``self.m()`` calls inside the class, from
  thread entries on one side and from public (non-underscore) methods on
  the other.  A helper reachable from both sides counts on both.
- An attribute written on only one side is single-writer and allowed —
  that is the ``# unlocked-ok:`` story made structural.
- A write site that IS reachable from both sides flags even when it is the
  only site: two threads can race through the same statement.
- The house ``*_locked`` suffix convention (FrontRouter._release_locked,
  ShardedReplay._append_locked, ...) is understood AND enforced: a
  ``*_locked`` method's body counts as lock-held, and in exchange every
  ``self.<name>_locked()`` call site must itself sit inside a held lock
  scope (or inside another ``*_locked`` method) — in EVERY class, threaded
  or not, since the suffix is the documented contract.

Sanctioned exceptions take ``# unlocked-ok: <reason>`` on (or directly
above) the write; a reason is mandatory (analysis/core.py pragma rules).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from rainbow_iqn_apex_tpu.analysis.core import (
    Finding,
    SourceModule,
    apply_pragmas,
    dotted_name,
    self_attr,
)

ANALYZER = "lock-discipline"

# segment-anchored: `_lock`, `_swap_lock`, `_wlock`, `_cv`, `_cond` are
# lock-family; `clock`, `seconds`, `blocked` are NOT (an unanchored match
# would silently exempt racy writes to them from tracking)
_LOCK_NAME_RE = re.compile(
    r"(^|_)[rw]?(lock|cv|cond|mutex)(_|$)", re.IGNORECASE
)
_CALLBACK_KWARG_RE = re.compile(r"(^|_)(fn|cb|callback|target|hook)$")
_CALLBACK_REGISTRARS = frozenset({"add_observer", "add_done_callback"})


def _is_lock_expr(node: ast.AST) -> bool:
    """True for a ``with`` subject that names a lock-family object."""
    if isinstance(node, ast.Call):  # e.g. ``with self._cv_for(x):``
        node = node.func
    name = dotted_name(node)
    if name is None:
        return False
    return bool(_LOCK_NAME_RE.search(name.rsplit(".", 1)[-1]))


class _MethodFacts:
    """Writes / self-calls / thread-entry registrations in one method body
    (nested thread-target functions are split out as pseudo-methods)."""

    def __init__(self, qualname: str, node: ast.AST):
        self.qualname = qualname
        self.node = node
        # attr -> [(lineno, locked)]
        self.writes: Dict[str, List[Tuple[int, bool]]] = {}
        self.self_calls: Set[str] = set()
        self.entries: Set[str] = set()  # methods this body hands to a thread
        self.local_thread_funcs: Set[str] = set()
        # self.<x>_locked() invoked while no lock is held: [(callee, lineno)]
        self.bare_locked_calls: List[Tuple[str, int]] = []

    def add_write(self, attr: str, lineno: int, locked: bool) -> None:
        if _LOCK_NAME_RE.search(attr):
            return  # creating/replacing the lock object itself
        self.writes.setdefault(attr, []).append((lineno, locked))


def _collect_method(
    qualname: str,
    fn: ast.AST,
    method_names: Set[str],
    split_nested: Optional[Set[str]] = None,
    initial_locked: bool = False,
) -> _MethodFacts:
    """Walk one function body tracking lock scope.  Nested function names in
    ``split_nested`` are skipped (collected separately as pseudo-methods).
    ``initial_locked`` marks a ``*_locked`` method whose caller holds the
    lock by contract."""
    facts = _MethodFacts(qualname, fn)
    split_nested = split_nested or set()

    def record_target(node: ast.AST, lineno: int, locked: bool) -> None:
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                record_target(elt, lineno, locked)
            return
        if isinstance(node, ast.Starred):
            record_target(node.value, lineno, locked)
            return
        attr = self_attr(node)
        if attr is not None:
            facts.add_write(attr, lineno, locked)

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn:
                if node.name in split_nested:
                    return  # its own pseudo-method
                # a plain closure: its writes belong to this method, but a
                # fresh lock scope — the surrounding ``with`` is not held
                # when the closure later runs
                for child in node.body:
                    visit(child, False)
                return
        if isinstance(node, ast.With):
            inner = locked or any(
                _is_lock_expr(item.context_expr) for item in node.items
            )
            for item in node.items:
                visit(item.context_expr, locked)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                record_target(tgt, node.lineno, locked)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if getattr(node, "value", None) is not None or isinstance(
                node, ast.AugAssign
            ):
                record_target(node.target, node.lineno, locked)
        elif isinstance(node, ast.Call):
            _scan_call(node, locked)
        else:
            attr = self_attr(node)
            if attr is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
                facts.add_write(attr, node.lineno, locked)
        for child in ast.iter_child_nodes(node):
            visit(child, locked)

    def _scan_call(call: ast.Call, locked: bool) -> None:
        func_name = dotted_name(call.func) or ""
        leaf = func_name.rsplit(".", 1)[-1]
        if self_attr(call.func) in method_names:
            facts.self_calls.add(call.func.attr)
            if call.func.attr.endswith("_locked") and not locked:
                facts.bare_locked_calls.append((call.func.attr, call.lineno))
        if leaf == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    tgt_attr = self_attr(kw.value)
                    if tgt_attr in method_names:
                        facts.entries.add(tgt_attr)
                    elif isinstance(kw.value, ast.Name):
                        facts.local_thread_funcs.add(kw.value.id)
            return
        # bound methods escaping to another thread's context
        if leaf in _CALLBACK_REGISTRARS:
            for arg in call.args:
                if self_attr(arg) in method_names:
                    facts.entries.add(arg.attr)
        for kw in call.keywords:
            if (
                kw.arg
                and _CALLBACK_KWARG_RE.search(kw.arg)
                and self_attr(kw.value) in method_names
            ):
                facts.entries.add(kw.value.attr)

    for child in fn.body:
        visit(child, initial_locked)
    return facts


def _reachable(
    entry: Set[str], facts_by_name: Dict[str, _MethodFacts]
) -> Set[str]:
    seen: Set[str] = set()
    frontier = [m for m in entry if m in facts_by_name]
    while frontier:
        m = frontier.pop()
        if m in seen:
            continue
        seen.add(m)
        for callee in facts_by_name[m].self_calls:
            if callee in facts_by_name and callee not in seen:
                frontier.append(callee)
    return seen


def _analyze_class(module: SourceModule, cls: ast.ClassDef) -> List[Finding]:
    methods = {
        n.name: n
        for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    method_names = set(methods)
    facts_by_name: Dict[str, _MethodFacts] = {}

    # first pass: find nested functions used as thread targets per method
    nested_targets: Dict[str, Set[str]] = {}
    for name, fn in methods.items():
        probe = _collect_method(name, fn, method_names)
        nested_defs = {
            n.name
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }
        nested_targets[name] = probe.local_thread_funcs & nested_defs

    thread_entries: Set[str] = set()
    for name, fn in methods.items():
        facts = _collect_method(
            name,
            fn,
            method_names,
            nested_targets[name],
            initial_locked=name.endswith("_locked"),
        )
        facts_by_name[name] = facts
        thread_entries |= facts.entries
        for nested_name in nested_targets[name]:
            for n in ast.walk(fn):
                if (
                    isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and n.name == nested_name
                ):
                    pseudo = f"{name}.<{nested_name}>"
                    facts_by_name[pseudo] = _collect_method(
                        pseudo, n, method_names
                    )
                    thread_entries.add(pseudo)
                    break

    findings: List[Finding] = []
    # the *_locked call-site contract holds in every class, threaded or not
    for name, facts in facts_by_name.items():
        for callee, lineno in facts.bare_locked_calls:
            findings.append(
                Finding(
                    analyzer=ANALYZER,
                    path=module.path,
                    line=lineno,
                    key=f"{ANALYZER}:{module.path}:{cls.name}:"
                    f"{name}->{callee}",
                    message=(
                        f"{cls.name}.{name}() calls self.{callee}() without "
                        f"a held lock — the _locked suffix is the "
                        f"caller-holds-the-lock contract"
                    ),
                )
            )

    if not thread_entries:
        return findings

    thread_side = _reachable(thread_entries, facts_by_name)
    public = {
        m
        for m in facts_by_name
        if not m.startswith("_") and "." not in m
    }
    public_side = _reachable(public, facts_by_name)

    # attr -> write sites per side ( __init__ exempt: pre-sharing )
    def side_writes(side: Set[str]) -> Dict[str, List[Tuple[str, int, bool]]]:
        out: Dict[str, List[Tuple[str, int, bool]]] = {}
        for m in side:
            if m == "__init__":
                continue
            for attr, sites in facts_by_name[m].writes.items():
                for lineno, locked in sites:
                    out.setdefault(attr, []).append((m, lineno, locked))
        return out

    t_writes = side_writes(thread_side)
    p_writes = side_writes(public_side)

    for attr in sorted(set(t_writes) & set(p_writes)):
        sites = {
            (m, lineno, locked)
            for m, lineno, locked in t_writes[attr] + p_writes[attr]
        }
        for m, lineno, locked in sorted(sites, key=lambda s: s[1]):
            if locked:
                continue
            findings.append(
                Finding(
                    analyzer=ANALYZER,
                    path=module.path,
                    line=lineno,
                    key=f"{ANALYZER}:{module.path}:{cls.name}.{attr}:{m}",
                    message=(
                        f"{cls.name}.{attr} is written by both the thread "
                        f"side ({', '.join(sorted(set(s[0] for s in t_writes[attr])))}) "
                        f"and the public side "
                        f"({', '.join(sorted(set(s[0] for s in p_writes[attr])))}); "
                        f"this write in {m}() is not under a self._lock-"
                        f"family lock"
                    ),
                )
            )
    return findings


def check_module(module: SourceModule) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(_analyze_class(module, node))
    return apply_pragmas(module, findings)
