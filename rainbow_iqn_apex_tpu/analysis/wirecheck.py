"""Wire-schema drift checker (id ``wire-drift``).

The replay plane's wire format is defined in FOUR places that must agree
or peers desync at runtime in ways no unit test of either side catches:

1. **codec ceilings**: ``replay/net/protocol.py WIRE_CODEC_MAX`` (what the
   server's piggyback advertises and the client caps negotiation at) must
   equal ``netcore/framing.py CODECS["replay_batch"]`` (the one registry
   of wire codec versions), and ``CODECS["frame"]`` must equal
   ``framing.FRAME_VERSION_MAX`` (the envelope version the reader
   accepts).  A bumped codec that misses the registry ships frames peers
   were never told to expect.
2. **encoding table**: ``protocol.V2_ENCODINGS`` (the declared v2 column
   encodings — the wire contract) must exactly match the keys of
   ``protocol._V2_DECODERS`` (what decode actually handles).  An encoder
   without a decoder corrupts every batch that picks it; a decoder without
   a declaration is dead wire surface.
3. **op sets**: the request ops `ReplayShardServer._handle` dispatches on
   must exactly equal ``protocol.OPS`` (the declared request surface), and
   every ``{"op": ...}`` request the client builds must be declared there
   too.  A handled-but-undeclared op is protocol drift; a declared-but-
   unhandled one is a client-visible ``rerr`` waiting to happen.
4. **shm preamble**: both magics in ``replay/net/shm.py`` must be exactly
   8 bytes (the ``>8sQ`` preamble struct) — a resized magic would shift
   the flags word and silently mis-negotiate every same-host dial.

Everything is stdlib-``ast`` extraction (the configcheck pattern — no
package imports), so drift is caught even when the modules no longer
import.  No inline pragma: wire drift has no legitimate "on purpose" —
an emergency lands via an explicit ``baseline.txt`` line instead.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, List, Optional, Tuple

from rainbow_iqn_apex_tpu.analysis.core import Finding

ANALYZER = "wire-drift"

FRAMING_PATH = "rainbow_iqn_apex_tpu/netcore/framing.py"
PROTOCOL_PATH = "rainbow_iqn_apex_tpu/replay/net/protocol.py"
SERVER_PATH = "rainbow_iqn_apex_tpu/replay/net/server.py"
CLIENT_PATH = "rainbow_iqn_apex_tpu/replay/net/client.py"
SHM_PATH = "rainbow_iqn_apex_tpu/replay/net/shm.py"


def _module_consts(tree: ast.Module) -> Dict[str, Tuple[Any, int]]:
    """name -> (value, lineno) for module-level assignments that resolve
    to literals — including dicts/tuples whose values are earlier
    module-level names (the ``CODECS = {"frame": FRAME_VERSION_MAX}``
    shape)."""
    out: Dict[str, Tuple[Any, int]] = {}

    def resolve(node: ast.AST) -> Any:
        if isinstance(node, ast.Name) and node.id in out:
            return out[node.id][0]
        if isinstance(node, ast.Dict):
            return {resolve(k): resolve(v)
                    for k, v in zip(node.keys, node.values)}
        if isinstance(node, (ast.Tuple, ast.List)):
            vals = [resolve(e) for e in node.elts]
            return tuple(vals) if isinstance(node, ast.Tuple) else vals
        return ast.literal_eval(node)  # constants; raises on the rest

    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            if isinstance(stmt.target, ast.Name):
                targets = [stmt.target]
        if not targets:
            continue
        try:
            value = resolve(stmt.value)
        except (ValueError, TypeError, SyntaxError, KeyError):
            continue
        for t in targets:
            out[t.id] = (value, stmt.lineno)
    return out


def _dict_keys_lineno(tree: ast.Module, name: str
                      ) -> Tuple[Optional[Tuple[str, ...]], int]:
    """Keys of a module-level ``name = {...}`` dict whose VALUES need not
    be literals (the ``_V2_DECODERS`` shape: values are function names)."""
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in stmt.targets):
            continue
        if isinstance(stmt.value, ast.Dict):
            try:
                return (tuple(ast.literal_eval(k) for k in stmt.value.keys),
                        stmt.lineno)
            except (ValueError, TypeError):
                return None, stmt.lineno
    return None, 1


def _compared_ops(tree: ast.Module, func: str = "_handle"
                  ) -> Dict[str, int]:
    """op literal -> first lineno, from every ``op == "x"`` /
    ``op in ("x", ...)`` comparison against a name called ``op`` INSIDE
    the function named ``func`` — the server's wire dispatch.  (The
    memory-worker loop dispatches internal ops like ``refill`` too;
    those never ride a frame and are deliberately out of scope.)"""
    scope: ast.AST = tree
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func:
            scope = node
            break
    out: Dict[str, int] = {}
    for node in ast.walk(scope):
        if not (isinstance(node, ast.Compare)
                and isinstance(node.left, ast.Name)
                and node.left.id == "op" and len(node.ops) == 1):
            continue
        cmp = node.comparators[0]
        lits: List[Any] = []
        if isinstance(node.ops[0], ast.Eq):
            lits = [cmp]
        elif isinstance(node.ops[0], ast.In) and isinstance(
                cmp, (ast.Tuple, ast.List)):
            lits = list(cmp.elts)
        for lit in lits:
            if isinstance(lit, ast.Constant) and isinstance(lit.value, str):
                out.setdefault(lit.value, node.lineno)
    return out


def _request_ops(tree: ast.Module) -> Dict[str, int]:
    """op literal -> first lineno, from every ``{"op": "<x>", ...}`` dict
    the client builds (its request headers)."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if (isinstance(k, ast.Constant) and k.value == "op"
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                out.setdefault(v.value, node.lineno)
    return out


def collect(repo_root: str) -> Dict[str, Any]:
    """Parse the four wire-defining modules into one comparable surface
    (split from `verify` so tests can inject drift without editing
    source files)."""
    trees = {}
    for path in (FRAMING_PATH, PROTOCOL_PATH, SERVER_PATH, CLIENT_PATH,
                 SHM_PATH):
        with open(os.path.join(repo_root, path), encoding="utf-8") as fh:
            trees[path] = ast.parse(fh.read(), filename=path)
    framing_c = _module_consts(trees[FRAMING_PATH])
    protocol_c = _module_consts(trees[PROTOCOL_PATH])
    shm_c = _module_consts(trees[SHM_PATH])
    decoder_keys, decoder_line = _dict_keys_lineno(trees[PROTOCOL_PATH],
                                                   "_V2_DECODERS")
    return {
        "framing_consts": framing_c,
        "protocol_consts": protocol_c,
        "shm_consts": shm_c,
        "decoder_keys": decoder_keys,
        "decoder_line": decoder_line,
        "server_ops": _compared_ops(trees[SERVER_PATH]),
        "client_ops": _request_ops(trees[CLIENT_PATH]),
    }


def verify(surface: Dict[str, Any]) -> List[Finding]:
    findings: List[Finding] = []

    def fail(path: str, line: int, key: str, msg: str) -> None:
        findings.append(Finding(ANALYZER, path, line,
                                f"wire-drift:{key}", msg))

    fr, pr = surface["framing_consts"], surface["protocol_consts"]
    codecs, codecs_line = fr.get("CODECS", ({}, 1))
    # 1a. replay batch codec ceiling vs the registry
    wire_max, wire_line = pr.get("WIRE_CODEC_MAX", (None, 1))
    if codecs.get("replay_batch") != wire_max:
        fail(FRAMING_PATH, codecs_line, "codecs-replay-batch",
             f"CODECS['replay_batch'] = {codecs.get('replay_batch')!r} but "
             f"protocol.WIRE_CODEC_MAX = {wire_max!r} — the codec registry "
             "and the negotiation ceiling disagree")
    # 1b. frame envelope version vs the registry
    fmax, _ = fr.get("FRAME_VERSION_MAX", (None, 1))
    if codecs.get("frame") != fmax:
        fail(FRAMING_PATH, codecs_line, "codecs-frame",
             f"CODECS['frame'] = {codecs.get('frame')!r} but "
             f"FRAME_VERSION_MAX = {fmax!r}")
    # 2. encoder declarations vs decoder table
    encs, encs_line = pr.get("V2_ENCODINGS", (None, 1))
    decs = surface["decoder_keys"]
    if encs is not None and decs is not None and set(encs) != set(decs):
        only_enc = sorted(set(encs) - set(decs))
        only_dec = sorted(set(decs) - set(encs))
        fail(PROTOCOL_PATH, encs_line, "v2-encodings",
             f"V2_ENCODINGS vs _V2_DECODERS drift: declared without a "
             f"decoder {only_enc}, decoded without a declaration "
             f"{only_dec}")
    # 3. op surfaces
    ops, ops_line = pr.get("OPS", ((), 1))
    ops_set = set(ops)
    server_ops = surface["server_ops"]
    for op, line in sorted(server_ops.items()):
        if op not in ops_set:
            fail(SERVER_PATH, line, f"server-op-{op}",
                 f"server dispatches request op {op!r} not declared in "
                 "protocol.OPS")
    for op in sorted(ops_set - set(server_ops)):
        fail(PROTOCOL_PATH, ops_line, f"unhandled-op-{op}",
             f"protocol.OPS declares {op!r} but the server's _handle "
             "never dispatches it")
    for op, line in sorted(surface["client_ops"].items()):
        if op not in ops_set:
            fail(CLIENT_PATH, line, f"client-op-{op}",
                 f"client sends request op {op!r} not declared in "
                 "protocol.OPS")
    # 4. shm preamble shape
    sc = surface["shm_consts"]
    for name in ("MAGIC_REQ", "MAGIC_HELLO"):
        magic, line = sc.get(name, (None, 1))
        if not isinstance(magic, bytes) or len(magic) != 8:
            fail(SHM_PATH, line, f"shm-{name.lower()}",
                 f"shm.{name} must be exactly 8 bytes (the >8sQ preamble "
                 f"struct); got {magic!r}")
    return findings


def check_repo(repo_root: str, modules=None) -> List[Finding]:
    """The runner entry point (``modules`` accepted for signature parity
    with configcheck; the checker parses its own fixed file set)."""
    return verify(collect(repo_root))
