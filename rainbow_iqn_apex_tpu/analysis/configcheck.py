"""Config/schema cross-checker (id ``config-drift``) + doc-reference lint
(id ``doc-drift``).

Four drift classes this repo has paid for by hand:

1. **cfg reads**: every ``cfg.X`` / ``config.X`` / ``self.cfg.X`` attribute
   read in the package must resolve to a declared ``Config`` field (or
   method/property).  A typo'd read of a frozen dataclass only explodes on
   the code path that executes it — statically it is free to catch.
2. **row kinds**: every ``logger.log("<kind>", ...)`` literal emitted in
   the package AND in scripts/ must be registered in
   ``obs/schema.py REQUIRED_KEYS`` (the ONE registry — lint_jsonl and the
   golden-schema test read the same dict) and listed in
   docs/OBSERVABILITY.md's row-kind table.
3. **default-off families**: flags documented as off-by-default gates
   (``league_*``, ``serve_net_*``, ``device_sampling``, ...) must actually
   default to their OFF value — the "no flag set => bitwise the previous
   PR" guarantee tier-1 asserts dynamically, checked at the source.
4. **doc refs** (``doc-drift``): a backticked ``cfg.<name>`` in docs/*.md
   must name a real Config field — the PR-8 "pmap-era" stale-doc incident
   class as a test failure.

Suppression: ``# drift-ok: <reason>`` (code) / ``<!-- drift-ok: reason -->``
on the same line (docs).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from rainbow_iqn_apex_tpu.analysis.core import (
    Finding,
    SourceModule,
    apply_pragmas,
    iter_package_files,
)

ANALYZER = "config-drift"
DOC_ANALYZER = "doc-drift"

CONFIG_PATH = "rainbow_iqn_apex_tpu/config.py"
SCHEMA_PATH = "rainbow_iqn_apex_tpu/obs/schema.py"
OBSERVABILITY_DOC = "docs/OBSERVABILITY.md"

# names that look like ``cfg``-rooted reads
_CFG_NAMES = frozenset({"cfg", "config", "_cfg", "_config"})

# gate fields documented default-off and the OFF value each must hold —
# "no flag set => bitwise the previous PR" (tier-1 asserts it dynamically;
# this pins the source default)
DEFAULT_OFF: Dict[str, object] = {
    "fault_spec": "",
    "trace_dir": "",
    "obs_http_port": 0,
    "trace_sample_every": 0,
    "heartbeat_interval_s": 0.0,
    "max_weight_lag": 0,
    "games": "",
    "device_sampling": False,
    "pipelined_actor": False,
    "serve_quantize": "off",
    "publish_compression": "off",
    "league_dir": "",
    "league_population": 0,
    "league_member_id": -1,
    "serve_net_host": "",
    "serve_net_port": 0,
    "serve_net_advertise": "",
    "serve_net_gossip_port": 0,
    "serve_net_gossip_peers": "",
    "replay_net_host": "",
    "replay_net_port": 0,
    "replay_net_advertise": "",
    "replay_net_remote": False,
    "mesh_shape": "",
    "coordinator_address": "",
    "snapshot_replay": False,
    "resume": "",
    "failover_standby": False,
    "failover_warm": False,
    "obs_net": False,
    "obs_net_host": "",
    "obs_net_port": 0,
    "obs_net_advertise": "",
    "obs_net_http_port": 0,
    "net_chaos_spec": "",
    "lease_skew_tolerance_s": 0.0,
}

_DOC_CFG_RE = re.compile(r"`cfg\.([A-Za-z_][A-Za-z0-9_]*)`")
_DOC_PRAGMA_RE = re.compile(r"<!--\s*drift-ok\s*:\s*\S")
_DOC_KIND_CELL_RE = re.compile(r"^\|\s*`([A-Za-z_][A-Za-z0-9_]*)`\s*\|")


def config_surface(repo_root: str) -> Tuple[Set[str], Dict[str, object]]:
    """(valid attribute names, field -> literal default) from config.py's
    AST — fields, methods, and properties, no import needed."""
    with open(os.path.join(repo_root, CONFIG_PATH), encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=CONFIG_PATH)
    names: Set[str] = set()
    defaults: Dict[str, object] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name
                ):
                    names.add(item.target.id)
                    if isinstance(item.value, ast.Constant):
                        defaults[item.target.id] = item.value.value
                    elif isinstance(item.value, ast.UnaryOp) and isinstance(
                        item.value.operand, ast.Constant
                    ):
                        # e.g. ``league_member_id: int = -1``
                        op = item.value.op
                        v = item.value.operand.value
                        defaults[item.target.id] = (
                            -v if isinstance(op, ast.USub) else v
                        )
                elif isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    names.add(item.name)
    return names, defaults


def registered_kinds(repo_root: str) -> Set[str]:
    """Keys of obs/schema.py REQUIRED_KEYS, read from the AST (one source
    of truth — the same dict lint_jsonl validates against)."""
    with open(os.path.join(repo_root, SCHEMA_PATH), encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=SCHEMA_PATH)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            targets = [node.target.id]
        elif isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "REQUIRED_KEYS" in targets and isinstance(node.value, ast.Dict):
            return {
                k.value
                for k in node.value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
    return set()


def documented_kinds(repo_root: str) -> Set[str]:
    """Backticked first-cell tokens of docs/OBSERVABILITY.md tables."""
    out: Set[str] = set()
    path = os.path.join(repo_root, OBSERVABILITY_DOC)
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            m = _DOC_KIND_CELL_RE.match(line.strip())
            if m:
                out.add(m.group(1))
    return out


def _cfg_reads(module: SourceModule) -> List[Tuple[str, int]]:
    reads: List[Tuple[str, int]] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Attribute):
            continue
        base = node.value
        if isinstance(base, ast.Name) and base.id in _CFG_NAMES:
            reads.append((node.attr, node.lineno))
        elif isinstance(base, ast.Attribute) and base.attr in _CFG_NAMES:
            reads.append((node.attr, node.lineno))
    return reads


def _emitted_kinds(module: SourceModule) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for node in ast.walk(module.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "log"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            out.append((node.args[0].value, node.lineno))
    return out


def check_repo(
    repo_root: str,
    modules: Optional[Sequence[SourceModule]] = None,
    config_path: str = CONFIG_PATH,
) -> List[Finding]:
    """cfg-read + row-kind + default-off checks over the package (and
    scripts/, whose soak harnesses emit row kinds of their own)."""
    if modules is None:
        paths = iter_package_files(
            repo_root, subdirs=("rainbow_iqn_apex_tpu", "scripts")
        )
        modules = [SourceModule(p, repo_root) for p in paths]
    valid, defaults = config_surface(repo_root)
    known = registered_kinds(repo_root)
    documented = documented_kinds(repo_root)

    findings: List[Finding] = []
    for module in modules:
        local: List[Finding] = []
        if module.path != config_path:
            for attr, lineno in _cfg_reads(module):
                if attr.startswith("__") or attr in valid:
                    continue
                local.append(
                    Finding(
                        analyzer=ANALYZER,
                        path=module.path,
                        line=lineno,
                        key=f"{ANALYZER}:{module.path}:cfg.{attr}",
                        message=(
                            f"cfg.{attr} does not resolve to a Config "
                            f"field/method ({config_path})"
                        ),
                    )
                )
        for kind, lineno in _emitted_kinds(module):
            if kind not in known:
                local.append(
                    Finding(
                        analyzer=ANALYZER,
                        path=module.path,
                        line=lineno,
                        key=f"{ANALYZER}:{module.path}:kind.{kind}",
                        message=(
                            f"row kind '{kind}' is emitted here but not "
                            f"registered in obs/schema.py REQUIRED_KEYS — "
                            f"lint_jsonl would reject the run dir"
                        ),
                    )
                )
            elif kind not in documented:
                local.append(
                    Finding(
                        analyzer=ANALYZER,
                        path=module.path,
                        line=lineno,
                        key=f"{ANALYZER}:{module.path}:kind-doc.{kind}",
                        message=(
                            f"row kind '{kind}' is emitted here but missing "
                            f"from the {OBSERVABILITY_DOC} row-kind table"
                        ),
                    )
                )
        findings.extend(apply_pragmas(module, local))

    # default-off families (anchored to config.py's Config class)
    cfg_module = SourceModule(os.path.join(repo_root, config_path), repo_root)
    off_findings: List[Finding] = []
    for field, off_value in sorted(DEFAULT_OFF.items()):
        if field not in valid:
            off_findings.append(
                Finding(
                    analyzer=ANALYZER,
                    path=config_path,
                    line=1,
                    key=f"{ANALYZER}:{config_path}:off-missing.{field}",
                    message=(
                        f"default-off gate '{field}' is declared in the "
                        f"analyzer but no longer a Config field"
                    ),
                )
            )
            continue
        got = defaults.get(field, "<non-literal>")
        if got != off_value or type(got) is not type(off_value):
            off_findings.append(
                Finding(
                    analyzer=ANALYZER,
                    path=config_path,
                    line=1,
                    key=f"{ANALYZER}:{config_path}:off.{field}",
                    message=(
                        f"'{field}' is documented default-off but defaults "
                        f"to {got!r} (expected {off_value!r}) — the "
                        f"no-flag path would no longer be the previous "
                        f"PR's bitwise behaviour"
                    ),
                )
            )
    findings.extend(apply_pragmas(cfg_module, off_findings))
    return findings


def check_docs(
    repo_root: str,
    doc_paths: Optional[Sequence[str]] = None,
    config_path: str = CONFIG_PATH,
) -> List[Finding]:
    """Backticked ``cfg.<name>`` doc references must resolve (doc-drift)."""
    valid, _ = config_surface(repo_root)
    if doc_paths is None:
        docs_dir = os.path.join(repo_root, "docs")
        doc_paths = sorted(
            os.path.join("docs", n)
            for n in os.listdir(docs_dir)
            if n.endswith(".md")
        )
    findings: List[Finding] = []
    for rel in doc_paths:
        with open(os.path.join(repo_root, rel), encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, 1):
                for m in _DOC_CFG_RE.finditer(line):
                    name = m.group(1)
                    if name in valid:
                        continue
                    if _DOC_PRAGMA_RE.search(line):
                        continue
                    findings.append(
                        Finding(
                            analyzer=DOC_ANALYZER,
                            path=rel.replace(os.sep, "/"),
                            line=lineno,
                            key=f"{DOC_ANALYZER}:{rel}:cfg.{name}",
                            message=(
                                f"doc names `cfg.{name}` but Config has no "
                                f"such field ({config_path}) — stale-doc "
                                f"drift (the PR-8 'pmap-era' class)"
                            ),
                        )
                    )
    return findings
