"""jax-free import checker (id ``jax-free``).

Several subsystems promise jax-free IMPORT in their docstrings and lean on
it operationally: respawned actor/league children must start in ~0.3s
(parallel/elastic.py consumers), router front-end processes own no device
(serving/fleet, serving/net), and the offline tooling (obs_report,
relay_watch, lint_jsonl) must run on boxes with no jax install at all.
The PEP-562 lazy package ``__init__``s exist exactly to protect this — and
a single eager ``from .apex import ...`` regression silently re-taints
every consumer (the PR-4 lesson).

This analyzer makes the claim structural: for every module in
``JAX_FREE_MODULES`` (and every lazy package ``__init__`` in
``LAZY_PACKAGE_INITS``), the TRANSITIVE closure of its top-level,
eagerly-executed imports — following package-internal edges — must not
reach ``jax`` (or jaxlib/flax/optax/orbax/chex, which all import jax).
``if TYPE_CHECKING:`` bodies and function-local imports are not eager and
do not count; ``try:`` bodies do (they execute).

The finding message carries the full import chain, so a taint introduced
three modules deep names every hop.  Suppression: ``# jax-ok: <reason>``
on the offending import line.

Self-hosting: ``analysis/*`` is itself in the declared set, and
scripts/obs_report.py + scripts/relay_watch.py are checked through their
repo-relative paths (the ISSUE-14 satellite).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Tuple

from rainbow_iqn_apex_tpu.analysis.core import (
    Finding,
    SourceModule,
    apply_pragmas,
)

ANALYZER = "jax-free"

PACKAGE = "rainbow_iqn_apex_tpu"

# modules that import jax (directly or by construction) — reaching any of
# these eagerly is the violation
_TAINT_ROOTS = ("jax", "jaxlib", "flax", "optax", "orbax", "chex")

# Modules whose docstrings/CHANGES claim jax-free import.  Directories end
# with "/" and mean every .py directly inside (obs/trace.py is the one
# deliberate exception: it IS the jax-facing half of obs/).
JAX_FREE_MODULES: Tuple[str, ...] = (
    "rainbow_iqn_apex_tpu/analysis/",
    "rainbow_iqn_apex_tpu/league/",
    "rainbow_iqn_apex_tpu/obs/__init__.py",
    "rainbow_iqn_apex_tpu/obs/export.py",
    "rainbow_iqn_apex_tpu/obs/health.py",
    "rainbow_iqn_apex_tpu/obs/pipeline_trace.py",
    "rainbow_iqn_apex_tpu/obs/registry.py",
    "rainbow_iqn_apex_tpu/netcore/",
    "rainbow_iqn_apex_tpu/obs/net/",
    "rainbow_iqn_apex_tpu/obs/schema.py",
    "rainbow_iqn_apex_tpu/parallel/elastic.py",
    "rainbow_iqn_apex_tpu/parallel/failover.py",
    "rainbow_iqn_apex_tpu/parallel/sharded_replay.py",
    "rainbow_iqn_apex_tpu/replay/net/",
    "rainbow_iqn_apex_tpu/serving/batcher.py",
    "rainbow_iqn_apex_tpu/serving/fleet/",
    "rainbow_iqn_apex_tpu/serving/metrics.py",
    "rainbow_iqn_apex_tpu/serving/net/",
    "rainbow_iqn_apex_tpu/utils/faults.py",
    "rainbow_iqn_apex_tpu/utils/logging.py",
    "rainbow_iqn_apex_tpu/utils/quantize.py",
    "scripts/lint_jsonl.py",
    "scripts/obs_report.py",
    "scripts/obs_top.py",
    "scripts/relay_watch.py",
)

# PEP-562 lazy package __init__s: importing the PACKAGE must stay jax-free
# (their submodule values may be tainted; eagerly importing one is the bug)
LAZY_PACKAGE_INITS: Tuple[str, ...] = (
    "rainbow_iqn_apex_tpu/analysis/__init__.py",
    "rainbow_iqn_apex_tpu/league/__init__.py",
    "rainbow_iqn_apex_tpu/netcore/__init__.py",
    "rainbow_iqn_apex_tpu/parallel/__init__.py",
    "rainbow_iqn_apex_tpu/replay/__init__.py",
    "rainbow_iqn_apex_tpu/replay/net/__init__.py",
    "rainbow_iqn_apex_tpu/serving/__init__.py",
    "rainbow_iqn_apex_tpu/serving/fleet/__init__.py",
    "rainbow_iqn_apex_tpu/serving/net/__init__.py",
    "rainbow_iqn_apex_tpu/utils/__init__.py",
)


def declared_paths(repo_root: str) -> List[str]:
    """Expand JAX_FREE_MODULES + LAZY_PACKAGE_INITS to concrete files."""
    out = []
    for entry in JAX_FREE_MODULES:
        absd = os.path.join(repo_root, entry)
        if entry.endswith("/"):
            for name in sorted(os.listdir(absd)):
                if name.endswith(".py"):
                    out.append(entry + name)
        else:
            out.append(entry)
    for entry in LAZY_PACKAGE_INITS:
        if entry not in out:
            out.append(entry)
    return sorted(set(out))


def _eager_imports(tree: ast.Module, pkg_dir: str) -> List[Tuple[str, int]]:
    """(module, lineno) for every import executed at import time.
    ``pkg_dir`` is the dotted package of the FILE (for relative imports)."""
    out: List[Tuple[str, int]] = []

    def visit(body) -> None:
        for n in body:
            if isinstance(n, ast.Import):
                out.extend((a.name, n.lineno) for a in n.names)
            elif isinstance(n, ast.ImportFrom):
                mod = n.module or ""
                if n.level:
                    base = pkg_dir
                    for _ in range(n.level - 1):
                        base = base.rsplit(".", 1)[0] if "." in base else ""
                    mod = base + ("." + mod if mod else "")
                out.append((mod, n.lineno))
                # ``from pkg import sub`` / ``from . import sub`` execute
                # the SUBMODULE too when the name resolves to one — the
                # eager edge a lazy package __init__ exists to avoid; the
                # composite either resolves to a real module file or is a
                # plain attribute import and drops out in _module_to_path
                for a in n.names:
                    if a.name != "*":
                        out.append(
                            (f"{mod}.{a.name}" if mod else a.name, n.lineno)
                        )
            elif isinstance(n, ast.If):
                if "TYPE_CHECKING" not in ast.dump(n.test):
                    visit(n.body)
                visit(n.orelse)
            elif isinstance(n, ast.Try):
                visit(n.body)
                for h in n.handlers:
                    visit(h.body)
                visit(n.orelse)
                visit(n.finalbody)
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                visit(n.body)
    visit(tree.body)
    return out


# repo-internal import roots the closure follows (scripts import each
# other as ``from scripts.lint_jsonl import ...``)
_INTERNAL_ROOTS = (PACKAGE, "scripts")


def _module_to_path(repo_root: str, mod: str) -> Optional[str]:
    root = mod.split(".", 1)[0]
    if root not in _INTERNAL_ROOTS:
        return None
    rel = mod.replace(".", "/")
    for cand in (rel + ".py", rel + "/__init__.py"):
        if os.path.isfile(os.path.join(repo_root, cand)):
            return cand
    return None


def _taint_chain(
    repo_root: str,
    rel_path: str,
    cache: Dict[str, Optional[Tuple[Tuple[str, int, str], ...]]],
    visiting: Optional[set] = None,
) -> Tuple[Optional[Tuple[Tuple[str, int, str], ...]], bool]:
    """(chain, complete): the (file, lineno, imported-module) chain from
    ``rel_path`` to the first taint root, or None when the eager import
    closure is jax-free.  ``complete=False`` marks a clean verdict computed
    with an import-cycle edge cut — correct for the traversal ROOT (the cut
    loops back into its own stack) but NOT cacheable for inner nodes, whose
    verdict would otherwise ignore an ancestor's still-pending taint."""
    if rel_path in cache:
        return cache[rel_path], True
    visiting = visiting if visiting is not None else set()
    if rel_path in visiting:
        return None, False  # cycle edge cut: verdict depends on an ancestor
    visiting.add(rel_path)
    abspath = os.path.join(repo_root, rel_path)
    try:
        with open(abspath, "r", encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=rel_path)
    except (OSError, SyntaxError):
        visiting.discard(rel_path)
        cache[rel_path] = None
        return None, True
    pkg_dir = os.path.dirname(rel_path).replace("/", ".")
    result: Optional[Tuple[Tuple[str, int, str], ...]] = None
    complete = True
    for mod, lineno in _eager_imports(tree, pkg_dir):
        root = mod.split(".", 1)[0]
        if root in _TAINT_ROOTS:
            result = ((rel_path, lineno, mod),)
            break
        sub = _module_to_path(repo_root, mod)
        if sub is not None:
            deeper, sub_complete = _taint_chain(
                repo_root, sub, cache, visiting
            )
            if deeper is not None:
                result = ((rel_path, lineno, mod),) + deeper
                break
            complete = complete and sub_complete
    visiting.discard(rel_path)
    if result is not None or complete:
        cache[rel_path] = result
    return result, result is not None or complete


def check_repo(
    repo_root: str, paths: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Run the checker over the declared set (or an explicit path list)."""
    rels = list(paths) if paths is not None else declared_paths(repo_root)
    cache: Dict[str, Optional[Tuple[Tuple[str, int, str], ...]]] = {}
    findings: List[Finding] = []
    for rel in rels:
        chain, _complete = _taint_chain(repo_root, rel, cache)
        if chain is None:
            continue
        hops = " -> ".join(
            f"{p}:{ln} imports {m}" for p, ln, m in chain
        )
        top_line = chain[0][1]
        findings.append(
            Finding(
                analyzer=ANALYZER,
                path=rel,
                line=top_line,
                key=f"{ANALYZER}:{rel}:{chain[-1][2].split('.', 1)[0]}",
                message=(
                    f"{rel} claims jax-free import but eagerly reaches "
                    f"{chain[-1][2]}: {hops}"
                ),
            )
        )
    # pragma filtering needs each module's comments
    out: List[Finding] = []
    for f in findings:
        try:
            module = SourceModule(os.path.join(repo_root, f.path), repo_root)
        except (OSError, SyntaxError):
            out.append(f)
            continue
        out.extend(apply_pragmas(module, [f]))
    return out
