"""Shared finding/report/baseline framework for the house static analyzers.

The analyzers (docs/OBSERVABILITY.md "Static invariants") machine-enforce
invariants this repo previously re-derived by hand in every review round:
lock discipline around thread-shared attributes (analysis/locks.py), the
host-sync ban in hot-path functions (analysis/hostsync_lint.py), jax-free
import claims (analysis/imports.py), and config/schema/doc drift
(analysis/configcheck.py).  Everything here is stdlib-``ast`` based and
imports jax-free — the jax-free checker verifies that about this package
itself (the self-hosting check).

Shared machinery:

- ``Finding``: one violation — analyzer id, file:line, a line-number-free
  ``key`` (stable across unrelated edits) used for baseline matching, and a
  human reason.
- Pragmas: each analyzer has a suppression comment tag (``unlocked-ok``,
  ``host-sync-ok``, ``jax-ok``, ``drift-ok``).  A pragma ONLY counts with a
  reason after the colon — ``# unlocked-ok:`` alone is itself a finding
  (``pragma-reason``), so every suppression is reviewable.
- Baseline: a checked-in file of finding keys (one per line, ``#`` comments
  allowed).  It ships EMPTY — any finding anywhere fails tier-1 — and
  exists so an emergency can land with an explicitly-listed debt line
  rather than by weakening an analyzer.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence

# analyzer id -> suppression pragma tag (written ``# <tag>: <reason>`` on
# the offending line or the line directly above it)
PRAGMA_TAGS: Dict[str, str] = {
    "lock-discipline": "unlocked-ok",
    "host-sync": "host-sync-ok",
    "jax-free": "jax-ok",
    "config-drift": "drift-ok",
    "doc-drift": "drift-ok",
}

# the colon is REQUIRED: "# unlocked-ok racy on purpose" (colon forgotten)
# is not a pragma at all — the finding stays live, pointing at the typo
_PRAGMA_RE = re.compile(r"#\s*(?P<tag>[a-z][a-z-]*-ok)\s*:\s*(?P<reason>.*)$")


@dataclasses.dataclass(frozen=True)
class Finding:
    analyzer: str  # id, e.g. "lock-discipline"
    path: str  # repo-relative, forward slashes
    line: int  # 1-based
    key: str  # stable baseline key: no line numbers, no volatile text
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.analyzer}] {self.message}"


class SourceModule:
    """One parsed source file: text, AST, and per-line pragma index."""

    def __init__(self, path: str, repo_root: str):
        self.abspath = os.path.abspath(path)
        self.path = os.path.relpath(self.abspath, repo_root).replace(os.sep, "/")
        with open(self.abspath, "r", encoding="utf-8") as fh:
            self.text = fh.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.path)
        # line -> (tag, reason) for every pragma-shaped REAL comment —
        # extracted via tokenize, so a docstring/string literal that merely
        # quotes "# host-sync-ok: ..." can never suppress a finding
        self.pragmas: Dict[int, tuple] = {}
        for lineno, comment in self._comments():
            m = _PRAGMA_RE.search(comment)
            if m:
                self.pragmas[lineno] = (m.group("tag"), m.group("reason").strip())

    def _comments(self):
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
            return [
                (tok.start[0], tok.string)
                for tok in tokens
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # unterminated constructs etc: fall back to the raw-line scan
            return list(enumerate(self.lines, 1))

    def pragma_at(self, lineno: int, tag: str) -> Optional[str]:
        """The pragma reason suppressing ``tag`` at ``lineno`` (same line or
        the line directly above), or None.  An empty reason returns ""."""
        for ln in (lineno, lineno - 1):
            got = self.pragmas.get(ln)
            if got is not None and got[0] == tag:
                return got[1]
        return None


def apply_pragmas(
    module: SourceModule, findings: Iterable[Finding]
) -> List[Finding]:
    """Drop findings suppressed by a reasoned pragma; a reason-less pragma
    converts the finding into a ``pragma-reason`` finding instead of
    silencing it."""
    out: List[Finding] = []
    for f in findings:
        tag = PRAGMA_TAGS.get(f.analyzer)
        reason = module.pragma_at(f.line, tag) if tag else None
        if reason is None:
            out.append(f)
        elif not reason:
            out.append(
                Finding(
                    analyzer=f.analyzer,
                    path=f.path,
                    line=f.line,
                    key=f.key + ":pragma-reason",
                    message=(
                        f"pragma '# {tag}:' suppressing [{f.analyzer}] needs "
                        f"a reason after the colon ({f.message})"
                    ),
                )
            )
    return out


def iter_package_files(
    repo_root: str,
    subdirs: Sequence[str] = ("rainbow_iqn_apex_tpu",),
    extra: Sequence[str] = (),
) -> List[str]:
    """Every .py file under the given package subdirs (sorted, repo-relative
    inputs resolved against ``repo_root``), plus explicit extras."""
    paths: List[str] = []
    for sub in subdirs:
        base = os.path.join(repo_root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    paths.append(os.path.join(dirpath, name))
    for rel in extra:
        paths.append(os.path.join(repo_root, rel))
    return sorted(set(paths))


def load_modules(repo_root: str, paths: Iterable[str]) -> List[SourceModule]:
    return [SourceModule(p, repo_root) for p in paths]


# ------------------------------------------------------------------ baseline
def load_baseline(path: str) -> frozenset:
    """Finding keys grandfathered by the checked-in baseline file.  Missing
    file = empty baseline (nothing grandfathered)."""
    if not os.path.exists(path):
        return frozenset()
    keys = set()
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line and not line.startswith("#"):
                keys.add(line)
    return frozenset(keys)


def filter_baseline(
    findings: Iterable[Finding], baseline: frozenset
) -> List[Finding]:
    return [f for f in findings if f.key not in baseline]


def render_report(findings: Sequence[Finding]) -> str:
    """Human report: one line per finding plus a per-analyzer tally."""
    lines = [f.render() for f in findings]
    by_analyzer: Dict[str, int] = {}
    for f in findings:
        by_analyzer[f.analyzer] = by_analyzer.get(f.analyzer, 0) + 1
    tally = ", ".join(f"{k}={v}" for k, v in sorted(by_analyzer.items()))
    lines.append(
        f"static-analysis: {len(findings)} finding(s)"
        + (f" ({tally})" if tally else "")
    )
    return "\n".join(lines)


# --------------------------------------------------------------- AST helpers
def self_attr(node: ast.AST) -> Optional[str]:
    """'x' when node is ``self.x``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """Flatten an attribute chain to 'a.b.c' (None when not a plain chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
