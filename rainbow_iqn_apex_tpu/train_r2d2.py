"""R2D2 training loop: recurrent actor + stored-state sequence replay.

Parity: the reference's R2D2 stretch configuration (BASELINE.json:10,
SURVEY.md §7 step 7).  Mirrors train.py's act/learn interleave, with the
frame-stack replaced by the LSTM state the actor threads through time and
the transition replay replaced by SequenceReplay.
"""

from __future__ import annotations

import collections
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.agents.agent import FrameStacker
from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.envs import make_env, make_vector_env
from rainbow_iqn_apex_tpu.obs import RunObs
from rainbow_iqn_apex_tpu.ops.r2d2 import (
    as_actor_input,
    build_r2d2_act_step,
    build_r2d2_learn_step,
    init_r2d2_state,
    to_device_seq_batch,
)
from rainbow_iqn_apex_tpu.replay.sequence import SequenceReplay
from rainbow_iqn_apex_tpu.train import priority_beta
from rainbow_iqn_apex_tpu.utils.checkpoint import (
    Checkpointer,
    maybe_restore_replay,
    maybe_resume,
    rng_from_extra,
    save_replay_snapshot,
)
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger


class R2D2Agent:
    """Host facade: recurrent act/learn with explicit LSTM state."""

    def __init__(self, cfg: Config, num_actions: int, frame_shape, key, train=True):
        self.cfg = cfg
        self.num_actions = num_actions
        key, k_init = jax.random.split(key)
        self.key = key
        self.state = init_r2d2_state(cfg, num_actions, k_init, frame_shape)
        self._act = jax.jit(build_r2d2_act_step(cfg, num_actions))
        self._act_eval = jax.jit(
            build_r2d2_act_step(cfg, num_actions, use_noise=cfg.eval_noisy)
        )
        self._learn = (
            jax.jit(build_r2d2_learn_step(cfg, num_actions), donate_argnums=0)
            if train
            else None
        )

    def _next_key(self):
        self.key, k = jax.random.split(self.key)
        return k

    def initial_lstm_state(self, batch: int):
        z = jnp.zeros((batch, self.cfg.lstm_size), jnp.float32)
        return (z, z)

    def act(self, obs, lstm_state, eval_mode=False):
        """obs [B, H, W] u8 (history 1) or [B, H, W, hist] stacked ->
        (actions [B], new_state)."""
        fn = self._act_eval if eval_mode else self._act
        x = as_actor_input(obs, self.cfg.history_length)
        a, q, new_state = fn(self.state.params, x, lstm_state, self._next_key())
        return np.asarray(a), new_state

    def learn(self, sample) -> Dict[str, Any]:
        self.state, info = self._learn(
            self.state, to_device_seq_batch(sample), self._next_key()
        )
        return info

    @property
    def step(self) -> int:
        return int(self.state.step)


def _mask_reset(lstm_state, terminals: np.ndarray):
    """Zero the (c, h) rows of lanes whose episode just ended."""
    keep = jnp.asarray(1.0 - terminals.astype(np.float32))[:, None]
    c, h = lstm_state
    return (c * keep, h * keep)


def evaluate_r2d2(cfg: Config, agent: R2D2Agent, episodes: Optional[int] = None,
                  seed: int = 0, max_steps: int = 200_000,
                  env=None) -> Dict[str, Any]:
    """``env`` overrides the cfg.env_id default — the multi-game apex path
    hands in each game's padded GameLaneEnv (docs/MULTITASK.md)."""
    episodes = episodes or cfg.eval_episodes
    env = env if env is not None else make_env(cfg.env_id, seed=seed)
    scores = []
    for _ in range(episodes):
        frame = env.reset()
        state = agent.initial_lstm_state(1)
        stacker = FrameStacker(1, env.frame_shape, cfg.history_length)
        ep_ret = 0.0
        for _ in range(max_steps):
            a, state = agent.act(stacker.push(frame[None]), state, eval_mode=True)
            ts = env.step(int(a[0]))
            frame = ts.obs
            ep_ret += ts.reward
            if ts.terminal or ts.truncated:
                if ts.info and "episode_return" in ts.info:
                    ep_ret = float(ts.info["episode_return"])
                break
        scores.append(ep_ret)
    arr = np.asarray(scores, np.float64)
    return {
        "episodes": episodes,
        "score_mean": float(arr.mean()),
        "score_median": float(np.median(arr)),
        "score_min": float(arr.min()),
        "score_max": float(arr.max()),
    }


def train_r2d2(cfg: Config, max_frames: Optional[int] = None) -> Dict[str, Any]:
    if cfg.replay_ratio > 1:
        raise ValueError(
            "replay_ratio > 1 (clipped replay reuse) is implemented for the "
            "single-process and apex IQN loops; sequence-batch reuse under "
            "LSTM state is the recorded ROADMAP follow-up")
    total_frames = max_frames or cfg.t_max
    lanes = cfg.num_envs_per_actor
    env = make_vector_env(cfg.env_id, lanes, seed=cfg.seed)
    agent = R2D2Agent(
        cfg, env.num_actions, env.frame_shape, jax.random.PRNGKey(cfg.seed)
    )

    seq_total = cfg.r2d2_burn_in + cfg.r2d2_seq_len
    memory = SequenceReplay(
        capacity=max(cfg.memory_capacity // seq_total, 64),
        seq_len=seq_total,
        frame_shape=env.frame_shape,
        lstm_size=cfg.lstm_size,
        lanes=lanes,
        stride=max(seq_total - cfg.r2d2_overlap, 1),
        priority_exponent=cfg.priority_exponent,
        priority_eps=cfg.priority_eps,
        seed=cfg.seed,
    )

    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(os.path.join(run_dir, "metrics.jsonl"), cfg.run_id)
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))
    obs_run = RunObs(cfg, metrics, role="learner")

    frames = 0
    restored = maybe_resume(cfg, ckpt, agent.state)
    if restored is not None:
        agent.state, extra, _ = restored
        frames = int(extra.get("frames", 0))
        agent.key = rng_from_extra(extra, agent.key)
        maybe_restore_replay(cfg, memory)
        metrics.log("resume", step=agent.step, frames=frames)

    obs = env.reset()
    lstm_state = agent.initial_lstm_state(lanes)
    stacker = FrameStacker(lanes, env.frame_shape, cfg.history_length)
    returns: collections.deque = collections.deque(maxlen=100)
    learn_start_seqs = max(cfg.learn_start // seq_total, 8)

    try:
        while frames < total_frames:
            state_c, state_h = np.asarray(lstm_state[0]), np.asarray(lstm_state[1])
            stacked = stacker.push(obs)  # actor sees the frame-stacked input
            with obs_run.span("act"):
                actions, lstm_state = agent.act(stacked, lstm_state)
            new_obs, rewards, terminals, truncs, ep_returns = env.step(actions)
            cuts = terminals | truncs  # truncation ends the sequence window too
            # the replay stores SINGLE frames; the learn step re-stacks on device
            memory.append_batch(
                obs, actions, rewards, terminals, state_c, state_h, truncations=truncs
            )
            lstm_state = _mask_reset(lstm_state, cuts)
            stacker.reset_lanes(cuts)
            obs = new_obs
            frames += lanes
            for r in ep_returns[~np.isnan(ep_returns)]:
                returns.append(float(r))

            if len(memory) >= learn_start_seqs:
                # Cadence normalised to the SAME per-transition reuse as the
                # feedforward path: an IQN step consumes batch_size transitions
                # per frames_per_learn frames; an R2D2 step consumes batch_size
                # sequences x seq_len trained steps, so one learn step per
                # frames_per_learn * seq_len env frames gives identical reuse.
                frames_per_step = cfg.frames_per_learn * cfg.r2d2_seq_len
                steps_due = frames // frames_per_step - agent.step
                for _ in range(max(steps_due, 0)):
                    with obs_run.span("replay_sample"):
                        sample = memory.sample(
                            cfg.batch_size, priority_beta(cfg, frames)
                        )
                    with obs_run.span("learn_step"):
                        info = agent.learn(sample)
                    memory.update_priorities(sample.idx, np.asarray(info["priorities"]))
                    step = agent.step
                    # the priority write-back above already synced on the step's
                    # outputs; a second barrier would be redundant
                    obs_run.after_learn_step(step)
                    if step % cfg.metrics_interval == 0:
                        metrics.log(
                            "learn",
                            step=step,
                            frames=frames,
                            fps=metrics.fps(frames),
                            loss=float(info["loss"]),
                            q_mean=float(info["q_mean"]),
                            mean_return=float(np.mean(returns)) if returns else float("nan"),
                            sequences=len(memory),
                        )
                        obs_run.periodic(step, frames, replay_size=len(memory))
                    if cfg.checkpoint_interval and step % cfg.checkpoint_interval == 0:
                        ckpt.save(step, agent.state, {"frames": frames})
                        save_replay_snapshot(cfg, memory)

    finally:
        obs_run.close(agent.step, frames)
    final_eval = evaluate_r2d2(cfg, agent, seed=cfg.seed + 977)
    metrics.log("eval", step=agent.step, **final_eval)
    ckpt.save(agent.step, agent.state, {"frames": frames})
    save_replay_snapshot(cfg, memory)
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": agent.step,
        "sequences": len(memory),
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }
