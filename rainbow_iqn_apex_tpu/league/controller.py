"""LeagueController: population-based training on the Ape-X substrate.

The controller is the league's only writer of exploit state: it supervises
N member trainer processes (each a `RoleSupervisor` role — respawn with
backoff keeps the SAME member id at epoch+1, eviction after the
FailureBudget), scores them from the eval telemetry they already emit
(league/fitness.py), and runs truncation exploit/explore
(league/exploit.py): bottom-quantile members receive a top-quantile
member's weights bit-exactly over the WeightMailbox int8-delta chain plus
a perturbed/resampled genome, under a monotone per-member generation
counter.

Everything is jax-free and file-backed — the controller is a small loop a
launcher runs next to (or instead of) a learner, and every decision it
takes is reconstructible from its JSONL:

    league row, event="exploit"  one weight copy (loser/winner/generation/
                                 digest/genome)
    league row, event="status"   periodic per-member table: fitness,
                                 generation, exploits/explores received,
                                 restarts, evictions, last copy source
                                 (+ ``collapsed`` when < 2 members remain
                                 alive — RunHealth degrades on it)

`scripts/league_soak.py` drives a real 2-member population end to end;
tests/test_league.py drives this class with fake member processes.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from rainbow_iqn_apex_tpu.league import exploit as exploit_mod
from rainbow_iqn_apex_tpu.league.fitness import (
    FitnessTracker,
    quantile_split,
    rank_members,
)
from rainbow_iqn_apex_tpu.league.population import (
    Genome,
    check_league_config,
    genome_from_config,
    genome_path,
    load_genome,
    perturb_genome,
    save_genome,
)


class MemberRecord:
    """Controller-side view of one member (fitness lives in the tracker)."""

    def __init__(self, member_id: int, genome: Genome, generation: int = 0):
        self.member_id = int(member_id)
        self.genome = genome
        self.generation = int(generation)
        self.exploits = 0  # times this member ADOPTED a winner's weights
        self.explores = 0  # explore steps received (every exploit carries
        # one: the per-gene perturb-or-resample of the winner's genome)
        self.copies_out = 0  # times this member was the SOURCE
        self.last_copy_source: Optional[int] = None
        self.evicted = False


class LeagueController:
    def __init__(
        self,
        cfg,
        spawn_member: Callable[[int, int], Any],
        metrics=None,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
        supervisor=None,
    ):
        """``spawn_member(member_id, epoch)`` returns a process-like object
        (``poll()`` -> rc | None, ``kill()``) running that member's trainer
        — the same contract RoleSupervisor spawns everywhere else."""
        check_league_config(cfg)
        if cfg.league_population < 2:
            raise ValueError(
                f"league_population ({cfg.league_population}) must be >= 2 "
                "to run a controller (docs/LEAGUE.md)")
        self.cfg = cfg
        self.league_dir = cfg.league_dir
        self.metrics = metrics
        self.registry = registry
        self.clock = clock
        self.rng = np.random.default_rng(cfg.seed + 4242)
        self.fitness = FitnessTracker(cfg.league_fitness_window)
        self.exploit_events = 0
        self.exploit_skips = 0
        self._offsets: Dict[str, int] = {}  # member jsonl tail offsets
        self._last_sweep = self.clock()
        # live fleet telemetry (obs/net/): the controller is a device-less
        # role that should still show up on the fleet dashboard — attach a
        # relay to its logger when the plane is on (None otherwise)
        self.obs_relay = None
        if metrics is not None and getattr(cfg, "obs_net", False):
            from rainbow_iqn_apex_tpu.obs.net.relay import ObsRelay

            self.obs_relay = ObsRelay.attach(
                cfg, metrics, registry=registry, role="league")

        os.makedirs(self.league_dir, exist_ok=True)
        # ---- population: resume genomes from disk, else seed them --------
        baseline = genome_from_config(cfg)
        self.members: Dict[int, MemberRecord] = {}
        for i in range(cfg.league_population):
            loaded = load_genome(genome_path(self.league_dir, i))
            if loaded is not None:
                genome, generation = loaded
            else:
                # member 0 keeps the config's own hyperparameters (the
                # operator's hand-picked point stays in the population);
                # the rest start perturbed around it for initial diversity
                genome = baseline if i == 0 else perturb_genome(
                    baseline, self.rng, cfg.league_perturb_factor,
                    cfg.league_resample_prob)
                generation = 0
                save_genome(genome_path(self.league_dir, i), genome,
                            generation, i)
            self.members[i] = MemberRecord(i, genome, generation)

        # ---- supervision: one role per member, role id carries the id ----
        from rainbow_iqn_apex_tpu.parallel.elastic import RoleSupervisor

        self.sup = supervisor or RoleSupervisor.from_config(
            cfg, metrics=metrics, registry=registry, clock=clock)
        for i in range(cfg.league_population):
            self.sup.register(
                self._role(i), self._spawn_fn(spawn_member, i),
                meta={"member": i, "role_host": i})
        self._observe()

    @staticmethod
    def _role(member_id: int) -> str:
        return f"member_m{int(member_id)}"

    def _is_done(self, member_id: int) -> bool:
        try:
            return self.sup.state(self._role(member_id)) == "done"
        except KeyError:
            return False

    def _restarts(self, member_id: int) -> int:
        """The supervisor's per-role restart counter IS the restart count —
        no shadow tally on MemberRecord to drift from it."""
        try:
            return int(self.sup.stats(
                self._role(member_id)).get("restarts", 0))
        except KeyError:
            return 0

    def _spawn_fn(self, spawn_member, member_id: int):
        def spawn(epoch: int):
            return spawn_member(member_id, epoch)

        return spawn

    # --------------------------------------------------------------- obs
    def _row(self, **fields) -> None:
        if self.metrics is not None:
            self.metrics.log("league", **fields)

    def _observe(self) -> None:
        if self.registry is None:
            return
        alive = sum(1 for m in self.members.values() if not m.evicted)
        self.registry.gauge("league_members_alive", "league").set(alive)
        self.registry.gauge("league_exploits_total", "league").set(
            self.exploit_events)

    def alive_members(self) -> List[int]:
        return sorted(m.member_id for m in self.members.values()
                      if not m.evicted)

    def collapsed(self) -> bool:
        """The population degenerated: fewer than 2 members still alive —
        selection has nobody left to select between."""
        return len(self.alive_members()) < 2

    # ------------------------------------------------------------- ingest
    def _ingest_evals(self) -> int:
        """Tail every member's JSONL (anything under league_dir/m<i>/) for
        eval / eval_mt rows; returns rows folded this call.  Offsets are
        per file, so a respawned incarnation's fresh file is picked up."""
        folded = 0
        for m in self.members.values():
            pattern = os.path.join(
                exploit_mod.member_dir(self.league_dir, m.member_id),
                "**", "*.jsonl")
            for path in glob.glob(pattern, recursive=True):
                off = self._offsets.get(path, 0)
                try:
                    with open(path) as f:
                        f.seek(off)
                        while True:
                            line = f.readline()
                            if not line or not line.endswith("\n"):
                                break  # EOF or a row mid-write
                            off = f.tell()
                            try:
                                row = json.loads(line)
                            except ValueError:
                                continue
                            if row.get("kind") in ("eval", "eval_mt"):
                                if self.fitness.note_row(m.member_id, row):
                                    folded += 1
                except OSError:
                    continue
                self._offsets[path] = off
        return folded

    # -------------------------------------------------------- supervision
    def poll(self, step: int = 0) -> List[Dict[str, Any]]:
        """One controller tick: supervise members (respawn keeps the member
        id, eviction is terminal), fold fresh evals, and run an exploit
        sweep when due.  Returns the supervisor events it saw."""
        events = self.sup.poll(step=step)
        for ev in events:
            member = ev.get("member")
            if member is None or member not in self.members:
                continue
            rec = self.members[member]
            if ev["event"] == "actor_respawn":
                # the respawned incarnation re-reads its genome FILE —
                # generation and genome survive member death by design.
                # Refresh the controller's view from the same file: the
                # disk is the single source of truth (a loser may have
                # adopted — and persisted — a generation this controller
                # never planned, e.g. after a controller restart)
                loaded = load_genome(
                    genome_path(self.league_dir, member))
                if loaded is not None:
                    rec.genome, rec.generation = loaded
            elif ev["event"] == "actor_done":
                # clean rc=0 completion (t_max reached): the member keeps
                # its fitness (its outbox still donates weights) but will
                # never adopt again — NOT a crash, NOT a collapse signal
                self._row(event="member_done", member=member, step=step,
                          restarts=self._restarts(member))
            elif ev["event"] == "actor_evicted":
                rec.evicted = True
                # an evicted member's scores must stop shaping the cut
                # lines (a ghost in the top quantile would donate stale
                # weights forever)
                self.fitness.forget(member)
                self._row(event="evicted", member=member, step=step,
                          restarts=self._restarts(member))
        self._ingest_evals()
        if (self.clock() - self._last_sweep
                >= self.cfg.league_exploit_interval_s):
            self.sweep(step=step)
        self._observe()
        return events

    def _refresh_from_disk(self, member_ids: List[int]) -> None:
        """Lift each member's (genome, generation) to the genome FILE's —
        the single source of truth, written by the member at adoption.
        The respawn handler's unconditional re-read can briefly REGRESS
        the in-memory view (a member that crashed before adopting reads
        back the old generation, then adopts the still-pending directive
        and persists the new one); planning the next exploit from the
        stale value would collide with the inbox's monotone-version check
        and wedge the member out of exploitation forever.  Forward-only on
        generation: a pending-unadopted directive legitimately keeps the
        in-memory generation ahead of disk.  EQUAL generations take the
        disk GENOME too — a member only writes a generation it has adopted
        (or clamped at loop start), so at equality disk is authoritative:
        an adoption-time n-step clamp rewrites the genome at the sweep's
        own generation, and without this the controller would report (and
        perturb, re-issuing infeasible directives from) an n_step the
        member never runs."""
        for m in member_ids:
            loaded = load_genome(genome_path(self.league_dir, m))
            if loaded is None:
                continue
            rec = self.members[m]
            genome, generation = loaded
            if generation >= rec.generation:
                rec.genome, rec.generation = genome, generation

    # ------------------------------------------------------------- exploit
    def sweep(self, step: int = 0) -> List[Dict[str, Any]]:
        """One truncation exploit/explore sweep.  Members without fitness
        are excluded on both sides (missing-eval tolerance); a sweep with
        < 2 scored members is a no-op."""
        self._last_sweep = self.clock()
        alive = self.alive_members()
        self._refresh_from_disk(alive)
        ranked = rank_members(self.fitness, alive)
        top, bottom = quantile_split(
            ranked, self.cfg.league_bottom_quantile,
            self.cfg.league_top_quantile)
        # a completed member (supervisor state "done") still donates weights
        # from its outbox but can never adopt — planning it as a loser would
        # write directives nobody reads and bump its generation forever
        bottom = [m for m in bottom if not self._is_done(m)]
        plans = exploit_mod.plan_exploits(
            top, bottom,
            {m: self.members[m].genome for m in alive},
            {m: self.members[m].generation for m in alive},
            self.rng, self.cfg.league_perturb_factor,
            self.cfg.league_resample_prob)
        done: List[Dict[str, Any]] = []
        for plan in plans:
            try:
                _params, digest = exploit_mod.copy_weights(
                    self.league_dir, plan)
            except RuntimeError as e:
                self.exploit_skips += 1
                self._row(event="exploit_skipped", member=plan.loser,
                          source=plan.winner, step=step,
                          reason=str(e)[:200])
                continue
            row = exploit_mod.write_directive(
                self.league_dir, plan, digest, step=step)
            loser, winner = self.members[plan.loser], self.members[plan.winner]
            loser.genome = plan.genome
            loser.generation = plan.generation
            loser.exploits += 1
            loser.explores += 1
            loser.last_copy_source = plan.winner
            winner.copies_out += 1
            self.exploit_events += 1
            self._row(event="exploit", member=plan.loser,
                      source=plan.winner, generation=plan.generation,
                      digest=digest,
                      genome=plan.genome.to_dict(), step=step,
                      fitness_loser=self.fitness.fitness(plan.loser),
                      fitness_winner=self.fitness.fitness(plan.winner))
            done.append(row)
        self._observe()
        return done

    def force_sweep(self, step: int = 0) -> List[Dict[str, Any]]:
        """Run a sweep NOW regardless of the interval (soak/test hook)."""
        return self.sweep(step=step)

    # ------------------------------------------------------------- status
    def status_row(self, step: int = 0) -> Dict[str, Any]:
        """Emit (and return) the periodic per-member `league` status row —
        the obs_report `league:` section's input."""
        members: Dict[str, Dict[str, Any]] = {}
        for m in sorted(self.members):
            rec = self.members[m]
            role = self._role(m)
            stats = self.sup.stats().get(role, {})
            members[str(m)] = {
                "fitness": self.fitness.fitness(m),
                "evals": self.fitness.evals(m),
                "generation": rec.generation,
                "exploits": rec.exploits,
                "explores": rec.explores,
                "copies_out": rec.copies_out,
                "last_copy_source": rec.last_copy_source,
                "restarts": stats.get("restarts", 0),
                "state": stats.get("state", "unknown"),
                "lr": rec.genome.learning_rate,
                "n_step": rec.genome.n_step,
            }
        row = {
            "event": "status",
            "step": int(step),
            "members": members,
            "alive": len(self.alive_members()),
            "exploit_events": self.exploit_events,
            "exploit_skips": self.exploit_skips,
            "collapsed": self.collapsed(),
        }
        self._row(**row)
        return row

    def stop_all(self) -> None:
        self.sup.stop_all()
        if self.obs_relay is not None:
            self.obs_relay.close()
            self.obs_relay = None
