"""Trainer-side league runtime: genome overlay, outbox publishes, and
mid-run exploit adoption at safe drain boundaries.

A member trainer is an ordinary train loop (`train.py` or
`parallel/apex.py`) with three small hooks, all no-ops when
``cfg.league_member_id < 0`` or ``cfg.league_dir`` is unset (the default —
the off path is bitwise the pre-league loop, tier-1 asserted):

1. **overlay** (loop start): the member's genome file overrides the
   config's hyperparameters (`population.overlay_config`), so a respawned
   incarnation — same member id, RoleSupervisor epoch+1 — resumes exactly
   the genome (and generation) it died with;
2. **publish** (weight-publish cadence): the learner's fp32 params go out
   on the member's OUTBOX mailbox as an int8-delta chain — the copy source
   other members adopt from;
3. **adopt** (drain boundaries, metrics cadence): the exploit directive is
   polled; when the controller raised the member's generation, the copied
   chain is replayed from the INBOX, digest-asserted against the
   directive, and handed to the loop's ``adopt_params``/``retune``
   callbacks — weights swap and live genes (lr / n-step /
   priority-exponent) apply WITHOUT restarting the process.  Restart
   genes (replay_ratio, multitask schedule) wait for the next respawn's
   overlay.

The poll runs only where the loop has just drained the write-back ring:
an adoption must never land while an unverified learn step is in flight
(the same safe-boundary rule weight publishes follow).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

from rainbow_iqn_apex_tpu.league import exploit as exploit_mod
from rainbow_iqn_apex_tpu.league.population import (
    Genome,
    genome_from_config,
    load_genome,
    overlay_config,
    save_genome,
)

# the RoleSupervisor spawn fn exports the incarnation epoch to the child
# (Config carries no epoch field; the epoch is supervisor state)
EPOCH_ENV = "RIA_LEAGUE_EPOCH"


def graft_tree(template: Any, new_tree: Any) -> Any:
    """Rebuild ``new_tree``'s leaves in ``template``'s exact container
    structure (dict vs FrozenDict never matters to the adopting loop).
    Leaf order is canonical on both sides — `flatten_tree` walks mappings
    sorted, and jax's dict pytree registry does too — so a path-keyed
    graft is exact.  Reasoned errors on a shape/key mismatch: adopting
    weights from a differently-shaped member is a config bug, not a race.
    """
    import jax
    import numpy as np

    from rainbow_iqn_apex_tpu.utils.quantize import flatten_tree

    flat_new = flatten_tree(new_tree)
    flat_cur = flatten_tree(template)
    if set(flat_new) != set(flat_cur):
        missing = sorted(set(flat_cur) - set(flat_new))[:3]
        extra = sorted(set(flat_new) - set(flat_cur))[:3]
        raise ValueError(
            f"adopted tree does not match this member's model: missing "
            f"{missing}, unexpected {extra} — league members must share "
            "one architecture (docs/LEAGUE.md)")
    for path in flat_cur:
        if flat_new[path].shape != flat_cur[path].shape:
            raise ValueError(
                f"adopted leaf {path!r} shape {flat_new[path].shape} != "
                f"{flat_cur[path].shape} — league members must share one "
                "architecture (docs/LEAGUE.md)")
    leaves = [np.asarray(flat_new[p], np.float32)
              for p in sorted(flat_new)]
    return jax.tree.unflatten(jax.tree.structure(template), leaves)


class LeagueMember:
    """One member trainer's league state + mailbox endpoints."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.member_id = int(cfg.league_member_id)
        self.league_dir = cfg.league_dir
        self.epoch = int(os.environ.get(EPOCH_ENV, "0") or 0)
        self._metrics = None
        self._registry = None
        self.adoptions = 0
        self.adopt_failures = 0
        self._clamped_from: Optional[int] = None
        from rainbow_iqn_apex_tpu.parallel.elastic import WeightMailbox

        self.outbox = WeightMailbox(
            exploit_mod.outbox_path(self.league_dir, self.member_id),
            base_interval=max(int(cfg.publish_base_interval), 1),
            host=self.member_id)
        self.inbox = WeightMailbox(
            exploit_mod.inbox_path(self.league_dir, self.member_id),
            host=self.member_id)
        from rainbow_iqn_apex_tpu.league.population import genome_path

        self._genome_path = genome_path(self.league_dir, self.member_id)
        loaded = load_genome(self._genome_path)
        if loaded is not None:
            self.genome, self.generation = loaded
        else:
            # first incarnation before the controller seeded a genome:
            # the baseline is the config itself (overlay becomes a no-op)
            self.genome, self.generation = genome_from_config(cfg), 0

    # ------------------------------------------------------------- lifecycle
    @classmethod
    def from_config(cls, cfg) -> Optional["LeagueMember"]:
        """None unless this process is a league member — the one branch the
        default-off path ever takes."""
        if not cfg.league_dir or int(cfg.league_member_id) < 0:
            return None
        return cls(cfg)

    def overlay(self, cfg):
        """Genome-driven config overlay (call at loop start, before any
        component reads the hyperparameters)."""
        return overlay_config(cfg, self.genome)

    def clamp_n_step(self, max_n: int) -> None:
        """Clamp the held genome's n_step to the replay geometry (call at
        loop start, BEFORE overlay).  The explore prior reaches n=10 with
        no knowledge of any member's ring; unclamped, a small-capacity
        member would fail the buffer's seg > history + n check at every
        respawn and crash-loop into eviction.  The clamped genome is
        persisted so respawns resume a feasible state."""
        import dataclasses

        max_n = max(int(max_n), 1)
        if self.genome.n_step <= max_n:
            return
        self._clamped_from = self.genome.n_step
        self.genome = dataclasses.replace(self.genome, n_step=max_n)
        save_genome(self._genome_path, self.genome, self.generation,
                    self.member_id)

    def attach_obs(self, metrics=None, registry=None) -> None:
        self._metrics = metrics
        self._registry = registry
        extra = ({"n_step_clamped_from": self._clamped_from}
                 if self._clamped_from is not None else {})
        self._row(event="member_up", epoch=self.epoch,
                  genome=self.genome.to_dict(), **extra)
        self._gauges()

    def _row(self, **fields) -> None:
        if self._metrics is not None:
            self._metrics.log("league", member=self.member_id,
                              generation=self.generation, **fields)

    def _gauges(self) -> None:
        if self._registry is None:
            return
        role = f"member_m{self.member_id}"
        self._registry.gauge("league_generation", role).set(self.generation)
        self._registry.gauge("league_adoptions", role).set(self.adoptions)

    def lease_payload(self) -> Dict[str, Any]:
        """Fields the member's HeartbeatWriter lease carries (the league
        controller reads member/generation straight off the lease)."""
        return {"member": self.member_id, "generation": self.generation}

    # --------------------------------------------------------------- publish
    def publish(self, host_params: Any, step: int = 0) -> int:
        """Publish the learner's fp32 params on the outbox chain.  Versions
        continue monotonically from whatever the outbox FILE holds, so a
        respawned incarnation (fresh encoder) never publishes backward."""
        version = self.outbox.version() + 1
        self.outbox.publish_params(
            host_params, version, step=int(step),
            member=self.member_id, generation=self.generation)
        return version

    # ----------------------------------------------------------------- adopt
    def pending(self) -> bool:
        """Cheap drain-boundary probe: is there a directive above the held
        generation?  (One small-file read per metrics cadence.)"""
        d = exploit_mod.read_directive(self.league_dir, self.member_id)
        return d is not None and int(d["generation"]) > self.generation

    def try_adopt(
        self,
        step: int,
        adopt_params: Callable[[Any], None],
        retune: Optional[Callable[[Genome], None]] = None,
        max_n_step: Optional[int] = None,
    ) -> Optional[Dict[str, Any]]:
        """Adopt the directive's weights + genome (call ONLY after a ring
        drain).  Returns the directive on success, None when there is
        nothing to adopt yet; a digest mismatch refuses the adoption (one
        reasoned ``league`` row) and retries next boundary."""
        from rainbow_iqn_apex_tpu.utils.quantize import tree_digest

        directive = exploit_mod.read_directive(self.league_dir,
                                               self.member_id)
        if directive is None or int(directive["generation"]) <= self.generation:
            return None
        row = self.inbox.read()
        if row is None or int(row.get("version", -1)) != int(
                directive["generation"]):
            return None  # inbox not yet at this generation; retry
        params = self.inbox.read_params()
        if params is None:
            return None  # racing the controller's copy; retry
        digest = tree_digest(params)
        if digest != directive.get("digest"):
            self.adopt_failures += 1
            self._row(event="adopt_refused",
                      reason="digest_mismatch", step=int(step),
                      want=directive.get("digest"), got=digest,
                      source=directive.get("source"))
            return None
        new_genome = Genome.from_dict(directive["genome"])
        if max_n_step is not None and new_genome.n_step > max(max_n_step, 1):
            # the explore prior reaches n=10 blind to this member's ring;
            # set_n_step would raise and kill the loop — clamp instead so
            # the adoption lands (and persists) a feasible genome
            import dataclasses

            clamped = max(int(max_n_step), 1)
            self._row(event="genome_clamped", step=int(step),
                      n_step_from=new_genome.n_step, n_step_to=clamped,
                      source=int(directive.get("source", -1)))
            new_genome = dataclasses.replace(new_genome, n_step=clamped)
        adopt_params(params)
        if retune is not None:
            retune(new_genome)
        self.genome = new_genome
        self.generation = int(directive["generation"])
        # persist BOTH so a respawn resumes the adopted state, and a
        # replayed directive (same generation) reads as already-held
        save_genome(self._genome_path, self.genome, self.generation,
                    self.member_id)
        self.adoptions += 1
        self._row(event="adopt", step=int(step), digest=digest,
                  source=int(directive.get("source", -1)),
                  genome=self.genome.to_dict())
        self._gauges()
        return directive
