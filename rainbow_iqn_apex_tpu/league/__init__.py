"""league/ — population-based training on the Ape-X substrate
(docs/LEAGUE.md).

Exports resolve lazily (PEP 562) and every submodule imports jax-free:
the controller and respawned member children are plain processes that
must start in ~0.3s, exactly like parallel/elastic.py's consumers.
"""

from typing import TYPE_CHECKING

_EXPORTS = {
    "Genome": "rainbow_iqn_apex_tpu.league.population",
    "check_league_config": "rainbow_iqn_apex_tpu.league.population",
    "genome_from_config": "rainbow_iqn_apex_tpu.league.population",
    "overlay_config": "rainbow_iqn_apex_tpu.league.population",
    "perturb_genome": "rainbow_iqn_apex_tpu.league.population",
    "resample_genome": "rainbow_iqn_apex_tpu.league.population",
    "FitnessTracker": "rainbow_iqn_apex_tpu.league.fitness",
    "quantile_split": "rainbow_iqn_apex_tpu.league.fitness",
    "rank_members": "rainbow_iqn_apex_tpu.league.fitness",
    "ExploitPlan": "rainbow_iqn_apex_tpu.league.exploit",
    "copy_weights": "rainbow_iqn_apex_tpu.league.exploit",
    "plan_exploits": "rainbow_iqn_apex_tpu.league.exploit",
    "LeagueMember": "rainbow_iqn_apex_tpu.league.member",
    "LeagueController": "rainbow_iqn_apex_tpu.league.controller",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)


if TYPE_CHECKING:  # pragma: no cover - static analysis only
    from rainbow_iqn_apex_tpu.league.controller import LeagueController
    from rainbow_iqn_apex_tpu.league.exploit import (
        ExploitPlan,
        copy_weights,
        plan_exploits,
    )
    from rainbow_iqn_apex_tpu.league.fitness import (
        FitnessTracker,
        quantile_split,
        rank_members,
    )
    from rainbow_iqn_apex_tpu.league.member import LeagueMember
    from rainbow_iqn_apex_tpu.league.population import (
        Genome,
        check_league_config,
        genome_from_config,
        overlay_config,
        perturb_genome,
        resample_genome,
    )
