"""Genomes and population state for league/PBT training (docs/LEAGUE.md).

Ape-X (arXiv:1803.00933) fixes one hyperparameter set per run, yet
Accelerated Methods (arXiv:1803.02811) shows distributed value-learners are
acutely sensitive to lr / n-step / batch choices at scale.  A *population*
tunes them online (PBT, arXiv:1711.09846): N member trainers run
concurrently, each with its own **genome** — the small hyperparameter
vector below — and the league controller periodically copies a winner's
weights into a loser and perturbs the loser's genome.

Genes split into two adoption classes:

- **live** genes (``learning_rate``, ``n_step``, ``priority_exponent``)
  are adopted MID-RUN at safe drain boundaries: the write-back ring is
  drained (no unverified step in flight), then the learner rebuilds its
  jitted step / re-fences the replay's n-step eligibility
  (`PrioritizedReplay.set_n_step`) without restarting the process;
- **restart** genes (``replay_ratio``, ``multitask_schedule``) change the
  shape of compiled executables or the replay sample plan — they take
  effect at the member's next (re)spawn via the genome-file config overlay
  (`overlay_config`, read at loop start).

Everything here is jax-free and file-backed: a genome is one small JSON
next to the member's mailboxes, so a respawned incarnation (RoleSupervisor
epoch+1) reads back the same member id, generation, and genome it died
with — member death never resets PBT state.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

# gene -> (Config field it overlays, adoption class)
GENES: Dict[str, Tuple[str, str]] = {
    "learning_rate": ("learning_rate", "live"),
    "n_step": ("multi_step", "live"),
    "priority_exponent": ("priority_exponent", "live"),
    "replay_ratio": ("replay_ratio", "restart"),
    "multitask_schedule": ("multitask_schedule", "restart"),
}
LIVE_GENES = tuple(g for g, (_f, c) in GENES.items() if c == "live")
RESTART_GENES = tuple(g for g, (_f, c) in GENES.items() if c == "restart")

# resample priors (explore's fresh-draw ranges; docs/LEAGUE.md genome table)
LR_PRIOR = (1e-5, 1e-2)  # log-uniform
N_STEP_PRIOR = (1, 10)
OMEGA_PRIOR = (0.1, 1.0)
REPLAY_RATIO_PRIOR = (1, 8)


@dataclasses.dataclass(frozen=True)
class Genome:
    """One member's hyperparameter vector (the PBT search space)."""

    learning_rate: float
    n_step: int
    priority_exponent: float
    replay_ratio: int = 1
    # "" = leave cfg.multitask_schedule untouched; otherwise a schedule mode
    # incl. explicit shares ("fixed:0.6,0.4" — multitask/replay.py)
    multitask_schedule: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Genome":
        return Genome(
            learning_rate=float(d["learning_rate"]),
            n_step=int(d["n_step"]),
            priority_exponent=float(d["priority_exponent"]),
            replay_ratio=int(d.get("replay_ratio", 1)),
            multitask_schedule=str(d.get("multitask_schedule", "")),
        )


def genome_from_config(cfg) -> Genome:
    """The baseline genome: the run's own hyperparameters."""
    return Genome(
        learning_rate=float(cfg.learning_rate),
        n_step=int(cfg.multi_step),
        priority_exponent=float(cfg.priority_exponent),
        replay_ratio=max(int(cfg.replay_ratio), 1),
        multitask_schedule="",
    )


def overlay_config(cfg, genome: Genome):
    """Genome-driven config overlay: the member trainer's Config with the
    genome's genes substituted (read at loop start, so restart genes land
    here too).  A genome equal to the config's own values returns an
    IDENTICAL config — the no-op overlay changes nothing."""
    fields: Dict[str, Any] = {
        "learning_rate": genome.learning_rate,
        "multi_step": genome.n_step,
        "priority_exponent": genome.priority_exponent,
        "replay_ratio": genome.replay_ratio,
    }
    if genome.multitask_schedule:
        fields["multitask_schedule"] = genome.multitask_schedule
    if all(getattr(cfg, k) == v for k, v in fields.items()):
        return cfg
    return cfg.replace(**fields)


def _mutate_shares(spec: str, rng: np.random.Generator) -> str:
    """Jitter explicit 'fixed:w1,w2,...' schedule shares (renormalized)."""
    shares = np.asarray([float(s) for s in spec.split(":", 1)[1].split(",")])
    shares = shares * rng.uniform(0.8, 1.25, size=shares.shape)
    shares = shares / shares.sum()
    return "fixed:" + ",".join(f"{s:.4f}" for s in shares)


def perturb_genome(genome: Genome, rng: np.random.Generator,
                   factor: float, resample_prob: float = 0.0) -> Genome:
    """Explore: every continuous gene multiplies or divides by ``factor``
    (seeded coin) — or, PER GENE with probability ``resample_prob``,
    redraws fresh from its prior — and discrete genes take a +/-1 step
    inside their prior range.  Deterministic under a seeded ``rng``; with
    factor != 1 the result always differs from the source (the soak's
    perturbed-not-equal gate): a draw where every gene happens to clip
    back onto its prior corner is retried, and as a last resort the
    learning rate is stepped INTO the prior interior (always possible —
    the coin can pin a gene at a bound, but both bounds cannot pin lr at
    once)."""
    def cont(v: float, lo: float, hi: float, log: bool = False) -> float:
        if resample_prob > 0 and rng.random() < resample_prob:
            if log:
                return float(np.exp(rng.uniform(np.log(lo), np.log(hi))))
            return float(rng.uniform(lo, hi))
        v = v * factor if rng.random() < 0.5 else v / factor
        return float(np.clip(v, lo, hi))

    def disc(v: int, lo: int, hi: int) -> int:
        if resample_prob > 0 and rng.random() < resample_prob:
            return int(rng.integers(lo, hi + 1))
        return int(np.clip(v + (1 if rng.random() < 0.5 else -1), lo, hi))

    def draw() -> Genome:
        return Genome(
            learning_rate=cont(genome.learning_rate, *LR_PRIOR, log=True),
            n_step=disc(genome.n_step, *N_STEP_PRIOR),
            priority_exponent=cont(genome.priority_exponent, *OMEGA_PRIOR),
            replay_ratio=disc(genome.replay_ratio, *REPLAY_RATIO_PRIOR),
            multitask_schedule=(
                _mutate_shares(genome.multitask_schedule, rng)
                if genome.multitask_schedule.startswith("fixed:")
                else genome.multitask_schedule),
        )

    for _ in range(8):
        child = draw()
        if child != genome or factor == 1.0:
            return child
    # every coin pushed its gene into the clip: force lr off the corner
    lo, hi = LR_PRIOR
    lr = genome.learning_rate
    lr = lr / factor if np.clip(lr * factor, lo, hi) == lr else lr * factor
    return Genome(
        learning_rate=float(np.clip(lr, lo, hi)),
        n_step=child.n_step,
        priority_exponent=child.priority_exponent,
        replay_ratio=child.replay_ratio,
        multitask_schedule=child.multitask_schedule,
    )


def resample_genome(rng: np.random.Generator,
                    schedule: str = "") -> Genome:
    """A fresh genome drawn from the priors (initial population diversity
    and the resample half of explore)."""
    lo, hi = LR_PRIOR
    return Genome(
        learning_rate=float(np.exp(rng.uniform(np.log(lo), np.log(hi)))),
        n_step=int(rng.integers(N_STEP_PRIOR[0], N_STEP_PRIOR[1] + 1)),
        priority_exponent=float(rng.uniform(*OMEGA_PRIOR)),
        replay_ratio=1,  # reuse > 1 is an operator escalation, not a prior
        multitask_schedule=schedule,
    )


# ------------------------------------------------------------- genome files
def genome_path(league_dir: str, member_id: int) -> str:
    return os.path.join(league_dir, f"m{int(member_id)}", "genome.json")


def save_genome(path: str, genome: Genome, generation: int,
                member_id: int) -> None:
    """Atomic write (tmp + rename) so a member mid-read never sees torn
    JSON; the generation rides with the genome so a respawned incarnation
    resumes PBT state, not just hyperparameters."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"member": int(member_id), "generation": int(generation),
                   "genome": genome.to_dict()}, f, indent=2)
    os.replace(tmp, path)


def load_genome(path: str) -> Optional[Tuple[Genome, int]]:
    """(genome, generation) or None when the file is absent/torn —
    the member falls back to its config-derived baseline."""
    try:
        with open(path) as f:
            row = json.load(f)
        return Genome.from_dict(row["genome"]), int(row.get("generation", 0))
    except (OSError, ValueError, KeyError):
        return None


# --------------------------------------------------------------- validation
def check_league_config(cfg) -> None:
    """Reasoned errors for malformed league_* specs, raised at loop start
    (the check_reuse_cadences house style: every clause names the field,
    the observed value, and why it cannot work — docs/LEAGUE.md)."""
    if int(cfg.league_member_id) >= 0 and not cfg.league_dir:
        raise ValueError(
            f"league_member_id ({cfg.league_member_id}) without a "
            "league_dir: a member rendezvouses with its controller through "
            "the league directory (genome file, mailboxes, directives) — "
            "set league_dir, or unset league_member_id to train solo "
            "(docs/LEAGUE.md)")
    if int(cfg.league_member_id) >= 0 and cfg.league_dir:
        mdir = os.path.abspath(
            os.path.join(cfg.league_dir, f"m{int(cfg.league_member_id)}"))
        rdir = os.path.abspath(cfg.results_dir)
        if rdir != mdir and not rdir.startswith(mdir + os.sep):
            raise ValueError(
                f"results_dir ({cfg.results_dir}) is outside this member's "
                f"league directory ({mdir}): the controller scores members "
                "by tailing eval rows under league_dir/m<k>/ — a member "
                "logging elsewhere is silently never scored and can "
                "neither win nor be exploited.  Set results_dir under "
                f"{mdir} (league_soak.py uses m<k>/results) "
                "(docs/LEAGUE.md)")
    if not cfg.league_dir and cfg.league_population <= 0:
        return  # league off: nothing to validate
    if cfg.league_population == 1:
        raise ValueError(
            "league_population (1) must be >= 2: a 1-member population has "
            "no peer to exploit — truncation selection needs at least one "
            "member in the top quantile and one in the bottom "
            "(docs/LEAGUE.md)")
    if cfg.league_population > 0 and not cfg.league_dir:
        raise ValueError(
            f"league_population ({cfg.league_population}) without a "
            "league_dir: the controller and its members rendezvous through "
            "the league directory (genomes, mailboxes, directives) — set "
            "league_dir (docs/LEAGUE.md)")
    for name in ("league_bottom_quantile", "league_top_quantile"):
        q = getattr(cfg, name)
        if not (0.0 < q < 1.0):
            raise ValueError(
                f"{name} ({q}) must lie strictly in (0, 1): 0 selects "
                "nobody and 1 selects everybody — truncation selection "
                "needs a strict subset on each side (docs/LEAGUE.md)")
    if cfg.league_bottom_quantile + cfg.league_top_quantile > 1.0:
        raise ValueError(
            f"league_bottom_quantile ({cfg.league_bottom_quantile}) + "
            f"league_top_quantile ({cfg.league_top_quantile}) must not "
            "exceed 1.0: overlapping quantiles would let a member exploit "
            "ITSELF (copy its own weights and perturb its own genome — a "
            "no-op that still burns an exploit slot) (docs/LEAGUE.md)")
    if cfg.league_perturb_factor <= 0:
        raise ValueError(
            f"league_perturb_factor ({cfg.league_perturb_factor}) must be "
            "> 0: explore multiplies or divides continuous genes by it, so "
            "a non-positive factor flips gene signs or zeroes them "
            "(docs/LEAGUE.md)")
    if not (0.0 <= cfg.league_resample_prob <= 1.0):
        raise ValueError(
            f"league_resample_prob ({cfg.league_resample_prob}) must lie "
            "in [0, 1]: it is the per-gene probability of a fresh prior "
            "draw instead of a perturbation (docs/LEAGUE.md)")
    if cfg.league_fitness_window < 1:
        raise ValueError(
            f"league_fitness_window ({cfg.league_fitness_window}) must be "
            ">= 1: fitness is the mean of this many recent eval rows — a "
            "zero window makes every member fitness-less forever "
            "(docs/LEAGUE.md)")
    if cfg.league_exploit_interval_s <= 0:
        raise ValueError(
            f"league_exploit_interval_s ({cfg.league_exploit_interval_s}) "
            "must be > 0: it is the controller's exploit sweep cadence "
            "(docs/LEAGUE.md)")
