"""Windowed human-normalized fitness from eval / eval_mt rows.

The league scores members on the SAME eval telemetry every run already
emits (obs/schema.py): single-game members on per-game ``eval`` rows
(``score_mean`` -> human-normalized via `eval.HUMAN_BASELINES` when the
game is known, raw score otherwise), multi-game members on the ``eval_mt``
aggregate (``hn_median`` — the Atari-57 reporting convention).  No second
eval pathway exists for the league to drift from.

Missing-eval tolerance is load-bearing: a member that has not evaluated
yet (cold start, crash-looping, slow game) has fitness ``None`` and is
excluded from exploit on BOTH sides — it can neither be exploited (killing
a member for being *unmeasured* is not selection) nor be a copy source.
NaN scores (a poisoned eval) are skipped row-wise, not propagated.
"""

from __future__ import annotations

import collections
import math
from typing import Any, Dict, List, Optional, Tuple


class FitnessTracker:
    """Per-member sliding window of eval fitness values."""

    def __init__(self, window: int):
        self.window = max(int(window), 1)
        self._scores: Dict[int, collections.deque] = {}
        self.rows_seen = 0
        self.rows_skipped = 0  # NaN / None / baseline-less rows

    def _window(self, member_id: int) -> collections.deque:
        return self._scores.setdefault(
            int(member_id), collections.deque(maxlen=self.window))

    def note_row(self, member_id: int, row: Dict[str, Any]) -> bool:
        """Fold one eval/eval_mt row; returns True when a fitness value
        landed.  ``eval_mt`` rows score by ``hn_median``; ``eval`` rows by
        ``human_normalized`` when present, else the raw ``score_mean``
        (games without a baseline still rank within themselves)."""
        kind = row.get("kind")
        if kind == "eval_mt":
            value = row.get("hn_median")
        elif kind == "eval":
            value = row.get("human_normalized", row.get("score_mean"))
        else:
            return False
        self.rows_seen += 1
        if value is None or not isinstance(value, (int, float)) \
                or math.isnan(float(value)) or math.isinf(float(value)):
            self.rows_skipped += 1
            return False
        self._window(member_id).append(float(value))
        return True

    def note_score(self, member_id: int, value: Optional[float]) -> bool:
        """Direct score entry (tests, synthetic members)."""
        if value is None or math.isnan(value) or math.isinf(value):
            self.rows_skipped += 1
            return False
        self._window(member_id).append(float(value))
        return True

    def fitness(self, member_id: int) -> Optional[float]:
        win = self._scores.get(int(member_id))
        if not win:
            return None  # missing-eval tolerance: unmeasured, not zero
        return float(sum(win) / len(win))

    def evals(self, member_id: int) -> int:
        win = self._scores.get(int(member_id))
        return len(win) if win else 0

    def forget(self, member_id: int) -> None:
        """Drop a member's window (eviction: its scores must not keep
        shaping the quantile cut lines)."""
        self._scores.pop(int(member_id), None)


def rank_members(tracker: FitnessTracker,
                 member_ids: List[int]) -> List[Tuple[int, float]]:
    """(member_id, fitness) best-first over the members WITH a fitness;
    ties break toward the lower member id (deterministic exploit plans)."""
    scored = [(m, f) for m in member_ids
              if (f := tracker.fitness(m)) is not None]
    return sorted(scored, key=lambda mf: (-mf[1], mf[0]))


def quantile_split(ranked: List[Tuple[int, float]], bottom_q: float,
                   top_q: float) -> Tuple[List[int], List[int]]:
    """(top_ids, bottom_ids) under truncation selection.  Quantiles round
    DOWN but never below 1 once >= 2 members are ranked — with only one
    scored member both sides are empty (nobody exploits an unmeasured
    field)."""
    n = len(ranked)
    if n < 2:
        return [], []
    k_top = max(1, int(n * top_q))
    k_bot = max(1, int(n * bottom_q))
    ids = [m for m, _f in ranked]
    return ids[:k_top], ids[n - k_bot:]
