"""Deterministic toy environments for CI and learning-integration tests.

The sandbox has no ALE/ROMs (SURVEY.md §7 build constraints), so these envs
play the role Pong plays for the reference (SURVEY.md §4: "Pong as the smoke
test"): small, fully observable pixel games a correct Rainbow-IQN agent must
solve quickly.  They emit the same uint8 frame surface as the Atari path so
the entire agent/replay/learner stack is exercised unchanged.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from rainbow_iqn_apex_tpu.envs.base import Env, TimeStep


class CatchEnv(Env):
    """Catch: a ball falls from the top; move the paddle to catch it.

    Actions: 0=stay, 1=left, 2=right.  Reward +1 on catch, -1 on miss, 0
    otherwise; episode ends when the ball reaches the bottom row.  Rendered
    as an (size*cell) x (size*cell) uint8 frame.
    """

    NUM_ACTIONS = 3

    def __init__(self, size: int = 10, cell: int = 8, seed: int = 0):
        self.size = size
        self.cell = cell
        self.rng = np.random.default_rng(seed)
        self.ball_row = 0
        self.ball_col = 0
        self.paddle = size // 2
        self._ret = 0.0

    @property
    def num_actions(self) -> int:
        return self.NUM_ACTIONS

    @property
    def frame_shape(self) -> Tuple[int, int]:
        return (self.size * self.cell, self.size * self.cell)

    def _render(self) -> np.ndarray:
        grid = np.zeros((self.size, self.size), np.uint8)
        grid[self.ball_row, self.ball_col] = 255
        grid[self.size - 1, self.paddle] = 128
        return np.kron(grid, np.ones((self.cell, self.cell), np.uint8))

    def reset(self) -> np.ndarray:
        self.ball_row = 0
        self.ball_col = int(self.rng.integers(0, self.size))
        self.paddle = self.size // 2
        self._ret = 0.0
        return self._render()

    def step(self, action: int) -> TimeStep:
        self.paddle = int(np.clip(self.paddle + (0, -1, 1)[action], 0, self.size - 1))
        self.ball_row += 1
        terminal = self.ball_row == self.size - 1
        reward = 0.0
        if terminal:
            reward = 1.0 if self.paddle == self.ball_col else -1.0
        self._ret += reward
        info = {"episode_return": self._ret} if terminal else None
        return TimeStep(self._render(), reward, terminal, False, info)


class ChainEnv(Env):
    """n-state chain: start at the left; RIGHT n-1 times earns the big
    reward, LEFT ends with a small one.  Exercises n-step credit assignment
    and exploration (greedy-myopic agents take the small exit)."""

    NUM_ACTIONS = 2  # 0=left, 1=right

    def __init__(self, length: int = 8, frame: int = 40, seed: int = 0):
        self.length = length
        self.frame = frame
        self.pos = 0
        self._ret = 0.0

    @property
    def num_actions(self) -> int:
        return self.NUM_ACTIONS

    @property
    def frame_shape(self) -> Tuple[int, int]:
        return (self.frame, self.frame)

    def _render(self) -> np.ndarray:
        img = np.zeros((self.frame, self.frame), np.uint8)
        w = self.frame // self.length
        img[:, self.pos * w : (self.pos + 1) * w] = 255
        return img

    def reset(self) -> np.ndarray:
        self.pos = 0
        self._ret = 0.0
        return self._render()

    def step(self, action: int) -> TimeStep:
        if action == 0:
            reward, terminal = 0.1, True
        else:
            self.pos += 1
            terminal = self.pos == self.length - 1
            reward = 1.0 if terminal else 0.0
        self._ret += reward
        info = {"episode_return": self._ret} if terminal else None
        return TimeStep(self._render(), reward, terminal, False, info)


def make_toy_env(name: str, seed: int = 0) -> Env:
    if name == "catch":
        return CatchEnv(seed=seed)
    if name == "chain":
        return ChainEnv(seed=seed)
    raise ValueError(f"unknown toy env '{name}' (have: catch, chain)")
