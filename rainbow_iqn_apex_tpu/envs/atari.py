"""Atari environment with DeepMind/SABER preprocessing.

Parity: reference `rainbowiqn/env.py` (SURVEY.md §2 row 2) — ALE lifecycle +
grayscale, 84x84 resize, action-repeat 4 with max over the last 2 raw frames,
reward clip to [-1, 1], and the SABER protocol options (arXiv:1908.04683):
sticky actions p=0.25, the full 18-action set, termination on game over (not
life loss), and the 30-minute (108k raw frame) episode cap.

Design: all preprocessing operates on a small ``RawAtari`` duck-type rather
than on ale_py directly, because this sandbox has no ALE/ROMs (SURVEY.md §7
"No ALE in this sandbox: keep every Atari-specific assumption behind the env
seam").  `ALEAdapter` binds the real ale_py when present; tests inject a fake.
"""

from __future__ import annotations

from typing import Optional, Protocol, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.envs.base import Env, TimeStep

try:  # optional: image resize via OpenCV, with a NumPy fallback
    import cv2  # type: ignore

    _HAVE_CV2 = True
except Exception:  # pragma: no cover
    _HAVE_CV2 = False


class RawAtari(Protocol):
    """The minimal ALE surface the preprocessing needs."""

    num_actions: int

    def reset(self) -> None: ...
    def act(self, action: int) -> float: ...  # raw (unclipped) reward
    def screen(self) -> np.ndarray: ...  # grayscale [H_raw, W_raw] uint8
    def game_over(self) -> bool: ...
    def lives(self) -> int: ...


def _resize(frame: np.ndarray, hw: Tuple[int, int]) -> np.ndarray:
    if _HAVE_CV2:
        return cv2.resize(frame, (hw[1], hw[0]), interpolation=cv2.INTER_AREA).astype(
            np.uint8
        )
    # NumPy area-mean fallback (exact when shapes divide evenly), fully
    # vectorised via two cumulative-sum passes — the previous per-pixel
    # Python double loop cost ~ms/frame, a silent preprocessing tax on the
    # actor hot path of any ALE box without cv2 (VERDICT r4).  Bin [i, j]
    # averages frame[ys[i]:ye[i], xs[j]:xe[j]] (ends forced >= 1 wide), and
    # the float->uint8 cast truncates, matching the old loop bit-for-bit.
    h, w = frame.shape
    th, tw = hw
    ys = (np.arange(th + 1) * h // th).astype(int)
    xs = (np.arange(tw + 1) * w // tw).astype(int)
    ye = np.maximum(ys[1:], ys[:-1] + 1)
    xe = np.maximum(xs[1:], xs[:-1] + 1)
    c = np.zeros((h + 1, w), np.float64)
    np.cumsum(frame, axis=0, out=c[1:])
    rowsum = c[ye] - c[ys[:-1]]  # [th, w] — per-bin row sums
    c2 = np.zeros((th, w + 1), np.float64)
    np.cumsum(rowsum, axis=1, out=c2[:, 1:])
    s = c2[:, xe] - c2[:, xs[:-1]]  # [th, tw] — per-bin area sums
    area = (ye - ys[:-1])[:, None] * (xe - xs[:-1])[None, :]
    return (s / area).astype(np.uint8)


class AtariEnv(Env):
    """SABER/DeepMind-preprocessed Atari over any RawAtari backend."""

    def __init__(
        self,
        raw: RawAtari,
        frame_shape: Tuple[int, int] = (84, 84),
        action_repeat: int = 4,
        sticky_actions: float = 0.25,
        reward_clip: float = 1.0,
        terminal_on_life_loss: bool = False,
        max_episode_frames: int = 108_000,
        seed: int = 0,
    ):
        self.raw = raw
        self._frame_shape = frame_shape
        self.action_repeat = action_repeat
        self.sticky = sticky_actions
        self.reward_clip = reward_clip
        self.life_loss = terminal_on_life_loss
        self.max_frames = max_episode_frames
        self.rng = np.random.default_rng(seed)
        self._prev_action = 0
        self._raw_frames = 0
        self._lives = 0
        self._ret = 0.0  # raw (unclipped) episode return, for eval parity

    @property
    def num_actions(self) -> int:
        return self.raw.num_actions

    @property
    def frame_shape(self) -> Tuple[int, int]:
        return self._frame_shape

    def reset(self) -> np.ndarray:
        self.raw.reset()
        self._prev_action = 0
        self._raw_frames = 0
        self._ret = 0.0
        self._lives = self.raw.lives()
        return _resize(self.raw.screen(), self._frame_shape)

    def step(self, action: int) -> TimeStep:
        # SABER sticky actions: with prob p the PREVIOUS action repeats.
        if self.sticky > 0 and self.rng.random() < self.sticky:
            action = self._prev_action
        self._prev_action = action

        reward = 0.0
        screens = []  # last two raw screens for flicker max-pooling
        terminal = False
        for _ in range(self.action_repeat):
            reward += float(self.raw.act(action))
            self._raw_frames += 1
            screens.append(self.raw.screen())
            if self.raw.game_over():
                terminal = True
                break
            if self.life_loss and self.raw.lives() < self._lives:
                self._lives = self.raw.lives()
                terminal = True
                break
        self._lives = self.raw.lives()

        pooled = np.maximum(screens[-1], screens[-2]) if len(screens) >= 2 else screens[-1]
        frame = _resize(pooled, self._frame_shape)

        self._ret += reward
        truncated = (not terminal) and self._raw_frames >= self.max_frames
        if self.reward_clip > 0:
            reward = float(np.clip(reward, -self.reward_clip, self.reward_clip))
        info = (
            {"episode_return": self._ret, "raw_frames": self._raw_frames}
            if (terminal or truncated)
            else None
        )
        return TimeStep(frame, reward, terminal, truncated, info)


class ALEAdapter:
    """Binds ale_py (when installed) to the RawAtari protocol.

    SABER uses the full 18-action legal set (reference behaviour); pass
    ``full_action_set=False`` for the minimal set.
    """

    def __init__(self, game: str, seed: int = 0, full_action_set: bool = True):
        try:
            import ale_py  # type: ignore
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "ale_py is not installed in this environment. Atari runs need "
                "ale-py + ROMs; use toy:* envs here, or install ale-py where "
                "available. The preprocessing stack itself is fully testable "
                "via the RawAtari seam."
            ) from e
        self._ale = ale_py.ALEInterface()
        self._ale.setInt("random_seed", seed)
        # repeat_action_probability=0 here: stickiness is implemented (and
        # unit-tested) in AtariEnv so the policy is backend-independent.
        self._ale.setFloat("repeat_action_probability", 0.0)
        self._ale.loadROM(ale_py.roms.get_rom_path(game))
        self._actions = (
            self._ale.getLegalActionSet()
            if full_action_set
            else self._ale.getMinimalActionSet()
        )
        self.num_actions = len(self._actions)

    def reset(self) -> None:
        self._ale.reset_game()

    def act(self, action: int) -> float:
        return self._ale.act(self._actions[action])

    def screen(self) -> np.ndarray:
        return self._ale.getScreenGrayscale().squeeze()

    def game_over(self) -> bool:
        return self._ale.game_over()

    def lives(self) -> int:
        return self._ale.lives()


def make_atari_env(game: str, seed: int = 0, **kwargs) -> AtariEnv:
    full = kwargs.pop("full_action_set", True)
    return AtariEnv(ALEAdapter(game, seed=seed, full_action_set=full), seed=seed, **kwargs)
