"""Gymnasium adapter: Procgen / CARLA / any pixel gym env behind our Env API.

Parity: BASELINE.json:11 lists "Procgen-16 + CARLA NoCrash driving (Valeo
domain — generalization bench)" as a reference benchmark config.  Neither
package is installed in this sandbox (SURVEY.md §7), so — like the Atari
path — the adapter keeps every gym-specific assumption behind one seam:
anything exposing gymnasium's `reset()/step()` with an RGB or grayscale
pixel observation becomes a framework Env producing preprocessed uint8
frames.  CI exercises it with a synthetic gymnasium env.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.envs.atari import _resize
from rainbow_iqn_apex_tpu.envs.base import Env, TimeStep


def _to_gray(frame: np.ndarray) -> np.ndarray:
    """RGB [H,W,3] (or [H,W]) uint8 -> grayscale [H,W] uint8 (BT.601)."""
    if frame.ndim == 2:
        return frame.astype(np.uint8)
    if frame.ndim == 3 and frame.shape[-1] == 3:
        g = frame @ np.asarray([0.299, 0.587, 0.114], np.float32)
        return g.astype(np.uint8)
    raise ValueError(f"expected [H,W] or [H,W,3] pixels, got {frame.shape}")


class GymEnv(Env):
    """Wraps a gymnasium-API env (Procgen, CARLA wrappers, Box2D pixels...).

    Rewards are optionally clipped (training parity with the Atari path);
    the raw episode return is reported via info for evaluation.
    """

    def __init__(
        self,
        gym_env: Any,
        frame_shape: Tuple[int, int] = (84, 84),
        reward_clip: float = 1.0,
        max_episode_steps: int = 0,  # 0 = trust the env's own limit
        seed: int = 0,
    ):
        self.gym = gym_env
        self._frame_shape = frame_shape
        self.reward_clip = reward_clip
        self.max_steps = max_episode_steps
        self._seed = seed
        self._steps = 0
        self._ret = 0.0
        n = getattr(gym_env.action_space, "n", None)
        if n is None:
            raise ValueError(
                "GymEnv needs a discrete action space (Procgen/CARLA discrete "
                "wrappers qualify); got " + repr(gym_env.action_space)
            )
        self._num_actions = int(n)

    @property
    def num_actions(self) -> int:
        return self._num_actions

    @property
    def frame_shape(self) -> Tuple[int, int]:
        return self._frame_shape

    def _frame(self, obs: np.ndarray) -> np.ndarray:
        return _resize(_to_gray(np.asarray(obs)), self._frame_shape)

    def reset(self) -> np.ndarray:
        try:
            out = self.gym.reset(seed=self._seed)
        except TypeError:  # legacy gym reset() without seed kwarg
            out = self.gym.reset()
        obs = out[0] if isinstance(out, tuple) else out
        self._seed = None  # gymnasium: seed only the first reset
        self._steps = 0
        self._ret = 0.0
        return self._frame(obs)

    def step(self, action: int) -> TimeStep:
        out = self.gym.step(action)
        if len(out) == 5:  # gymnasium API
            obs, reward, terminated, truncated, _info = out
        elif len(out) == 4:  # legacy gym 4-tuple (procgen et al.)
            obs, reward, done, _info = out
            truncated = bool(_info.get("TimeLimit.truncated", False))
            terminated = bool(done) and not truncated
        else:  # pragma: no cover
            raise ValueError(f"unrecognised step() return of length {len(out)}")
        self._steps += 1
        self._ret += float(reward)
        if self.max_steps and self._steps >= self.max_steps and not terminated:
            truncated = True
        r = float(reward)
        if self.reward_clip > 0:
            r = float(np.clip(r, -self.reward_clip, self.reward_clip))
        info = (
            {"episode_return": self._ret} if (terminated or truncated) else None
        )
        return TimeStep(self._frame(obs), r, bool(terminated), bool(truncated), info)

    def close(self) -> None:
        self.gym.close()


def make_gym_env(env_id: str, seed: int = 0, **kwargs) -> GymEnv:
    """Factory for `gym:<id>` env ids (any gymnasium-registered pixel env)."""
    try:
        import gymnasium
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "gymnasium is not installed; gym:/procgen: env ids need it"
        ) from e
    return GymEnv(gymnasium.make(env_id), seed=seed, **kwargs)


def make_procgen_env(game: str, seed: int = 0, **kwargs) -> GymEnv:
    """Factory for `procgen:<game>`.

    procgen registers its envs with legacy gym, not gymnasium, so we go
    through gymnasium's compatibility entry point when available and fall
    back to wrapping the legacy env directly (GymEnv.step handles both the
    5-tuple and legacy 4-tuple returns).
    """
    shim_error: Optional[Exception] = None
    try:
        import gymnasium

        try:  # gymnasium shim over a legacy-gym registration (needs shimmy)
            env = gymnasium.make(
                "GymV21Environment-v0", env_id=f"procgen:procgen-{game}-v0"
            )
            return GymEnv(env, seed=seed, **kwargs)
        except Exception as e:
            shim_error = e  # keep for the final error chain
    except ImportError:
        pass
    try:
        import gym as legacy_gym  # procgen's native registry

        env = legacy_gym.make(f"procgen:procgen-{game}-v0")
        return GymEnv(env, seed=seed, **kwargs)
    except ImportError as e:
        raise ImportError(
            "procgen env ids need the procgen package (registered with "
            "legacy gym) or a gymnasium+shimmy compatibility shim"
            + (f"; the gymnasium shim attempt failed with: {shim_error!r}"
               if shim_error else "")
        ) from (shim_error or e)
