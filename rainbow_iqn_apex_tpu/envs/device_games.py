"""Pure-JAX games: Atari-class dynamics that run INSIDE the XLA graph.

Why this exists: the reference's env layer is ALE behind atari-py (SURVEY.md
§2 row 2) — host-side C++ that caps every TPU design at the host->device
frame-transfer rate.  These games keep the reference's observation contract
(uint8 single-channel frames, small discrete action set, clipped-scale
rewards, episodic terminals + time-limit truncations) but are written as pure
jittable functions of (state, action, key), so they can be:

  * vmapped over lanes  -> one [L, H, W] frame tensor per tick, on device;
  * fused into the Anakin trainer's act->step->append->learn graph
    (train_anakin.py), eliminating host traffic entirely — the full Podracer
    "everything on chip" topology the reference's Redis loop cannot express;
  * driven from the host through the ordinary `Env` adapter (JaxGameEnv) so
    every trainer/eval path runs them unchanged.

Dynamics are in the MinAtar family (Young & Tian, arXiv:1903.03176 — cited
as the public spec these games follow; implementations here are original):
10x10 logic grids, one entity class per game mechanic, rendered by intensity
so a frame-stacking conv agent must learn motion.  Design rules for TPU:
static shapes everywhere, no data-dependent Python control flow (jnp.where
only), randomness through explicit keys, state as a NamedTuple of arrays.

Intended dynamics note (collision semantics): collisions are checked at
post-move coincidence only.  On ticks where two entities move toward each
other (a bullet and a marching alien, a bomb and the player, a car and the
freeway chicken) they can swap cells without registering a hit — classic
discrete-grid "tunneling".  This is deliberate: it keeps every entity update
one vectorised move-then-compare (no sub-tick sweep), it is identical for
the agent and for the scripted baselines (jaxsuite.py), and MinAtar-family
play is unaffected beyond an occasional lucky pass-through that the agent
can in fact learn to exploit, like any other game rule.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rainbow_iqn_apex_tpu.envs.base import Env, TimeStep

G = 10  # logic grid is GxG for every game

# render intensities (distinct so the conv net can tell entities apart)
I_PLAYER = jnp.uint8(140)
I_BALL = jnp.uint8(255)
I_BRICK = jnp.uint8(90)
I_ENEMY = jnp.uint8(200)
I_GOLD = jnp.uint8(255)
I_BULLET = jnp.uint8(255)


def _upscale(grid: jnp.ndarray, cell: int) -> jnp.ndarray:
    """[G, G] u8 -> [G*cell, G*cell] u8 (nearest-neighbour)."""
    return jnp.repeat(jnp.repeat(grid, cell, axis=0), cell, axis=1)


def _rand_signs(key, shape=()) -> jnp.ndarray:
    """Uniform ±1 i32 draw — the shared direction-sampling convention."""
    return jnp.where(jax.random.bernoulli(key, 0.5, shape), 1, -1).astype(
        jnp.int32
    )


class DeviceGame:
    """Base: a pure-functional game.  Subclasses define init/step/render as
    jit-safe single-instance functions; batching is the caller's vmap."""

    num_actions: int
    # frame = (G*cell, G*cell).  cell=8 -> 80x80: the canonical DQN trunk
    # reduces that to a 6x6 feature grid; at cell=5 (50x50) the final grid is
    # only 2x2, too coarse to localise entities (measured: catch learns ~3x
    # slower at 50x50 than at 80x80 on both the host and fused trainers).
    cell: int = 8

    @property
    def frame_shape(self) -> Tuple[int, int]:
        return (G * self.cell, G * self.cell)

    def init(self, key):  # -> state
        raise NotImplementedError

    def step(self, state, action, key):  # -> (state, reward f32, term bool, trunc bool)
        raise NotImplementedError

    def render(self, state) -> jnp.ndarray:  # -> [H, W] uint8
        raise NotImplementedError


# --------------------------------------------------------------------------
# Catch — the learnability anchor (same rules as envs/toy.py CatchEnv)
# --------------------------------------------------------------------------


class CatchState(NamedTuple):
    ball_r: jnp.ndarray  # i32 scalar
    ball_c: jnp.ndarray
    paddle: jnp.ndarray
    t: jnp.ndarray


class CatchGame(DeviceGame):
    """Ball falls straight down; catch it with the bottom paddle.
    Actions: 0=stay 1=left 2=right.  +1 catch / -1 miss, episode ends at the
    bottom row — the in-graph twin of toy.py's CatchEnv (SURVEY §4 Pong-role)."""

    num_actions = 3

    def init(self, key) -> CatchState:
        return CatchState(
            ball_r=jnp.int32(0),
            ball_c=jax.random.randint(key, (), 0, G, jnp.int32),
            paddle=jnp.int32(G // 2),
            t=jnp.int32(0),
        )

    def step(self, s: CatchState, action, key):
        move = jnp.array([0, -1, 1], jnp.int32)[action]
        paddle = jnp.clip(s.paddle + move, 0, G - 1)
        ball_r = s.ball_r + 1
        ball_c = self._ball_col(s, ball_r)
        terminal = ball_r == G - 1
        reward = jnp.where(
            terminal, jnp.where(paddle == ball_c, 1.0, -1.0), 0.0
        ).astype(jnp.float32)
        ns = s._replace(ball_r=ball_r, ball_c=ball_c, paddle=paddle,
                        t=s.t + 1)
        return ns, reward, terminal, jnp.bool_(False)

    def _ball_col(self, s, ball_r):
        """Ball column on entering row `ball_r` — the dynamics hook the
        seeded-level variant overrides (base ball falls straight down)."""
        return s.ball_c

    def render(self, s: CatchState) -> jnp.ndarray:
        grid = jnp.zeros((G, G), jnp.uint8)
        grid = grid.at[s.ball_r, s.ball_c].set(I_BALL)
        grid = grid.at[G - 1, s.paddle].set(I_PLAYER)
        return _upscale(grid, self.cell)


# --------------------------------------------------------------------------
# Breakout
# --------------------------------------------------------------------------


class BreakoutState(NamedTuple):
    paddle: jnp.ndarray  # i32 col
    ball_r: jnp.ndarray
    ball_c: jnp.ndarray
    dr: jnp.ndarray  # i32 in {-1, +1}
    dc: jnp.ndarray
    bricks: jnp.ndarray  # [G, G] bool (rows 1..3 used)
    t: jnp.ndarray


class BreakoutGame(DeviceGame):
    """Paddle/ball/brick-wall: +1 per brick, wall respawns when cleared,
    episode ends when the ball passes the paddle.  Actions: 0=stay 1=left
    2=right."""

    num_actions = 3
    BRICK_ROWS = (1, 2, 3)

    def _wall(self) -> jnp.ndarray:
        bricks = jnp.zeros((G, G), bool)
        for r in self.BRICK_ROWS:
            bricks = bricks.at[r].set(True)
        return bricks

    def init(self, key) -> BreakoutState:
        kc, kd = jax.random.split(key)
        return BreakoutState(
            paddle=jnp.int32(G // 2),
            ball_r=jnp.int32(4),
            ball_c=jax.random.randint(kc, (), 0, G, jnp.int32),
            dr=jnp.int32(1),
            dc=_rand_signs(kd),
            bricks=self._wall(),
            t=jnp.int32(0),
        )

    def step(self, s: BreakoutState, action, key):
        move = jnp.array([0, -1, 1], jnp.int32)[action]
        paddle = jnp.clip(s.paddle + move, 0, G - 1)

        # diagonal flight with side/top reflection
        nc = s.ball_c + s.dc
        dc = jnp.where((nc < 0) | (nc > G - 1), -s.dc, s.dc)
        nc = jnp.clip(nc, 0, G - 1)  # reflected into the wall cell it hit
        nr = s.ball_r + s.dr
        dr = jnp.where(nr < 0, jnp.int32(1), s.dr)
        nr = jnp.where(nr < 0, jnp.int32(1), nr)

        # brick hit: clear it, bounce back (ball keeps its old row)
        nr_idx = jnp.clip(nr, 0, G - 1)
        hit_brick = s.bricks[nr_idx, nc]
        bricks = s.bricks.at[nr_idx, nc].set(
            jnp.where(hit_brick, False, s.bricks[nr_idx, nc])
        )
        reward = jnp.where(hit_brick, 1.0, 0.0).astype(jnp.float32)
        dr = jnp.where(hit_brick, -dr, dr)
        nr = jnp.where(hit_brick, s.ball_r, nr)

        # paddle plane: bounce if aligned, lose otherwise
        at_bottom = nr >= G - 1
        caught = at_bottom & (nc == paddle)
        dr = jnp.where(caught, jnp.int32(-1), dr)
        nr = jnp.where(caught, jnp.int32(G - 2), nr)
        terminal = at_bottom & ~caught

        # cleared wall respawns (dense long-horizon reward, like the
        # reference's multi-life Atari episodes)
        cleared = ~bricks.any()
        bricks = jnp.where(cleared, self._respawn(s), bricks)

        # _replace keeps any subclass state fields (e.g. the variant's
        # per-level wall template) flowing through unchanged
        ns = s._replace(paddle=paddle, ball_r=nr, ball_c=nc, dr=dr, dc=dc,
                        bricks=bricks, t=s.t + 1)
        return ns, reward, terminal, jnp.bool_(False)

    def _respawn(self, s) -> jnp.ndarray:
        return self._wall()

    def render(self, s: BreakoutState) -> jnp.ndarray:
        grid = jnp.where(s.bricks, I_BRICK, jnp.uint8(0)).astype(jnp.uint8)
        grid = grid.at[s.ball_r, s.ball_c].set(I_BALL)
        grid = grid.at[G - 1, s.paddle].set(I_PLAYER)
        return _upscale(grid, self.cell)


# --------------------------------------------------------------------------
# Freeway
# --------------------------------------------------------------------------


class FreewayState(NamedTuple):
    chicken: jnp.ndarray  # i32 row (col fixed at CHICKEN_COL)
    cars: jnp.ndarray  # [8] i32 col of the car in lanes rows 1..8
    t: jnp.ndarray


class FreewayGame(DeviceGame):
    """Cross 8 lanes of traffic: +1 at the top (then restart at the bottom);
    a collision sends the chicken back down.  No terminal state — episodes
    end by time-limit truncation (`cap` ticks), exercising the two-channel
    terminal/truncation replay contract end-to-end."""

    num_actions = 3  # 0=stay 1=up 2=down
    CHICKEN_COL = 4
    # per-lane (speed, direction): car advances every `speed` ticks
    SPEEDS = jnp.array([2, 3, 2, 4, 2, 3, 4, 2], jnp.int32)
    DIRS = jnp.array([1, -1, 1, -1, -1, 1, -1, 1], jnp.int32)

    def __init__(self, cap: int = 500):
        self.cap = cap

    def init(self, key) -> FreewayState:
        return FreewayState(
            chicken=jnp.int32(G - 1),
            cars=jax.random.randint(key, (8,), 0, G, jnp.int32),
            t=jnp.int32(0),
        )

    def _lane_dynamics(self, s):
        """(speeds [8], dirs [8]) — the variant subclass reads them from the
        per-level state instead of the class constants."""
        return self.SPEEDS, self.DIRS

    def step(self, s: FreewayState, action, key):
        move = jnp.array([0, -1, 1], jnp.int32)[action]
        chicken = jnp.clip(s.chicken + move, 0, G - 1)

        speeds, dirs = self._lane_dynamics(s)
        advance = (s.t % speeds) == 0
        cars = (s.cars + jnp.where(advance, dirs, 0)) % G

        # lanes are rows 1..8; car in the chicken's row at the chicken's col?
        lane = chicken - 1  # -1 or 8+ when off the road
        on_road = (lane >= 0) & (lane < 8)
        car_col = cars[jnp.clip(lane, 0, 7)]
        hit = on_road & (car_col == self.CHICKEN_COL)
        chicken = jnp.where(hit, jnp.int32(G - 1), chicken)

        scored = chicken == 0
        reward = jnp.where(scored, 1.0, 0.0).astype(jnp.float32)
        chicken = jnp.where(scored, jnp.int32(G - 1), chicken)

        t = s.t + 1
        trunc = t >= self.cap
        ns = s._replace(chicken=chicken, cars=cars, t=t)
        return ns, reward, jnp.bool_(False), trunc

    def render(self, s: FreewayState) -> jnp.ndarray:
        grid = jnp.zeros((G, G), jnp.uint8)
        grid = grid.at[jnp.arange(1, 9), s.cars].set(I_ENEMY)
        grid = grid.at[s.chicken, self.CHICKEN_COL].set(I_PLAYER)
        return _upscale(grid, self.cell)


# --------------------------------------------------------------------------
# Asterix
# --------------------------------------------------------------------------


class AsterixState(NamedTuple):
    pr: jnp.ndarray  # player row/col, i32
    pc: jnp.ndarray
    active: jnp.ndarray  # [8] bool — one entity per lane (rows 1..8)
    col: jnp.ndarray  # [8] i32
    dirn: jnp.ndarray  # [8] i32 in {-1, +1}
    gold: jnp.ndarray  # [8] bool — collectible vs lethal
    t: jnp.ndarray


class AsterixGame(DeviceGame):
    """Dodge enemies, collect gold.  Entities stream through 8 lanes; walking
    into gold is +1, into an enemy is death.  Actions: 0=stay 1=left 2=right
    3=up 4=down (player confined to the road rows 1..8)."""

    num_actions = 5
    SPAWN_P = 0.25  # per empty lane per tick
    MOVE_EVERY = 2  # entities advance every 2nd tick

    def _lane_speeds(self, s):
        """[8] i32 per-lane entity beat (advance every `speed` ticks) — the
        variant subclass reads it from the per-level state."""
        return jnp.full((8,), self.MOVE_EVERY, jnp.int32)

    def _spawn_dirs(self, s, key):
        """[8] i32 direction a spawn in each lane would take."""
        return _rand_signs(key, (8,))

    def _gold_probs(self, s):
        """[8] f32 per-lane gold probability (base: MinAtar's 1-in-3)."""
        return jnp.full((8,), 1.0 / 3.0, jnp.float32)

    def init(self, key) -> AsterixState:
        return AsterixState(
            pr=jnp.int32(G // 2),
            pc=jnp.int32(G // 2),
            active=jnp.zeros(8, bool),
            col=jnp.zeros(8, jnp.int32),
            dirn=jnp.ones(8, jnp.int32),
            gold=jnp.zeros(8, bool),
            t=jnp.int32(0),
        )

    def step(self, s: AsterixState, action, key):
        k_spawn, k_dir, k_gold = jax.random.split(key, 3)
        dmove = jnp.array([[0, 0], [0, -1], [0, 1], [-1, 0], [1, 0]], jnp.int32)
        pr = jnp.clip(s.pr + dmove[action, 0], 1, 8)
        pc = jnp.clip(s.pc + dmove[action, 1], 0, G - 1)

        # advance entities on their beat; deactivate on exit
        advance = s.active & ((s.t % self._lane_speeds(s)) == 0)
        col = s.col + jnp.where(advance, s.dirn, 0)
        exited = (col < 0) | (col > G - 1)
        active = s.active & ~exited
        col = jnp.clip(col, 0, G - 1)

        # spawn into empty lanes (left edge moving right / right edge moving
        # left), 1-in-3 gold — MinAtar's treasure ratio
        spawn = (~active) & (jax.random.uniform(k_spawn, (8,)) < self.SPAWN_P)
        new_dir = self._spawn_dirs(s, k_dir)
        new_gold = jax.random.uniform(k_gold, (8,)) < self._gold_probs(s)
        dirn = jnp.where(spawn, new_dir, s.dirn)
        col = jnp.where(spawn, jnp.where(new_dir > 0, 0, G - 1), col)
        gold = jnp.where(spawn, new_gold, s.gold)
        active = active | spawn

        # collision in the player's lane
        lane = pr - 1
        collide = active[lane] & (col[lane] == pc)
        hit_gold = collide & gold[lane]
        terminal = collide & ~gold[lane]
        reward = jnp.where(hit_gold, 1.0, 0.0).astype(jnp.float32)
        active = active.at[lane].set(jnp.where(hit_gold, False, active[lane]))

        ns = s._replace(pr=pr, pc=pc, active=active, col=col, dirn=dirn,
                        gold=gold, t=s.t + 1)
        return ns, reward, terminal, jnp.bool_(False)

    def render(self, s: AsterixState) -> jnp.ndarray:
        grid = jnp.zeros((G, G), jnp.uint8)
        lane_rows = jnp.arange(1, 9)
        val = jnp.where(
            s.active, jnp.where(s.gold, I_GOLD, I_ENEMY), jnp.uint8(0)
        ).astype(jnp.uint8)
        grid = grid.at[lane_rows, s.col].max(val)
        grid = grid.at[s.pr, s.pc].set(I_PLAYER)
        return _upscale(grid, self.cell)


# --------------------------------------------------------------------------
# Space Invaders
# --------------------------------------------------------------------------


class InvadersState(NamedTuple):
    pc: jnp.ndarray  # player col (row G-1), i32
    aliens: jnp.ndarray  # [G, G] bool (block starts rows 1..4, cols 2..7)
    adir: jnp.ndarray  # i32 march direction
    shot_r: jnp.ndarray  # player bullet (-1 row = inactive)
    shot_c: jnp.ndarray
    bomb_r: jnp.ndarray  # alien bomb (-1 row = inactive)
    bomb_c: jnp.ndarray
    t: jnp.ndarray


class InvadersGame(DeviceGame):
    """March-and-shoot: +1 per alien; death by bomb or by the fleet reaching
    the bottom row; fleet respawns when cleared.  Actions: 0=stay 1=left
    2=right 3=fire."""

    num_actions = 4
    MARCH_EVERY = 4  # fleet advances every 4th tick
    BOMB_EVERY = 6  # a random front-line alien bombs every 6th tick

    def _fleet(self) -> jnp.ndarray:
        a = jnp.zeros((G, G), bool)
        return a.at[1:5, 2:8].set(True)

    def _march_every(self, s):
        """Fleet march beat — the variant subclass reads it per-level."""
        return jnp.int32(self.MARCH_EVERY)

    def _bomb_every(self, s):
        """Bomb release beat — the variant subclass reads it per-level."""
        return jnp.int32(self.BOMB_EVERY)

    def _respawn_fleet(self, s) -> jnp.ndarray:
        """Fleet pattern a cleared wave respawns with."""
        return self._fleet()

    def init(self, key) -> InvadersState:
        return InvadersState(
            pc=jnp.int32(G // 2),
            aliens=self._fleet(),
            adir=jnp.int32(1),
            shot_r=jnp.int32(-1),
            shot_c=jnp.int32(0),
            bomb_r=jnp.int32(-1),
            bomb_c=jnp.int32(0),
            t=jnp.int32(0),
        )

    def step(self, s: InvadersState, action, key):
        move = jnp.array([0, -1, 1, 0], jnp.int32)[action]
        pc = jnp.clip(s.pc + move, 0, G - 1)

        # fire: one player bullet in flight at a time
        fire = (action == 3) & (s.shot_r < 0)
        shot_r = jnp.where(fire, jnp.int32(G - 2), s.shot_r - (s.shot_r >= 0))
        shot_c = jnp.where(fire, pc, s.shot_c)

        # bullet hits the alien it flies into
        shot_live = shot_r >= 0
        sr = jnp.clip(shot_r, 0, G - 1)
        hit = shot_live & s.aliens[sr, shot_c]
        aliens = s.aliens.at[sr, shot_c].set(
            jnp.where(hit, False, s.aliens[sr, shot_c])
        )
        reward = jnp.where(hit, 1.0, 0.0).astype(jnp.float32)
        shot_r = jnp.where(hit, jnp.int32(-1), shot_r)

        # fleet march: sideways on the beat, down + reverse at an edge
        march = (s.t % self._march_every(s)) == 0
        cols_occ = aliens.any(axis=0)
        leftmost = jnp.argmax(cols_occ)
        rightmost = G - 1 - jnp.argmax(cols_occ[::-1])
        at_edge = jnp.where(s.adir > 0, rightmost >= G - 1, leftmost <= 0)
        drop = march & at_edge & cols_occ.any()
        shift = march & ~at_edge
        aliens = jnp.where(drop, jnp.roll(aliens, 1, axis=0), aliens)
        adir = jnp.where(drop, -s.adir, s.adir)
        aliens = jnp.where(shift, jnp.roll(aliens, s.adir, axis=1), aliens)

        # bombing: a pseudorandom occupied column releases a bomb from its
        # lowest alien on the bomb beat
        bomb_due = ((s.t % self._bomb_every(s)) == 0) & (s.bomb_r < 0) & aliens.any()
        occ = aliens.any(axis=0)
        pick = jax.random.randint(key, (), 0, G, jnp.int32)
        # nearest occupied column to `pick` (static-shape argmin trick)
        dist = jnp.where(occ, jnp.abs(jnp.arange(G) - pick), G + 1)
        bcol = jnp.argmin(dist).astype(jnp.int32)
        lowest = G - 1 - jnp.argmax(aliens[::-1, bcol]).astype(jnp.int32)
        bomb_r = jnp.where(bomb_due, lowest + 1, s.bomb_r + (s.bomb_r >= 0))
        bomb_c = jnp.where(bomb_due, bcol, s.bomb_c)
        bomb_r = jnp.where(bomb_r > G - 1, jnp.int32(-1), bomb_r)

        # deaths: bomb reaches the player row at the player's col, or the
        # fleet reaches the bottom row
        killed = (bomb_r == G - 1) & (bomb_c == pc)
        terminal = killed | aliens[G - 1].any()

        # cleared fleet respawns
        cleared = ~aliens.any()
        aliens = jnp.where(cleared, self._respawn_fleet(s), aliens)

        ns = s._replace(pc=pc, aliens=aliens, adir=adir, shot_r=shot_r,
                        shot_c=shot_c, bomb_r=bomb_r, bomb_c=bomb_c, t=s.t + 1)
        return ns, reward, terminal, jnp.bool_(False)

    def render(self, s: InvadersState) -> jnp.ndarray:
        grid = jnp.where(s.aliens, I_ENEMY, jnp.uint8(0)).astype(jnp.uint8)
        shot_live = s.shot_r >= 0
        grid = grid.at[jnp.clip(s.shot_r, 0, G - 1), s.shot_c].max(
            jnp.where(shot_live, I_BULLET, jnp.uint8(0))
        )
        bomb_live = s.bomb_r >= 0
        grid = grid.at[jnp.clip(s.bomb_r, 0, G - 1), s.bomb_c].max(
            jnp.where(bomb_live, I_BULLET, jnp.uint8(0))
        )
        grid = grid.at[G - 1, s.pc].set(I_PLAYER)
        return _upscale(grid, self.cell)


# --------------------------------------------------------------------------
# seeded level variants (the Procgen-class generalization stand-in,
# BASELINE.md config 5): "<game>@var" draws each episode's level from a
# TRAIN pool of seeds, "<game>@var-test" from a disjoint HELD-OUT pool.
# A level is a deterministic function of its id (fold_in of a fixed base
# key), so train/test splits are reproducible everywhere; per-episode
# randomness (ball entry, car phases) stays on top of the level layout.
# --------------------------------------------------------------------------

N_TRAIN_LEVELS = 16
N_TEST_LEVELS = 16
_LEVEL_BASE_KEY = 9137


def _level_fold(level):
    """Level id -> the level's layout key.  `level` may be a traced i32, so
    per-level eval harnesses can vmap a pinned level over lanes."""
    return jax.random.fold_in(jax.random.PRNGKey(_LEVEL_BASE_KEY), level)


def _draw_level(pool_base: int, pool_size: int, key):
    return pool_base + jax.random.randint(key, (), 0, pool_size, jnp.int32)


class BreakoutVarState(NamedTuple):
    paddle: jnp.ndarray
    ball_r: jnp.ndarray
    ball_c: jnp.ndarray
    dr: jnp.ndarray
    dc: jnp.ndarray
    bricks: jnp.ndarray
    wall: jnp.ndarray  # [G, G] bool — this level's respawn template
    t: jnp.ndarray


class BreakoutVarGame(BreakoutGame):
    """Level-randomized breakout: the level id fixes the brick-wall pattern
    (random ~3/4-density mask over rows 1..3) and the paddle start; ball
    entry column/direction remain per-episode randomness.  The wall template
    rides in the state so cleared walls respawn THIS level's pattern."""

    def __init__(self, pool_base: int, pool_size: int):
        self.pool_base = pool_base
        self.pool_size = pool_size

    def init(self, key) -> BreakoutVarState:
        kl, kc, kd = jax.random.split(key, 3)
        level = _draw_level(self.pool_base, self.pool_size, kl)
        return self._init_level(level, kc, kd)

    def init_at_level(self, level, key) -> BreakoutVarState:
        """Pinned-level init (per-level generalization eval): the layout
        comes from `level` (traced i32 welcome), per-episode randomness
        (ball entry column/direction) from `key`."""
        kc, kd = jax.random.split(key)
        return self._init_level(level, kc, kd)

    def _init_level(self, level, kc, kd) -> BreakoutVarState:
        kw, kp = jax.random.split(_level_fold(level))
        mask = jax.random.uniform(kw, (3, G)) < 0.75
        mask = mask.at[1, G // 2].set(True)  # a level can never be brickless
        wall = jnp.zeros((G, G), bool).at[1:4].set(mask)
        return BreakoutVarState(
            paddle=jax.random.randint(kp, (), 0, G, jnp.int32),
            ball_r=jnp.int32(4),
            ball_c=jax.random.randint(kc, (), 0, G, jnp.int32),
            dr=jnp.int32(1),
            dc=_rand_signs(kd),
            # distinct buffers: bricks and wall both ride the (donated)
            # fused-trainer carry, and donating one buffer twice is a
            # runtime error
            bricks=jnp.array(wall),
            wall=wall,
            t=jnp.int32(0),
        )

    def _respawn(self, s) -> jnp.ndarray:
        return s.wall


class FreewayVarState(NamedTuple):
    chicken: jnp.ndarray
    cars: jnp.ndarray
    speeds: jnp.ndarray  # [8] i32 — this level's per-lane beat
    dirs: jnp.ndarray  # [8] i32 in {-1, +1}
    t: jnp.ndarray


class FreewayVarGame(FreewayGame):
    """Level-randomized freeway: the level id fixes per-lane speeds (2..4)
    and directions; car starting phases remain per-episode randomness."""

    def __init__(self, pool_base: int, pool_size: int, cap: int = 500):
        super().__init__(cap=cap)
        self.pool_base = pool_base
        self.pool_size = pool_size

    def init(self, key) -> FreewayVarState:
        kl, kc = jax.random.split(key)
        level = _draw_level(self.pool_base, self.pool_size, kl)
        return self._init_level(level, kc)

    def init_at_level(self, level, key) -> FreewayVarState:
        """Pinned-level init: lane speeds/dirs from `level` (traced i32
        welcome), car starting phases from `key`."""
        return self._init_level(level, key)

    def _init_level(self, level, kc) -> FreewayVarState:
        ks, kd = jax.random.split(_level_fold(level))
        return FreewayVarState(
            chicken=jnp.int32(G - 1),
            cars=jax.random.randint(kc, (8,), 0, G, jnp.int32),
            speeds=jax.random.randint(ks, (8,), 2, 5, jnp.int32),
            dirs=_rand_signs(kd, (8,)),
            t=jnp.int32(0),
        )

    def _lane_dynamics(self, s):
        return s.speeds, s.dirs


class AsterixVarState(NamedTuple):
    pr: jnp.ndarray
    pc: jnp.ndarray
    active: jnp.ndarray
    col: jnp.ndarray
    dirn: jnp.ndarray
    gold: jnp.ndarray
    speeds: jnp.ndarray  # [8] i32 — this level's per-lane entity beat
    lane_dir: jnp.ndarray  # [8] i32 — this level's fixed per-lane stream dir
    gold_p: jnp.ndarray  # [8] f32 — this level's per-lane gold probability
    t: jnp.ndarray


class AsterixVarGame(AsterixGame):
    """Level-randomized asterix: the level id fixes per-lane entity speeds
    (beat 1..3 — some lanes faster than the base game's 2), a fixed stream
    direction per lane, and a per-lane gold probability (the 'gold layout');
    spawn timing and which lanes fire remain per-episode randomness."""

    def __init__(self, pool_base: int, pool_size: int):
        self.pool_base = pool_base
        self.pool_size = pool_size

    def init(self, key) -> AsterixVarState:
        return self.init_at_level(
            _draw_level(self.pool_base, self.pool_size, key), key
        )

    def init_at_level(self, level, key) -> AsterixVarState:
        """Pinned-level init: asterix levels fully determine the initial
        state (spawn timing is step randomness), so `key` is unused."""
        del key
        ks, kd, kg = jax.random.split(_level_fold(level), 3)
        return AsterixVarState(
            pr=jnp.int32(G // 2),
            pc=jnp.int32(G // 2),
            active=jnp.zeros(8, bool),
            col=jnp.zeros(8, jnp.int32),
            dirn=jnp.ones(8, jnp.int32),
            gold=jnp.zeros(8, bool),
            speeds=jax.random.randint(ks, (8,), 1, 4, jnp.int32),
            lane_dir=_rand_signs(kd, (8,)),
            gold_p=jax.random.uniform(kg, (8,), minval=0.15, maxval=0.5),
            t=jnp.int32(0),
        )

    def _lane_speeds(self, s):
        return s.speeds

    def _spawn_dirs(self, s, key):
        return s.lane_dir

    def _gold_probs(self, s):
        return s.gold_p


class InvadersVarState(NamedTuple):
    pc: jnp.ndarray
    aliens: jnp.ndarray
    adir: jnp.ndarray
    shot_r: jnp.ndarray
    shot_c: jnp.ndarray
    bomb_r: jnp.ndarray
    bomb_c: jnp.ndarray
    fleet: jnp.ndarray  # [G, G] bool — this level's respawn template
    march_every: jnp.ndarray  # i32 — this level's march beat
    bomb_every: jnp.ndarray  # i32 — this level's bomb beat
    t: jnp.ndarray


class InvadersVarGame(InvadersGame):
    """Level-randomized invaders: the level id fixes the initial fleet
    pattern (~4/5-density mask over the 4x6 block), the march beat (3..5)
    and the bomb beat (4..8), plus the starting march direction; bomb column
    choice stays per-episode randomness.  The fleet template rides in the
    state so cleared waves respawn THIS level's pattern."""

    def __init__(self, pool_base: int, pool_size: int):
        self.pool_base = pool_base
        self.pool_size = pool_size

    def init(self, key) -> InvadersVarState:
        return self.init_at_level(
            _draw_level(self.pool_base, self.pool_size, key), key
        )

    def init_at_level(self, level, key) -> InvadersVarState:
        """Pinned-level init: invaders levels fully determine the initial
        state (bomb columns are step randomness), so `key` is unused."""
        del key
        kf, km, kb, kd = jax.random.split(_level_fold(level), 4)
        mask = jax.random.uniform(kf, (4, 6)) < 0.8
        mask = mask.at[0, 3].set(True)  # a level can never start alien-less
        fleet = jnp.zeros((G, G), bool).at[1:5, 2:8].set(mask)
        return InvadersVarState(
            pc=jnp.int32(G // 2),
            # distinct buffers: aliens and fleet both ride the (donated)
            # fused-trainer carry, and donating one buffer twice is a
            # runtime error
            aliens=jnp.array(fleet),
            adir=_rand_signs(kd),
            shot_r=jnp.int32(-1),
            shot_c=jnp.int32(0),
            bomb_r=jnp.int32(-1),
            bomb_c=jnp.int32(0),
            fleet=fleet,
            march_every=jax.random.randint(km, (), 3, 6, jnp.int32),
            bomb_every=jax.random.randint(kb, (), 4, 9, jnp.int32),
            t=jnp.int32(0),
        )

    def _march_every(self, s):
        return s.march_every

    def _bomb_every(self, s):
        return s.bomb_every

    def _respawn_fleet(self, s) -> jnp.ndarray:
        return s.fleet


class CatchVarState(NamedTuple):
    ball_r: jnp.ndarray
    ball_c: jnp.ndarray
    paddle: jnp.ndarray
    drift: jnp.ndarray  # [G] i32 in {-1,0,+1} — this level's per-row wind
    t: jnp.ndarray


class CatchVarGame(CatchGame):
    """Level-randomized catch: the level id fixes a per-row lateral drift
    pattern ('wind' in {-1,0,+1} per row) the ball rides on its way down;
    ball entry column remains per-episode randomness.  Completes 5/5
    variant coverage of the jaxsuite (the Procgen-class stand-in,
    BASELINE.md config 5).

    Design note — this is the suite's NULL-CALIBRATION probe: with the
    terminal row wind-free (see _init_level), a level-blind greedy tracker
    measures 1.0 on BOTH pools (wall clipping lets the 1-cell/step paddle
    catch any persistent wind), so a competent agent's train/held-out gap
    should be ~0 BY CONSTRUCTION.  A measured nonzero gap on catch@var
    flags harness or pool-variance artifacts, not memorization — the
    memorization-sensitive probes are the other four variants, whose
    layouts/dynamics gate score more deeply.  (With terminal wind left in,
    tracking measured 0.06 train / -0.63 held-out vs random -0.69: the
    last-row shift is a coin-flip for any pixel policy since it lands
    after the paddle's final move, which would make the off_random gate
    unclearable by fair play — hence wind-free.)"""

    def __init__(self, pool_base: int, pool_size: int):
        self.pool_base = pool_base
        self.pool_size = pool_size

    def init(self, key) -> CatchVarState:
        kl, kc = jax.random.split(key)
        return self._init_level(_draw_level(self.pool_base, self.pool_size,
                                            kl), kc)

    def init_at_level(self, level, key) -> CatchVarState:
        """Pinned-level init: the wind from `level` (traced i32 welcome),
        the ball entry column from `key`."""
        return self._init_level(level, key)

    def _init_level(self, level, kc) -> CatchVarState:
        drift = jax.random.randint(_level_fold(level), (G,), -1, 2,
                                   jnp.int32)
        # no wind on the terminal row: a last-step shift lands after the
        # paddle's final move and is unobservable-before-commit, so it
        # would be a coin-flip for ANY pixel policy, memorizer or not
        drift = drift.at[G - 1].set(0)
        return CatchVarState(
            ball_r=jnp.int32(0),
            ball_c=jax.random.randint(kc, (), 0, G, jnp.int32),
            paddle=jnp.int32(G // 2),
            drift=drift,
            t=jnp.int32(0),
        )

    def _ball_col(self, s, ball_r):
        return jnp.clip(s.ball_c + s.drift[ball_r], 0, G - 1)


VARIANT_GAMES = {
    "catch": CatchVarGame,
    "breakout": BreakoutVarGame,
    "freeway": FreewayVarGame,
    "asterix": AsterixVarGame,
    "invaders": InvadersVarGame,
}


# --------------------------------------------------------------------------
# registry + batched auto-reset step (the Anakin building block)
# --------------------------------------------------------------------------

GAMES = {
    "catch": CatchGame,
    "breakout": BreakoutGame,
    "freeway": FreewayGame,
    "asterix": AsterixGame,
    "invaders": InvadersGame,
}

# the suite's episode cap, in ticks — the SABER 30-min-cap analog for these
# games: eval/baseline rollouts score each lane's FIRST episode, and a lane
# still mid-episode at the cap contributes its partial return (capped-return
# semantics, eval.py parity) rather than being censored, so unbounded games
# (breakout/invaders respawn their targets) cannot under-count strong agents
EPISODE_TICK_BUDGET = {"catch": 64, "breakout": 512, "freeway": 600,
                       "asterix": 512, "invaders": 512}


def build_rollout(game: "DeviceGame", action_fn, episodes: int,
                  max_ticks: int, history: int = 0, actor_init=None,
                  init_fn=None):
    """One jitted (aux, key) -> first-episode returns [episodes] rollout over
    `episodes` parallel auto-reset lanes — the single episode-accounting core
    shared by the trainers' in-graph eval (train_anakin.build_fused_eval) and
    the benchmark baselines (jaxsuite.rollout_returns).

    `action_fn(aux, states, stack, key) -> actions [episodes]` chooses
    actions from either the game states (state-based scripts; `history=0`
    skips stack upkeep) or the device frame stack (`history=C` maintains a
    [L, H, W, C] stack with cut-zeroing exactly like the training tick).

    Recurrent actors: pass `actor_init(episodes) -> actor_state` (a pytree
    of [episodes, ...] leaves whose reset value is zero, e.g. an LSTM (c, h))
    and an `action_fn(aux, states, stack, key, actor_state) -> (actions,
    actor_state)`; lanes whose episode cut are zero-reset by a keep mask,
    exactly like the training tick's LSTM handling (train_anakin_r2d2.py).

    `init_fn(aux, key) -> [episodes, ...] state pytree` overrides the default
    per-lane pool init (per-level generalization eval pins each lane's level
    via `game.init_at_level`; taking `aux` lets the lane->level assignment be
    a traced argument, so one compile serves every level chunk).  Mid-rollout
    auto-resets still draw from the game's own pool, which is harmless under
    first-episode accounting.

    Returns are capped, never censored: a lane whose first episode is still
    running at `max_ticks` yields its partial return."""
    step = batched_reset_step(game)
    h, w = game.frame_shape

    def mask_actor(actor_state, keep):
        return jax.tree.map(
            lambda x: x * keep.astype(x.dtype).reshape(
                (-1,) + (1,) * (x.ndim - 1)
            ),
            actor_state,
        )

    @jax.jit
    def run(aux, key):
        k_init, k_scan = jax.random.split(key)
        states = (init_fn(aux, k_init) if init_fn is not None
                  else batched_init(game, k_init, episodes))

        def tick(carry, k):
            states, ep, stack, frame, keep, first, done, actor = carry
            ka, ks = jax.random.split(k)
            if history:
                from rainbow_iqn_apex_tpu.parallel.multihost import shift_stack

                stack = shift_stack(stack, frame, keep)
            if actor_init is None:
                actions = action_fn(aux, states, stack, ka)
            else:
                actions, actor = action_fn(aux, states, stack, ka, actor)
            states, ep, nframe, _r, term, trunc, out_ret = step(
                states, ep, actions, ks
            )
            ended = ~jnp.isnan(out_ret)
            first = jnp.where(ended & ~done, out_ret, first)
            done = done | ended
            keep = (~(term | trunc)).astype(jnp.uint8)
            if actor_init is not None:
                actor = mask_actor(actor, keep)
            return (states, ep, stack, nframe, keep, first, done, actor), None

        carry = (
            states, jnp.zeros(episodes),
            jnp.zeros((episodes, h, w, max(history, 1)), jnp.uint8),
            jax.vmap(game.render)(states), jnp.ones(episodes, jnp.uint8),
            jnp.full((episodes,), jnp.nan), jnp.zeros(episodes, bool),
            actor_init(episodes) if actor_init is not None else (),
        )
        carry, _ = jax.lax.scan(tick, carry, jax.random.split(k_scan, max_ticks))
        _s, ep, _st, _f, _k, first, done, _a = carry
        # capped-return semantics: an unfinished first episode scores its
        # running return (ep still tracks the first episode iff never done)
        return jnp.where(done, first, ep)

    return run


def make_device_game(name: str) -> DeviceGame:
    if "@" in name:
        base, variant = name.split("@", 1)
        cls = VARIANT_GAMES.get(base)
        if cls is None:
            raise ValueError(
                f"game '{base}' has no seeded-variant mode (have: "
                f"{', '.join(sorted(VARIANT_GAMES))})"
            )
        if variant == "var":
            return cls(0, N_TRAIN_LEVELS)
        if variant == "var-test":
            return cls(N_TRAIN_LEVELS, N_TEST_LEVELS)
        raise ValueError(
            f"unknown variant '@{variant}' for '{base}' (want '@var' for the "
            "train pool or '@var-test' for the held-out pool)"
        )
    try:
        return GAMES[name]()
    except KeyError:
        raise ValueError(
            f"unknown jax game '{name}' (have: {', '.join(sorted(GAMES))})"
        ) from None


def tick_budget(name: str, default: int = 512) -> int:
    """Episode tick cap for a game id, variant-suffix aware."""
    return EPISODE_TICK_BUDGET.get(name.split("@", 1)[0], default)


def batched_init(game: DeviceGame, key, lanes: int):
    """Per-lane independent initial states: [L, ...] state pytree."""
    return jax.vmap(game.init)(jax.random.split(key, lanes))


def batched_reset_step(game: DeviceGame):
    """Returns step(states, actions, key) -> (states, frames, reward,
    terminal, truncated, ep_return) for [L]-batched lanes, with auto-reset:
    on terminal OR truncation the lane's state is re-initialised and the
    returned frame is the new episode's first observation — the exact
    VectorEnv.step contract (envs/base.py), in-graph.  ep_return is the
    completed episode's return on cut ticks and NaN elsewhere; the running
    accumulator rides in the state pytree via a wrapper field."""

    def one(carry, action, key):
        state, ep_ret = carry
        k_step, k_reset = jax.random.split(key)
        ns, reward, term, trunc = game.step(state, action, k_step)
        cut = term | trunc
        ep_ret = ep_ret + reward
        out_ret = jnp.where(cut, ep_ret, jnp.nan)
        fresh = game.init(k_reset)
        ns = jax.tree.map(lambda new, init: jnp.where(cut, init, new), ns, fresh)
        frame = game.render(ns)
        ep_ret = jnp.where(cut, 0.0, ep_ret)
        return (ns, ep_ret), frame, reward, term, trunc & ~term, out_ret

    vone = jax.vmap(one)

    def step(states, ep_rets, actions, key):
        lanes = actions.shape[0]
        keys = jax.random.split(key, lanes)
        (states, ep_rets), frames, reward, term, trunc, out_ret = vone(
            (states, ep_rets), actions, keys
        )
        return states, ep_rets, frames, reward, term, trunc, out_ret

    return step


# --------------------------------------------------------------------------
# host adapter: a DeviceGame as an ordinary Env (works in every trainer)
# --------------------------------------------------------------------------


class JaxGameEnv(Env):
    """Host-loop adapter.  Heavier per step than a native NumPy env (one
    jitted dispatch per step) — it exists for eval/CI parity and for running
    jax games through the host trainers; the fused Anakin path is where
    these games perform."""

    def __init__(self, name: str, seed: int = 0):
        self.game = make_device_game(name)
        self._key = jax.random.PRNGKey(seed)
        self._step = jax.jit(self.game.step)
        self._init = jax.jit(self.game.init)
        self._render = jax.jit(self.game.render)
        self._state = None
        self._ret = 0.0

    @property
    def num_actions(self) -> int:
        return self.game.num_actions

    @property
    def frame_shape(self) -> Tuple[int, int]:
        return self.game.frame_shape

    def _split(self):
        self._key, k = jax.random.split(self._key)
        return k

    def reset(self) -> np.ndarray:
        self._state = self._init(self._split())
        self._ret = 0.0
        return np.asarray(self._render(self._state))

    def step(self, action: int) -> TimeStep:
        self._state, reward, term, trunc = self._step(
            self._state, jnp.int32(action), self._split()
        )
        reward = float(reward)
        self._ret += reward
        done = bool(term) or bool(trunc)
        info = {"episode_return": self._ret} if done else None
        return TimeStep(
            np.asarray(self._render(self._state)),
            reward,
            bool(term),
            bool(trunc),
            info,
        )
