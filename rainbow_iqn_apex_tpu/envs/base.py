"""Environment interface.

Parity: reference `rainbowiqn/env.py` exposes reset/step/action_space
(SURVEY.md §1 row "Environment").  We keep that minimal surface but define it
as an explicit ABC with a TimeStep record, plus a batched VectorEnv — the
TPU-native actor shape is a *batch* of environments stepped in lockstep so
device inference sees one [L, H, W, C] tensor per tick (SURVEY.md §2 native-dep
table: "batched, vectorized host env layer feeding pmapped actor inference").
"""

from __future__ import annotations

import abc
import dataclasses
import sys
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class TimeStep:
    obs: np.ndarray  # [H, W] uint8 preprocessed frame (pre-stack)
    reward: float
    terminal: bool  # episode over (game over under SABER rules)
    truncated: bool = False  # time-limit cut (108k-frame cap), not a true terminal
    info: Optional[dict] = None


class Env(abc.ABC):
    """Single environment: produces preprocessed uint8 frames."""

    @property
    @abc.abstractmethod
    def num_actions(self) -> int: ...

    @property
    @abc.abstractmethod
    def frame_shape(self) -> Tuple[int, int]: ...

    @abc.abstractmethod
    def reset(self) -> np.ndarray:
        """Start an episode; returns the first preprocessed frame."""

    @abc.abstractmethod
    def step(self, action: int) -> TimeStep: ...

    def close(self) -> None:  # optional
        pass


class VectorEnv:
    """Steps L independent Env instances in lockstep with auto-reset.

    On terminal/truncation the lane resets immediately and the returned obs is
    the first frame of the new episode (the terminal flag tells the replay to
    cut the stack/n-step window there — matching the reference's per-process
    reset-then-continue actor loop, SURVEY §3.2).

    Failure tolerance: the reference's story is "actors only produce data; if
    one dies, restart it by hand" (SURVEY §5 / Ape-X paper).  Here a lane
    whose env raises is rebuilt automatically from ``env_factory`` (when
    given) and reported as a terminal step with zero reward, so the replay
    cleanly cuts the episode — the in-process equivalent of an actor restart.
    """

    def __init__(self, envs: Sequence[Env], env_factory=None, max_lane_restarts: int = 20):
        if not envs:
            raise ValueError("need at least one env")
        self.envs: List[Env] = list(envs)
        self.env_factory = env_factory  # lane index -> new Env
        self.max_lane_restarts = max_lane_restarts
        self.lane_restarts = 0
        self._restarts_per_lane = [0] * len(envs)
        n0, f0 = envs[0].num_actions, envs[0].frame_shape
        if any(e.num_actions != n0 or e.frame_shape != f0 for e in envs):
            raise ValueError("all lanes must share action/frame spaces")

    def __len__(self) -> int:
        return len(self.envs)

    @property
    def num_actions(self) -> int:
        return self.envs[0].num_actions

    @property
    def frame_shape(self) -> Tuple[int, int]:
        return self.envs[0].frame_shape

    def reset(self) -> np.ndarray:
        return np.stack([e.reset() for e in self.envs])

    def step(
        self, actions: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Returns (obs [L,H,W] u8, reward [L] f32, terminal [L] bool,
        truncated [L] bool, episode_return [L] f32 — NaN except on the tick
        an episode ended).

        Both terminal and truncation auto-reset the lane and MUST cut the
        replay's stack/n-step/sequence windows; only `terminal` stops value
        bootstrapping.  Both replays honour this two-channel contract: the
        frame replay stores cuts separately from terminals
        (replay/buffer.py) and the sequence replay flushes on either channel
        while recording done only for true terminals (replay/sequence.py).
        """
        L = len(self.envs)
        obs = np.empty((L, *self.frame_shape), np.uint8)
        rew = np.empty(L, np.float32)
        term = np.empty(L, bool)
        trunc = np.zeros(L, bool)
        ep_ret = np.full(L, np.nan, np.float32)
        for i, env in enumerate(self.envs):
            try:
                ts = env.step(int(actions[i]))
            except Exception as e:
                if self.env_factory is None:
                    raise
                if self._restarts_per_lane[i] >= self.max_lane_restarts:
                    raise RuntimeError(
                        f"env lane {i} exceeded {self.max_lane_restarts} "
                        "restarts — persistently broken, not transient"
                    ) from e
                self._restarts_per_lane[i] += 1
                self.lane_restarts += 1
                print(
                    f"[vector-env] lane {i} crashed ({type(e).__name__}: {e}); "
                    f"restarting (restart #{self._restarts_per_lane[i]})",
                    file=sys.stderr,
                )
                try:
                    env.close()
                except Exception:
                    pass
                self.envs[i] = self.env_factory(i)
                obs[i] = self.envs[i].reset()
                rew[i] = 0.0
                term[i] = False
                trunc[i] = True  # cut the episode cleanly, don't poison values
                continue
            rew[i] = ts.reward
            term[i] = ts.terminal
            trunc[i] = ts.truncated and not ts.terminal
            if ts.terminal or ts.truncated:
                if ts.info and "episode_return" in ts.info:
                    ep_ret[i] = ts.info["episode_return"]
                obs[i] = env.reset()
            else:
                obs[i] = ts.obs
        return obs, rew, term, trunc, ep_ret
