from rainbow_iqn_apex_tpu.envs.base import Env, TimeStep, VectorEnv
from rainbow_iqn_apex_tpu.envs.toy import CatchEnv, ChainEnv, make_toy_env
from rainbow_iqn_apex_tpu.envs.atari import ALEAdapter, AtariEnv, make_atari_env


def make_env(env_id: str, seed: int = 0, **kwargs) -> Env:
    """Env factory keyed by the config's env_id: "toy:catch", "atari:Pong"."""
    kind, _, name = env_id.partition(":")
    if kind == "toy":
        return make_toy_env(name, seed=seed)
    if kind == "atari":
        return make_atari_env(name, seed=seed, **kwargs)
    raise ValueError(f"unknown env id '{env_id}' (want 'toy:...' or 'atari:...')")


def make_vector_env(env_id: str, num_envs: int, seed: int = 0, **kwargs) -> VectorEnv:
    return VectorEnv([make_env(env_id, seed=seed + i, **kwargs) for i in range(num_envs)])


__all__ = [
    "Env",
    "TimeStep",
    "VectorEnv",
    "CatchEnv",
    "ChainEnv",
    "AtariEnv",
    "ALEAdapter",
    "make_env",
    "make_toy_env",
    "make_atari_env",
    "make_vector_env",
]
