from rainbow_iqn_apex_tpu.envs.base import Env, TimeStep, VectorEnv
from rainbow_iqn_apex_tpu.envs.toy import CatchEnv, ChainEnv, make_toy_env
from rainbow_iqn_apex_tpu.envs.atari import ALEAdapter, AtariEnv, make_atari_env


def make_env(env_id: str, seed: int = 0, **kwargs) -> Env:
    """Env factory keyed by the config's env_id:
    "toy:catch" | "atari:Pong" | "gym:<gymnasium id>" | "procgen:<game>"."""
    kind, _, name = env_id.partition(":")
    if kind == "toy":
        return make_toy_env(name, seed=seed)
    if kind == "jaxgame":
        from rainbow_iqn_apex_tpu.envs.device_games import JaxGameEnv

        return JaxGameEnv(name, seed=seed)
    if kind == "atari":
        return make_atari_env(name, seed=seed, **kwargs)
    if kind == "gym":
        from rainbow_iqn_apex_tpu.envs.gym import make_gym_env

        return make_gym_env(name, seed=seed, **kwargs)
    if kind == "procgen":
        from rainbow_iqn_apex_tpu.envs.gym import make_procgen_env

        return make_procgen_env(name, seed=seed, **kwargs)
    raise ValueError(
        f"unknown env id '{env_id}' "
        "(want 'toy:', 'jaxgame:', 'atari:', 'gym:' or 'procgen:')"
    )


def make_vector_env(env_id: str, num_envs: int, seed: int = 0, **kwargs) -> VectorEnv:
    def factory(lane: int) -> Env:
        return make_env(env_id, seed=seed + lane, **kwargs)

    return VectorEnv([factory(i) for i in range(num_envs)], env_factory=factory)


__all__ = [
    "Env",
    "TimeStep",
    "VectorEnv",
    "CatchEnv",
    "ChainEnv",
    "AtariEnv",
    "ALEAdapter",
    "make_env",
    "make_toy_env",
    "make_atari_env",
    "make_vector_env",
]
