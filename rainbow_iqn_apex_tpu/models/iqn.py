"""Dueling noisy-net IQN Q-network (flax), the framework's flagship model.

Parity: reference `rainbowiqn/model.py` (SURVEY.md §2 row 3, §3.3) — conv trunk
-> phi(s); tau ~ U[0,1] -> 64-cosine embedding -> psi(tau); Hadamard phi ⊙ psi;
dueling NoisyLinear value/advantage heads; output Z_tau(s, a) per sampled tau.

TPU-first design notes:
- The tau dimension is folded into the batch for every head matmul, so the MXU
  sees one [B*N, F] x [F, H] GEMM instead of N small ones.
- The number of tau samples is a static (trace-time) constant, so each role
  (actor K=32, learner N=64/N'=64) compiles exactly one XLA program.
- uint8 frames are shipped to the device and normalised on-chip (u8 -> bf16
  * 1/255), cutting host->HBM traffic 4x vs fp32 frames.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from rainbow_iqn_apex_tpu.models.layers import ConvTrunk, CosineTauEmbedding, NoisyLinear

Dtype = Any


class RainbowIQN(nn.Module):
    """Implicit Quantile Network with dueling + noisy heads.

    Call signature:
        quantiles, taus = model.apply(params, obs, num_taus,
                                      rngs={"taus": k1, "noise": k2})

    obs:       [B, H, W, C] uint8 (or float already in [0, 1])
    quantiles: [B, num_taus, num_actions] fp32 quantile values Z_tau(s, a)
    taus:      [B, num_taus] fp32, the sampled quantile fractions
    """

    num_actions: int
    hidden_size: int = 512
    num_cosines: int = 64
    noisy_sigma0: float = 0.5
    dueling: bool = True
    use_noise: bool = True
    compute_dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(
        self,
        obs: jnp.ndarray,
        num_taus: int,
        taus: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        batch = obs.shape[0]
        if obs.dtype == jnp.uint8:
            obs = obs.astype(self.compute_dtype) * (1.0 / 255.0)

        phi = ConvTrunk(compute_dtype=self.compute_dtype)(obs)  # [B, F]
        feat = phi.shape[-1]

        if taus is None:
            taus = jax.random.uniform(
                self.make_rng("taus"), (batch, num_taus), jnp.float32
            )
        psi = CosineTauEmbedding(
            features=feat,
            num_cosines=self.num_cosines,
            compute_dtype=self.compute_dtype,
        )(taus)  # [B, N, F]

        # Hadamard merge, then fold taus into batch: [B*N, F] for one big GEMM.
        h = phi[:, None, :].astype(self.compute_dtype) * psi
        h = h.reshape(batch * num_taus, feat)

        def head(name: str, out_dim: int) -> jnp.ndarray:
            h1 = NoisyLinear(
                self.hidden_size,
                sigma0=self.noisy_sigma0,
                use_noise=self.use_noise,
                compute_dtype=self.compute_dtype,
                name=f"{name}_hidden",
            )(h)
            h1 = nn.relu(h1)
            return NoisyLinear(
                out_dim,
                sigma0=self.noisy_sigma0,
                use_noise=self.use_noise,
                compute_dtype=self.compute_dtype,
                name=f"{name}_out",
            )(h1)

        if self.dueling:
            value = head("value", 1)  # [B*N, 1]
            adv = head("advantage", self.num_actions)  # [B*N, A]
            q = value + adv - adv.mean(axis=-1, keepdims=True)
        else:
            q = head("q", self.num_actions)

        quantiles = q.reshape(batch, num_taus, self.num_actions).astype(jnp.float32)
        return quantiles, taus


def q_values(quantiles: jnp.ndarray) -> jnp.ndarray:
    """Mean over the tau dimension: [B, N, A] -> [B, A] expected Q."""
    return quantiles.mean(axis=1)


def greedy_action(quantiles: jnp.ndarray) -> jnp.ndarray:
    """Greedy action from quantile means: [B, N, A] -> [B] int32."""
    return jnp.argmax(q_values(quantiles), axis=-1).astype(jnp.int32)
