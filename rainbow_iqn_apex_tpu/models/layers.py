"""Building-block layers for the TPU-native Rainbow-IQN network.

Parity: reference `rainbowiqn/model.py` (SURVEY.md §2 row 3) — NoisyLinear with
factorised Gaussian noise (sigma0=0.5, Fortunato et al. arXiv:1706.10295) and
the IQN cosine tau embedding (Dabney et al. arXiv:1806.06923).

TPU-first design notes:
- Noise is never hidden module state (the torch pattern of `.reset_noise()`
  mutating buffers).  It is drawn from an explicit PRNG key per call via the
  flax "noise" RNG collection, so noisy forward passes are pure functions that
  jit/vmap/shard_map cleanly and noise-resampling semantics are decided by
  whoever supplies the key (SURVEY.md §7 "NoisyNet semantics under jit/pmap").
- Matmuls run in a configurable compute dtype (bfloat16 by default) with fp32
  parameters, so the MXU sees bf16 operands while optimizer state stays fp32.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

Dtype = Any


def _f(x: jnp.ndarray) -> jnp.ndarray:
    """Factorised-noise squashing f(x) = sign(x) * sqrt(|x|)."""
    return jnp.sign(x) * jnp.sqrt(jnp.abs(x))


class NoisyLinear(nn.Module):
    """Factorised-Gaussian noisy linear layer.

    y = (w_mu + w_sigma * (f(eps_out) f(eps_in)^T)) x + (b_mu + b_sigma * f(eps_out))

    When ``use_noise`` is False (evaluation), only the mu parameters are used —
    matching the reference's eval-time behaviour of acting without noise.
    """

    features: int
    sigma0: float = 0.5
    use_noise: bool = True
    compute_dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        in_features = x.shape[-1]
        bound = 1.0 / float(in_features) ** 0.5

        def _mu_init(key, shape, dtype=jnp.float32):
            return jax.random.uniform(key, shape, dtype, -bound, bound)

        w_mu = self.param("w_mu", _mu_init, (in_features, self.features), jnp.float32)
        b_mu = self.param("b_mu", _mu_init, (self.features,), jnp.float32)
        sigma_init = self.sigma0 / float(in_features) ** 0.5
        w_sigma = self.param(
            "w_sigma",
            nn.initializers.constant(sigma_init),
            (in_features, self.features),
            jnp.float32,
        )
        b_sigma = self.param(
            "b_sigma",
            nn.initializers.constant(sigma_init),
            (self.features,),
            jnp.float32,
        )

        xc = x.astype(self.compute_dtype)
        y = jnp.dot(xc, w_mu.astype(self.compute_dtype), preferred_element_type=jnp.float32)
        if self.use_noise:
            key = self.make_rng("noise")
            k_in, k_out = jax.random.split(key)
            eps_in = _f(jax.random.normal(k_in, (in_features,), jnp.float32))
            eps_out = _f(jax.random.normal(k_out, (self.features,), jnp.float32))
            # The noise is rank-1, so the noisy term factorises exactly:
            #   x @ (w_sigma * eps_in eps_out^T) == ((x * eps_in) @ w_sigma) * eps_out
            # — two GEMMs and two row/col scalings, never materialising the
            # [in, out] noise matrix in HBM.
            noisy = jnp.dot(
                xc * eps_in.astype(self.compute_dtype),
                w_sigma.astype(self.compute_dtype),
                preferred_element_type=jnp.float32,
            )
            y = y + noisy * eps_out
            b = b_mu + b_sigma * eps_out
        else:
            b = b_mu
        return y + b  # fp32 accumulate + fp32 bias


class CosineTauEmbedding(nn.Module):
    """IQN tau embedding: psi(tau)_j = ReLU(Linear(cos(pi * i * tau), i=1..n)).

    Input taus [..., N] -> output [..., N, features].
    """

    features: int
    num_cosines: int = 64
    compute_dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, taus: jnp.ndarray) -> jnp.ndarray:
        i = jnp.arange(1, self.num_cosines + 1, dtype=jnp.float32)
        cos = jnp.cos(jnp.pi * taus[..., None] * i)  # [..., N, num_cosines]
        dense = nn.Dense(
            self.features,
            dtype=self.compute_dtype,
            param_dtype=jnp.float32,
            name="embed",
        )
        return nn.relu(dense(cos.astype(self.compute_dtype)))


class ConvTrunk(nn.Module):
    """Canonical DQN conv trunk (32x8x8/4, 64x4x4/2, 64x3x3/1) in NHWC.

    NHWC keeps channels on the TPU lane dimension; XLA maps these convs onto
    the MXU without layout transposes (unlike a literal NCHW translation).
    """

    compute_dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        # x: [B, H, W, C] float in [0, 1]
        x = x.astype(self.compute_dtype)
        for features, kernel, stride in ((32, 8, 4), (64, 4, 2), (64, 3, 1)):
            x = nn.Conv(
                features,
                (kernel, kernel),
                strides=(stride, stride),
                padding="VALID",
                dtype=self.compute_dtype,
                param_dtype=jnp.float32,
            )(x)
            x = nn.relu(x)
        return x.reshape(x.shape[0], -1)  # [B, 3136] for 84x84x4
