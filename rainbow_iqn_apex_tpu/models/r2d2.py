"""R2D2 recurrent Q-network (flax): conv trunk -> LSTM -> dueling noisy head.

Parity: the reference's R2D2 stretch configuration (BASELINE.json:10,
SURVEY.md §7 step 7; Kapturowski et al., "Recurrent Experience Replay in
Distributed Reinforcement Learning", R2D2) — an LSTM Q-network trained on
stored-state replay sequences with burn-in.  R2D2 uses a plain (scalar)
dueling Q head rather than IQN quantiles; noisy layers keep the Rainbow
exploration story.

TPU-first notes:
- Time unrolling is a `lax.scan` over an `OptimizedLSTMCell` step inside one
  jit: [B, T, H, W, C] -> conv trunk applied as one [B*T] batch (single big
  MXU GEMM per layer), then the scan carries only the small LSTM state.
- Recurrent state is an explicit (c, h) pair the caller owns — nothing hidden
  in module state, so actor-side stored-state replay and burn-in are pure
  data plumbing.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from rainbow_iqn_apex_tpu.models.layers import ConvTrunk, NoisyLinear

Dtype = Any
LSTMState = Tuple[jnp.ndarray, jnp.ndarray]  # (c, h), each [B, lstm_size]


class _ResettableLSTMStep(nn.Module):
    """One LSTM step with an optional pre-step state reset (episode cut)."""

    features: int

    @nn.compact
    def __call__(self, carry: LSTMState, xs):
        x_t, reset_t = xs  # [B, F], [B] bool
        c, h = carry
        keep = (1.0 - reset_t.astype(jnp.float32))[:, None]
        c, h = c * keep, h * keep
        (c, h), out = nn.OptimizedLSTMCell(features=self.features, name="cell")(
            (c, h), x_t
        )
        return (c, h), out


class R2D2Net(nn.Module):
    """Recurrent dueling noisy Q-network over frame sequences."""

    num_actions: int
    lstm_size: int = 512
    hidden_size: int = 512
    noisy_sigma0: float = 0.5
    dueling: bool = True
    use_noise: bool = True
    compute_dtype: Dtype = jnp.bfloat16

    def initial_state(self, batch: int) -> LSTMState:
        z = jnp.zeros((batch, self.lstm_size), jnp.float32)
        return (z, z)

    @nn.compact
    def __call__(
        self,
        obs_seq: jnp.ndarray,  # [B, T, H, W, C] uint8 (or float in [0,1])
        state: LSTMState,
        resets: Optional[jnp.ndarray] = None,  # [B, T] bool: reset state BEFORE step t
    ) -> Tuple[jnp.ndarray, LSTMState]:
        """Returns (q_values [B, T, A] fp32, final LSTM state)."""
        B, T = obs_seq.shape[:2]
        if obs_seq.dtype == jnp.uint8:
            obs_seq = obs_seq.astype(self.compute_dtype) * (1.0 / 255.0)

        # conv trunk over the folded [B*T] batch: one large GEMM per layer
        phi = ConvTrunk(compute_dtype=self.compute_dtype)(
            obs_seq.reshape(B * T, *obs_seq.shape[2:])
        )
        phi = phi.reshape(B, T, -1).astype(jnp.float32)  # LSTM carries in fp32

        xs = (
            jnp.moveaxis(phi, 1, 0),  # [T, B, F]
            jnp.moveaxis(
                resets if resets is not None else jnp.zeros((B, T), bool), 1, 0
            ),
        )
        scan = nn.scan(
            _ResettableLSTMStep,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            out_axes=0,
        )
        final_state, outs = scan(features=self.lstm_size, name="lstm")(state, xs)
        feat = jnp.moveaxis(outs, 0, 1).reshape(B * T, self.lstm_size)  # [B*T, L]

        def head(name: str, out_dim: int) -> jnp.ndarray:
            h1 = NoisyLinear(
                self.hidden_size,
                sigma0=self.noisy_sigma0,
                use_noise=self.use_noise,
                compute_dtype=self.compute_dtype,
                name=f"{name}_hidden",
            )(feat)
            h1 = nn.relu(h1)
            return NoisyLinear(
                out_dim,
                sigma0=self.noisy_sigma0,
                use_noise=self.use_noise,
                compute_dtype=self.compute_dtype,
                name=f"{name}_out",
            )(h1)

        if self.dueling:
            value = head("value", 1)
            adv = head("advantage", self.num_actions)
            q = value + adv - adv.mean(axis=-1, keepdims=True)
        else:
            q = head("q", self.num_actions)
        return q.reshape(B, T, self.num_actions).astype(jnp.float32), final_state
