from rainbow_iqn_apex_tpu.models.iqn import RainbowIQN, greedy_action, q_values
from rainbow_iqn_apex_tpu.models.layers import (
    ConvTrunk,
    CosineTauEmbedding,
    NoisyLinear,
)

__all__ = [
    "RainbowIQN",
    "greedy_action",
    "q_values",
    "ConvTrunk",
    "CosineTauEmbedding",
    "NoisyLinear",
]
