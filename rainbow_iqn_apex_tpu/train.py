"""Single-process Rainbow-IQN training loop (reference parity: the 1-actor,
no-Ape-X mode of `train_agent_apex.py`, SURVEY.md §3.1+§3.2 merged into one
process — act/learn interleaved at `frames_per_learn` env frames per learner step,
scheduled target update, Orbax checkpoints, JSONL metrics, periodic eval).

The Ape-X multi-role path lives in parallel/apex.py; this file is the
minimum end-to-end slice (SURVEY.md §7 step 4).
"""

from __future__ import annotations

import collections
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from rainbow_iqn_apex_tpu.agents.agent import Agent, FrameStacker
from rainbow_iqn_apex_tpu.utils.prefetch import BatchPrefetcher, make_replay_prefetcher
from rainbow_iqn_apex_tpu.utils.writeback import (
    RingCommitter,
    WritebackRing,
    cadence_hit,
    check_reuse_cadences,
    pipeline_gauges,
    reuse_health,
    reuse_learn_row,
)
from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.envs import make_vector_env
from rainbow_iqn_apex_tpu.eval import evaluate
from rainbow_iqn_apex_tpu.obs import RunObs
from rainbow_iqn_apex_tpu.replay.buffer import PrioritizedReplay
from rainbow_iqn_apex_tpu.utils import faults
from rainbow_iqn_apex_tpu.utils.checkpoint import (
    Checkpointer,
    maybe_restore_replay,
    maybe_resume,
    rng_extra,
    rng_from_extra,
)
from rainbow_iqn_apex_tpu.utils.logging import MetricsLogger


def priority_beta(cfg: Config, frames: int) -> float:
    """Linear beta_0 -> 1 anneal over the training budget (reference IS
    schedule, SURVEY §2 row 1)."""
    frac = min(frames / max(cfg.t_max, 1), 1.0)
    return cfg.priority_weight + (1.0 - cfg.priority_weight) * frac


def train(cfg: Config, max_frames: Optional[int] = None) -> Dict[str, Any]:
    """Runs training; returns a summary dict (final eval, fps, steps)."""
    # league membership (league/; docs/LEAGUE.md): validate the league_*
    # spec, then overlay this member's genome onto the config BEFORE any
    # component reads a hyperparameter.  Default-off (league_dir unset /
    # league_member_id < 0) takes none of this: `member` is None, the
    # overlay never runs, and the loop below is bitwise the pre-league path
    # (tier-1 asserted).
    from rainbow_iqn_apex_tpu.league.member import LeagueMember
    from rainbow_iqn_apex_tpu.league.population import check_league_config

    check_league_config(cfg)
    member = LeagueMember.from_config(cfg)
    if member is not None:
        # genome n_step must respect the ring geometry (seg > history + n)
        # or the buffer constructor below crash-loops every respawn
        member.clamp_n_step(
            cfg.memory_capacity // cfg.num_envs_per_actor
            - cfg.history_length - 1)
        cfg = member.overlay(cfg)
    total_frames = max_frames or cfg.t_max
    lanes = cfg.num_envs_per_actor
    env = make_vector_env(cfg.env_id, lanes, seed=cfg.seed)

    agent = Agent(
        cfg,
        env.num_actions,
        jax.random.PRNGKey(cfg.seed),
        state_shape=(*env.frame_shape, cfg.history_length),
    )
    memory = PrioritizedReplay(
        cfg.memory_capacity,
        env.frame_shape,
        history=cfg.history_length,
        n_step=cfg.multi_step,
        gamma=cfg.gamma,
        lanes=lanes,
        priority_exponent=cfg.priority_exponent,
        priority_eps=cfg.priority_eps,
        seed=cfg.seed,
        use_native=cfg.use_native_sumtree,
    )
    run_dir = os.path.join(cfg.results_dir, cfg.run_id)
    metrics = MetricsLogger(os.path.join(run_dir, "metrics.jsonl"), cfg.run_id)
    ckpt = Checkpointer(os.path.join(cfg.checkpoint_dir, cfg.run_id))
    faults.install_from(cfg)
    obs_run = RunObs(cfg, metrics, role="learner")
    # deferred: the parallel package's __init__ imports the apex drivers,
    # which import THIS module (priority_beta) — a module-level import here
    # would be circular for `--role single` entry
    from rainbow_iqn_apex_tpu.parallel.supervisor import TrainSupervisor

    sup = TrainSupervisor(cfg, metrics=metrics, registry=obs_run.registry)

    frames = 0
    restored = maybe_resume(cfg, ckpt, agent.state)
    if restored is not None:
        agent.state, extra, _ = restored
        frames = int(extra.get("frames", 0))
        agent.key = rng_from_extra(extra, agent.key)
        maybe_restore_replay(cfg, memory)
        metrics.log("resume", step=agent.step, frames=frames)

    stacker = FrameStacker(lanes, env.frame_shape, cfg.history_length)
    obs = env.reset()
    returns: collections.deque = collections.deque(maxlen=100)
    last_eval: Dict[str, Any] = {}
    prefetcher: Optional[BatchPrefetcher] = None

    # pipelined priority write-back + deferred in-graph NaN guard
    # (utils/writeback.py; docs/PERFORMANCE.md): zero blocking device->host
    # transfers per learn step — syncs happen only at ring boundaries
    # (snapshot/eval/checkpoint cadence) and on retirement of K-old steps;
    # the commit/quarantine/drain rollback protocol is the shared
    # RingCommitter
    ring = WritebackRing(cfg.writeback_depth, registry=obs_run.registry)
    committer = RingCommitter(
        ring, memory.update_priorities, sup, agent.load_snapshot
    )
    last_scalars = committer.scalars
    _commit, _drain = committer.commit, committer.drain
    # replay reuse (docs/PERFORMANCE.md "Replay reuse"): each sampled batch
    # drives one fused K-pass learn dispatch, so the step counter jumps K
    # per sample — cadences use cadence_hit (crossing, not % == 0) and the
    # sample trigger divides the step count back into samples
    reuse_k = agent.reuse_k
    check_reuse_cadences(cfg, "metrics_interval", "eval_interval",
                         "checkpoint_interval", "guard_snapshot_interval")
    heartbeat = None
    if member is not None:
        member.attach_obs(metrics, obs_run.registry)
        # the publish cadence is live in member mode (outbox publishes)
        check_reuse_cadences(cfg, "weight_publish_interval")
        if cfg.heartbeat_interval_s > 0:
            # member lease under the LEAGUE dir (the controller's watch
            # point): payload carries member id + exploit generation
            from rainbow_iqn_apex_tpu.parallel.elastic import HeartbeatWriter

            heartbeat = HeartbeatWriter(
                os.path.join(cfg.league_dir, "heartbeats"),
                cfg.league_member_id, cfg.heartbeat_interval_s,
                role="member", epoch=member.epoch,
                payload_fn=member.lease_payload,
            ).start()

    def _member_retune(genome) -> None:
        """Live-gene adoption at a drained boundary: lr rebuilds the learn
        jit, n-step re-fences replay eligibility, omega applies to future
        write-backs.  Restart genes wait for the next respawn's overlay."""
        agent.retune(learning_rate=genome.learning_rate)
        memory.set_n_step(genome.n_step)
        memory.set_priority_exponent(genome.priority_exponent)

    try:
        while frames < total_frames:
            stacked = stacker.push(obs)
            with obs_run.span("act"):
                actions = agent.act(stacked)
            new_obs, rewards, terminals, truncs, ep_returns = env.step(actions)
            # store the pre-step frame with the transition's reward/terminal
            # (reference memory layout: SURVEY §2 row 5 frame-dedup scheme).
            # Truncations are a separate channel: they cut stack/n-step
            # windows but never fake a terminal (docs/DESIGN.md).
            memory.append_batch(obs, actions, rewards, terminals, truncations=truncs)
            stacker.reset_lanes(terminals | truncs)
            obs = new_obs
            frames += lanes
            for r in ep_returns[~np.isnan(ep_returns)]:
                returns.append(float(r))

            # one learner step per `frames_per_learn` env frames once warm
            if len(memory) >= cfg.learn_start and memory.sampleable:
                if cfg.prefetch_depth > 0 and prefetcher is None:
                    # background sampler overlaps batch assembly + transfer
                    # with the device step (beta_fn reads live `frames`)
                    prefetcher = make_replay_prefetcher(
                        memory, cfg, lambda: priority_beta(cfg, frames),
                        registry=obs_run.registry,
                    )
                steps_due = frames // cfg.frames_per_learn - agent.step // reuse_k
                for _ in range(max(steps_due, 0)):
                    if sup.snapshot_due(agent.step):
                        # drain first: the rollback target must never hold a
                        # step whose finiteness is still in flight
                        if not _drain():
                            continue
                        sup.snapshot_if_due(
                            agent.step, lambda: (agent.state, agent.key)
                        )
                    if prefetcher is not None:
                        idx, batch = prefetcher.get()
                        with obs_run.span("learn_step"):
                            info = agent.learn_batch(sup.poison_maybe(batch))
                    else:
                        with obs_run.span("replay_sample"):
                            sample = memory.sample(
                                cfg.batch_size, priority_beta(cfg, frames)
                            )
                        idx = sample.idx
                        with obs_run.span("learn_step"):
                            info = agent.learn(sup.poison_maybe(sample))
                    sup.maybe_stall()
                    # dispatch-only: info stays on device; step t-K retires
                    # (priority write-back + deferred NaN guard) while step
                    # t executes
                    if not _commit(ring.push(agent.step, idx, info)):
                        continue

                    step = agent.step
                    obs_run.after_learn_step(step, units=reuse_k)
                    if member is not None and cadence_hit(
                            step, cfg.weight_publish_interval, reuse_k):
                        # outbox publish (the copy source other members
                        # adopt from) — drained first so the chain never
                        # carries an unverified step's params
                        if not _drain():
                            continue
                        from rainbow_iqn_apex_tpu.utils import hostsync

                        with hostsync.sanctioned():
                            member.publish(agent.state.params, step=step)
                    if (member is not None
                            and cadence_hit(step, cfg.metrics_interval,
                                            reuse_k)
                            and member.pending()):
                        # exploit adoption at a SAFE drain boundary: no
                        # unverified step in flight when the weights swap
                        if not _drain():
                            continue
                        from rainbow_iqn_apex_tpu.utils import hostsync

                        with hostsync.sanctioned():
                            member.try_adopt(step, agent.adopt_params,
                                             retune=_member_retune,
                                             max_n_step=memory.max_n_step)
                    if cadence_hit(step, cfg.metrics_interval, reuse_k):
                        metrics.log(
                            "learn",
                            step=step,
                            frames=frames,
                            fps=metrics.fps(frames),
                            loss=last_scalars.get("loss", float("nan")),
                            q_mean=last_scalars.get("q_mean", float("nan")),
                            grad_norm=last_scalars.get("grad_norm", float("nan")),
                            mean_return=float(np.mean(returns)) if returns else float("nan"),
                            **reuse_learn_row(reuse_k, last_scalars),
                        )
                        obs_run.periodic(
                            step,
                            frames,
                            replay_size=len(memory),
                            replay_occupancy=round(
                                len(memory) / max(cfg.memory_capacity, 1), 4
                            ),
                            **pipeline_gauges(
                                ring, obs_run.registry,
                                reuse=reuse_health(reuse_k, last_scalars),
                            ),
                        )
                    if cadence_hit(step, cfg.eval_interval, reuse_k):
                        if not _drain():  # evaluate only verified params
                            continue
                        last_eval = evaluate(cfg, agent, seed=cfg.seed + 977)
                        metrics.log("eval", step=step, **last_eval)
                    if cadence_hit(step, cfg.checkpoint_interval, reuse_k):
                        if not _drain():  # checkpoint only verified params
                            continue
                        sup.save_checkpoint(
                            ckpt, step, agent.state,
                            {"frames": frames, **rng_extra(agent.key)},
                        )
                        sup.save_replay(cfg, memory)
        # end of run: retire the in-flight tail before the final eval/save
        _drain()
    finally:
        if prefetcher is not None:
            prefetcher.close()
        if heartbeat is not None:
            heartbeat.stop()
        sup.close()
        obs_run.close(agent.step, frames)
    final_eval = evaluate(cfg, agent, seed=cfg.seed + 977)
    metrics.log("eval", step=agent.step, **final_eval)
    sup.save_checkpoint(
        ckpt, agent.step, agent.state,
        {"frames": frames, **rng_extra(agent.key)}, critical=True,
    )
    sup.save_replay(cfg, memory, critical=True)
    ckpt.wait()
    metrics.close()
    return {
        "frames": frames,
        "learn_steps": agent.step,
        "train_return_mean": float(np.mean(returns)) if returns else float("nan"),
        "rollbacks": sup.rollbacks,
        "stalls": sup.stalls,
        "io_faults": sup.io_faults,
        **{f"eval_{k}": v for k, v in final_eval.items()},
    }
