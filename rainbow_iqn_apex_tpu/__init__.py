"""TPU-native Rainbow-IQN Ape-X framework.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities of
`valeoai/rainbow-iqn-apex` (see SURVEY.md): a dueling, noisy-net IQN Q-network
trained with the quantile-Huber loss under the full Rainbow recipe, scaled out
Ape-X style — with the TPU pod acting as both the learner and the actor fleet,
and the Redis-backed distributed replay replaced by pod-sharded host-DRAM
replay plus XLA collectives for weight sync.
"""

from rainbow_iqn_apex_tpu.config import Config, parse_config

__version__ = "0.1.0"

__all__ = ["Config", "parse_config", "__version__"]
