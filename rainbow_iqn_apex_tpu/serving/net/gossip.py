"""Router federation: periodic gossiped load/version snapshots between
shared-nothing `FrontRouter`s.

N routers over one fleet coordinate through exactly two channels: the lease
files (membership + per-engine depth at lease cadence) and THIS — small UDP
datagrams carrying each router's live per-engine inflight and its rollout
target version.  With gossip, weighted least-depth dispatch stays honest
(router A sees the load router B already placed on engine 0 and stops piling
onto it) and the staleness fence stays honest (a router that never heard of
version N+1 fences against the freshest version ANY federated router knows).

UDP is the right transport for gossip: the snapshot is idempotent state, not
a command — a dropped datagram is healed by the next interval, and framing
reuses the TCP codec (one datagram = one frame, CRC-checked).  Peer
snapshots expire after ``stale_factor`` intervals, so a dead router's stale
load claims stop skewing dispatch on the monitor's own clock.

jax-free; a `gossip` JSONL row at a low cadence records peer freshness for
obs_report/relay_watch.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from rainbow_iqn_apex_tpu.netcore import chaos
from rainbow_iqn_apex_tpu.serving.net import framing

# a gossip datagram is one frame; snapshots are tiny (per-engine ints), so
# anything near this bound is a protocol violation, not a big fleet
_MAX_DATAGRAM = 60_000


class RouterGossip:
    """One router's gossip endpoint: broadcast its snapshot, hold peers'.

    ``snapshot_fn`` returns this router's live view —
    ``{"inflight": {engine_id: n}, "target_version": v, "accepted": n}``
    (`FrontRouter.gossip_snapshot`).  ``peer_inflight(engine_id)`` sums the
    fresh peers' inflight for the router's dispatch weighting;
    ``peer_target_version()`` is the freshest rollout target any peer
    claims (the federated fence input).
    """

    def __init__(self, router_id: int,
                 snapshot_fn: Callable[[], Dict[str, Any]],
                 bind: Tuple[str, int] = ("127.0.0.1", 0),
                 peers: Sequence[Tuple[str, int]] = (),
                 interval_s: float = 1.0,
                 stale_factor: float = 3.0,
                 row_every: int = 5,
                 logger=None, obs_registry=None):
        self.router_id = int(router_id)
        self.snapshot_fn = snapshot_fn
        self.interval_s = float(interval_s)
        self.stale_after_s = float(stale_factor) * self.interval_s
        self.row_every = max(int(row_every), 1)
        self.logger = logger
        self.obs_registry = obs_registry
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(bind)
        self._sock.settimeout(0.05)
        self.host, self.port = self._sock.getsockname()[:2]
        self._sock = chaos.maybe_wrap(self._sock, peer="gossip",
                                      logger=self.logger)
        self._peers: List[Tuple[str, int]] = [tuple(p) for p in peers]
        self._lock = threading.Lock()
        # peer router id -> (snapshot dict, monotonic rx time)
        self._view: Dict[int, Tuple[Dict[str, Any], float]] = {}
        self._seq = 0
        self.sent = 0
        self.received = 0
        self.bad_frames = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_config(cls, cfg, router_id: int,
                    snapshot_fn: Callable[[], Dict[str, Any]],
                    logger=None, obs_registry=None
                    ) -> Optional["RouterGossip"]:
        """None unless ``serve_net_gossip_peers`` names peers — a solo
        router needs no federation and pays nothing."""
        spec = getattr(cfg, "serve_net_gossip_peers", "") or ""
        peers = []
        for part in filter(None, (p.strip() for p in spec.split(","))):
            host, sep, port = part.rpartition(":")
            if not sep or not host or not port.isdigit():
                raise ValueError(
                    f"serve_net_gossip_peers entry {part!r} is not "
                    "host:port (e.g. \"10.0.0.1:7600,10.0.0.2:7600\"; "
                    "IPv4 or hostname only)")
            peers.append((host, int(port)))
        if not peers:
            return None
        return cls(
            router_id, snapshot_fn,
            bind=("0.0.0.0", int(cfg.serve_net_gossip_port)),
            peers=peers,
            interval_s=cfg.serve_net_gossip_interval_s,
            logger=logger, obs_registry=obs_registry)

    def set_peers(self, peers: Sequence[Tuple[str, int]]) -> None:
        with self._lock:
            self._peers = [tuple(p) for p in peers]

    # ----------------------------------------------------------------- loop
    def start(self) -> "RouterGossip":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name=f"gossip-{self.router_id}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self._sock.close()
        except OSError:
            pass

    def _run(self) -> None:
        next_send = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            if now >= next_send:
                self.broadcast()
                next_send = now + self.interval_s
            self._drain(until=min(next_send, now + self.interval_s))

    def _drain(self, until: float) -> None:
        while not self._stop.is_set() and time.monotonic() < until:
            try:
                data, _addr = self._sock.recvfrom(_MAX_DATAGRAM)
            except socket.timeout:
                continue
            except OSError:
                return
            self._receive(data)

    # ------------------------------------------------------------- exchange
    def broadcast(self) -> int:
        """One gossip round: snapshot -> datagram -> every peer.  Returns
        peers reached (sendto errors are skipped — UDP gossip heals itself
        next interval)."""
        try:
            snap = dict(self.snapshot_fn())
        except Exception:
            return 0  # a flaky snapshot must not kill the gossip loop
        with self._lock:
            self._seq += 1
            seq = self._seq
            peers = list(self._peers)
        data = framing.encode_frame({
            "op": "gossip", "router": self.router_id, "seq": seq,
            "ts": round(time.time(), 3), "snap": snap,
        })
        reached = 0
        for peer in peers:
            try:
                self._sock.sendto(data, peer)
                reached += 1
            except OSError:
                pass
        with self._lock:
            self.sent += 1
            emit = self.sent % self.row_every == 0
        if emit:
            self._emit_row()
        return reached

    def _receive(self, data: bytes) -> None:
        try:
            frames = framing.FrameReader(_MAX_DATAGRAM).feed(data)
        except framing.FrameError:
            with self._lock:
                self.bad_frames += 1
            return
        for header, _blob in frames:
            if header.get("op") != "gossip":
                continue
            peer_id = header.get("router")
            if peer_id is None or int(peer_id) == self.router_id:
                continue  # self-echo (a peer list naming ourselves)
            now = time.monotonic()
            with self._lock:
                prev = self._view.get(int(peer_id))
                # out-of-order datagrams: keep the newest seq only — but a
                # seq LOWER than a STALE entry's is a restarted peer whose
                # counter reset, not reordering; refusing it would deafen
                # this router to the peer until its new seq caught up
                if (prev is not None
                        and now - prev[1] <= self.stale_after_s
                        and prev[0].get("_seq", -1) >= int(
                            header.get("seq", 0))):
                    continue
                snap = dict(header.get("snap") or {})
                snap["_seq"] = int(header.get("seq", 0))
                self._view[int(peer_id)] = (snap, now)
                self.received += 1

    def poll_once(self, budget_s: float = 0.2) -> None:
        """Drain pending datagrams inline (thread-less mode for tests and
        single-threaded harnesses)."""
        self._drain(until=time.monotonic() + float(budget_s))

    # ----------------------------------------------------------------- reads
    def _fresh_view(self) -> Dict[int, Dict[str, Any]]:
        now = time.monotonic()
        with self._lock:
            return {pid: snap for pid, (snap, t_rx) in self._view.items()
                    if now - t_rx <= self.stale_after_s}

    def peer_inflight(self, engine_id: int) -> int:
        """Load other routers currently have in flight on ``engine_id`` —
        the federation term in weighted least-depth dispatch."""
        total = 0
        for snap in self._fresh_view().values():
            total += int((snap.get("inflight") or {}).get(
                str(int(engine_id)), 0))
        return total

    def peer_target_version(self) -> int:
        """The freshest rollout target any fresh peer claims (0 when no
        peer is fresh) — max() this with the local target so a router that
        missed a publish still fences engines against the fleet's truth."""
        return max((int(snap.get("target_version", 0))
                    for snap in self._fresh_view().values()), default=0)

    def peers_fresh(self) -> int:
        return len(self._fresh_view())

    # ------------------------------------------------------------------- obs
    def _emit_row(self) -> None:
        fresh = self.peers_fresh()
        with self._lock:
            known = len(self._view)
            n_peers = len(self._peers)
        if self.obs_registry is not None:
            self.obs_registry.gauge("gossip_peers_fresh", "router").set(fresh)
        if self.logger is not None:
            try:
                self.logger.log(
                    "gossip", router=self.router_id, peers=n_peers,
                    fresh=fresh, stale=known - fresh, sent=self.sent,
                    received=self.received, bad_frames=self.bad_frames)
            except Exception:
                pass
