"""serving/net: the cross-host serving plane (docs/SERVING.md "cross-host").

Zero-dependency socket transport — length-prefixed CRC-checked frames over
stdlib ``socket``/``selectors`` — filling the `ServerTransport` protocol
seam in serving/fleet/registry.py, so a `FrontRouter` on host A dispatches
to `FleetEngine`s on hosts B..N:

- `framing`     — the frame codec (torn-read/oversize/checksum hardening)
- `RemoteTransport` / `RemoteEngine` — the router/rollout-side client
- `TransportServer` — the engine-side listener (lease advertises addr:port)
- `RouterGossip` — shared-nothing router federation over UDP snapshots

Everything here is jax-free: router front-ends and gossip daemons own no
device runtime.  With no ``serve_net_*`` config set nothing in this package
is constructed and the in-process fleet path is untouched.
"""

from rainbow_iqn_apex_tpu.serving.net import framing
from rainbow_iqn_apex_tpu.serving.net.client import (
    RemoteEngine,
    RemoteFuture,
    RemoteTransport,
)
from rainbow_iqn_apex_tpu.serving.net.gossip import RouterGossip
from rainbow_iqn_apex_tpu.serving.net.server import TransportServer

__all__ = [
    "framing",
    "RemoteEngine",
    "RemoteFuture",
    "RemoteTransport",
    "RouterGossip",
    "TransportServer",
]
