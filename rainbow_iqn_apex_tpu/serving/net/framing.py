"""Back-compat re-export: the frame codec moved to ``netcore/`` so the
replay plane (replay/net/) can speak it without importing the serving
package.  Everything importable from here before the hoist still is —
``from rainbow_iqn_apex_tpu.serving.net import framing`` and
``rainbow_iqn_apex_tpu.serving.net.framing.X`` both keep working, and the
classes ARE the netcore classes (``except framing.FrameError`` catches
frames raised by either plane's transport).  New code should import
``rainbow_iqn_apex_tpu.netcore.framing`` directly.
"""

from rainbow_iqn_apex_tpu.netcore.framing import (  # noqa: F401
    DEFAULT_MAX_FRAME,
    MAGIC,
    PREFIX_BYTES,
    TRAILER_BYTES,
    VERSION,
    FrameCorrupt,
    FrameError,
    FrameProtocol,
    FrameReader,
    FrameTooLarge,
    FrameTruncated,
    decode_ndarray,
    encode_frame,
    encode_frame_views,
    encode_ndarray,
    ndarray_view,
    pack_blobs,
    recv_exact,
    recv_frame,
    recv_frame_view,
    send_frame,
    send_frame_views,
    unpack_blobs,
)

__all__ = [
    "DEFAULT_MAX_FRAME",
    "MAGIC",
    "PREFIX_BYTES",
    "TRAILER_BYTES",
    "VERSION",
    "FrameCorrupt",
    "FrameError",
    "FrameProtocol",
    "FrameReader",
    "FrameTooLarge",
    "FrameTruncated",
    "decode_ndarray",
    "encode_frame",
    "encode_frame_views",
    "encode_ndarray",
    "ndarray_view",
    "pack_blobs",
    "recv_exact",
    "recv_frame",
    "recv_frame_view",
    "send_frame",
    "send_frame_views",
    "unpack_blobs",
]
