"""Client half of the cross-host serving plane: `RemoteTransport` fills the
`ServerTransport` protocol seam in serving/fleet/registry.py with a real
socket, so a `FrontRouter` on host A dispatches to engines on hosts B..N
through exactly the surface it already speaks — submit/depth/alive/version/
lanes — and `RemoteEngine` gives `FleetRollout` the same adopt/adopt_packet/
adopt_chain surface over the wire.

Design points:

- **one connection, demultiplexed**: a reader thread parses result frames
  and settles the matching `ServeFuture` by request id; request submission
  waits only for the engine's ACCEPT/SHED ack (one RTT), so the router's
  synchronous shed-probe semantics survive the network hop.
- **connection loss fails fast**: every in-flight future is settled with
  `EngineDead` the moment the socket dies — the router's re-route path
  treats that exactly like an in-process engine kill (accepted requests
  re-dispatch to survivors; zero-loss invariant intact).
- **reconnect-with-backoff**: re-dials ride the shared `RetryPolicy`
  schedule (utils/faults.py — the one backoff training IO, respawn and
  hot-swap already share), driven lazily from ``alive()``/``probe()`` so a
  dead remote costs the registry scan one bounded attempt per due slot, not
  a spin.
- **bounded probes**: every connect/probe carries ``probe_timeout_s`` — a
  hung remote (SYN-accepted but wedged) can never stall the registry's
  discovery/eviction sweep past its bound.

State the router polls hot (depth/version) is piggybacked on every frame the
engine sends and refreshed by probes, so ranking N engines costs zero RPCs.
jax-free by design (the `serving` package front-end contract).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from rainbow_iqn_apex_tpu.serving.batcher import (
    ServeFuture,
    ServerClosed,
    ServerOverloaded,
)
from rainbow_iqn_apex_tpu.netcore import chaos
from rainbow_iqn_apex_tpu.serving.fleet.registry import EngineDead
from rainbow_iqn_apex_tpu.serving.net import framing
from rainbow_iqn_apex_tpu.utils import quantize
from rainbow_iqn_apex_tpu.utils.faults import RetryPolicy

# etype strings on the wire -> the exception the caller expects (the same
# types the in-process transport raises, so router/rollout error handling
# is transport-agnostic)
_ETYPES: Dict[str, Callable[[str], BaseException]] = {
    "overloaded": ServerOverloaded,
    "closed": ServerClosed,
    "dead": EngineDead,
    "backward": ValueError,
    "chain_broken": quantize.DeltaChainBroken,
    "cancelled": ServerClosed,
    "unsupported": RuntimeError,
}


def _wire_error(etype: str, msg: str) -> BaseException:
    return _ETYPES.get(str(etype), RuntimeError)(msg)


class RemoteFuture(ServeFuture):
    """A `ServeFuture` whose cancel also tells the remote engine to skip the
    batch slot (best-effort — a lost cancel frame only costs the engine one
    padded slot, never correctness)."""

    __slots__ = ("_rid", "_transport")

    def __init__(self, obs, rid: int, transport: "RemoteTransport"):
        super().__init__(obs)
        self._rid = rid
        self._transport = transport

    def cancel(self) -> bool:
        won = super().cancel()
        if won:
            self._transport._send_cancel(self._rid)
        return won


class _PendingAck:
    __slots__ = ("event", "ok", "error")

    def __init__(self):
        self.event = threading.Event()
        self.ok = False
        self.error: Optional[BaseException] = None


class RemoteTransport:
    """`ServerTransport`-protocol client over one TCP connection.

    Satisfies the full seam the router and registry speak — ``submit``,
    ``depth``, ``alive``, ``version``/``set_version``, ``lanes``,
    ``buckets`` — plus the wire-only ``probe``/``request`` surface the
    registry's liveness sweep and `RemoteEngine`'s adopts ride on.
    """

    def __init__(self, host: str, port: int, engine_id: Optional[int] = None,
                 retry: Optional[RetryPolicy] = None,
                 probe_timeout_s: float = 0.5,
                 ack_timeout_s: float = 5.0,
                 max_frame_bytes: int = framing.DEFAULT_MAX_FRAME,
                 logger=None, obs_registry=None,
                 connect: bool = True):
        self.host = str(host)
        self.port = int(port)
        self.engine_id = engine_id
        self.peer = f"{self.host}:{self.port}"
        self.retry = retry if retry is not None else RetryPolicy(
            attempts=6, base_delay_s=0.2, max_delay_s=5.0)
        self.probe_timeout_s = float(probe_timeout_s)
        self.ack_timeout_s = float(ack_timeout_s)
        self.max_frame_bytes = int(max_frame_bytes)
        self.logger = logger
        self.obs_registry = obs_registry
        # ServerTransport surface defaults until the first pong teaches us
        self.lanes = 1
        self.buckets: Tuple[int, ...] = ()
        self._version = 0
        self._depth = 0
        self.digest: Optional[str] = None
        # counters (the registry's periodic `net` stats row)
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.reconnects = 0
        self.probe_timeouts = 0
        self.rtt_ms: Optional[float] = None
        self._lock = threading.Lock()  # socket lifecycle + pending maps
        self._wlock = threading.Lock()  # serialises frame writes
        self._sock: Optional[socket.socket] = None
        self._reader: Optional[threading.Thread] = None
        self._gen = 0  # connection generation (reader threads self-retire)
        self._rid = 0
        self._pending: Dict[int, ServeFuture] = {}
        self._acks: Dict[int, _PendingAck] = {}
        self._ever_connected = False
        self._closed = False
        # reconnect backoff state: the shared RetryPolicy schedule, clamped
        # at its last delay (a dead remote is retried forever at the ceiling
        # — eviction is the REGISTRY's call via the lease, not the socket's)
        self._delays = list(self.retry.delays()) or [self.retry.base_delay_s]
        self._fail_streak = 0
        self._next_dial = 0.0
        if connect:
            # eager best-effort dial (bounded): callers that want pure-lazy
            # construction (the registry's discovery factory, built under
            # its lock) pass connect=False and the first probe/submit dials
            self.connect()

    # ---------------------------------------------------------- connection
    def _log(self, event: str, **fields: Any) -> None:
        if self.logger is not None:
            try:
                self.logger.log("net", event=event, peer=self.peer,
                                engine=self.engine_id, **fields)
            except Exception:
                pass  # telemetry must never break the transport

    def _count(self, name: str) -> None:
        if self.obs_registry is not None:
            self.obs_registry.counter(name, "net").inc()

    def connect(self, timeout_s: Optional[float] = None) -> bool:
        """One bounded dial attempt; True when a connection is live."""
        with self._lock:
            if self._closed:
                return False
            if self._sock is not None:
                return True
        try:
            sock = socket.create_connection(
                (self.host, self.port),
                timeout=self.probe_timeout_s if timeout_s is None
                else timeout_s)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(None)  # reader blocks; writes are sendall
            sock = chaos.maybe_wrap(sock, peer=f"engine{self.engine_id}",
                                    logger=self.logger)
        except OSError:
            with self._lock:
                self._fail_streak += 1
                delay = self._delays[
                    min(self._fail_streak - 1, len(self._delays) - 1)]
                self._next_dial = time.monotonic() + delay
            return False
        with self._lock:
            if self._closed:
                sock.close()
                return False
            self._sock = sock
            self._gen += 1
            gen = self._gen
            self._fail_streak = 0
            reconnected = self._ever_connected
            self._ever_connected = True
            if reconnected:
                self.reconnects += 1
        self._reader = threading.Thread(
            target=self._read_loop, args=(sock, gen),
            name=f"net-client-{self.peer}", daemon=True)
        self._reader.start()
        self._log("reconnect" if reconnected else "connect")
        if reconnected:
            self._count("net_reconnects_total")
        return True

    def _ensure_connected(self) -> bool:
        """Connected, or one dial attempt if the backoff schedule is due."""
        with self._lock:
            if self._sock is not None:
                return True
            if self._closed or time.monotonic() < self._next_dial:
                return False
        return self.connect()

    def connected(self) -> bool:
        with self._lock:
            return self._sock is not None

    def _drop(self, sock: socket.socket, gen: int, why: str) -> None:
        """Tear the connection down once; fail every in-flight request."""
        with self._lock:
            if gen != self._gen or self._sock is not sock:
                return  # an older generation already replaced
            self._sock = None
            pending, self._pending = self._pending, {}
            acks, self._acks = self._acks, {}
            self._next_dial = time.monotonic()  # first re-dial is immediate
        try:
            sock.close()
        except OSError:
            pass
        err = EngineDead(f"connection to engine {self.peer} lost ({why})")
        for ack in acks.values():
            ack.error = err
            ack.event.set()
        for fut in pending.values():
            fut.set_error(err)
        if not self._closed:
            self._log("disconnect", why=why, inflight=len(pending))
            self._count("net_disconnects_total")

    def close(self) -> None:
        with self._lock:
            self._closed = True
            sock, gen = self._sock, self._gen
        if sock is not None:
            self._drop(sock, gen, "closed")

    # ---------------------------------------------------------- frame I/O
    def _send(self, sock: socket.socket, gen: int,
              header: Dict[str, Any], blob: bytes = b"") -> None:
        try:
            with self._wlock:
                self.bytes_sent += framing.send_frame(sock, header, blob)
        except OSError as e:
            self._drop(sock, gen, f"send failed: {e}")
            raise EngineDead(
                f"engine {self.peer} unreachable mid-send: {e}") from e

    def _register(self, fut_factory=None):
        """Allocate a rid and register its ack (and future) ATOMICALLY with
        the connection-liveness check: a _drop racing an unlocked
        registration would swap the maps without failing the new entry,
        stranding the caller until its timeout (and mislabelling a dead
        link as a probe_timeout).  Returns (sock, gen, rid, ack, fut)."""
        ack = _PendingAck()
        with self._lock:
            if self._sock is None:
                raise EngineDead(f"no connection to engine {self.peer}")
            sock, gen = self._sock, self._gen
            rid = self._rid = self._rid + 1
            fut = fut_factory(rid) if fut_factory is not None else None
            self._acks[rid] = ack
            if fut is not None:
                self._pending[rid] = fut
        return sock, gen, rid, ack, fut

    def _send_cancel(self, rid: int) -> None:
        with self._lock:
            self._pending.pop(rid, None)
            sock, gen = self._sock, self._gen
        if sock is None:
            return
        try:
            self._send(sock, gen, {"op": "cancel", "rid": rid})
        except EngineDead:
            pass  # the engine is gone; nothing left to skip

    def _read_loop(self, sock: socket.socket, gen: int) -> None:
        while True:
            try:
                frame = framing.recv_frame(sock, self.max_frame_bytes)
            except (OSError, framing.FrameError) as e:
                self._drop(sock, gen, f"{type(e).__name__}: {e}")
                return
            if frame is None:
                self._drop(sock, gen, "peer closed")
                return
            header, blob = frame
            self.bytes_recv += (framing.PREFIX_BYTES + framing.TRAILER_BYTES
                                + len(blob) + 64)  # header ~estimated
            try:
                self._on_frame(header, blob)
            except Exception:
                pass  # one malformed-but-framed reply must not kill the link

    def _refresh(self, header: Dict[str, Any]) -> None:
        """Fold the state every engine frame piggybacks (depth/version)."""
        if "depth" in header:
            self._depth = int(header["depth"])
        if "version" in header:
            with self._lock:
                self._version = int(header["version"])
        if "lanes" in header:
            self.lanes = max(int(header["lanes"]), 1)
        if "buckets" in header:
            self.buckets = tuple(int(b) for b in header["buckets"])
        if "digest" in header:
            self.digest = header["digest"]

    def _on_frame(self, header: Dict[str, Any], blob: bytes) -> None:
        self._refresh(header)
        op = header.get("op")
        rid = header.get("rid")
        if op == "ack":
            ack = self._acks.pop(rid, None) if rid is not None else None
            if ack is not None:
                ack.ok = bool(header.get("ok"))
                if not ack.ok:
                    ack.error = _wire_error(
                        header.get("etype", "overloaded"),
                        header.get("msg", f"engine {self.peer} shed"))
                ack.event.set()
        elif op == "result":
            fut = self._pending.pop(rid, None) if rid is not None else None
            if fut is not None:
                try:
                    q = framing.decode_ndarray(
                        {"dtype": header["dtype"], "shape": header["shape"]},
                        blob)
                    action = int(header["action"])
                except Exception as e:
                    # a malformed result must SETTLE the future — dropping
                    # it would hang the caller to its outer deadline
                    fut.set_error(framing.FrameCorrupt(
                        f"undecodable result from {self.peer}: "
                        f"{type(e).__name__}: {e}"))
                else:
                    fut.set_result(action, q)
        elif op == "rerr":
            fut = self._pending.pop(rid, None) if rid is not None else None
            if fut is not None:
                fut.set_error(_wire_error(header.get("etype", ""),
                                          header.get("msg", "engine error")))
        elif op in ("pong", "adopt_ok", "adopt_err"):
            ack = self._acks.pop(rid, None) if rid is not None else None
            if ack is not None:
                ack.ok = op != "adopt_err"
                if not ack.ok:
                    ack.error = _wire_error(header.get("etype", ""),
                                            header.get("msg", "adopt failed"))
                ack.event.set()

    # ------------------------------------------------- ServerTransport seam
    def submit(self, obs) -> ServeFuture:
        """One request: send, wait for the engine's accept/shed ack (one
        RTT), return the future the reader thread will settle.  Sheds raise
        ``ServerOverloaded`` exactly like the in-process transport, so the
        router's try-next-engine probe loop is transport-agnostic."""
        if not self._ensure_connected():
            raise EngineDead(f"engine {self.peer} unreachable")
        arr = np.asarray(obs)
        meta, blob = framing.encode_ndarray(arr)
        sock, gen, rid, ack, fut = self._register(
            lambda rid: RemoteFuture(arr, rid, self))
        self._send(sock, gen, {"op": "submit", "rid": rid, **meta}, blob)
        if not ack.event.wait(self.ack_timeout_s):
            self._acks.pop(rid, None)
            self._pending.pop(rid, None)
            raise EngineDead(
                f"engine {self.peer} did not ack within "
                f"{self.ack_timeout_s}s (hung or dying)")
        if ack.error is not None:
            self._pending.pop(rid, None)
            raise ack.error
        return fut

    def depth(self) -> int:
        return self._depth

    def alive(self) -> bool:
        """Connected, or a due (bounded) re-dial succeeded.  The registry's
        transport-liveness fallback and the router's routable() both land
        here; a dead remote costs at most one ``probe_timeout_s`` dial per
        backoff slot."""
        if self._closed:
            return False
        return self._ensure_connected()

    def version(self) -> int:
        return self._version

    def set_version(self, version: int) -> None:
        with self._lock:
            self._version = int(version)

    # --------------------------------------------------------- wire-only ops
    def request(self, header: Dict[str, Any], blob: bytes = b"",
                timeout_s: Optional[float] = None) -> _PendingAck:
        """One synchronous RPC (ping/adopt): send, wait for the matching
        reply, return the settled ack.  Raises the mapped wire error."""
        if not self._ensure_connected():
            raise EngineDead(f"engine {self.peer} unreachable")
        sock, gen, rid, ack, _fut = self._register()
        self._send(sock, gen, {**header, "rid": rid}, blob)
        budget = self.ack_timeout_s if timeout_s is None else timeout_s
        if not ack.event.wait(budget):
            self._acks.pop(rid, None)
            raise TimeoutError(
                f"engine {self.peer} did not answer {header.get('op')!r} "
                f"within {budget}s")
        if ack.error is not None:
            raise ack.error
        return ack

    def probe(self, timeout_s: Optional[float] = None) -> Optional[float]:
        """Bounded liveness probe: ping -> rtt_ms, refreshing the cached
        depth/version/lanes/digest.  None on timeout or a dead link (the
        registry marks the engine unroutable) — NEVER blocks past the
        bound, so one hung remote cannot stall the discovery sweep."""
        budget = self.probe_timeout_s if timeout_s is None else timeout_s
        t0 = time.monotonic()
        try:
            self.request({"op": "ping"}, timeout_s=budget)
        except TimeoutError:
            # connected but not answering: a WEDGED engine — the signal the
            # RUNBOOK's "probe_timeout with a fresh lease" triage keys on
            self.probe_timeouts += 1
            self._log("probe_timeout", budget_s=budget)
            self._count("net_probe_timeouts_total")
            return None
        except EngineDead:
            # unreachable (refused / mid-backoff): the disconnect row and
            # the lease expiry already tell THAT story — a probe_timeout
            # row here would steer triage at the wrong layer
            return None
        self.rtt_ms = round((time.monotonic() - t0) * 1e3, 3)
        return self.rtt_ms

    def stats(self) -> Dict[str, Any]:
        return {
            "peer": self.peer,
            "engine": self.engine_id,
            "connected": self.connected(),
            "rtt_ms": self.rtt_ms,
            "reconnects": self.reconnects,
            "probe_timeouts": self.probe_timeouts,
            "bytes_sent": self.bytes_sent,
            "bytes_recv": self.bytes_recv,
        }


class RemoteEngine:
    """`FleetRollout`-protocol proxy: adopt/adopt_packet/adopt_chain over a
    `RemoteTransport` — the controller-side handle for an engine on another
    host.  Backward refusal stays enforced at BOTH ends: the remote
    `FleetEngine` refuses locally and the refusal travels back as the same
    ``ValueError`` the in-process path raises."""

    def __init__(self, engine_id: int, transport: RemoteTransport):
        self.engine_id = int(engine_id)
        self.transport = transport

    @classmethod
    def from_lease(cls, lease, **transport_kwargs: Any) -> "RemoteEngine":
        """Build from an engine lease that advertises ``addr``/``port``
        (parallel/elastic.py — the payload grown by TransportServer)."""
        if not lease.addr or not lease.port:
            raise ValueError(
                f"lease for host {lease.host} carries no addr:port "
                "(engine not serving over the net)")
        return cls(lease.host, RemoteTransport(
            lease.addr, lease.port, engine_id=lease.host,
            **transport_kwargs))

    def _adopt(self, mode: str, blobs: List[bytes],
               version: Optional[int] = None) -> int:
        header: Dict[str, Any] = {"op": "adopt", "mode": mode,
                                  "n": len(blobs)}
        if version is not None:
            header["version"] = int(version)
        ack = self.transport.request(header, framing.pack_blobs(blobs),
                                     timeout_s=self.transport.ack_timeout_s)
        # adopt_ok piggybacks version/digest; the refresh already cached them
        _ = ack
        return self.transport.version()

    def adopt(self, params: Any, version: int) -> int:
        """Full uncompressed adopt: ships one fp32 base packet (bit-exact
        round-trip; no delta state needed on either side)."""
        packet = quantize.params_packet(params, version)
        return self._adopt("params", [quantize.packet_to_bytes(packet)],
                           version=version)

    def adopt_packet(self, packet: Any) -> int:
        return self._adopt("packet", [quantize.packet_to_bytes(packet)])

    def adopt_chain(self, packets: Any) -> int:
        return self._adopt(
            "chain", [quantize.packet_to_bytes(p) for p in packets])

    def served_digest(self, timeout_s: Optional[float] = None
                      ) -> Optional[str]:
        """The digest of the params the engine currently serves (refreshed
        by a bounded ping) — the cross-host bit-exactness witness."""
        if self.transport.probe(timeout_s=timeout_s) is None:
            return None
        return self.transport.digest
