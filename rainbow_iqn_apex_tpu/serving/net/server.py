"""Engine half of the cross-host serving plane: `TransportServer` listens on
a stdlib socket and speaks the framed protocol to N `RemoteTransport`
clients, translating frames onto the SAME seams the in-process fleet uses —
``try_submit`` on the policy server, ``adopt``/``adopt_packet``/
``adopt_chain`` on the `FleetEngine`.  Nothing below the socket changes:
batching, shedding, hot-swap, the monotonicity guards all run exactly the
in-process code paths.

One selectors-driven daemon thread owns accepts and reads (the obs/export.py
no-deps style); replies are written directly by whichever thread settles the
future (the serve worker, an adopt caller), serialised by a per-connection
lock — the event loop never blocks on a slow peer's inference.

Piggyback contract: every reply frame carries the engine's live
``depth``/``version`` (and ``digest`` on pongs/adopts), so clients rank
engines without dedicated RPCs.

``for_engine`` is the deployment shape: wrap a `FleetEngine`, bind, and
advertise ``addr:port`` in the engine's lease payload — the router's
`EngineRegistry` then discovers the remote engine through the SAME lease
files that already carry its depth/version, no second discovery protocol.
"""

from __future__ import annotations

import queue
import selectors
import socket
import threading
from typing import Any, Dict, Optional, Tuple

from rainbow_iqn_apex_tpu.netcore import chaos
from rainbow_iqn_apex_tpu.serving.batcher import ServerClosed, ServerOverloaded
from rainbow_iqn_apex_tpu.serving.net import framing
from rainbow_iqn_apex_tpu.utils import quantize

# bound on one reply write: a peer that stalls reading for this long is
# dropped (its requests re-route) instead of wedging the writing thread
_SEND_TIMEOUT_S = 5.0


class _Conn:
    """One accepted client connection: its socket, incremental frame
    reader, the request ids with live engine futures, and a bounded
    outbound queue drained by this connection's OWN writer thread — so
    neither the selector loop nor another connection's worker can ever
    block on this peer's full send buffer."""

    __slots__ = ("sock", "reader", "rids", "peer", "outq")

    def __init__(self, sock: socket.socket, max_frame_bytes: int):
        self.sock = sock
        self.reader = framing.FrameReader(max_frame_bytes)
        self.rids: Dict[int, Any] = {}
        # bounded: a peer stalled past ~this many un-sent replies is dead
        # weight — the enqueue failure drops the connection instead of
        # growing reply memory without bound
        self.outq: "queue.Queue" = queue.Queue(maxsize=4096)
        try:
            self.peer = "%s:%s" % sock.getpeername()[:2]
        except OSError:
            self.peer = "?"


class TransportServer:
    """Serve the framed protocol for one engine.

    ``server`` needs the `PolicyServer` surface the in-process
    `ServerTransport` already drives (``try_submit``, a queue depth); the
    optional ``engine`` (a `FleetEngine`, or any object with
    ``adopt``/``adopt_packet``/``adopt_chain`` + a versioned ``transport``)
    enables the wire rollout ops.  ``port=0`` binds an ephemeral port (read
    ``.port``); ``stop()`` closes the listener and every connection but
    leaves the engine itself running (the engine's own lifecycle is its
    owner's job).
    """

    def __init__(self, server: Any, engine: Any = None,
                 host: str = "127.0.0.1", port: int = 0,
                 advertise: Optional[str] = None,
                 max_frame_bytes: int = framing.DEFAULT_MAX_FRAME,
                 logger=None):
        self.server = server
        self.engine = engine
        self.max_frame_bytes = int(max_frame_bytes)
        self.logger = logger
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(64)
        self._listener.setblocking(False)
        self.port = self._listener.getsockname()[1]
        # what peers should dial: an explicit advertise address, else the
        # bind host unless it is a wildcard (peers cannot dial 0.0.0.0)
        self.advertise = advertise or (
            "127.0.0.1" if host in ("", "0.0.0.0") else host)
        self._selector = selectors.DefaultSelector()
        self._selector.register(self._listener, selectors.EVENT_READ, None)
        self._conns: Dict[int, _Conn] = {}  # fd -> conn
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.frames_in = 0
        self.bytes_out = 0

    @classmethod
    def for_engine(cls, engine: Any, host: str = "127.0.0.1", port: int = 0,
                   advertise: Optional[str] = None,
                   max_frame_bytes: int = framing.DEFAULT_MAX_FRAME,
                   logger=None) -> "TransportServer":
        """Wrap a `FleetEngine` and advertise ``addr:port`` in its lease
        payload, so routers discover this engine's wire endpoint through
        the lease files they already watch.  Call BEFORE ``engine.start()``
        so the very first beat carries the address."""
        ts = cls(engine.server, engine=engine, host=host, port=port,
                 advertise=advertise, max_frame_bytes=max_frame_bytes,
                 logger=logger)
        engine.writer.update_payload(addr=ts.advertise, port=ts.port)
        return ts

    @classmethod
    def from_config(cls, cfg, engine: Any, logger=None) -> Optional["TransportServer"]:
        """The config seam: ``serve_net_host`` unset (default) returns None
        — the fleet stays in-process, bitwise the pre-net path."""
        if not getattr(cfg, "serve_net_host", ""):
            return None
        return cls.for_engine(
            engine, host=cfg.serve_net_host, port=cfg.serve_net_port,
            advertise=cfg.serve_net_advertise or None,
            max_frame_bytes=int(cfg.serve_net_max_frame_mb) << 20,
            logger=logger)

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "TransportServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name=f"net-server-{self.port}", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Close the listener and every connection.  Clients see the drop as
        `EngineDead` and re-route — the wire analog of an engine kill."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            self._close_conn(conn, unregister=False)
        try:
            self._selector.close()
        except (OSError, RuntimeError):
            pass
        try:
            self._listener.close()
        except OSError:
            pass

    # -------------------------------------------------------------- event loop
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                events = self._selector.select(timeout=0.1)
            except OSError:
                return
            for key, _mask in events:
                if key.fileobj is self._listener:
                    self._accept()
                else:
                    self._read(key.data)

    def _accept(self) -> None:
        try:
            sock, _addr = self._listener.accept()
        except OSError:
            return
        # blocking with a bound, NOT non-blocking: replies go out via
        # sendall from whatever thread settles the future, and sendall on a
        # non-blocking socket raises the moment the kernel buffer fills —
        # a client merely slow to READ would be torn down mid-frame.  With
        # a timeout, sendall loops through partial writes and only a peer
        # stalled past the bound is dropped.  Reads stay selector-driven
        # (recv after a readiness event returns promptly).
        sock.settimeout(_SEND_TIMEOUT_S)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        sock = chaos.maybe_wrap(sock, peer=f"{_addr[0]}:{_addr[1]}",
                                logger=self.logger)
        conn = _Conn(sock, self.max_frame_bytes)
        with self._lock:
            self._conns[sock.fileno()] = conn
        threading.Thread(target=self._write_loop, args=(conn,),
                         name=f"net-writer-{self.port}", daemon=True).start()
        self._selector.register(sock, selectors.EVENT_READ, conn)

    def _close_conn(self, conn: _Conn, unregister: bool = True) -> None:
        if unregister:
            try:
                self._selector.unregister(conn.sock)
            except (KeyError, OSError, ValueError):
                pass
            with self._lock:
                self._conns.pop(conn.sock.fileno(), None)
        try:
            conn.outq.put_nowait(None)  # stop the writer thread
        except queue.Full:
            pass  # writer will exit on the closed socket's send error
        try:
            conn.sock.close()
        except OSError:
            pass
        # the client is gone: cancel its queued requests so abandoned slots
        # don't burn batch capacity (the slow-client story, wire edition)
        rids, conn.rids = dict(conn.rids), {}
        for fut in rids.values():
            try:
                fut.cancel()
            except Exception:
                pass

    def _read(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(1 << 16)
        except (BlockingIOError, socket.timeout):
            return  # spurious readiness; nothing to read this round
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)
            return
        try:
            frames = conn.reader.feed(data)
        except framing.FrameError as e:
            # a peer that breaks framing (oversize, corrupt, wrong protocol)
            # is dropped with one reasoned row — stream state past a framing
            # error is unrecoverable by contract
            self._log("bad_frame", peer=conn.peer,
                      why=f"{type(e).__name__}: {e}")
            self._close_conn(conn)
            return
        for header, blob in frames:
            self.frames_in += 1
            try:
                self._handle(conn, header, blob)
            except Exception as e:
                self._reply(conn, {"op": "rerr",
                                   "rid": header.get("rid"),
                                   "etype": "closed",
                                   "msg": f"{type(e).__name__}: {e}"})

    # ---------------------------------------------------------------- replies
    def _log(self, event: str, **fields: Any) -> None:
        if self.logger is not None:
            try:
                self.logger.log("net", event=event, **fields)
            except Exception:
                pass

    def _depth(self) -> int:
        batcher = getattr(self.server, "batcher", None)
        if batcher is not None:
            return int(batcher.depth())
        depth = getattr(self.server, "depth", None)
        return int(depth()) if callable(depth) else 0

    def _state(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"depth": self._depth()}
        if self.engine is not None:
            t = self.engine.transport
            out["version"] = int(t.version())
            out["lanes"] = int(getattr(t, "lanes", 1))
            out["buckets"] = list(getattr(t, "buckets", ()) or ())
            digest = getattr(self.engine, "served_digest", None)
            if digest:
                out["digest"] = digest
        return out

    def _reply(self, conn: _Conn, header: Dict[str, Any],
               blob: bytes = b"") -> None:
        """Enqueue one reply for the connection's writer thread.  Callers
        (the selector loop, serve workers, adopt threads) never touch the
        socket, so a peer with a full send buffer can only stall its OWN
        writer — a full queue means the peer is long stalled and the
        connection is dropped instead."""
        header = {**header, **self._state()}
        try:
            conn.outq.put_nowait((header, blob))
        except queue.Full:
            self._close_conn(conn)

    def _write_loop(self, conn: _Conn) -> None:
        while True:
            item = conn.outq.get()
            if item is None:  # close sentinel
                return
            header, blob = item
            try:
                self.bytes_out += framing.send_frame(conn.sock, header, blob)
            except (OSError, ValueError):
                self._close_conn(conn)
                return

    # ---------------------------------------------------------------- handlers
    def _handle(self, conn: _Conn, header: Dict[str, Any],
                blob: bytes) -> None:
        op = header.get("op")
        rid = header.get("rid")
        if op == "ping":
            self._reply(conn, {"op": "pong", "rid": rid, "alive": True})
        elif op == "submit":
            self._handle_submit(conn, rid, header, blob)
        elif op == "cancel":
            fut = conn.rids.get(rid)
            if fut is not None:
                fut.cancel()
        elif op == "adopt":
            # OFF the event loop: a real-size adopt (npz decode + device
            # transfer + digest) runs long past the probe budget, and
            # blocking the loop here would make every weight rollout read
            # as a wedged engine (probe-suspect eviction fleet-wide).
            # Adopts are publish-cadence rare; controller-side RPCs are
            # sequential per connection, so ordering is preserved.
            threading.Thread(
                target=self._handle_adopt, args=(conn, rid, header, blob),
                name=f"net-adopt-{self.port}", daemon=True).start()
        else:
            self._reply(conn, {"op": "rerr", "rid": rid,
                               "etype": "unsupported",
                               "msg": f"unknown op {op!r}"})

    def _handle_submit(self, conn: _Conn, rid: Any,
                       header: Dict[str, Any], blob: bytes) -> None:
        try:
            obs = framing.decode_ndarray(header, blob)
            fut = self.server.try_submit(obs)
        except ServerClosed as e:
            self._reply(conn, {"op": "ack", "rid": rid, "ok": False,
                               "etype": "closed", "msg": str(e)})
            return
        except (framing.FrameError, TypeError, ValueError) as e:
            self._reply(conn, {"op": "ack", "rid": rid, "ok": False,
                               "etype": "unsupported",
                               "msg": f"{type(e).__name__}: {e}"})
            return
        if fut is None:  # engine queue full: the CLIENT router owns the shed
            self._reply(conn, {"op": "ack", "rid": rid, "ok": False,
                               "etype": "overloaded",
                               "msg": "engine queue full"})
            return
        conn.rids[rid] = fut
        self._reply(conn, {"op": "ack", "rid": rid, "ok": True})
        fut.add_done_callback(
            lambda f, conn=conn, rid=rid: self._on_done(conn, rid, f))

    def _on_done(self, conn: _Conn, rid: Any, fut: Any) -> None:
        """Runs on whichever thread settled the engine future (the serve
        worker on results, abort_pending on kills)."""
        conn.rids.pop(rid, None)
        if fut.cancelled():
            return  # the client cancelled; it is not waiting for a reply
        err = fut._error  # settled: no race left (batcher contract)
        if err is None:
            meta, blob = framing.encode_ndarray(fut._q)
            self._reply(conn, {"op": "result", "rid": rid,
                               "action": int(fut._action), **meta}, blob)
        else:
            etype = ("closed" if isinstance(err, ServerClosed)
                     else "overloaded" if isinstance(err, ServerOverloaded)
                     else "dead")
            self._reply(conn, {"op": "rerr", "rid": rid, "etype": etype,
                               "msg": str(err)})

    def _handle_adopt(self, conn: _Conn, rid: Any,
                      header: Dict[str, Any], blob: bytes) -> None:
        if self.engine is None:
            self._reply(conn, {"op": "adopt_err", "rid": rid,
                               "etype": "unsupported",
                               "msg": "this endpoint serves no FleetEngine "
                                      "(adopt ops unavailable)"})
            return
        mode = header.get("mode")
        try:
            packets = [quantize.packet_from_bytes(b)
                       for b in framing.unpack_blobs(blob)]
            if mode == "params":
                # one fp32 base packet = the uncompressed rollout payload
                params = quantize.unflatten_tree({
                    p: data for p, (data, _s) in packets[0].leaves.items()})
                version = self.engine.adopt(
                    params, int(header.get("version", packets[0].version)))
            elif mode == "packet":
                version = self.engine.adopt_packet(packets[0])
            elif mode == "chain":
                version = self.engine.adopt_chain(packets)
            else:
                raise RuntimeError(f"unknown adopt mode {mode!r}")
        except ValueError as e:  # backward/duplicate: refused at THIS end too
            self._reply(conn, {"op": "adopt_err", "rid": rid,
                               "etype": "backward", "msg": str(e)})
            return
        except quantize.DeltaChainBroken as e:
            self._reply(conn, {"op": "adopt_err", "rid": rid,
                               "etype": "chain_broken", "msg": str(e)})
            return
        except Exception as e:
            self._reply(conn, {"op": "adopt_err", "rid": rid,
                               "etype": "dead",
                               "msg": f"{type(e).__name__}: {e}"})
            return
        self._reply(conn, {"op": "adopt_ok", "rid": rid,
                           "version": int(version)})

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._conns)
        return {"port": self.port, "connections": n,
                "frames_in": self.frames_in, "bytes_out": self.bytes_out}
