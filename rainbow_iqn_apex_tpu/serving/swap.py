"""Weight hot-swap: feed the serving engine fresh learner checkpoints.

The training side already has a weight path (learner -> actor broadcast,
parallel/apex.py publish_weights).  Serving mirrors it from the durable end:
the learner saves Orbax checkpoints on its schedule (utils/checkpoint.py) and
the server either polls for new steps (``CheckpointWatcher``) or is told
explicitly (``reload()``).  Either way the actual swap is
``InferenceEngine.load_params`` — stage on the mesh off-thread, atomic
reference flip, zero dropped in-flight requests.

A corrupt or torn checkpoint must never take the server down: restore
failures are caught, emitted as ``swap`` rows with ``ok=false``, and the
engine keeps serving the previous params.  A failing step is retried up to
``max_restore_failures`` times (a transient I/O blip on a networked FS must
not strand the server on stale weights) and then poisoned — no retry storm
against a genuinely bad file.

Swaps adopt the training side's weight-version discipline
(parallel/elastic.py): each successful swap bumps the engine's monotone
``params_version``, ``healthz()`` reports ``weights_version`` +
``weights_age_s`` so serving staleness is externally monitorable, and a
swap never rolls BACKWARDS to an older checkpoint step unless forced.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from rainbow_iqn_apex_tpu.config import Config
from rainbow_iqn_apex_tpu.ops.learn import TrainState, init_train_state
from rainbow_iqn_apex_tpu.utils.checkpoint import Checkpointer
from rainbow_iqn_apex_tpu.utils.faults import FailureBudget


def params_template(
    cfg: Config, num_actions: int, state_shape=None
) -> TrainState:
    """An abstract TrainState with the right shapes/dtypes for restore —
    serving never trains, so the optimizer slots are just restore scaffolding."""
    return init_train_state(
        cfg, num_actions, jax.random.PRNGKey(0), state_shape=state_shape
    )


def restore_params(
    ckpt: Checkpointer,
    template: TrainState,
    step: Optional[int] = None,
) -> Any:
    """Load ONLINE params (what acting uses) from a checkpoint step."""
    state, _ = ckpt.restore(template, step=step)
    return state.params


class CheckpointWatcher:
    """Poll an Orbax checkpoint dir; hot-swap the engine on each new step.

    ``swap_fn`` is ``engine.load_params``; ``metrics`` (ServeMetrics) gets a
    ``swap`` event per attempt, success or failure.  ``reload()`` runs one
    swap attempt synchronously (explicit-reload API); the poll thread does
    the same on its interval.
    """

    def __init__(
        self,
        ckpt: Checkpointer,
        template: TrainState,
        swap_fn: Callable[[Any], int],
        poll_interval_s: float = 2.0,
        metrics=None,
        max_restore_failures: int = 3,
    ):
        self.ckpt = ckpt
        self.template = jax.tree.map(np.asarray, template)
        self.swap_fn = swap_fn
        self.poll_interval_s = float(poll_interval_s)
        self.metrics = metrics
        self.max_restore_failures = int(max_restore_failures)
        self.last_step: Optional[int] = None
        self._refused_backward: Optional[int] = None  # dedupe metric rows
        # the shared bounded-failure policy (utils/faults.py): training's
        # supervisor and the serving hot-swap count strikes the same way
        self._budget = FailureBudget(max_restore_failures)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._swap_lock = threading.Lock()  # one restore at a time

    # ------------------------------------------------------------- swapping
    def reload(self, step: Optional[int] = None, force: bool = False) -> Dict[str, Any]:
        """Attempt one swap from ``step`` (default: latest).  Returns an event
        dict mirroring the emitted metrics row.  ``force`` re-swaps even when
        the step was already loaded (params-delta testing, manual recovery)."""
        with self._swap_lock:
            # refresh, not latest_step: the learner writing the dir is a
            # different process, invisible to the manager's cached listing
            target = self.ckpt.refresh() if step is None else step
            if target is None:
                return {"ok": False, "reason": "no_checkpoint"}
            if self._budget.poisoned(target) and not force:
                return {"ok": False, "step": target, "reason": "poisoned"}
            if target == self.last_step and not force:
                return {"ok": True, "step": target, "reason": "already_loaded"}
            if (self.last_step is not None and target < self.last_step
                    and not force):
                # never roll the fleet BACKWARDS: the checkpoint step is the
                # weight-version stamp (parallel/elastic.py semantics), and a
                # listing that momentarily surfaces an older step (pruned dir
                # resync, explicit reload(step=) typo) must not regress live
                # traffic to stale weights.  Deliberate rollback = force=True.
                # The metrics row fires once per refused step, not once per
                # poll — a training lineage legitimately restarted from an
                # older checkpoint would otherwise spam a swap row every
                # poll_interval_s until its step count caught up.
                event = {"ok": False, "step": target,
                         "loaded_step": self.last_step,
                         "reason": "older_than_loaded"}
                if self.metrics is not None and target != self._refused_backward:
                    self._refused_backward = target
                    self.metrics.record_swap(**event)
                return event
            try:
                params = restore_params(self.ckpt, self.template, step=target)
                version = self.swap_fn(params)
            except Exception as e:  # torn/corrupt file: keep serving old params
                event = {
                    "ok": False,
                    "step": target,
                    "failures": self._budget.record(target),
                    "reason": f"{type(e).__name__}: {e}"[:200],
                }
                if self.metrics is not None:
                    self.metrics.record_swap(**event)
                return event
            self.last_step = target
            # a recovered step (forced or retried) is whole again — un-poison
            self._budget.clear(target)
            # any successful swap closes the refused-backward episode: a
            # LATER regression to the same old step is a new incident and
            # must emit its own telemetry row
            self._refused_backward = None
            event = {"ok": True, "step": target, "params_version": version}
            if self.metrics is not None:
                self.metrics.record_swap(**event)
            return event

    # ------------------------------------------------------------ poll loop
    def _poll_once(self) -> None:
        # reload() refreshes the step listing and restores under _swap_lock;
        # touching the (thread-unsafe) manager out here would race an
        # explicit reload() mid-restore
        self.reload()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self._poll_once()
            except Exception as e:  # a flaky listing must not kill the thread
                if self.metrics is not None:
                    self.metrics.record_swap(
                        ok=False, reason=f"poll: {type(e).__name__}: {e}"[:200]
                    )

    def start(self) -> "CheckpointWatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="serve-ckpt-watcher", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
