"""Dynamic micro-batching: coalesce concurrent act() requests into one batch.

Batched inference is where accelerator throughput lives (Stooke & Abbeel,
arXiv:1803.02811): one [B, H, W, C] dispatch amortises the fixed
per-dispatch cost over B requests.  The batcher's contract:

- requests enter a BOUNDED queue (backpressure); a full queue sheds the
  request immediately with ``ServerOverloaded`` instead of growing latency
  without bound — the caller sees the overload and can back off;
- the worker drains the queue into one batch per dispatch, waiting at most
  ``deadline_s`` past the OLDEST queued request's arrival before dispatching
  whatever it has (latency bound), and never waiting at all once ``max_batch``
  requests are queued (throughput bound);
- the batch is padded up to a small set of bucketed sizes chosen at
  construction, so XLA compiles one executable per bucket and never again
  (see engine.py — shape churn is the recompile trap).

All of this is plain host threading: requests are tiny numpy arrays and the
device call itself happens outside the lock.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np


class ServerOverloaded(RuntimeError):
    """Raised to the submitting client when the request queue is full."""


class ServerClosed(RuntimeError):
    """Raised to the submitting client when the server is shut down."""


class ServeFuture:
    """One in-flight request: the client blocks on ``result()``; the worker
    fulfils with ``set_result``/``set_error``."""

    __slots__ = ("obs", "t_enqueue", "_event", "_action", "_q", "_error")

    def __init__(self, obs: np.ndarray):
        self.obs = obs
        self.t_enqueue = time.monotonic()
        self._event = threading.Event()
        self._action: Optional[int] = None
        self._q: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None

    def set_result(self, action: int, q: np.ndarray) -> None:
        self._action = action
        self._q = q
        self._event.set()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Tuple[int, np.ndarray]:
        """Block until fulfilled; returns (action, q_values [A])."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request not fulfilled in time")
        if self._error is not None:
            raise self._error
        return self._action, self._q

    @property
    def latency_ms(self) -> float:
        return (time.monotonic() - self.t_enqueue) * 1e3


def pick_bucket(buckets: Sequence[int], n: int) -> int:
    """Smallest bucket >= n (buckets sorted ascending; n <= max bucket)."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"batch of {n} exceeds the largest bucket {buckets[-1]}")


class MicroBatcher:
    """Bounded request queue + deadline-driven coalescing.

    The worker thread (server.py) calls ``take()`` in a loop; client threads
    call ``submit()``.  ``close()`` wakes everyone; queued requests are still
    drained by the worker (graceful shutdown), new submissions are refused.
    """

    def __init__(
        self,
        buckets: Sequence[int],
        deadline_s: float,
        queue_bound: int,
        metrics=None,
    ):
        if not buckets:
            raise ValueError("need at least one batch bucket")
        self.buckets = sorted(set(int(b) for b in buckets))
        if self.buckets[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {self.buckets}")
        self.max_batch = self.buckets[-1]
        self.deadline_s = float(deadline_s)
        self.queue_bound = int(queue_bound)
        self.metrics = metrics
        self._queue: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False

    # ---------------------------------------------------------- client side
    def submit(self, obs: np.ndarray) -> ServeFuture:
        fut = ServeFuture(obs)
        with self._lock:
            if self._closed:
                raise ServerClosed("server is shut down")
            if len(self._queue) >= self.queue_bound:
                if self.metrics is not None:
                    self.metrics.record_shed()
                raise ServerOverloaded(
                    f"request queue full ({self.queue_bound}); shedding"
                )
            self._queue.append(fut)
            self._nonempty.notify()
        return fut

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # ---------------------------------------------------------- worker side
    def take(
        self, poll_s: float = 0.05, idle_timeout_s: Optional[float] = None
    ) -> Optional[List[ServeFuture]]:
        """Block for the next coalesced batch.

        Returns up to ``max_batch`` requests: immediately when the queue
        already holds a full batch, otherwise after the oldest queued request
        has waited ``deadline_s``.  With ``idle_timeout_s`` set, an EMPTY
        queue for that long returns ``[]`` — the worker's cue to emit a
        liveness heartbeat and call again.  Returns None only when closed
        AND drained — the worker's signal to exit.
        """
        t_start = time.monotonic()
        with self._lock:
            while True:
                if self._queue:
                    deadline = self._queue[0].t_enqueue + self.deadline_s
                    if len(self._queue) >= self.max_batch or self._closed:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._nonempty.wait(timeout=min(remaining, poll_s))
                else:
                    if self._closed:
                        return None
                    if (idle_timeout_s is not None
                            and time.monotonic() - t_start >= idle_timeout_s):
                        return []
                    self._nonempty.wait(timeout=poll_s)
            n = min(len(self._queue), self.max_batch)
            batch = [self._queue.popleft() for _ in range(n)]
            depth_after = len(self._queue)
        if self.metrics is not None:
            self.metrics.record_batch(
                n, pick_bucket(self.buckets, n), depth_after
            )
        return batch

    def close(self) -> None:
        """Refuse new submissions; the worker keeps draining what's queued."""
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()

    def abort_pending(self, err: BaseException) -> int:
        """Fail every queued request (hard shutdown path); returns count."""
        with self._lock:
            pending = list(self._queue)
            self._queue.clear()
        for fut in pending:
            fut.set_error(err)
        return len(pending)
