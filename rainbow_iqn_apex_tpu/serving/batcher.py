"""Dynamic micro-batching: coalesce concurrent act() requests into one batch.

Batched inference is where accelerator throughput lives (Stooke & Abbeel,
arXiv:1803.02811): one [B, H, W, C] dispatch amortises the fixed
per-dispatch cost over B requests.  The batcher's contract:

- requests enter a BOUNDED queue (backpressure); a full queue sheds the
  request immediately with ``ServerOverloaded`` instead of growing latency
  without bound — the caller sees the overload and can back off;
- the worker drains the queue into one batch per dispatch, waiting at most
  ``deadline_s`` past the OLDEST queued request's arrival before dispatching
  whatever it has (latency bound), and never waiting at all once ``max_batch``
  requests are queued (throughput bound);
- the batch is padded up to a small set of bucketed sizes chosen at
  construction, so XLA compiles one executable per bucket and never again
  (see engine.py — shape churn is the recompile trap).

All of this is plain host threading: requests are tiny numpy arrays and the
device call itself happens outside the lock.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np


class ServerOverloaded(RuntimeError):
    """Raised to the submitting client when the request queue is full."""


class ServerClosed(RuntimeError):
    """Raised to the submitting client when the server is shut down."""


class RequestCancelled(RuntimeError):
    """Raised from ``result()`` after the future was cancelled."""


class ServeFuture:
    """One in-flight request: the client blocks on ``result()``; the worker
    fulfils with ``set_result``/``set_error``.

    A client that gives up (``result()`` timeout, disconnect) should call
    ``cancel()``: a cancelled future is skipped by the batcher instead of
    padding, dispatching and fulfilling a dead slot — under a slow-client
    cohort the abandoned requests would otherwise silently burn batch
    capacity the live clients need."""

    __slots__ = ("obs", "t_enqueue", "_lock", "_event", "_action", "_q",
                 "_error", "_cancelled", "_callbacks")

    def __init__(self, obs: np.ndarray):
        self.obs = obs
        self.t_enqueue = time.monotonic()
        # the lock serialises settle-vs-cancel and callback registration:
        # exactly one of {result, error, cancelled} wins, and a callback
        # added after settling still fires exactly once
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._action: Optional[int] = None
        self._q: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._callbacks: List = []

    def _settle(self) -> Optional[List]:
        """Mark settled; returns the callbacks to run (None if already set)."""
        if self._event.is_set():
            return None
        self._event.set()
        cbs, self._callbacks = self._callbacks, []
        return cbs

    def _run_callbacks(self, cbs: Optional[List]) -> None:
        for cb in cbs or ():
            try:
                cb(self)
            except Exception:
                pass  # an observer bug must never poison the worker loop

    def set_result(self, action: int, q: np.ndarray) -> None:
        with self._lock:
            self._action = action
            self._q = q
            cbs = self._settle()
        self._run_callbacks(cbs)

    def set_error(self, err: BaseException) -> None:
        with self._lock:
            if not self._event.is_set():
                self._error = err
            cbs = self._settle()
        self._run_callbacks(cbs)

    def cancel(self) -> bool:
        """Abandon the request.  True when the cancel won (the future was not
        yet fulfilled): the batcher will drop it instead of dispatching, and
        ``result()`` raises RequestCancelled.  False when a result/error
        already landed — the outcome stands and nothing changes."""
        with self._lock:
            if self._event.is_set():
                return False
            self._cancelled = True
            self._error = RequestCancelled("request cancelled by client")
            cbs = self._settle()
        self._run_callbacks(cbs)
        return True

    def cancelled(self) -> bool:
        return self._cancelled

    def add_done_callback(self, fn) -> None:
        """Run ``fn(self)`` once the future settles (result, error or
        cancel); runs immediately when already settled.  The router uses
        this for inflight accounting and dead-engine re-dispatch."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        self._run_callbacks([fn])

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Tuple[int, np.ndarray]:
        """Block until fulfilled; returns (action, q_values [A])."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request not fulfilled in time")
        if self._error is not None:
            raise self._error
        return self._action, self._q

    @property
    def latency_ms(self) -> float:
        return (time.monotonic() - self.t_enqueue) * 1e3


def pick_bucket(buckets: Sequence[int], n: int) -> int:
    """Smallest bucket >= n (buckets sorted ascending; n <= max bucket)."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"batch of {n} exceeds the largest bucket {buckets[-1]}")


class MicroBatcher:
    """Bounded request queue + deadline-driven coalescing.

    The worker thread (server.py) calls ``take()`` in a loop; client threads
    call ``submit()``.  ``close()`` wakes everyone; queued requests are still
    drained by the worker (graceful shutdown), new submissions are refused.
    """

    def __init__(
        self,
        buckets: Sequence[int],
        deadline_s: float,
        queue_bound: int,
        metrics=None,
    ):
        if not buckets:
            raise ValueError("need at least one batch bucket")
        self.buckets = sorted(set(int(b) for b in buckets))
        if self.buckets[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {self.buckets}")
        self.max_batch = self.buckets[-1]
        self.deadline_s = float(deadline_s)
        self.queue_bound = int(queue_bound)
        self.metrics = metrics
        self._queue: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False

    # ---------------------------------------------------------- client side
    def submit(self, obs: np.ndarray) -> ServeFuture:
        fut = self.try_submit(obs)
        if fut is None:
            if self.metrics is not None:
                self.metrics.record_shed()
            raise ServerOverloaded(
                f"request queue full ({self.queue_bound}); shedding"
            )
        return fut

    def try_submit(self, obs: np.ndarray) -> Optional[ServeFuture]:
        """submit() minus the shed accounting: returns None when the queue
        is full instead of recording a shed and raising.  For probing
        callers that own their own shed story (the fleet router tries
        several engines per request — a probe that lands elsewhere is not
        an engine shed, and counting it would flip health to degraded on
        phantom pressure).  Still raises ServerClosed after close()."""
        fut = ServeFuture(obs)
        with self._lock:
            if self._closed:
                raise ServerClosed("server is shut down")
            if len(self._queue) >= self.queue_bound:
                return None
            self._queue.append(fut)
            self._nonempty.notify()
        return fut

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # ---------------------------------------------------------- worker side
    def take(
        self, poll_s: float = 0.05, idle_timeout_s: Optional[float] = None
    ) -> Optional[List[ServeFuture]]:
        """Block for the next coalesced batch.

        Returns up to ``max_batch`` requests: immediately when the queue
        already holds a full batch, otherwise after the oldest queued request
        has waited ``deadline_s``.  With ``idle_timeout_s`` set, an EMPTY
        queue for that long returns ``[]`` — the worker's cue to emit a
        liveness heartbeat and call again.  Returns None only when closed
        AND drained — the worker's signal to exit.
        """
        t_start = time.monotonic()
        cancelled = 0
        with self._lock:
            while True:
                # drop cancelled heads eagerly: an abandoned request must not
                # hold the deadline clock (its enqueue time is the oldest) or
                # a batch slot — the slow-client cohort would otherwise burn
                # capacity live clients need
                while self._queue and self._queue[0].cancelled():
                    self._queue.popleft()
                    cancelled += 1
                if self._queue:
                    deadline = self._queue[0].t_enqueue + self.deadline_s
                    if len(self._queue) >= self.max_batch or self._closed:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._nonempty.wait(timeout=min(remaining, poll_s))
                else:
                    if self._closed:
                        if cancelled and self.metrics is not None:
                            self.metrics.record_cancelled(cancelled)
                        return None
                    if (idle_timeout_s is not None
                            and time.monotonic() - t_start >= idle_timeout_s):
                        if cancelled and self.metrics is not None:
                            self.metrics.record_cancelled(cancelled)
                        return []
                    self._nonempty.wait(timeout=poll_s)
            batch: List[ServeFuture] = []
            while self._queue and len(batch) < self.max_batch:
                fut = self._queue.popleft()
                if fut.cancelled():
                    cancelled += 1
                    continue
                batch.append(fut)
            n = len(batch)
            depth_after = len(self._queue)
        if self.metrics is not None:
            if cancelled:
                self.metrics.record_cancelled(cancelled)
            if n:
                self.metrics.record_batch(
                    n, pick_bucket(self.buckets, n), depth_after
                )
                # queue-to-slot wait (pipeline lag attribution): how long
                # this batch's requests sat queued before coalescing granted
                # them a slot — guarded getattr so metrics stand-ins without
                # the obs surface keep working
                record_wait = getattr(self.metrics, "record_queue_wait", None)
                if record_wait is not None:
                    now = time.monotonic()
                    record_wait(
                        sum((now - f.t_enqueue) for f in batch) / n * 1e3)
        return batch

    def close(self) -> None:
        """Refuse new submissions; the worker keeps draining what's queued."""
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()

    def abort_pending(self, err: BaseException) -> int:
        """Fail every queued request (hard shutdown path); returns count."""
        with self._lock:
            pending = list(self._queue)
            self._queue.clear()
        for fut in pending:
            fut.set_error(err)
        return len(pending)
